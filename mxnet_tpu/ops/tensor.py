"""Tensor operators: elemwise, broadcast, scalar, reduce, shape, indexing.

Reference: ``src/operator/tensor/`` (elemwise_unary_op, elemwise_binary_op,
broadcast_reduce_op, matrix_op, indexing_op, ordering_op, init_op —
SURVEY.md 2.1 "Operator library").  Each op here is a pure JAX function;
XLA fuses elementwise chains into matmul epilogues automatically, which is
why there is no hand-written kernel per op (the mshadow expression-template
role is played by the XLA fusion pass).

Naming follows the reference op names so generated frontends are
drop-in (`broadcast_add`, `_plus_scalar`, `slice_axis`, ...).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias

# ---------------------------------------------------------------------------
# Elemwise unary (reference: src/operator/tensor/elemwise_unary_op_basic.cc)
# ---------------------------------------------------------------------------

_UNARY = {
    "abs": jnp.abs, "sign": jnp.sign, "ceil": jnp.ceil, "floor": jnp.floor,
    "rint": jnp.rint, "round": jnp.round, "trunc": jnp.trunc,
    "exp": jnp.exp, "log": jnp.log, "log2": jnp.log2, "log10": jnp.log10,
    "log1p": jnp.log1p, "expm1": jnp.expm1, "sqrt": jnp.sqrt,
    "cbrt": jnp.cbrt, "square": jnp.square,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "arcsin": jnp.arcsin, "arccos": jnp.arccos, "arctan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh, "arccosh": jnp.arccosh, "arctanh": jnp.arctanh,
    "degrees": jnp.degrees, "radians": jnp.radians,
    "erf": jax.scipy.special.erf, "erfinv": jax.scipy.special.erfinv,
    "gammaln": jax.scipy.special.gammaln,
    "negative": jnp.negative,
}

for _name, _fn in _UNARY.items():
    register(_name)(
        (lambda f: (lambda data: f(data)))(_fn))

register("reciprocal")(lambda data: 1.0 / data)
register("rsqrt")(lambda data: lax.rsqrt(data))
register("rcbrt")(lambda data: 1.0 / jnp.cbrt(data))
register("gamma")(lambda data: jnp.exp(jax.scipy.special.gammaln(data)))
register("logical_not", differentiable=False)(
    lambda data: jnp.logical_not(data).astype(data.dtype))
register("relu")(lambda data: jnp.maximum(data, 0))
register("sigmoid")(lambda data: jax.nn.sigmoid(data))
register("softsign")(lambda data: data / (1 + jnp.abs(data)))
register("erfc")(lambda data: 1.0 - jax.scipy.special.erf(data))


@register("clip")
def clip(data, *, a_min: float = None, a_max: float = None):
    """Clip values to [a_min, a_max] (reference: tensor/matrix_op.cc Clip)."""
    return jnp.clip(data, a_min, a_max)


@register("cast", aliases=["Cast"])
def cast(data, *, dtype: str = "float32"):
    return data.astype(jnp.dtype(dtype))


@register("zeros_like", differentiable=False)
def zeros_like(data):
    return jnp.zeros_like(data)


@register("ones_like", differentiable=False)
def ones_like(data):
    return jnp.ones_like(data)


@register("full_like", differentiable=False)
def full_like(data, *, fill_value: float = 0.0):
    return jnp.full_like(data, fill_value)


@register("shape_array", differentiable=False)
def shape_array(data):
    return jnp.asarray(data.shape, dtype=jnp.int64)


@register("size_array", differentiable=False)
def size_array(data):
    return jnp.asarray([data.size], dtype=jnp.int64)


@register("stop_gradient", aliases=["BlockGrad"])
def stop_gradient(data):
    return lax.stop_gradient(data)


@register("identity", aliases=["_copy"])
def identity(data):
    return data


@register("make_loss", aliases=["MakeLoss"])
def make_loss(data, *, grad_scale: float = 1.0, valid_thresh: float = 0.0,
              normalization: str = "null"):
    return data


# ---------------------------------------------------------------------------
# Elemwise binary + broadcast (reference: elemwise_binary_broadcast_op_*.cc).
# In the reference elemwise_* require equal shapes and broadcast_* broadcast;
# XLA broadcasting covers both, but both names are kept for API parity.
# ---------------------------------------------------------------------------

_BINARY = {
    "add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
    "div": jnp.divide, "mod": jnp.mod, "power": jnp.power,
    "maximum": jnp.maximum, "minimum": jnp.minimum,
    "hypot": jnp.hypot, "arctan2": jnp.arctan2,
}

for _name, _fn in _BINARY.items():
    register(f"broadcast_{_name}", num_inputs=2)(
        (lambda f: (lambda lhs, rhs: f(lhs, rhs)))(_fn))

alias("broadcast_add", "broadcast_plus")
alias("broadcast_sub", "broadcast_minus")
alias("broadcast_power", "_power")

for _name in ("add", "sub", "mul", "div"):
    register(f"elemwise_{_name}", num_inputs=2,
             aliases=[f"_{_name}"] if _name != "sub" else ["_sub", "_minus"])(
        (lambda f: (lambda lhs, rhs: f(lhs, rhs)))(_BINARY[_name]))

_CMP = {
    "equal": jnp.equal, "not_equal": jnp.not_equal,
    "greater": jnp.greater, "greater_equal": jnp.greater_equal,
    "lesser": jnp.less, "lesser_equal": jnp.less_equal,
}
for _name, _fn in _CMP.items():
    register(f"broadcast_{_name}", num_inputs=2, differentiable=False)(
        (lambda f: (lambda lhs, rhs: f(lhs, rhs).astype(lhs.dtype)))(_fn))

for _name, _fn in (("logical_and", jnp.logical_and),
                   ("logical_or", jnp.logical_or),
                   ("logical_xor", jnp.logical_xor)):
    register(f"broadcast_{_name}", num_inputs=2, differentiable=False)(
        (lambda f: (lambda lhs, rhs: f(lhs, rhs).astype(lhs.dtype)))(_fn))


# Scalar ops (reference: elemwise_binary_scalar_op_*.cc)
@register("_plus_scalar")
def _plus_scalar(data, *, scalar: float = 0.0):
    return data + scalar


@register("_minus_scalar")
def _minus_scalar(data, *, scalar: float = 0.0):
    return data - scalar


@register("_rminus_scalar")
def _rminus_scalar(data, *, scalar: float = 0.0):
    return scalar - data


@register("_mul_scalar")
def _mul_scalar(data, *, scalar: float = 1.0):
    return data * scalar


@register("_div_scalar")
def _div_scalar(data, *, scalar: float = 1.0):
    return data / scalar


@register("_rdiv_scalar")
def _rdiv_scalar(data, *, scalar: float = 1.0):
    return scalar / data


@register("_mod_scalar")
def _mod_scalar(data, *, scalar: float = 1.0):
    return jnp.mod(data, scalar)


@register("_rmod_scalar")
def _rmod_scalar(data, *, scalar: float = 1.0):
    return jnp.mod(scalar, data)


@register("_power_scalar")
def _power_scalar(data, *, scalar: float = 1.0):
    return jnp.power(data, scalar)


@register("_rpower_scalar")
def _rpower_scalar(data, *, scalar: float = 1.0):
    return jnp.power(scalar, data)


@register("_maximum_scalar")
def _maximum_scalar(data, *, scalar: float = 0.0):
    return jnp.maximum(data, scalar)


@register("_minimum_scalar")
def _minimum_scalar(data, *, scalar: float = 0.0):
    return jnp.minimum(data, scalar)


@register("_hypot_scalar")
def _hypot_scalar(data, *, scalar: float = 0.0):
    return jnp.hypot(data, scalar)


for _name, _fn in _CMP.items():
    register(f"_{_name}_scalar", differentiable=False)(
        (lambda f: (lambda data, *, scalar=0.0:
                    f(data, scalar).astype(data.dtype)))(_fn))
register("_greater_scalar_rev", differentiable=False)(
    lambda data, *, scalar=0.0: jnp.greater(scalar, data).astype(data.dtype))


# ---------------------------------------------------------------------------
# Reductions (reference: broadcast_reduce_op_value.cc)
# ---------------------------------------------------------------------------

def _norm_axis(axis):
    if axis is None or axis == ():
        return None
    if isinstance(axis, int):
        return (axis,)
    return tuple(axis)


def _reduce(fn, data, axis, keepdims, exclude=False):
    axis = _norm_axis(axis)
    if exclude and axis is not None:
        axis = tuple(i for i in range(data.ndim) if i not in
                     tuple(a % data.ndim for a in axis))
    return fn(data, axis=axis, keepdims=keepdims)


@register("sum", aliases=["sum_axis"])
def sum_op(data, *, axis=None, keepdims: bool = False, exclude: bool = False):
    """Sum along axes (reference: tensor/broadcast_reduce_op_value.cc)."""
    return _reduce(jnp.sum, data, axis, keepdims, exclude)


@register("mean")
def mean(data, *, axis=None, keepdims: bool = False, exclude: bool = False):
    return _reduce(jnp.mean, data, axis, keepdims, exclude)


@register("prod")
def prod(data, *, axis=None, keepdims: bool = False, exclude: bool = False):
    return _reduce(jnp.prod, data, axis, keepdims, exclude)


@register("nansum")
def nansum(data, *, axis=None, keepdims: bool = False, exclude: bool = False):
    return _reduce(jnp.nansum, data, axis, keepdims, exclude)


@register("nanprod")
def nanprod(data, *, axis=None, keepdims: bool = False, exclude: bool = False):
    return _reduce(jnp.nanprod, data, axis, keepdims, exclude)


@register("max", aliases=["max_axis"])
def max_op(data, *, axis=None, keepdims: bool = False, exclude: bool = False):
    return _reduce(jnp.max, data, axis, keepdims, exclude)


@register("min", aliases=["min_axis"])
def min_op(data, *, axis=None, keepdims: bool = False, exclude: bool = False):
    return _reduce(jnp.min, data, axis, keepdims, exclude)


@register("norm")
def norm(data, *, ord: int = 2, axis=None, keepdims: bool = False):
    axis = _norm_axis(axis)
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=axis, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=axis, keepdims=keepdims))


@register("argmax", differentiable=False)
def argmax(data, *, axis=None, keepdims: bool = False):
    out = jnp.argmax(data, axis=axis, keepdims=keepdims)
    return out.astype(jnp.float32)


@register("argmin", differentiable=False)
def argmin(data, *, axis=None, keepdims: bool = False):
    return jnp.argmin(data, axis=axis, keepdims=keepdims).astype(jnp.float32)


@register("argmax_channel", differentiable=False)
def argmax_channel(data):
    return jnp.argmax(data, axis=-1).astype(jnp.float32)


# ---------------------------------------------------------------------------
# Ordering (reference: tensor/ordering_op.cc)
# ---------------------------------------------------------------------------

@register("sort")
def sort(data, *, axis: int = -1, is_ascend: bool = True):
    out = jnp.sort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out


@register("argsort", differentiable=False)
def argsort(data, *, axis: int = -1, is_ascend: bool = True, dtype="float32"):
    idx = jnp.argsort(data, axis=axis)
    if not is_ascend:
        idx = jnp.flip(idx, axis=axis)
    return idx.astype(jnp.dtype(dtype))


def _topk_nout(kwargs):
    return 2 if kwargs.get("ret_typ", "indices") == "both" else 1


@register("topk", differentiable=False, num_outputs=_topk_nout)
def topk(data, *, axis: int = -1, k: int = 1, ret_typ: str = "indices",
         is_ascend: bool = False, dtype="float32"):
    """Top-k (reference: ordering_op.cc TopK)."""
    src = -data if is_ascend else data
    moved = jnp.moveaxis(src, axis, -1)
    vals, idx = lax.top_k(moved, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis).astype(jnp.dtype(dtype))
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return vals, idx
    return idx


# ---------------------------------------------------------------------------
# Matrix ops (reference: tensor/matrix_op.cc, dot-inl.h)
# ---------------------------------------------------------------------------

@register("dot", num_inputs=2)
def dot(lhs, rhs, *, transpose_a: bool = False, transpose_b: bool = False):
    """Generalized dot: contracts last axis of lhs with first of rhs
    (reference: src/operator/tensor/dot-inl.h).  Lowers to the MXU."""
    if transpose_a:
        lhs = jnp.moveaxis(lhs, 0, -1) if lhs.ndim > 1 else lhs
    if transpose_b:
        rhs = jnp.moveaxis(rhs, -1, 0) if rhs.ndim > 1 else rhs
    if lhs.ndim == 1 and rhs.ndim == 1:
        return jnp.dot(lhs, rhs)
    return jnp.tensordot(lhs, rhs, axes=([lhs.ndim - 1], [0]))


@register("batch_dot", num_inputs=2)
def batch_dot(lhs, rhs, *, transpose_a: bool = False,
              transpose_b: bool = False):
    """Batched matmul over leading batch dims (reference: dot-inl.h
    BatchDot); maps directly onto the MXU as a batched GEMM."""
    if transpose_a:
        lhs = jnp.swapaxes(lhs, -1, -2)
    if transpose_b:
        rhs = jnp.swapaxes(rhs, -1, -2)
    return jnp.matmul(lhs, rhs)


@register("khatri_rao", num_inputs=None)
def khatri_rao(*mats):
    out = mats[0]
    for m in mats[1:]:
        out = (out[:, None, :] * m[None, :, :]).reshape(-1, out.shape[-1])
    return out


# ---------------------------------------------------------------------------
# Shape manipulation (reference: tensor/matrix_op.cc)
# ---------------------------------------------------------------------------

@register("reshape", aliases=["Reshape"])
def reshape(data, *, shape=(), reverse: bool = False):
    """Reshape with MXNet's special codes 0 (keep), -1 (infer), -2 (copy
    rest), -3 (merge two), -4 (split) — reference: matrix_op.cc Reshape."""
    shape = tuple(shape)
    if not any(s in (0, -2, -3, -4) for s in shape):
        return jnp.reshape(data, shape)
    src = list(data.shape)[::-1] if reverse else list(data.shape)
    out = []
    i = 0
    it = iter(range(len(shape)))
    shape_l = list(shape)
    j = 0
    while j < len(shape_l):
        s = shape_l[j]
        if s == 0:
            out.append(src[i]); i += 1
        elif s == -1:
            out.append(-1); i += 1
        elif s == -2:
            out.extend(src[i:]); i = len(src)
        elif s == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif s == -4:
            a, b = shape_l[j + 1], shape_l[j + 2]
            if a == -1:
                a = src[i] // b
            if b == -1:
                b = src[i] // a
            out.extend([a, b]); i += 1; j += 2
        else:
            out.append(s); i += 1
        j += 1
    if reverse:
        out = out[::-1]
    return jnp.reshape(data, tuple(out))


@register("transpose")
def transpose(data, *, axes=()):
    axes = tuple(axes) if axes else None
    return jnp.transpose(data, axes)


@register("expand_dims")
def expand_dims(data, *, axis: int = 0):
    return jnp.expand_dims(data, axis)


@register("squeeze")
def squeeze(data, *, axis=None):
    return jnp.squeeze(data, axis=_norm_axis(axis))


@register("flatten", aliases=["Flatten"])
def flatten(data):
    """Collapse all but the first axis (reference: matrix_op.cc Flatten)."""
    return jnp.reshape(data, (data.shape[0], -1))


@register("flip", aliases=["reverse"])
def flip(data, *, axis=0):
    return jnp.flip(data, axis=_norm_axis(axis))


@register("repeat")
def repeat(data, *, repeats: int = 1, axis=None):
    return jnp.repeat(data, repeats, axis=axis)


@register("tile")
def tile(data, *, reps=()):
    return jnp.tile(data, tuple(reps))


@register("pad", aliases=["Pad"])
def pad(data, *, mode: str = "constant", pad_width=(), constant_value: float = 0.0):
    """N-d pad (reference: src/operator/pad.cc). pad_width is the flat
    (before, after) per-axis list like the reference."""
    pw = tuple(pad_width)
    pairs = [(pw[2 * i], pw[2 * i + 1]) for i in range(len(pw) // 2)]
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(data, pairs, mode=jmode, constant_values=constant_value)
    return jnp.pad(data, pairs, mode=jmode)


@register("stack", num_inputs=None)
def stack(*data, axis: int = 0):
    return jnp.stack(data, axis=axis)


@register("concat", num_inputs=None, aliases=["Concat"])
def concat(*data, dim: int = 1, num_args: int = 0):
    """Concatenate along dim (reference: src/operator/concat.cc; note the
    reference's default dim=1, kept here)."""
    return jnp.concatenate(data, axis=dim)


def _split_nout(kwargs):
    return int(kwargs.get("num_outputs", 1))


@register("split", num_outputs=_split_nout, aliases=["SliceChannel"])
def split(data, *, num_outputs: int = 1, axis: int = 1,
          squeeze_axis: bool = False):
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if num_outputs > 1 else parts[0]


@register("broadcast_to")
def broadcast_to(data, *, shape=()):
    tgt = tuple(s if s != 0 else d for s, d in zip(shape, data.shape))
    return jnp.broadcast_to(data, tgt)


@register("broadcast_like", num_inputs=2)
def broadcast_like(lhs, rhs, *, lhs_axes=None, rhs_axes=None):
    return jnp.broadcast_to(lhs, rhs.shape)


@register("broadcast_axis", aliases=["broadcast_axes"])
def broadcast_axis(data, *, axis=(), size=()):
    axis = (axis,) if isinstance(axis, int) else tuple(axis)
    size = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(data.shape)
    for a, s in zip(axis, size):
        tgt[a] = s
    return jnp.broadcast_to(data, tuple(tgt))


@register("swapaxes", aliases=["SwapAxis"])
def swapaxes(data, *, dim1: int = 0, dim2: int = 0):
    return jnp.swapaxes(data, dim1, dim2)


@register("depth_to_space")
def depth_to_space(data, *, block_size: int = 1):
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, b, b, c // (b * b), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c // (b * b), h * b, w * b)


@register("space_to_depth")
def space_to_depth(data, *, block_size: int = 1):
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


@register("diag")
def diag(data, *, k: int = 0, axis1: int = 0, axis2: int = 1):
    if data.ndim == 1:
        return jnp.diag(data, k)
    return jnp.diagonal(data, offset=k, axis1=axis1, axis2=axis2)


# ---------------------------------------------------------------------------
# Slicing / indexing (reference: tensor/matrix_op.cc + indexing_op.cc)
# ---------------------------------------------------------------------------

@register("slice", aliases=["crop"])
def slice_op(data, *, begin=(), end=(), step=()):
    step = tuple(step) if step else (None,) * len(begin)
    idx = [slice(b, e, s) for b, e, s in zip(begin, end, step)]
    return data[tuple(idx)]


@register("slice_axis")
def slice_axis(data, *, axis: int = 0, begin: int = 0, end=None):
    nd_slice = [slice(None)] * data.ndim
    nd_slice[axis] = slice(begin, end)
    return data[tuple(nd_slice)]


@register("slice_like", num_inputs=2)
def slice_like(lhs, rhs, *, axes=()):
    axes = tuple(axes) if axes else tuple(range(lhs.ndim))
    nd_slice = [slice(None)] * lhs.ndim
    for a in axes:
        nd_slice[a] = slice(0, rhs.shape[a])
    return lhs[tuple(nd_slice)]


@register("take", num_inputs=2)
def take(a, indices, *, axis: int = 0, mode: str = "clip"):
    """Gather rows (reference: indexing_op.cc Take); the Embedding backward
    pattern.  mode='clip' clips OOB indices like the reference default."""
    idx = indices.astype(jnp.int32)
    if mode == "wrap":
        idx = jnp.mod(idx, a.shape[axis])
    else:
        idx = jnp.clip(idx, 0, a.shape[axis] - 1)
    return jnp.take(a, idx, axis=axis)


@register("pick", num_inputs=2)
def pick(data, index, *, axis: int = -1, keepdims: bool = False,
         mode: str = "clip"):
    idx = jnp.clip(index.astype(jnp.int32), 0, data.shape[axis] - 1)
    picked = jnp.take_along_axis(data, jnp.expand_dims(idx, axis), axis=axis)
    if not keepdims:
        picked = jnp.squeeze(picked, axis=axis)
    return picked


@register("gather_nd", num_inputs=2)
def gather_nd(data, indices):
    """reference: indexing_op.cc GatherND; indices shape (M, ...)."""
    idx = tuple(indices.astype(jnp.int32))
    return data[idx]


@register("scatter_nd", num_inputs=2)
def scatter_nd(data, indices, *, shape=()):
    idx = tuple(indices.astype(jnp.int32))
    out = jnp.zeros(tuple(shape), dtype=data.dtype)
    return out.at[idx].add(data)


@register("one_hot", differentiable=False)
def one_hot(indices, *, depth: int = 0, on_value: float = 1.0,
            off_value: float = 0.0, dtype: str = "float32"):
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=jnp.dtype(dtype))
    return oh * (on_value - off_value) + off_value


@register("where", num_inputs=3)
def where(condition, x, y):
    return jnp.where(condition.astype(bool), x, y)


@register("sequence_mask", num_inputs=2, aliases=["SequenceMask"])
def sequence_mask(data, sequence_length, *, use_sequence_length: bool = True,
                  value: float = 0.0, axis: int = 0):
    """Mask positions past each sequence's length (reference:
    src/operator/sequence_mask.cc; axis 0 = time-major like the reference)."""
    maxlen = data.shape[axis]
    steps = jnp.arange(maxlen)
    mask = steps[:, None] < sequence_length[None, :].astype(jnp.int32)
    extra = data.ndim - 2
    mask = mask.reshape(mask.shape + (1,) * extra)
    if axis == 1:
        mask = jnp.swapaxes(mask, 0, 1)
    return jnp.where(mask, data, value)


@register("sequence_last", num_inputs=2, aliases=["SequenceLast"])
def sequence_last(data, sequence_length, *, use_sequence_length: bool = True,
                  axis: int = 0):
    idx = (sequence_length.astype(jnp.int32) - 1)
    if axis == 0:
        return jnp.take_along_axis(
            data, idx.reshape((1, -1) + (1,) * (data.ndim - 2)), axis=0)[0]
    return jnp.take_along_axis(
        data, idx.reshape((-1, 1) + (1,) * (data.ndim - 2)), axis=1)[:, 0]


@register("sequence_reverse", num_inputs=2, aliases=["SequenceReverse"])
def sequence_reverse(data, sequence_length, *,
                     use_sequence_length: bool = True, axis: int = 0):
    maxlen = data.shape[0]
    steps = jnp.arange(maxlen)[:, None]
    lens = sequence_length.astype(jnp.int32)[None, :]
    rev_idx = jnp.where(steps < lens, lens - 1 - steps, steps)
    rev_idx = rev_idx.reshape(rev_idx.shape + (1,) * (data.ndim - 2))
    return jnp.take_along_axis(data, rev_idx, axis=0)


@register("boolean_mask", num_inputs=2, aliases=["_contrib_boolean_mask"],
          differentiable=False)
def boolean_mask(data, index, *, axis: int = 0):
    """Dynamic-shape op: materializes on host (reference:
    contrib/boolean_mask.cc).  Not jittable by design; eager only."""
    import numpy as np
    mask = np.asarray(index).astype(bool)
    return jnp.asarray(np.asarray(data)[mask])


# ---------------------------------------------------------------------------
# Init ops (reference: tensor/init_op.cc) — used by Symbol graphs
# ---------------------------------------------------------------------------

@register("_zeros", num_inputs=0, differentiable=False)
def _zeros(*, shape=(), dtype: str = "float32", ctx: str = ""):
    return jnp.zeros(tuple(shape), dtype=jnp.dtype(dtype))


@register("_ones", num_inputs=0, differentiable=False)
def _ones(*, shape=(), dtype: str = "float32", ctx: str = ""):
    return jnp.ones(tuple(shape), dtype=jnp.dtype(dtype))


@register("_full", num_inputs=0, differentiable=False)
def _full(*, shape=(), value: float = 0.0, dtype: str = "float32", ctx: str = ""):
    return jnp.full(tuple(shape), value, dtype=jnp.dtype(dtype))


@register("_arange", num_inputs=0, differentiable=False)
def _arange(*, start: float = 0, stop=None, step: float = 1.0, repeat: int = 1,
            dtype: str = "float32", ctx: str = "", infer_range: bool = False):
    out = jnp.arange(start, stop, step, dtype=jnp.dtype(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return out


@register("_linspace", num_inputs=0, differentiable=False)
def _linspace(*, start: float = 0, stop: float = 1, num: int = 50,
              endpoint: bool = True, dtype: str = "float32", ctx: str = ""):
    return jnp.linspace(start, stop, num, endpoint=endpoint,
                        dtype=jnp.dtype(dtype))


@register("_eye", num_inputs=0, differentiable=False)
def _eye(*, N: int = 0, M: int = 0, k: int = 0, dtype: str = "float32",
         ctx: str = ""):
    return jnp.eye(N, M if M else None, k, dtype=jnp.dtype(dtype))


@register("_contrib_arange_like", differentiable=False,
          aliases=["arange_like"])
def arange_like(data, *, start: float = 0.0, step: float = 1.0,
                repeat: int = 1, axis=None):
    if axis is None:
        n = data.size
        return (jnp.arange(n, dtype=data.dtype) * step + start).reshape(data.shape)
    n = data.shape[axis]
    return jnp.arange(n, dtype=data.dtype) * step + start


# ---------------------------------------------------------------------------
# AMP support ops (reference: src/operator/tensor/amp_cast.cc)
# ---------------------------------------------------------------------------

@register("amp_cast")
def amp_cast(data, *, dtype: str = "float32"):
    """Dtype cast inserted by AMP (reference: amp_cast.cc).  Identical to
    Cast; a distinct op so AMP graph rewrites are identifiable."""
    return data.astype(jnp.dtype(dtype))


def _amp_multicast_nout(kw):
    return int(kw.get("num_outputs", 1))


@register("amp_multicast", num_inputs=None, num_outputs=_amp_multicast_nout)
def amp_multicast(*data, num_outputs: int = 0):
    """Cast all inputs to the widest dtype among them (reference:
    amp_cast.cc AMPMultiCast).  num_outputs must equal the input count —
    validated like the reference, since the dispatcher uses it to decide
    how many outputs to hand back."""
    if num_outputs != len(data):
        raise ValueError(
            f"amp_multicast: num_outputs={num_outputs} must equal the "
            f"number of inputs ({len(data)})")
    widest = jnp.result_type(*[d.dtype for d in data])
    return tuple(d.astype(widest) for d in data)


@register("all_finite", num_inputs=None, differentiable=False)
def all_finite(*data, init_output: bool = True):
    """1.0 if every element of every input is finite else 0.0 (reference:
    contrib/all_finite.cc — AMP's overflow test).  One fused reduction so
    dynamic loss scaling costs a single scalar readback."""
    ok = jnp.ones((), dtype=jnp.bool_)
    for d in data:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(d)))
    return ok.astype(jnp.float32).reshape(1)


@register("cumsum", aliases=["_np_cumsum"])
def cumsum(data, *, axis=None, dtype=None):
    """Cumulative sum (reference: tensor/cumsum.cc; axis=None flattens,
    numpy semantics)."""
    out = jnp.cumsum(data if axis is not None else data.ravel(),
                     axis=axis)
    return out.astype(dtype) if dtype else out


@register("cumprod")
def cumprod(data, *, axis=None, dtype=None):
    """Cumulative product (numpy semantics; axis=None flattens)."""
    out = jnp.cumprod(data if axis is not None else data.ravel(),
                      axis=axis)
    return out.astype(dtype) if dtype else out


@register("digamma")
def digamma(data):
    """Derivative of gammaln (reference: unary math op family)."""
    return jax.scipy.special.digamma(data)


@register("unravel_index", differentiable=False)
def unravel_index(data, *, shape=()):
    """Flat index -> multi-index, stacked on a leading ndim axis
    (reference: tensor/ravel.cc Unravel)."""
    idxs = jnp.unravel_index(data.astype(jnp.int32), shape)
    return jnp.stack(idxs, axis=0)


def _split_v2_n_out(kwargs):
    ios = kwargs.get("indices_or_sections", 1)
    if isinstance(ios, int):
        return ios
    return len(tuple(ios)) + 1


@register("split_v2", num_outputs=_split_v2_n_out)
def split_v2(data, *, indices_or_sections=1, axis: int = 0,
             squeeze_axis: bool = False):
    """numpy-style split (reference: matrix_op split_v2: int = equal
    sections, tuple = split points)."""
    ios = indices_or_sections
    parts = jnp.split(data, ios if isinstance(ios, int) else list(ios),
                      axis=axis)
    if squeeze_axis:
        parts = [p.squeeze(axis=axis) for p in parts]
    return tuple(parts) if len(parts) > 1 else parts[0]


@register("Crop", num_inputs=None, aliases=["crop_v1"])
def Crop(*inputs, offset=(0, 0), h_w=(0, 0), center_crop: bool = False,
         num_args: int = 1):
    """Spatial crop of NCHW data (reference: src/operator/crop.cc).
    With two inputs, crops data to the second input's (H, W)."""
    data = inputs[0]
    H, W = data.shape[2], data.shape[3]
    if len(inputs) == 2:
        th, tw = inputs[1].shape[2], inputs[1].shape[3]
    else:
        th, tw = h_w
    if center_crop:
        oy, ox = (H - th) // 2, (W - tw) // 2
    else:
        oy, ox = offset
    if not (0 <= oy and 0 <= ox and oy + th <= H and ox + tw <= W):
        raise ValueError(
            f"Crop: region offset={int(oy), int(ox)} h_w={th, tw} "
            f"exceeds input spatial size {H, W}")
    return data[:, :, oy:oy + th, ox:ox + tw]
