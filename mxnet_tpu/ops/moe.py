"""Mixture-of-Experts operators (GShard-style dense routing).

New TPU-first capability — the reference has no MoE (SURVEY.md §2.4:
EP is ABSENT upstream; flagged as new capability for the pod-scale
north star).  Design follows the GShard/Switch dispatch pattern the TPU
ecosystem standardized on: routing is expressed as dense one-hot
einsums over a fixed expert ``capacity`` (never ragged gathers), so the
whole layer is a handful of MXU matmuls that XLA shards cleanly — with
the expert dimension partitioned over the mesh's ``ep`` axis, the
dispatch/combine einsums lower to all-to-alls on ICI.

Ops:
  ``moe_top1_dispatch`` — router: gate probs -> combine/dispatch tensors
  ``moe_ffn``           — full MoE FFN block (router + expert MLPs)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

__all__ = ["moe_top1_dispatch", "moe_ffn"]


def _top1_tensors(gates, capacity):
    """gates (S, E) -> combine (S, E, C), dispatch bool (S, E, C),
    aux_loss (Switch load-balancing loss)."""
    S, E = gates.shape
    expert = jnp.argmax(gates, axis=-1)                   # (S,)
    onehot = jax.nn.one_hot(expert, E, dtype=gates.dtype)  # (S, E)
    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0        # (S, E)
    keep = (pos >= 0) & (pos < capacity)
    pos_cap = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
    pos_onehot = jax.nn.one_hot(pos_cap, capacity,
                                dtype=gates.dtype)        # (S, E, C)
    dispatch = pos_onehot * keep.astype(gates.dtype)[..., None]
    gate_val = jnp.sum(gates * onehot, axis=-1, keepdims=True)  # (S, 1)
    combine = dispatch * gate_val[..., None]
    # Switch-transformer aux loss: E * sum_e (frac_tokens_e * mean_gate_e)
    frac = onehot.mean(axis=0)
    mean_gate = gates.mean(axis=0)
    aux = E * jnp.sum(frac * mean_gate)
    return combine, dispatch, aux


@register("_contrib_moe_top1_dispatch", num_outputs=3,
          aliases=["moe_top1_dispatch"])
def moe_top1_dispatch(gate_logits, *, capacity: int = 0,
                      capacity_factor: float = 1.25):
    """Top-1 (Switch) router. ``gate_logits``: (S, E).

    Returns (combine (S,E,C), dispatch (S,E,C), aux_loss ()).  Tokens
    beyond an expert's capacity are dropped (their combine weights are
    zero — the residual connection carries them, as in GShard).
    """
    S, E = gate_logits.shape
    cap = int(capacity) if capacity else \
        max(1, int(capacity_factor * S / E))
    gates = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    combine, dispatch, aux = _top1_tensors(gates, cap)
    return (combine.astype(gate_logits.dtype),
            dispatch.astype(gate_logits.dtype), aux)


@register("_contrib_moe_ffn", num_inputs=6, num_outputs=2,
          aliases=["moe_ffn"])
def moe_ffn(x, wg, w1, b1, w2, b2, *, capacity_factor: float = 1.25,
            activation: str = "gelu"):
    """Full MoE FFN: route -> expert MLPs -> combine.

    x (B, L, C) or (S, C); wg (C, E); w1 (E, C, H); b1 (E, H);
    w2 (E, H, C); b2 (E, C).  Returns (out with x's shape, aux_loss ())
    — add ``aux_weight * aux_loss`` to the training loss to balance
    expert load (Switch-transformer recipe).
    """
    orig_shape = x.shape
    C = orig_shape[-1]
    xs = x.reshape(-1, C)                                 # (S, C)
    S = xs.shape[0]
    E = w1.shape[0]
    cap = max(1, int(capacity_factor * S / E))

    if activation not in ("relu", "gelu"):
        from ..base import MXNetError
        raise MXNetError(
            f"moe_ffn: unsupported activation {activation!r} "
            f"(supported: 'relu', 'gelu')")
    gates = jax.nn.softmax(
        (xs.astype(jnp.float32) @ wg.astype(jnp.float32)), axis=-1)
    combine, dispatch, aux = _top1_tensors(gates, cap)
    combine = combine.astype(xs.dtype)
    dispatch = dispatch.astype(xs.dtype)

    expert_in = jnp.einsum("sec,sm->ecm", dispatch, xs)   # (E, cap, C)
    h = jnp.einsum("ecm,emh->ech", expert_in, w1) + b1[:, None, :]
    if activation == "relu":
        h = jax.nn.relu(h)
    else:
        h = jax.nn.gelu(h)
    expert_out = jnp.einsum("ech,ehm->ecm", h, w2) + b2[:, None, :]
    out = jnp.einsum("sec,ecm->sm", combine, expert_out)  # (S, C)
    return out.reshape(orig_shape), aux
