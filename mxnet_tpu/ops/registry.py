"""Operator registry + imperative dispatch.

Reference design being re-created (SURVEY.md 2.1, 3.1):

- ``NNVM_REGISTER_OP(name).set_attr<FCompute>(...)`` — a single registry both
  the imperative and symbolic paths consult (``src/operator/``, nnvm op
  registry).
- ``dmlc::Parameter<XParam>`` declarative op schemas — single source of truth
  for argument parsing, docstring generation and serialization
  (SURVEY.md 5.6, "keystone pattern").
- ``MXListAllOpNames`` + Python codegen (``python/mxnet/ndarray/register.py``)
  — frontend functions are *generated* from the registry at import.

TPU-native redesign: an op's FCompute is a **pure JAX function** (traceable,
differentiable, shardable).  The same function serves four consumers:

1. eager dispatch (``invoke`` below) — XLA async execution, NDArray in/out;
2. the autograd tape — ``jax.vjp`` of the same function gives FGradient;
3. symbolic/graph mode — Symbol nodes store the op name; executors interpret
   the graph by calling the same functions under ``jax.jit``;
4. hybridize/CachedOp — the traced program embeds these functions directly.

There is no CPU/GPU kernel split: XLA owns code generation for every
backend; Pallas kernels slot in as alternative FCompute bodies (ops/pallas).
"""
from __future__ import annotations

import functools
import inspect
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..base import MXNetError, Registry
from . import shape_rules

__all__ = ["OpDef", "LightOpDef", "register", "get_op", "list_ops",
           "invoke", "OP_REGISTRY", "alias"]

OP_REGISTRY = Registry("op")


class OpDef:
    """A registered operator.

    Attributes mirror the reference's nnvm attrs:
      fn           : FCompute — pure jax function (arrays..., **params)
      num_inputs   : FListInputNames arity (None = variadic first arg list)
      num_outputs  : 1 or a callable(kwargs)->int for output_mean_var-style ops
      differentiable : False cuts the autograd tape (integer/compare ops)
      params       : declarative schema harvested from the fn signature
                     (dmlc::Parameter equivalent)
    """

    __slots__ = ("name", "fn", "num_inputs", "num_outputs", "differentiable",
                 "params", "doc", "aliases", "mutates_rng", "aux_update",
                 "open_schema", "shape_rule")

    def __init__(self, name: str, fn: Callable, num_inputs, num_outputs,
                 differentiable: bool, mutates_rng: bool = False):
        self.name = name
        self.fn = fn
        self.num_inputs = num_inputs
        self.num_outputs = num_outputs
        self.differentiable = differentiable
        self.mutates_rng = mutates_rng
        # optional stateful-op hook for graph executors: called as
        # aux_update(args, kwargs) during a *training* interpretation;
        # returns None (not applicable) or (outputs_tuple,
        # {input_slot: new_aux_value}) — the jit-pure equivalent of the
        # reference's in-op aux-state mutation (e.g. BatchNorm moving stats)
        self.aux_update = None
        self.aliases: List[str] = []
        sig = inspect.signature(fn)
        self.params: Dict[str, inspect.Parameter] = {
            k: p for k, p in sig.parameters.items()
            if p.kind == inspect.Parameter.KEYWORD_ONLY
        }
        # ops with **kwargs (Custom: user-defined ctor args pass through
        # the string-kv ABI like the reference) accept arbitrary keys
        self.open_schema = any(p.kind == inspect.Parameter.VAR_KEYWORD
                               for p in sig.parameters.values())
        self.doc = inspect.getdoc(fn) or f"Operator {name}."
        # declarative ahead-of-trace inference rule (shape_rules.py):
        # the same metadata serves symbol-shape queries, deploy manifest
        # checks, and tools/mxlint's abstract interpreter
        self.shape_rule = shape_rules.rule_for(name)

    def infer_signature(self, input_sigs, kwargs=None):
        """Ahead-of-trace output signature: ``input_sigs`` is a list of
        ``(shape, dtype)`` pairs (dims may be ints,
        :class:`shape_rules.Dim` symbols, or None for unknown; dtype a
        canonical name or None).  Returns ``(shape, dtype)`` — possibly
        partially unknown — or ``None`` when the op carries no rule.
        Raises :class:`MXNetError` on a provably infeasible signature,
        before any tracing or device work happens.
        """
        if self.shape_rule is None:
            return None
        shapes, dtypes = [], []
        for shape, dtype in input_sigs:
            if shape is None:
                shapes.append(None)
            else:
                shapes.append(tuple(
                    shape_rules.lit(d) if isinstance(d, int)
                    else d for d in shape))
            dtypes.append(dtype)
        try:
            return self.shape_rule(shapes, dtypes, dict(kwargs or ()))
        except shape_rules.ShapeError as e:
            raise MXNetError(
                f"operator {self.name}: infeasible signature: {e}") from e

    def n_outputs(self, kwargs) -> int:
        if callable(self.num_outputs):
            return self.num_outputs(kwargs)
        return self.num_outputs

    def validate_kwargs(self, kwargs: Dict[str, Any]):
        if self.open_schema:
            return
        for k in kwargs:
            if k not in self.params:
                raise MXNetError(
                    f"operator {self.name}: unknown argument {k!r}; "
                    f"schema: {sorted(self.params)}")

    def __repr__(self):
        return f"OpDef({self.name})"


class LightOpDef(OpDef):
    """An OpDef for per-call synthetic ops (taped np calls, CachedOp
    dispatch): skips the inspect.signature schema harvest — ~10us of
    host-side latency that matters on the imperative hot path.  The fn
    is always ``*arrays`` with no keyword schema."""

    def __init__(self, name, fn, num_inputs, num_outputs,
                 differentiable=True):
        self.name = name
        self.fn = fn
        self.num_inputs = num_inputs
        self.num_outputs = num_outputs
        self.differentiable = differentiable
        self.mutates_rng = False
        self.aux_update = None
        self.aliases = []
        self.params = {}
        self.open_schema = False
        self.doc = f"Operator {name}."
        self.shape_rule = None


def register(name: str, num_inputs=1, num_outputs=1, differentiable=True,
             mutates_rng=False, aliases: Sequence[str] = ()):
    """Decorator: register a pure JAX function as an operator.

    The function's positional args are the data inputs; keyword-only args
    (with defaults) form the declarative parameter schema.
    """

    def _decorator(fn):
        opdef = OpDef(name, fn, num_inputs, num_outputs, differentiable,
                      mutates_rng)
        OP_REGISTRY.register(name, opdef)
        for a in aliases:
            opdef.aliases.append(a)
            OP_REGISTRY.register(a, opdef)
        return fn

    return _decorator


def alias(existing: str, new: str):
    opdef = OP_REGISTRY[existing]
    opdef.aliases.append(new)
    OP_REGISTRY.register(new, opdef)


def get_op(name: str) -> OpDef:
    return OP_REGISTRY[name]


def list_ops() -> List[str]:
    """Reference: MXListAllOpNames."""
    return OP_REGISTRY.list_names()


# ---------------------------------------------------------------------------
# Imperative dispatch (reference: MXImperativeInvokeEx -> Imperative::Invoke
# -> Engine::PushAsync; SURVEY.md 3.1).  XLA dispatch is already async; the
# explicit engine push is replaced by the call itself.
# ---------------------------------------------------------------------------

def invoke(opdef: OpDef, inputs, kwargs: Dict[str, Any], out=None):
    """Run an op eagerly over NDArray inputs; returns NDArray(s).

    Recording mirrors Imperative::RecordOp: a TapeNode holding the pure fn
    and input links is attached to every differentiable output.
    """
    from ..ndarray import NDArray
    from .. import autograd
    from ..engine import engine, is_naive

    raw = []
    pend = autograd.peek_pending()
    for a in inputs:
        if isinstance(a, NDArray):
            if a._lazy_cb is not None:
                a._lazy_materialize()   # deferred forward consumed eagerly
            if pend is not None and id(a) in pend["grad_ids"]:
                # consuming a deferred-backward grad buffer as an op input
                # (e.g. clip_global_norm over hoisted grad aliases) must
                # see THIS step's gradients
                autograd.flush_pending()
                pend = None
            a._var.check()          # async error propagation: raise pending
            raw.append(a._data)
        else:
            raw.append(a)

    if kwargs:
        opdef.validate_kwargs(kwargs)
        fn = functools.partial(opdef.fn, **kwargs)
    else:
        fn = opdef.fn

    if opdef.mutates_rng:
        # draw the op's key NOW and pin it into the closure: backward's
        # vjp replay (and any re-execution) must see the SAME randomness
        # as the forward (reference: resource randomness is drawn once per
        # op), and a replay-time next_key() inside a vjp trace would leak
        # a tracer into the global stream
        from .. import random as mxrand
        _fixed_key = mxrand.next_key()
        _base_rng_fn = fn

        def fn(*args, _k=_fixed_key, _f=_base_rng_fn):
            with mxrand.trace_key_scope(_k):
                return _f(*args)

        # bulk backward re-parametrizes the key as a program INPUT so the
        # compiled replay can be cached across steps (each step's key
        # varies; the program must not bake one in)
        fn._rng_base = _base_rng_fn
        fn._rng_key = _fixed_key

    from .. import profiler as _prof
    from .. import runtime_metrics as _rm
    # one bool each for the two observability planes: the disabled path
    # costs these two loads + branch (microbench-verified <2%)
    _collect = _rm._ENABLED
    t0 = _prof._now_us() if (_prof._ACTIVE or _collect) else None
    try:
        result = fn(*raw)
    except Exception as e:
        raise MXNetError(f"operator {opdef.name} failed: {e}") from e
    if t0 is not None:
        t1 = _prof._now_us()
        _prof.record_op(opdef.name, t0, t1)
        if _collect:
            _rm.record_op_invoke(opdef.name, (t1 - t0) * 1e-6)

    nout = opdef.n_outputs(kwargs)
    outs_raw = (result,) if nout == 1 and not isinstance(result, tuple) \
        else tuple(result)

    ctx = None
    for a in inputs:
        if isinstance(a, NDArray):
            ctx = a.context
            break

    # Record every differentiable op while the record() scope is active
    # (reference: Imperative::RecordOp runs unconditionally when recording);
    # backward prunes paths that reach no marked variable.
    record = (autograd.is_recording() and opdef.differentiable
              and any(isinstance(a, NDArray) for a in inputs))

    outs = [NDArray(o, ctx=ctx) for o in outs_raw]

    if record:
        nd_inputs = [a for a in inputs if isinstance(a, NDArray)]
        # a (name, kwargs) signature fully determines the computation when
        # every positional input is an NDArray — the bulk backward keys
        # compiled replay programs on it (None = closed-over constants,
        # not bulkable)
        key = None
        if len(nd_inputs) == len(inputs) and \
                not getattr(opdef, "no_bulk_key", False):
            try:
                key = (opdef.name, tuple(sorted(kwargs.items())))
                hash(key)
            except TypeError:
                key = (opdef.name, tuple(sorted(
                    (k, repr(v)) for k, v in kwargs.items())))
        # fn must close over non-NDArray positional inputs as constants
        if len(nd_inputs) != len(inputs):
            idxs = [i for i, a in enumerate(inputs) if isinstance(a, NDArray)]
            consts = list(raw)
            base_fn = fn

            def fn(*arrs, _idxs=idxs, _consts=consts, _f=base_fn):
                buf = list(_consts)
                for i, a in zip(_idxs, arrs):
                    buf[i] = a
                return _f(*buf)

        entries = []
        for a in nd_inputs:
            prod = a._autograd_node
            entries.append((None, 0, a) if prod is None
                           else (prod[0], prod[1], a))
        node = autograd.TapeNode(fn=fn, input_entries=entries,
                                 n_outputs=len(outs), name=opdef.name,
                                 key=key)
        for i, o in enumerate(outs):
            o._autograd_node = (node, i)

    if is_naive():
        for o in outs:
            o.wait_to_read()

    eng = engine()
    for o in outs:
        eng.track(o)

    if out is not None:
        out_list = [out] if isinstance(out, NDArray) else list(out)
        for dst, src in zip(out_list, outs):
            dst._set_data(src._data)
            dst._autograd_node = src._autograd_node
        return out

    return outs[0] if nout == 1 else outs


def make_frontend(opdef: OpDef) -> Callable:
    """Generate the user-facing function for an op (reference:
    _make_ndarray_function in python/mxnet/ndarray/register.py)."""

    def frontend(*args, out=None, **kwargs):
        from ..ndarray import NDArray
        from ..symbol import Symbol
        if args and isinstance(args[0], Symbol) or (
                args and isinstance(args[0], (list, tuple)) and args[0]
                and isinstance(args[0][0], Symbol)):
            from ..symbol.symbol import invoke_symbolic
            return invoke_symbolic(opdef, args, kwargs)
        if opdef.num_inputs is None and args and isinstance(args[0], (list, tuple)):
            args = tuple(args[0]) + tuple(args[1:])
        return invoke(opdef, args, kwargs, out=out)

    params_doc = "\n".join(
        f"    {k} : default={p.default!r}" for k, p in opdef.params.items())
    frontend.__name__ = opdef.name
    frontend.__qualname__ = opdef.name
    frontend.__doc__ = (f"{opdef.doc}\n\nParameters\n----------\n"
                        f"{params_doc}\n    out : NDArray, optional\n")
    return frontend
