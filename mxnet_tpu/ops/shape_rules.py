"""Declarative symbolic shape/dtype algebra + per-op inference rules.

Ahead-of-time shape inference is what makes static TPU compilation
viable (the Julia-to-TPU and TensorFlow graph-level propagation results
— PAPERS.md): a shape or dtype mistake caught before trace time costs a
lint message instead of an opaque XLA error or, worse, a silent
recompile.  This module is the single source of that algebra for three
consumers:

1. ``OpDef.infer_signature`` (ops/registry.py) — registry ops with a
   rule here can answer "what comes out?" without tracing;
2. ``deploy.validate_manifest`` — StableHLO manifest v2 signatures are
   checked for structural soundness before serving trusts them;
3. ``tools/mxlint``'s ``mxshape`` abstract interpreter — which loads
   this file *standalone* (by path, never importing ``mxnet_tpu``), so
   the linter stays jax-free.

Because of (3) this module is deliberately self-contained: stdlib only,
no relative imports, importable both as ``mxnet_tpu.ops.shape_rules``
and as a bare file.

The dim lattice
---------------
A dimension is a :class:`Dim` — a rational coefficient times a product
of named symbols with integer exponents (``2*B*H/heads``) — or ``None``
for ⊤ (unknown).  Symbols stand for *unknown positive extents* (>= 1):
a program written for the degenerate empty-axis case only is assumed
not to exist, which is what lets ``2*B == 3*B`` be *provably false*
instead of "true when B == 0".  All provability answers are
three-valued (True / False / None-unknown) and every consumer treats
unknown as "stay quiet" — no false positives by construction.

Dtypes follow the JAX promotion lattice (weak python scalars included),
so ``bfloat16 + float16 -> float32`` and ``uint64 + int8 -> weak
float`` come out exactly as ``jnp`` would resolve them.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Dim", "ShapeError", "lit", "sym", "dim_mul", "dim_div", "dim_add",
    "dim_eq", "product", "fmt_dim", "fmt_shape",
    "check_reshape", "check_transpose", "broadcast", "check_matmul",
    "check_einsum", "reduce_shape", "concat_shapes",
    "promote", "DTYPES", "FLOAT_DTYPES", "INT_DTYPES", "QUANT_DTYPES",
    "SHAPE_RULES", "shape_rule", "rule_for",
]


class ShapeError(Exception):
    """A *provably* infeasible shape/dtype combination (never raised on
    merely-unknown inputs)."""


# --------------------------------------------------------------------- dims
class Dim:
    """``(num/den) * prod(sym_i ** exp_i)`` with num, den coprime ints,
    den >= 1, exponents nonzero.  Immutable; construct via :func:`lit` /
    :func:`sym` / the ``dim_*`` operations."""

    __slots__ = ("num", "den", "syms")

    def __init__(self, num: int, den: int = 1,
                 syms: Tuple[Tuple[str, int], ...] = ()):
        if den < 0:
            num, den = -num, -den
        if num == 0:
            den, syms = 1, ()
        g = math.gcd(abs(num), den) or 1
        self.num = num // g
        self.den = den // g
        self.syms = tuple(sorted((s, e) for s, e in syms if e != 0))

    # concrete = a plain nonnegative integer
    @property
    def concrete(self) -> Optional[int]:
        if not self.syms and self.den == 1:
            return self.num
        return None

    def _key(self):
        return (self.num, self.den, self.syms)

    def __eq__(self, other):
        return isinstance(other, Dim) and self._key() == other._key()

    def __hash__(self):
        return hash(self._key())

    def __repr__(self):
        return f"Dim({fmt_dim(self)})"


def lit(n: int) -> Dim:
    return Dim(int(n))


def sym(name: str) -> Dim:
    return Dim(1, 1, ((name, 1),))


def _merge_syms(a, b, negate_b=False):
    out: Dict[str, int] = {}
    for s, e in a:
        out[s] = out.get(s, 0) + e
    for s, e in b:
        out[s] = out.get(s, 0) + (-e if negate_b else e)
    return tuple((s, e) for s, e in out.items() if e != 0)


def dim_mul(a: Optional[Dim], b: Optional[Dim]) -> Optional[Dim]:
    if a is None or b is None:
        return None
    return Dim(a.num * b.num, a.den * b.den, _merge_syms(a.syms, b.syms))


def dim_div(a: Optional[Dim], b: Optional[Dim]) -> Optional[Dim]:
    """Exact symbolic division (the static model of ``//`` in shape
    arithmetic: code that floor-divides an extent intends it to divide,
    and if it does not the runtime fails regardless)."""
    if a is None or b is None or b.num == 0:
        return None
    return Dim(a.num * b.den, a.den * b.num,
               _merge_syms(a.syms, b.syms, negate_b=True))


def dim_add(a: Optional[Dim], b: Optional[Dim]) -> Optional[Dim]:
    """Addition is only closed over concrete dims; symbolic sums leave
    the product domain and go to ⊤."""
    if a is None or b is None:
        return None
    ca, cb = a.concrete, b.concrete
    if ca is not None and cb is not None:
        return lit(ca + cb)
    return None


def dim_eq(a: Optional[Dim], b: Optional[Dim]) -> Optional[bool]:
    """True / False / None(unknown).  Uses the symbols-are->=1
    assumption: if a/b reduces to a symbol-free ratio != 1, the dims are
    provably unequal."""
    if a is None or b is None:
        return None
    if a == b:
        return True
    if a.num == 0 or b.num == 0:
        # one side is exactly 0: symbols are >= 1, concretes differ
        return (a.num == 0) == (b.num == 0) or False
    r = dim_div(a, b)
    if r is not None and not r.syms:
        return r.num == r.den
    return None


def product(dims: Sequence[Optional[Dim]]) -> Optional[Dim]:
    out: Optional[Dim] = lit(1)
    for d in dims:
        out = dim_mul(out, d)
    return out


def fmt_dim(d: Optional[Dim]) -> str:
    if d is None:
        return "?"
    if d.concrete is not None:
        return str(d.concrete)
    parts = []
    if d.num != 1 or not d.syms:
        parts.append(str(d.num))
    for s, e in d.syms:
        parts.append(s if e == 1 else f"{s}^{e}")
    text = "*".join(parts)
    return f"{text}/{d.den}" if d.den != 1 else text


def fmt_shape(shape: Optional[Sequence[Optional[Dim]]]) -> str:
    if shape is None:
        return "(?)"
    return "(" + ", ".join(fmt_dim(d) for d in shape) + ")"


Shape = Optional[Tuple[Optional[Dim], ...]]


# ----------------------------------------------------------------- checkers
def check_reshape(in_shape: Shape, out_dims: List) -> Shape:
    """Feasibility of reshaping ``in_shape`` to ``out_dims`` (entries:
    Dim, None for unknown, or the python int ``-1`` to infer).

    Raises :class:`ShapeError` only on *provable* infeasibility: both
    element products symbol-free and unequal, or the products' ratio
    symbol-free and != 1 (same symbols, incompatible concrete factors —
    the ``reshape(L, B, heads, n, D)`` class where the factors cannot
    divide the input).  Returns the (possibly partially unknown) result
    shape otherwise.
    """
    if sum(1 for d in out_dims if isinstance(d, int) and d == -1) > 1:
        raise ShapeError("reshape target has more than one -1")
    infer = any(isinstance(d, int) and d == -1 for d in out_dims)
    known = [d for d in out_dims if not (isinstance(d, int) and d == -1)]

    def _resolved(inferred: Optional[Dim]) -> Shape:
        return tuple(inferred if isinstance(d, int) and d == -1 else d
                     for d in out_dims)

    if in_shape is None or any(d is None for d in in_shape) \
            or any(d is None for d in known):
        return _resolved(None)
    in_p = product(in_shape)
    out_p = product(known)
    if in_p is None or out_p is None:
        return _resolved(None)
    if infer:
        q = dim_div(in_p, out_p)
        if q is not None and not q.syms:
            if q.den != 1 or q.num < 1:
                raise ShapeError(
                    f"cannot reshape {fmt_shape(in_shape)} to "
                    f"{fmt_shape(_resolved(None))}: the -1 dimension "
                    f"resolves to {q.num}/{q.den}, not a positive "
                    f"integer — the explicit factors do not divide the "
                    f"input element count")
            return _resolved(lit(q.num))
        if q is not None and all(e > 0 for _, e in q.syms) and q.den == 1:
            return _resolved(q)     # -1 binds to a clean symbolic factor
        return _resolved(None)
    ok = dim_eq(in_p, out_p)
    if ok is False:
        raise ShapeError(
            f"reshape {fmt_shape(in_shape)} -> "
            f"{fmt_shape(tuple(known))} changes the element count "
            f"({fmt_dim(in_p)} vs {fmt_dim(out_p)}): the target factors "
            f"cannot tile the input")
    return _resolved(None)


def check_transpose(shape: Shape, axes) -> Shape:
    """``axes=None`` reverses; otherwise must be a permutation of
    ``range(rank)`` (negatives allowed)."""
    if shape is None:
        return None
    rank = len(shape)
    if axes is None:
        return tuple(reversed(shape))
    axes = list(axes)
    if len(axes) != rank:
        raise ShapeError(
            f"transpose axes {tuple(axes)} has {len(axes)} entries for a "
            f"rank-{rank} input {fmt_shape(shape)}")
    norm = []
    for a in axes:
        if not isinstance(a, int):
            return None
        if a < -rank or a >= rank:
            raise ShapeError(
                f"transpose axis {a} out of range for rank {rank}")
        norm.append(a % rank)
    if sorted(norm) != list(range(rank)):
        raise ShapeError(
            f"transpose axes {tuple(axes)} is not a permutation of "
            f"rank {rank}: axes repeat or are omitted")
    return tuple(shape[a] for a in norm)


def broadcast(s1: Shape, s2: Shape) -> Shape:
    """NumPy broadcast join.  Flags only concrete mismatches where
    neither side is 1 (a symbol could still *be* 1 and broadcast)."""
    if s1 is None or s2 is None:
        return None
    if len(s1) < len(s2):
        s1, s2 = s2, s1
    s2 = (lit(1),) * (len(s1) - len(s2)) + tuple(s2)
    out = []
    for a, b in zip(s1, s2):
        ca = a.concrete if a is not None else None
        cb = b.concrete if b is not None else None
        if ca == 1:
            out.append(b)
        elif cb == 1:
            out.append(a)
        elif dim_eq(a, b) is True:
            out.append(a)
        elif ca is not None and cb is not None:
            raise ShapeError(
                f"operands {fmt_shape(s1)} and {fmt_shape(s2)} are not "
                f"broadcast-compatible: {ca} vs {cb} (neither is 1)")
        else:
            out.append(None)
    return tuple(out)


def check_matmul(s1: Shape, s2: Shape) -> Shape:
    """``a @ b`` contraction check: last axis of ``a`` against
    second-to-last of ``b`` (numpy matmul semantics, 1-D promotion)."""
    if s1 is None or s2 is None or not s1 or not s2:
        return None
    k1 = s1[-1]
    k2 = s2[-2] if len(s2) >= 2 else s2[-1]
    if dim_eq(k1, k2) is False:
        raise ShapeError(
            f"matmul contraction mismatch: {fmt_shape(s1)} @ "
            f"{fmt_shape(s2)} contracts {fmt_dim(k1)} against "
            f"{fmt_dim(k2)}")
    a_batch = s1[:-2] if len(s1) >= 2 else ()
    b_batch = s2[:-2] if len(s2) >= 2 else ()
    batch = broadcast(a_batch, b_batch)
    if batch is None:
        batch = ()
    out = list(batch)
    if len(s1) >= 2:
        out.append(s1[-2])
    if len(s2) >= 2:
        out.append(s2[-1])
    return tuple(out)


def check_einsum(spec: str, shapes: Sequence[Shape]) -> Shape:
    """Einsum axis algebra over explicit letter specs; ``...`` specs are
    left unchecked (⊤).  Flags rank mismatches and a letter bound to two
    provably different extents."""
    spec = spec.replace(" ", "")
    if "..." in spec:
        return None
    if "->" in spec:
        lhs, out_term = spec.split("->", 1)
    else:
        lhs, out_term = spec, None
    terms = lhs.split(",")
    if len(terms) != len(shapes):
        raise ShapeError(
            f"einsum spec {spec!r} names {len(terms)} operand(s) but "
            f"{len(shapes)} were supplied")
    binding: Dict[str, Optional[Dim]] = {}
    for term, shape in zip(terms, shapes):
        if shape is None:
            for letter in term:
                binding.setdefault(letter, None)
            continue
        if len(term) != len(shape):
            raise ShapeError(
                f"einsum term {term!r} has {len(term)} axes but its "
                f"operand is {fmt_shape(shape)} (rank {len(shape)})")
        for letter, d in zip(term, shape):
            if letter in binding:
                prev = binding[letter]
                same = dim_eq(prev, d)
                if same is False:
                    raise ShapeError(
                        f"einsum axis {letter!r} is bound to both "
                        f"{fmt_dim(prev)} and {fmt_dim(d)}")
                if same is not True:
                    binding[letter] = None
            else:
                binding[letter] = d
    if out_term is None:
        counts: Dict[str, int] = {}
        for t in terms:
            for letter in t:
                counts[letter] = counts.get(letter, 0) + 1
        out_term = "".join(sorted(k for k, v in counts.items() if v == 1))
    for letter in out_term:
        if letter not in binding:
            raise ShapeError(
                f"einsum output axis {letter!r} does not appear in any "
                f"input term of {spec!r}")
    return tuple(binding[letter] for letter in out_term)


def reduce_shape(shape: Shape, axis, keepdims: bool = False) -> Shape:
    """Reduction result shape; flags a concrete out-of-range axis."""
    if shape is None:
        return None
    rank = len(shape)
    if axis is None:
        return tuple(lit(1) for _ in shape) if keepdims else ()
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    norm = set()
    for a in axes:
        if not isinstance(a, int):
            return None
        if a < -rank or a >= rank:
            raise ShapeError(
                f"reduction axis {a} out of range for input "
                f"{fmt_shape(shape)} (rank {rank})")
        norm.add(a % rank)
    if keepdims:
        return tuple(lit(1) if i in norm else d
                     for i, d in enumerate(shape))
    return tuple(d for i, d in enumerate(shape) if i not in norm)


def concat_shapes(shapes: Sequence[Shape], axis: int) -> Shape:
    """Concatenate along ``axis``: every other axis must agree."""
    if any(s is None for s in shapes) or not shapes:
        return None
    rank = len(shapes[0])
    for s in shapes[1:]:
        if len(s) != rank:
            raise ShapeError(
                f"concat operands disagree on rank: {fmt_shape(shapes[0])}"
                f" vs {fmt_shape(s)}")
    if not isinstance(axis, int) or axis < -rank or axis >= rank:
        return None
    axis %= rank
    out: List[Optional[Dim]] = list(shapes[0])
    for s in shapes[1:]:
        for i in range(rank):
            if i == axis:
                out[i] = dim_add(out[i], s[i])
            elif dim_eq(out[i], s[i]) is False:
                raise ShapeError(
                    f"concat operands disagree on non-concat axis {i}: "
                    f"{fmt_dim(out[i])} vs {fmt_dim(s[i])}")
            elif dim_eq(out[i], s[i]) is not True:
                out[i] = None
    return tuple(out)


# --------------------------------------------------------------- dtype join
# The JAX type-promotion lattice (jax.numpy.promote_types): weak python
# scalars are first-class members ('int', 'float', 'complex'), so
# `x_f32 * 2.0` stays float32 while `x_f32 * np.float64(2)` widens.
_LATTICE_EDGES = {
    "bool": ("int",),
    "int": ("uint8", "int8", "float"),
    "uint8": ("uint16", "int16"),
    "uint16": ("uint32", "int32"),
    "uint32": ("uint64", "int64"),
    "uint64": ("float",),
    "int8": ("int16",),
    "int16": ("int32",),
    "int32": ("int64",),
    # float8 members mirror jnp.promote_types exactly: each fp8 flavor
    # joins with every int (int64 sits atop the signed-int chain) but
    # with NO other float — jax raises TypePromotionError there, which
    # this lattice models as "no common ancestor" (promote -> None,
    # checkers stay quiet)
    "int64": ("float", "float8_e4m3fn", "float8_e5m2"),
    "float8_e4m3fn": (),
    "float8_e5m2": (),
    "float": ("bfloat16", "float16", "complex"),
    "bfloat16": ("float32",),
    "float16": ("float32",),
    "float32": ("float64", "complex64"),
    "float64": ("complex128",),
    "complex": ("complex64",),
    "complex64": ("complex128",),
    "complex128": (),
}
DTYPES = frozenset(_LATTICE_EDGES)
FLOAT_DTYPES = frozenset({"bfloat16", "float16", "float32", "float64"})
INT_DTYPES = frozenset({"int8", "int16", "int32", "int64",
                        "uint8", "uint16", "uint32", "uint64"})
# wire/storage dtypes a quantized artifact may declare for its packed
# weights (deploy manifest v4 `quantization` block)
QUANT_DTYPES = frozenset({"int8", "float8_e4m3fn", "float8_e5m2"})

_ANCESTORS: Dict[str, frozenset] = {}


def _ancestors(dt: str) -> frozenset:
    cached = _ANCESTORS.get(dt)
    if cached is None:
        out = {dt}
        for parent in _LATTICE_EDGES[dt]:
            out |= _ancestors(parent)
        cached = _ANCESTORS[dt] = frozenset(out)
    return cached


def promote(a: Optional[str], b: Optional[str]) -> Optional[str]:
    """Least upper bound in the JAX lattice; None (unknown) absorbs."""
    if a is None or b is None:
        return None
    if a not in DTYPES or b not in DTYPES:
        return None
    if a == b:
        return a
    common = _ancestors(a) & _ancestors(b)
    if not common:
        return None
    # the JAX lattice has a unique least element of every common set:
    # the one that is an ancestor of no *other* common element
    for c in common:
        if all(c == d or c not in _ancestors(d) for d in common):
            return c
    return None


# ---------------------------------------------------------- per-op rules
# A rule maps the op's input signatures to its output signature without
# tracing: rule(shapes, dtypes, kw) -> (shape, dtype), raising
# ShapeError on provable infeasibility and returning (None, None) when
# unknown.  `kw` values are python literals where the caller had them,
# Dim for symbolic extents, None otherwise — rules must treat missing
# or unknown entries as ⊤.
SHAPE_RULES: Dict[str, "callable"] = {}


def shape_rule(*names):
    """Register one inference rule under the op's registry name(s)."""

    def _deco(fn):
        for n in names:
            SHAPE_RULES[n] = fn
        return fn

    return _deco


def rule_for(name: str):
    return SHAPE_RULES.get(name)


def _as_dim(v):
    if isinstance(v, Dim):
        return v
    if isinstance(v, bool):
        return None
    if isinstance(v, int):
        return v if v == -1 else lit(v)
    return None


def _first(shapes, dtypes):
    shape = shapes[0] if shapes else None
    dtype = dtypes[0] if dtypes else None
    return shape, dtype


@shape_rule("reshape", "Reshape")
def _rule_reshape(shapes, dtypes, kw):
    shape, dtype = _first(shapes, dtypes)
    target = kw.get("shape")
    if not isinstance(target, (tuple, list)) or kw.get("reverse"):
        return None, dtype
    out = []
    src = list(shape) if shape is not None else None
    i = 0
    for s in target:
        if isinstance(s, int) and s in (-2, -3, -4):
            return None, dtype          # MXNet splice codes: stay quiet
        if isinstance(s, int) and s == 0:
            # 0 = copy the input dim at this position
            out.append(src[i] if src is not None and i < len(src)
                       else None)
        else:
            out.append(_as_dim(s))
        i += 1
    return check_reshape(shape, out), dtype


@shape_rule("transpose")
def _rule_transpose(shapes, dtypes, kw):
    shape, dtype = _first(shapes, dtypes)
    axes = kw.get("axes")
    axes = tuple(axes) if isinstance(axes, (tuple, list)) and axes else None
    return check_transpose(shape, axes), dtype


@shape_rule("expand_dims")
def _rule_expand_dims(shapes, dtypes, kw):
    shape, dtype = _first(shapes, dtypes)
    axis = kw.get("axis", 0)
    if shape is None or not isinstance(axis, int):
        return None, dtype
    rank = len(shape)
    if axis < -rank - 1 or axis > rank:
        raise ShapeError(
            f"expand_dims axis {axis} out of range for rank {rank}")
    axis %= (rank + 1)
    return shape[:axis] + (lit(1),) + shape[axis:], dtype


@shape_rule("flatten", "Flatten")
def _rule_flatten(shapes, dtypes, kw):
    shape, dtype = _first(shapes, dtypes)
    if shape is None:
        return None, dtype
    if len(shape) == 0:
        return None, dtype
    return check_reshape(shape, [shape[0], -1]), dtype


@shape_rule("dot")
def _rule_dot(shapes, dtypes, kw):
    if len(shapes) < 2 or kw.get("transpose_a") or kw.get("transpose_b"):
        return None, None
    s1, s2 = shapes[0], shapes[1]
    dtype = promote(dtypes[0], dtypes[1])
    if s1 is None or s2 is None:
        return None, dtype
    # contracts last axis of lhs with FIRST of rhs (mxnet dot semantics)
    if dim_eq(s1[-1] if s1 else None, s2[0] if s2 else None) is False:
        raise ShapeError(
            f"dot contraction mismatch: {fmt_shape(s1)} . {fmt_shape(s2)}"
            f" contracts {fmt_dim(s1[-1])} against {fmt_dim(s2[0])}")
    return tuple(s1[:-1]) + tuple(s2[1:]), dtype


@shape_rule("batch_dot")
def _rule_batch_dot(shapes, dtypes, kw):
    if len(shapes) < 2:
        return None, None
    s1, s2 = shapes[0], shapes[1]
    dtype = promote(dtypes[0], dtypes[1])
    if kw.get("transpose_a") or kw.get("transpose_b"):
        return None, dtype
    return check_matmul(s1, s2), dtype


def _rule_reduce(shapes, dtypes, kw):
    shape, dtype = _first(shapes, dtypes)
    axis = kw.get("axis")
    if kw.get("exclude") or not (axis is None or isinstance(axis, int)
                                 or isinstance(axis, (tuple, list))):
        return None, dtype
    if isinstance(axis, (tuple, list)):
        axis = tuple(axis)
    keep = kw.get("keepdims", False)
    if not isinstance(keep, bool):
        return None, dtype
    return reduce_shape(shape, axis, keep), dtype


for _name in ("sum", "sum_axis", "mean", "prod", "nansum", "nanprod",
              "max", "max_axis", "min", "min_axis"):
    SHAPE_RULES[_name] = _rule_reduce


@shape_rule("concat", "Concat")
def _rule_concat(shapes, dtypes, kw):
    axis = kw.get("dim", kw.get("axis", 1))
    dtype = None
    if dtypes:
        dtype = dtypes[0]
        for d in dtypes[1:]:
            dtype = promote(dtype, d)
    if not isinstance(axis, int):
        return None, dtype
    return concat_shapes(list(shapes), axis), dtype
