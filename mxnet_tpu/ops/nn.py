"""Neural-network operators.

Reference: ``src/operator/nn/`` (Convolution, BatchNorm, FullyConnected,
Pooling, Activation, Dropout, LayerNorm, Softmax, Embedding — SURVEY.md 2.1)
plus ``src/operator/{rnn.cc,lrn.cc,l2_normalization.cc}``.

TPU-native notes:
- Conv/FC lower straight to ``lax.conv_general_dilated`` / ``dot_general``
  → MXU.  No cuDNN/oneDNN dispatch layer exists: XLA owns kernel selection,
  and Pallas alternatives (ops/pallas_kernels.py) override via the same
  registry when profitable.
- Layouts follow the reference default (NCHW / NCW / NCDHW, TNC for RNN) at
  the API level; XLA relayouts internally for the hardware, so API-level
  layout costs nothing at steady state.
- Dropout/random ops draw from mxnet_tpu.random, which yields *traced* keys
  inside a hybridize trace (counter-based fold_in) and a global key in eager
  mode — keeping op signatures reference-compatible while staying pure
  under jit.
- Training-vs-inference branches (BatchNorm, Dropout) read
  ``autograd.is_training()`` at *trace/call* time — static per compiled
  program, matching how the reference dispatches on ``ctx.is_train``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import register
from .. import autograd


def _act(data, act_type):
    if act_type == "relu":
        return jnp.maximum(data, 0)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return data / (1 + jnp.abs(data))
    raise ValueError(f"unknown act_type {act_type!r}")


@register("Activation", aliases=["activation"])
def Activation(data, *, act_type: str = "relu"):
    """Elementwise activation (reference: nn/activation.cc)."""
    return _act(data, act_type)


def _leaky_nin(kwargs):
    return 2 if kwargs.get("act_type", "leaky") == "prelu" else 1


@register("LeakyReLU", num_inputs=_leaky_nin)
def LeakyReLU(data, gamma=None, *, act_type: str = "leaky",
              slope: float = 0.25, lower_bound: float = 0.125,
              upper_bound: float = 0.334):
    """Leaky-family activations incl. prelu/elu/selu/gelu
    (reference: src/operator/leaky_relu.cc)."""
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2)) \
            if gamma.ndim == 1 and data.ndim > 1 else gamma
        return jnp.where(data >= 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(data >= 0, data, alpha * jnp.expm1(data))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":
        # inference behavior (fixed mean slope), like reference in test mode
        return jnp.where(data >= 0, data,
                         data * (lower_bound + upper_bound) / 2)
    raise ValueError(f"unknown act_type {act_type!r}")


@register("softmax")
def softmax(data, *, axis: int = -1, temperature=None, dtype=None,
            use_length: bool = False):
    """reference: nn/softmax.cc."""
    x = data / temperature if temperature else data
    out = jax.nn.softmax(x, axis=axis)
    return out.astype(jnp.dtype(dtype)) if dtype else out


@register("log_softmax")
def log_softmax(data, *, axis: int = -1, temperature=None, dtype=None):
    x = data / temperature if temperature else data
    out = jax.nn.log_softmax(x, axis=axis)
    return out.astype(jnp.dtype(dtype)) if dtype else out


@register("softmin")
def softmin(data, *, axis: int = -1, temperature=None, dtype=None):
    return jax.nn.softmax(-data, axis=axis)


@register("SoftmaxActivation")
def SoftmaxActivation(data, *, mode: str = "instance"):
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


def _softmax_output_fwd(data, label, grad_scale, ignore_label,
                        use_ignore, multi_output, normalization,
                        smooth_alpha):
    axis = 1 if multi_output else -1
    return jax.nn.softmax(data, axis=axis)


@jax.custom_vjp
def _softmax_output_core(data, label):
    return jax.nn.softmax(data, axis=-1)


def _soc_fwd(data, label):
    out = jax.nn.softmax(data, axis=-1)
    return out, (out, label)


def _soc_bwd(res, g):
    out, label = res
    oh = jax.nn.one_hot(label.astype(jnp.int32), out.shape[-1],
                        dtype=out.dtype)
    oh = oh.reshape(out.shape)
    # Loss-layer semantics: incoming cotangent ignored (reference:
    # softmax_output.cc backward writes (p - onehot) regardless).
    return (out - oh, jnp.zeros_like(label))


_softmax_output_core.defvjp(_soc_fwd, _soc_bwd)


@register("SoftmaxOutput", num_inputs=2, aliases=["Softmax"])
def SoftmaxOutput(data, label, *, grad_scale: float = 1.0,
                  ignore_label: float = -1.0, multi_output: bool = False,
                  use_ignore: bool = False, preserve_shape: bool = False,
                  normalization: str = "null", out_grad: bool = False,
                  smooth_alpha: float = 0.0):
    """Softmax forward + cross-entropy-style gradient (reference:
    src/operator/softmax_output.cc).  The backward writes
    ``(softmax - onehot(label)) * grad_scale`` into data's grad and ignores
    the incoming cotangent, exactly like the reference loss layer."""
    if multi_output:
        # (N, C, ...) softmax over C with per-position labels
        x = jnp.moveaxis(data, 1, -1)
        out = _softmax_output_core(x, label.reshape(x.shape[:-1]))
        return jnp.moveaxis(out, -1, 1) * 1.0
    return _softmax_output_core(data, label) * 1.0


@register("softmax_cross_entropy", num_inputs=2)
def softmax_cross_entropy(data, label):
    """reference: src/operator/loss_binary_op.cc — scalar summed CE."""
    logp = jax.nn.log_softmax(data, axis=-1)
    oh = jax.nn.one_hot(label.astype(jnp.int32), data.shape[-1],
                        dtype=data.dtype)
    return -jnp.sum(oh * logp)


@register("FullyConnected", num_inputs=lambda kw: 2 if kw.get("no_bias") else 3)
def FullyConnected(data, weight, bias=None, *, num_hidden: int = 0,
                   no_bias: bool = False, flatten: bool = True):
    """y = x W^T + b (reference: nn/fully_connected.cc).  dot_general on the
    MXU; weight layout (num_hidden, input_dim) matches the reference."""
    x = data.reshape(data.shape[0], -1) if flatten and data.ndim > 2 else data
    y = jnp.matmul(x, weight.T)
    if bias is not None:
        y = y + bias
    return y


def _conv_dims(kernel_len):
    # (lhs spec, rhs spec, out spec) for NC* layouts
    spatial = "DHW"[3 - kernel_len:]
    return ("NC" + spatial, "OI" + spatial, "NC" + spatial)


@register("Convolution",
          num_inputs=lambda kw: 2 if kw.get("no_bias") else 3)
def Convolution(data, weight, bias=None, *, kernel=(), stride=(), dilate=(),
                pad=(), num_filter: int = 0, num_group: int = 1,
                no_bias: bool = False, layout=None, cudnn_off: bool = False,
                cudnn_tune=None, workspace: int = 1024):
    """N-d convolution, NC* layout, weight (O, I/g, *k)
    (reference: nn/convolution.cc).  Lowers to conv_general_dilated → MXU."""
    k = len(kernel)
    stride = tuple(stride) or (1,) * k
    dilate = tuple(dilate) or (1,) * k
    pad = tuple(pad) or (0,) * k
    dn = lax.conv_dimension_numbers(data.shape, weight.shape, _conv_dims(k))
    out = lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=num_group,
        preferred_element_type=None)
    if bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * k)
    return out


@register("Deconvolution",
          num_inputs=lambda kw: 2 if kw.get("no_bias", True) else 3)
def Deconvolution(data, weight, bias=None, *, kernel=(), stride=(), dilate=(),
                  pad=(), adj=(), num_filter: int = 0, num_group: int = 1,
                  no_bias: bool = True, target_shape=(), layout=None,
                  cudnn_off: bool = False, cudnn_tune=None,
                  workspace: int = 512):
    """Transposed convolution (reference: nn/deconvolution.cc); weight
    layout (I, O/g, *k) like the reference."""
    k = len(kernel)
    stride = tuple(stride) or (1,) * k
    pad = tuple(pad) or (0,) * k
    adj = tuple(adj) or (0,) * k
    dn = lax.conv_dimension_numbers(
        data.shape, (weight.shape[1] * num_group, weight.shape[0] // num_group)
        + tuple(weight.shape[2:]), _conv_dims(k))
    # grad-of-conv formulation: transpose via lhs dilation
    w = weight
    if num_group > 1:
        w = w.reshape((num_group, w.shape[0] // num_group) + w.shape[1:])
        w = jnp.concatenate([w[g] for g in range(num_group)], axis=1)
    w_t = jnp.swapaxes(w, 0, 1)  # (O/g*g? , I, *k) -> use flipped kernel
    w_t = jnp.flip(w_t, axis=tuple(range(2, 2 + k)))
    pads = [(kernel[i] - 1 - pad[i], kernel[i] - 1 - pad[i] + adj[i])
            for i in range(k)]
    out = lax.conv_general_dilated(
        data, w_t, window_strides=(1,) * k, padding=pads,
        lhs_dilation=stride, dimension_numbers=dn,
        feature_group_count=num_group)
    if bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * k)
    return out


@register("Pooling", aliases=["pooling"])
def Pooling(data, *, kernel=(), pool_type: str = "max", stride=(), pad=(),
            global_pool: bool = False, cudnn_off: bool = False,
            pooling_convention: str = "valid", count_include_pad: bool = True,
            layout=None):
    """Max/avg/sum/lp pooling (reference: nn/pooling.cc)."""
    nsp = data.ndim - 2
    if global_pool:
        axes = tuple(range(2, data.ndim))
        if pool_type == "max":
            return jnp.max(data, axis=axes, keepdims=True)
        if pool_type in ("avg", "lp"):
            return jnp.mean(data, axis=axes, keepdims=True)
        return jnp.sum(data, axis=axes, keepdims=True)
    k = tuple(kernel)
    stride = tuple(stride) or (1,) * nsp
    pad = tuple(pad) or (0,) * nsp
    window = (1, 1) + k
    strides = (1, 1) + stride
    if pooling_convention == "full":
        # ceil division semantics: pad on the high side as needed
        pads = [(0, 0), (0, 0)]
        for i in range(nsp):
            in_sz = data.shape[2 + i] + 2 * pad[i]
            out_sz = -(-(in_sz - k[i]) // stride[i]) + 1
            need = max(0, (out_sz - 1) * stride[i] + k[i] - in_sz)
            pads.append((pad[i], pad[i] + need))
    else:
        pads = [(0, 0), (0, 0)] + [(p, p) for p in pad]
    if pool_type == "max":
        init = -jnp.inf
        out = lax.reduce_window(data, init, lax.max, window, strides, pads)
        return out.astype(data.dtype)
    if pool_type == "sum":
        return lax.reduce_window(data, 0.0, lax.add, window, strides, pads)
    # avg
    summed = lax.reduce_window(data, 0.0, lax.add, window, strides, pads)
    if count_include_pad:
        denom = float(np.prod(k))
        return summed / denom
    ones = jnp.ones_like(data)
    counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
    return summed / counts


def _bn_nout(kwargs):
    return 3 if kwargs.get("output_mean_var") else 1


@register("BatchNorm", num_inputs=5, num_outputs=_bn_nout,
          aliases=["batch_norm"])
def BatchNorm(data, gamma, beta, moving_mean, moving_var, *,
              eps: float = 1e-3, momentum: float = 0.9,
              fix_gamma: bool = True, use_global_stats: bool = False,
              output_mean_var: bool = False, axis: int = 1,
              cudnn_off: bool = False):
    """Batch normalization (reference: nn/batch_norm.cc).

    Training mode (autograd.is_training() and not use_global_stats) uses
    batch statistics; inference uses the moving stats.  With
    ``output_mean_var`` the batch mean and inverse-std are returned so the
    Gluon layer can update its running stats functionally (the reference
    mutates aux states inside the op; here state threading is explicit —
    see gluon/nn/basic_layers.py BatchNorm)."""
    if fix_gamma:
        gamma = jnp.ones_like(gamma)
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    training = autograd.is_training() and not use_global_stats
    if training:
        # stats accumulate AND flow onward in fp32 regardless of
        # activation dtype: a bf16 sum over B*H*W (≈1e5-1e6) elements
        # loses ~3 decimal digits, which corrupts the moving-stat EMA
        # over a long schedule.  Only the normalize expression casts
        # back to the activation dtype, so XLA still fuses it into the
        # producing conv with no extra HBM traffic and the
        # output_mean_var / aux-update consumers see full precision.
        red = tuple(i for i in range(data.ndim) if i != axis)
        data32 = data.astype(jnp.float32)
        mean = jnp.mean(data32, axis=red)
        var = jnp.var(data32, axis=red)
    else:
        mean, var = moving_mean, moving_var
    inv_std = lax.rsqrt(var + eps)
    out = (data - mean.astype(data.dtype).reshape(shape)) \
        * inv_std.astype(data.dtype).reshape(shape) \
        * gamma.reshape(shape) + beta.reshape(shape)
    if output_mean_var:
        return out, mean, inv_std
    return out


def _batchnorm_aux_update(args, kwargs):
    """OpDef.aux_update hook: training-time moving-stat transition
    (reference: batch_norm.cc mutates moving_mean/var in Forward; here the
    executor applies the returned update functionally)."""
    if kwargs.get("use_global_stats") or kwargs.get("output_mean_var"):
        return None
    out, mean, inv_std = BatchNorm(*args,
                                   **dict(kwargs, output_mean_var=True))
    eps = float(kwargs.get("eps", 1e-3))
    mom = float(kwargs.get("momentum", 0.9))
    var = 1.0 / (inv_std * inv_std) - eps
    return (out,), {
        3: mom * args[3] + (1.0 - mom) * mean.astype(args[3].dtype),
        4: mom * args[4] + (1.0 - mom) * var.astype(args[4].dtype),
    }


from .registry import get_op as _get_op  # noqa: E402
_get_op("BatchNorm").aux_update = _batchnorm_aux_update


@register("LayerNorm", num_inputs=3, num_outputs=_bn_nout,
          aliases=["layer_norm"])
def LayerNorm(data, gamma, beta, *, axis: int = -1, eps: float = 1e-5,
              output_mean_var: bool = False):
    """reference: nn/layer_norm.cc."""
    mean = jnp.mean(data, axis=axis, keepdims=True)
    var = jnp.var(data, axis=axis, keepdims=True)
    inv_std = lax.rsqrt(var + eps)
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    out = (data - mean) * inv_std * gamma.reshape(shape) + beta.reshape(shape)
    if output_mean_var:
        return out, jnp.squeeze(mean, axis), jnp.squeeze(inv_std, axis)
    return out


@register("InstanceNorm", num_inputs=3)
def InstanceNorm(data, gamma, beta, *, eps: float = 1e-3):
    """reference: src/operator/instance_norm.cc (NC+ layout)."""
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    return (data - mean) * lax.rsqrt(var + eps) * gamma.reshape(shape) \
        + beta.reshape(shape)


@register("GroupNorm", num_inputs=3)
def GroupNorm(data, gamma, beta, *, num_groups: int = 1, eps: float = 1e-5):
    """reference: nn/group_norm.cc."""
    n, c = data.shape[:2]
    x = data.reshape((n, num_groups, c // num_groups) + data.shape[2:])
    red = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.var(x, axis=red, keepdims=True)
    x = (x - mean) * lax.rsqrt(var + eps)
    x = x.reshape(data.shape)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    return x * gamma.reshape(shape) + beta.reshape(shape)


@register("L2Normalization")
def L2Normalization(data, *, eps: float = 1e-10, mode: str = "instance"):
    """reference: src/operator/l2_normalization.cc."""
    if mode == "instance":
        red = tuple(range(1, data.ndim))
        keep = True
    elif mode == "channel":
        red, keep = (1,), True
    else:  # spatial
        red, keep = tuple(range(2, data.ndim)), True
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=red, keepdims=keep) + eps)
    return data / norm


@register("LRN")
def LRN(data, *, alpha: float = 1e-4, beta: float = 0.75, knorm: float = 2.0,
        nsize: int = 5):
    """Local response norm across channels (reference: src/operator/lrn.cc)."""
    sq = jnp.square(data)
    half = nsize // 2
    padded = jnp.pad(sq, [(0, 0), (half, half)] + [(0, 0)] * (data.ndim - 2))
    windows = sum(padded[:, i:i + data.shape[1]] for i in range(nsize))
    return data / jnp.power(knorm + alpha * windows / nsize, beta)


@register("Dropout", mutates_rng=True)
def Dropout(data, *, p: float = 0.5, mode: str = "training", axes=(),
            cudnn_off: bool = False):
    """Dropout (reference: nn/dropout.cc).  Scales by 1/(1-p) at train time.
    Key comes from mxnet_tpu.random (traced key under hybridize)."""
    if not autograd.is_training() and mode != "always":
        return data
    if p <= 0:
        return data
    from .. import random as mxrand
    key = mxrand.next_key()
    if axes:
        shape = tuple(1 if i in tuple(axes) else s
                      for i, s in enumerate(data.shape))
    else:
        shape = data.shape
    keep = jax.random.bernoulli(key, 1.0 - p, shape=shape)
    return jnp.where(keep, data / (1.0 - p), 0.0).astype(data.dtype)


@register("Embedding", num_inputs=2)
def Embedding(data, weight, *, input_dim: int = 0, output_dim: int = 0,
              dtype: str = "float32", sparse_grad: bool = False):
    """Lookup table (reference: indexing_op.cc EmbeddingOp); gather on
    data indices into weight rows."""
    idx = jnp.clip(data.astype(jnp.int32), 0, weight.shape[0] - 1)
    return jnp.take(weight, idx, axis=0)


@register("UpSampling", num_inputs=None)
def UpSampling(*data, scale: int = 1, sample_type: str = "nearest",
               num_args: int = 1, num_filter: int = 0,
               multi_input_mode: str = "concat", workspace: int = 512):
    """reference: src/operator/upsampling.cc (nearest mode)."""
    outs = []
    for d in data:
        n, c, h, w = d.shape
        if sample_type == "nearest":
            o = jnp.repeat(jnp.repeat(d, scale, axis=2), scale, axis=3)
        else:
            o = jax.image.resize(d, (n, c, h * scale, w * scale), "bilinear")
        outs.append(o)
    if len(outs) == 1:
        return outs[0]
    return jnp.concatenate(outs, axis=1)


@register("BilinearSampler", num_inputs=2)
def BilinearSampler(data, grid, *, cudnn_off: bool = False):
    """reference: src/operator/bilinear_sampler.cc; grid in [-1, 1]."""
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1) * (w - 1) / 2
    gy = (grid[:, 1] + 1) * (h - 1) / 2
    x0 = jnp.floor(gx).astype(jnp.int32)
    y0 = jnp.floor(gy).astype(jnp.int32)
    x1, y1 = x0 + 1, y0 + 1
    wx, wy = gx - x0, gy - y0

    def gather(yy, xx):
        yy = jnp.clip(yy, 0, h - 1)
        xx = jnp.clip(xx, 0, w - 1)
        flat = data.reshape(n, c, h * w)
        lin = (yy * w + xx).reshape(n, -1)
        out = jnp.take_along_axis(flat, lin[:, None, :], axis=2)
        return out.reshape(n, c, *gx.shape[1:])

    val = (gather(y0, x0) * ((1 - wx) * (1 - wy))[:, None]
           + gather(y0, x1) * (wx * (1 - wy))[:, None]
           + gather(y1, x0) * ((1 - wx) * wy)[:, None]
           + gather(y1, x1) * (wx * wy)[:, None])
    return val


# ---------------------------------------------------------------------------
# Fused RNN (reference: src/operator/rnn.cc + rnn-inl.h; cuDNN packed-weight
# layout).  TPU-native: lax.scan over time — compiles to one fused loop, the
# idiomatic XLA recurrence (no per-step dispatch).
# ---------------------------------------------------------------------------

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def _rnn_nout(kwargs):
    if not kwargs.get("state_outputs", False):
        return 1
    return 3 if kwargs.get("mode", "lstm") == "lstm" else 2


def _unpack_rnn_params(params, mode, num_layers, input_size, H, D):
    """Split the flat cudnn-style parameter vector: all i2h/h2h weights
    (layer-major, direction-minor), then all biases — the layout the
    reference documents for rnn.cc."""
    G = _GATES[mode]
    ws, bs = [], []
    offset = 0
    for layer in range(num_layers):
        for d in range(D):
            in_sz = input_size if layer == 0 else H * D
            w_i2h = (G * H, in_sz)
            w_h2h = (G * H, H)
            ws.append((w_i2h, w_h2h))
    weights = []
    for (s1, s2) in ws:
        n1 = s1[0] * s1[1]
        weights.append(params[offset:offset + n1].reshape(s1))
        offset += n1
        n2 = s2[0] * s2[1]
        weights.append(params[offset:offset + n2].reshape(s2))
        offset += n2
    biases = []
    for layer in range(num_layers):
        for d in range(D):
            biases.append(params[offset:offset + G * H])
            offset += G * H
            biases.append(params[offset:offset + G * H])
            offset += G * H
    return weights, biases


def _cell_step(mode, H):
    if mode == "lstm":
        def step(carry, gates):
            h, c = carry
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = f * c + i * g
            h_new = o * jnp.tanh(c_new)
            return (h_new, c_new)
        return step
    if mode == "gru":
        def step(carry, pair):
            h = carry[0]
            gi, gh = pair
            ir, iz, inn = jnp.split(gi, 3, axis=-1)
            hr, hz, hn = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            n = jnp.tanh(inn + r * hn)
            h_new = (1 - z) * n + z * h
            return (h_new,)
        return step
    act = jnp.tanh if mode == "rnn_tanh" else (lambda x: jnp.maximum(x, 0))

    def step(carry, gates):
        return (act(gates),)
    return step


def _run_layer(x, mode, w_i2h, w_h2h, b_i2h, b_h2h, h0, c0, reverse):
    """x: (T, N, I). Returns (T, N, H), h_T, c_T."""
    H = w_h2h.shape[1]
    cell = _cell_step(mode, H)
    xin = jnp.flip(x, axis=0) if reverse else x
    gates_i = jnp.einsum("tni,gi->tng", xin, w_i2h) + b_i2h

    def scan_fn(carry, g_i):
        h = carry[0]
        g_h = jnp.matmul(h, w_h2h.T) + b_h2h
        if mode == "gru":
            new = cell(carry, (g_i, g_h))
        else:
            new = cell(carry, g_i + g_h)
        return new, new[0]

    init = (h0, c0) if mode == "lstm" else (h0,)
    carry, ys = lax.scan(scan_fn, init, gates_i)
    if reverse:
        ys = jnp.flip(ys, axis=0)
    h_T = carry[0]
    c_T = carry[1] if mode == "lstm" else None
    return ys, h_T, c_T


@register("RNN", num_inputs=lambda kw: 4 if kw.get("mode") == "lstm" else 3,
          num_outputs=_rnn_nout, mutates_rng=True)
def RNN(data, parameters, state, state_cell=None, *, state_size: int = 0,
        num_layers: int = 1, mode: str = "lstm", bidirectional: bool = False,
        p: float = 0.0, state_outputs: bool = False,
        projection_size=None, use_sequence_length: bool = False,
        lstm_state_clip_min=None, lstm_state_clip_max=None,
        lstm_state_clip_nan: bool = False):
    """Fused multi-layer (bi)RNN/LSTM/GRU over TNC input (reference:
    src/operator/rnn.cc).  lax.scan recurrence; packed cudnn-layout params."""
    T, N, I = data.shape
    H = state_size
    D = 2 if bidirectional else 1
    weights, biases = _unpack_rnn_params(parameters, mode, num_layers, I, H, D)
    x = data
    h_states, c_states = [], []
    for layer in range(num_layers):
        outs = []
        for d in range(D):
            li = layer * D + d
            w_i2h, w_h2h = weights[2 * li], weights[2 * li + 1]
            b_i2h, b_h2h = biases[2 * li], biases[2 * li + 1]
            h0 = state[li]
            c0 = state_cell[li] if mode == "lstm" else None
            ys, h_T, c_T = _run_layer(x, mode, w_i2h, w_h2h, b_i2h, b_h2h,
                                      h0, c0, reverse=(d == 1))
            outs.append(ys)
            h_states.append(h_T)
            if mode == "lstm":
                c_states.append(c_T)
        x = outs[0] if D == 1 else jnp.concatenate(outs, axis=-1)
        if p > 0 and layer < num_layers - 1 and autograd.is_training():
            from .. import random as mxrand
            keep = jax.random.bernoulli(mxrand.next_key(), 1.0 - p, x.shape)
            x = jnp.where(keep, x / (1.0 - p), 0.0)
    if not state_outputs:
        return x
    h_out = jnp.stack(h_states, axis=0)
    if mode == "lstm":
        return x, h_out, jnp.stack(c_states, axis=0)
    return x, h_out


@register("Correlation", num_inputs=2)
def Correlation(data1, data2, *, kernel_size: int = 1,
                max_displacement: int = 1, stride1: int = 1, stride2: int = 1,
                pad_size: int = 0, is_multiply: bool = True):
    """FlowNet cost volume (reference: src/operator/correlation.cc).

    One output channel per displacement in the stride2 grid; each is a
    channel-summed, kernel-window-summed patch product (or abs-difference),
    normalized by kernel_size^2 * C.  The displacement grid is static, so
    the whole volume lowers to a fused stack of shifted multiplies + a
    reduce_window — no gather, MXU/VPU friendly.
    """
    N, C, H, W = data1.shape
    kr = (kernel_size - 1) // 2
    border = max_displacement + kr
    pH, pW = H + 2 * pad_size, W + 2 * pad_size
    if pH - 2 * border < 1 or pW - 2 * border < 1:
        raise ValueError(
            f"Correlation: displacement border {border} "
            f"(max_displacement + kernel radius) leaves no valid output "
            f"for padded input {pH}x{pW}; increase pad_size or shrink "
            f"max_displacement/kernel_size")
    top_h = int(-(-(pH - 2 * border) // stride1))
    top_w = int(-(-(pW - 2 * border) // stride1))
    grid_r = max_displacement // stride2
    sumelems = float(kernel_size * kernel_size * C)
    pad = ((0, 0), (0, 0), (pad_size, pad_size), (pad_size, pad_size))
    p1 = jnp.pad(data1, pad)
    p2 = jnp.pad(data2, pad)
    start = border - kr
    planes = []
    for dy in range(-grid_r * stride2, grid_r * stride2 + 1, stride2):
        for dx in range(-grid_r * stride2, grid_r * stride2 + 1, stride2):
            shifted = jnp.roll(p2, (-dy, -dx), axis=(2, 3))
            prod = p1 * shifted if is_multiply else jnp.abs(p1 - shifted)
            s = prod.sum(axis=1)
            if kernel_size > 1:
                s = lax.reduce_window(s, 0.0, lax.add,
                                      (1, kernel_size, kernel_size),
                                      (1, 1, 1), "VALID")
            sub = lax.slice(s, (0, start, start),
                            (N, start + (top_h - 1) * stride1 + 1,
                             start + (top_w - 1) * stride1 + 1),
                            (1, stride1, stride1))
            planes.append(sub / sumelems)
    return jnp.stack(planes, axis=1)


@register("GridGenerator")
def GridGenerator(data, *, transform_type: str = "affine", target_shape=()):
    h, w = target_shape
    ys = jnp.linspace(-1, 1, h)
    xs = jnp.linspace(-1, 1, w)
    gx, gy = jnp.meshgrid(xs, ys)
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()], axis=0)
    theta = data.reshape(-1, 2, 3)
    out = jnp.einsum("nij,jk->nik", theta, base)
    return out.reshape(-1, 2, h, w)


# ---------------------------------------------------------------------------
# CTC loss (reference: src/operator/nn/ctc_loss.cc / mx.nd.CTCLoss).
# Log-domain forward algorithm via lax.scan (TPU-friendly: static shapes,
# no data-dependent python control flow); vmapped over the batch.
# Convention (blank_label='first'): channel 0 is blank, labels are 1..C-1,
# label padding value is 0.
# ---------------------------------------------------------------------------

def _ctc_forward_single(logprobs, label, t_len, l_len):
    """logprobs (T, C) log-softmax; label (L,) ints; returns -log p(label)."""
    T, C = logprobs.shape
    L = label.shape[0]
    S = 2 * L + 1
    neg_inf = jnp.float32(-1e30)
    # extended label sequence: blank, l1, blank, l2, ..., blank
    z = jnp.zeros((S,), dtype=label.dtype)
    z = z.at[1::2].set(label)
    s_idx = jnp.arange(S)
    # transitions: from s, s-1 always; from s-2 iff z[s] != z[s-2] and odd s
    z_prev2 = jnp.concatenate([jnp.zeros((2,), z.dtype), z[:-2]])
    can_skip = (s_idx % 2 == 1) & (z != z_prev2)

    alpha0 = jnp.full((S,), neg_inf)
    alpha0 = alpha0.at[0].set(logprobs[0, 0])
    alpha0 = alpha0.at[1].set(
        jnp.where(l_len > 0, logprobs[0, z[1]], neg_inf))

    def step(alpha, t):
        a_prev1 = jnp.concatenate([jnp.array([neg_inf]), alpha[:-1]])
        a_prev2 = jnp.concatenate([jnp.full((2,), neg_inf), alpha[:-2]])
        a_prev2 = jnp.where(can_skip, a_prev2, neg_inf)
        stacked = jnp.stack([alpha, a_prev1, a_prev2])
        merged = jax.scipy.special.logsumexp(stacked, axis=0)
        new_alpha = merged + logprobs[t, z]
        # freeze the recursion past this sample's length
        new_alpha = jnp.where(t < t_len, new_alpha, alpha)
        return new_alpha, None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
    end1 = alpha[2 * l_len]        # final blank
    end2 = jnp.where(l_len > 0, alpha[2 * l_len - 1], neg_inf)
    logp = jnp.logaddexp(end1, end2)
    return -logp


@register("CTCLoss", num_inputs=4, aliases=["ctc_loss", "_contrib_CTCLoss",
                                            "_contrib_ctc_loss"])
def CTCLoss(data, label, data_lengths=None, label_lengths=None, *,
            use_data_lengths: bool = False, use_label_lengths: bool = False,
            blank_label: str = "first"):
    """data (T, N, C) unnormalized activations; label (N, L)."""
    T, N, C = data.shape
    logprobs = jax.nn.log_softmax(data, axis=-1)  # (T, N, C)
    label = label.astype(jnp.int32)
    if blank_label == "last":
        # rotate so blank becomes channel 0 (internal convention)
        logprobs = jnp.concatenate(
            [logprobs[..., -1:], logprobs[..., :-1]], axis=-1)
        label = label + 1
    if data_lengths is None or not use_data_lengths:
        t_lens = jnp.full((N,), T, dtype=jnp.int32)
    else:
        t_lens = data_lengths.astype(jnp.int32)
    if label_lengths is None or not use_label_lengths:
        l_lens = jnp.sum(label > 0, axis=1).astype(jnp.int32)
    else:
        l_lens = label_lengths.astype(jnp.int32)
    per_n = jax.vmap(_ctc_forward_single, in_axes=(1, 0, 0, 0))(
        logprobs, label, t_lens, l_lens)
    return per_n.astype(data.dtype)


@register("hard_sigmoid")
def hard_sigmoid(data, *, alpha: float = 0.2, beta: float = 0.5):
    """Piecewise-linear sigmoid (reference: mshadow_op hard_sigmoid)."""
    return jnp.clip(alpha * data + beta, 0.0, 1.0)


@register("im2col")
def im2col(data, *, kernel=(), stride=(1, 1), dilate=(1, 1),
           pad=(0, 0)):
    """Sliding-window patch extraction, NCHW -> (N, C*kh*kw, L)
    (reference: src/operator/nn/im2col.h).  XLA's dilated-patch
    primitive keeps it one fused op."""
    kh, kw = kernel
    patches = jax.lax.conv_general_dilated_patches(
        data, (kh, kw), tuple(stride),
        [(pad[0], pad[0]), (pad[1], pad[1])],
        rhs_dilation=tuple(dilate),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    n, ckk, oh, ow = patches.shape
    return patches.reshape(n, ckk, oh * ow)


@register("col2im")
def col2im(data, *, output_size=(), kernel=(), stride=(1, 1),
           dilate=(1, 1), pad=(0, 0)):
    """Inverse of im2col: scatter-add patches back to NCHW (reference:
    src/operator/nn/im2col.h col2im).  Implemented as the linear
    transpose of im2col — exact adjoint by construction."""
    H, W = output_size
    n, ckk, _L = data.shape
    kh, kw = kernel
    c = ckk // (kh * kw)

    def fwd(img):
        return im2col(img, kernel=kernel, stride=stride, dilate=dilate,
                      pad=pad)

    img_shape = jax.ShapeDtypeStruct((n, c, H, W), data.dtype)
    (out,) = jax.linear_transpose(fwd, img_shape)(data)
    return out


@register("SpatialTransformer", num_inputs=2)
def SpatialTransformer(data, loc, *, target_shape=(),
                       transform_type: str = "affine",
                       sampler_type: str = "bilinear",
                       cudnn_off: bool = False):
    """Affine spatial transformer network: GridGenerator +
    BilinearSampler composed (reference:
    src/operator/spatial_transformer.cc)."""
    grid = GridGenerator(loc, transform_type=transform_type,
                         target_shape=target_shape)
    return BilinearSampler(data, grid)


@register("ROIPooling", num_inputs=2)
def ROIPooling(data, rois, *, pooled_size=(), spatial_scale: float = 1.0):
    """Max pooling over ROI bins (reference: src/operator/roi_pooling.cc).

    TPU-native deviation: the reference max-pools over the exact integer
    pixels of each quantized bin (data-dependent bin sizes); here each
    bin is sampled on a static sub-grid DENSE ENOUGH that consecutive
    samples are <= 1 pixel apart for any ROI in the feature map
    (sg = ceil(H/ph) per side), so the nearest-pixel gather + max sees
    every pixel of every bin — equal to the reference max up to corner
    quantization.  Prefer ROIAlign for new models."""
    ph, pw = pooled_size
    n, c, h, w = data.shape
    batch_idx = rois[:, 0].astype(jnp.int32)
    # quantize roi corners like the reference (round to pixels)
    x1 = jnp.round(rois[:, 1] * spatial_scale)
    y1 = jnp.round(rois[:, 2] * spatial_scale)
    x2 = jnp.round(rois[:, 3] * spatial_scale)
    y2 = jnp.round(rois[:, 4] * spatial_scale)
    bin_h = jnp.maximum(y2 - y1 + 1, 1.0) / ph
    bin_w = jnp.maximum(x2 - x1 + 1, 1.0) / pw
    # sub-samples per bin side: max bin size is H/ph (W/pw) pixels, so
    # this guarantees <=1px sample spacing for any ROI
    sgy = max(2, -(-h // ph))
    sgx = max(2, -(-w // pw))
    iy = (jnp.arange(ph * sgy) + 0.5) / sgy    # (ph*sgy,) in bin units
    ix = (jnp.arange(pw * sgx) + 0.5) / sgx
    ys = y1[:, None] + iy[None, :] * bin_h[:, None]     # (R, ph*sgy)
    xs = x1[:, None] + ix[None, :] * bin_w[:, None]     # (R, pw*sgx)
    yi = jnp.clip(jnp.floor(ys), 0, h - 1).astype(jnp.int32)
    xi = jnp.clip(jnp.floor(xs), 0, w - 1).astype(jnp.int32)
    imgs = data[batch_idx]                     # (R, C, H, W)
    rows = jnp.take_along_axis(
        imgs, yi[:, None, :, None], axis=2)    # (R, C, ph*sgy, W)
    vals = jnp.take_along_axis(
        rows, xi[:, None, None, :], axis=3)    # (R, C, ph*sgy, pw*sgx)
    R = vals.shape[0]
    vals = vals.reshape(R, c, ph, sgy, pw, sgx)
    return vals.max(axis=(3, 5))
