"""Object-detection operators: the SSD MultiBox family.

Reference: ``src/operator/contrib/multibox_prior.cc`` /
``multibox_target.cc`` / ``multibox_detection.cc`` (SURVEY.md §2.1
operator-library contrib subtree; consumed by ``example/ssd``).

TPU-native redesign: the reference runs per-box scalar loops on
CPU/GPU threads; here every stage is expressed as dense, statically
shaped array math — IoU matrices as one broadcast op, bipartite gt
matching as a masked argmax sweep over the (small) gt count, and NMS as
a ``lax.fori_loop`` of suppress-the-max rounds — so the whole pipeline
compiles into a handful of fused XLA kernels and works under ``jit``.

Layout contracts (match the reference):
  anchors   : (1, N, 4) corner-format [xmin, ymin, xmax, ymax], normalized
  labels    : (B, M, 5) rows [cls, xmin, ymin, xmax, ymax]; cls < 0 pads
  cls_pred  : (B, num_cls+1, N) — class 0 is background
  loc_pred  : (B, N*4) center-format offsets scaled by ``variances``
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import register

__all__ = ["MultiBoxPrior", "MultiBoxTarget", "MultiBoxDetection"]


def _corner_to_center(boxes):
    """[xmin,ymin,xmax,ymax] -> (cx, cy, w, h) along last axis."""
    xmin, ymin, xmax, ymax = jnp.split(boxes, 4, axis=-1)
    w = xmax - xmin
    h = ymax - ymin
    return xmin + w / 2, ymin + h / 2, w, h


def _iou_matrix(a, b):
    """IoU between corner boxes a (N,4) and b (M,4) -> (N, M)."""
    ax0, ay0, ax1, ay1 = [a[:, i, None] for i in range(4)]
    bx0, by0, bx1, by1 = [b[None, :, i] for i in range(4)]
    ix = jnp.clip(jnp.minimum(ax1, bx1) - jnp.maximum(ax0, bx0), 0.0)
    iy = jnp.clip(jnp.minimum(ay1, by1) - jnp.maximum(ay0, by0), 0.0)
    inter = ix * iy
    area_a = jnp.clip(ax1 - ax0, 0.0) * jnp.clip(ay1 - ay0, 0.0)
    area_b = jnp.clip(bx1 - bx0, 0.0) * jnp.clip(by1 - by0, 0.0)
    return inter / jnp.maximum(area_a + area_b - inter, 1e-12)


@register("_contrib_MultiBoxPrior", aliases=["MultiBoxPrior"],
          differentiable=False)
def MultiBoxPrior(data, *, sizes=(1.0,), ratios=(1.0,), clip=False,
                  steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Anchor boxes for one feature map (reference: multibox_prior.cc).

    ``data`` is (B, C, H, W); output (1, H*W*(S+R-1), 4) corner boxes:
    per cell, one box per size plus one box per extra ratio at sizes[0]
    — the reference's exact enumeration order.  Widths carry the
    reference's ``H/W`` aspect factor so a ratio-1 box is square in
    IMAGE space, not in normalized coordinates (multibox_prior.cc:
    ``w = size * in_h / in_w / 2``).
    """
    sizes = tuple(float(s) for s in (sizes if isinstance(sizes, (tuple, list))
                                     else (sizes,)))
    ratios = tuple(float(r) for r in
                   (ratios if isinstance(ratios, (tuple, list))
                    else (ratios,)))
    H, W = data.shape[2], data.shape[3]
    step_y = 1.0 / H if steps[0] <= 0 else float(steps[0])
    step_x = 1.0 / W if steps[1] <= 0 else float(steps[1])
    cy = (jnp.arange(H, dtype=jnp.float32) + float(offsets[0])) * step_y
    cx = (jnp.arange(W, dtype=jnp.float32) + float(offsets[1])) * step_x
    cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")       # (H, W)

    aspect = float(H) / float(W)
    half = []
    for s in sizes:
        half.append((s * aspect / 2.0, s / 2.0))
    for r in ratios[1:]:
        rs = float(np.sqrt(r))
        half.append((sizes[0] * aspect * rs / 2.0, sizes[0] / rs / 2.0))
    hw = jnp.asarray([w for w, _ in half], jnp.float32)   # (K,)
    hh = jnp.asarray([h for _, h in half], jnp.float32)

    cxg = cxg[:, :, None]
    cyg = cyg[:, :, None]
    boxes = jnp.stack([cxg - hw, cyg - hh, cxg + hw, cyg + hh],
                      axis=-1)                            # (H, W, K, 4)
    out = boxes.reshape(1, -1, 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out.astype(jnp.float32)


@register("_contrib_MultiBoxTarget", num_inputs=3, num_outputs=3,
          aliases=["MultiBoxTarget"], differentiable=False)
def MultiBoxTarget(anchor, label, cls_pred, *, overlap_threshold=0.5,
                   ignore_label=-1.0, negative_mining_ratio=-1.0,
                   negative_mining_thresh=0.5,
                   minimum_negative_samples=0,
                   variances=(0.1, 0.1, 0.2, 0.2)):
    """Anchor-to-ground-truth matching + box-offset encoding
    (reference: multibox_target.cc).

    Returns [box_target (B, N*4), box_mask (B, N*4), cls_target (B, N)].
    Matching: each gt claims its best anchor (bipartite sweep), then any
    anchor with IoU > overlap_threshold joins its argmax gt.  With
    ``negative_mining_ratio > 0`` only the hardest
    ``ratio * num_pos`` negatives (lowest predicted background score
    among those under ``negative_mining_thresh`` IoU) keep cls_target 0;
    the rest become ``ignore_label``.
    """
    anchors = anchor.reshape(-1, 4)                       # (N, 4)
    N = anchors.shape[0]
    M = label.shape[1]
    var = jnp.asarray(variances, jnp.float32)
    acx, acy, aw, ah = _corner_to_center(anchors)

    def one_sample(lab, cpred):
        valid = lab[:, 0] >= 0                            # (M,)
        gt = lab[:, 1:5]
        iou = _iou_matrix(anchors, gt)                    # (N, M)
        iou = jnp.where(valid[None, :], iou, -1.0)

        # bipartite: each valid gt grabs its best anchor, sequentially
        # masking claimed anchors (reference's greedy matching)
        def bip_body(j, carry):
            match, claimed = carry                        # (N,), (N,)
            col = jnp.where(claimed, -1.0, iou[:, j])
            best = jnp.argmax(col)
            ok = valid[j] & (col[best] > 1e-12)
            match = jnp.where(
                ok, match.at[best].set(j), match)
            claimed = jnp.where(
                ok, claimed.at[best].set(True), claimed)
            return match, claimed

        match = jnp.full((N,), -1, jnp.int32)
        claimed = jnp.zeros((N,), bool)
        match, claimed = lax.fori_loop(0, M, bip_body, (match, claimed))

        # threshold matching for the rest
        best_gt = jnp.argmax(iou, axis=1).astype(jnp.int32)
        best_iou = jnp.max(iou, axis=1)
        match = jnp.where((match < 0) &
                          (best_iou > overlap_threshold),
                          best_gt, match)

        matched = match >= 0
        gt_cls = jnp.where(valid, lab[:, 0], 0.0)
        safe_match = jnp.clip(match, 0, M - 1)
        cls_t = jnp.where(matched, gt_cls[safe_match] + 1.0, 0.0)

        # hard-negative mining on the background score of cls_pred
        if negative_mining_ratio > 0:
            num_pos = matched.sum()
            max_neg = jnp.maximum(
                (negative_mining_ratio * num_pos).astype(jnp.int32),
                jnp.asarray(int(minimum_negative_samples), jnp.int32))
            is_neg = (~matched) & (best_iou < negative_mining_thresh)
            bg_score = cpred[0]                           # (N,)
            order = jnp.argsort(jnp.where(is_neg, bg_score, jnp.inf))
            rank = jnp.argsort(order)                     # rank per anchor
            keep_neg = is_neg & (rank < max_neg)
            cls_t = jnp.where(matched, cls_t,
                              jnp.where(keep_neg, 0.0,
                                        float(ignore_label)))

        # encode offsets for matched anchors (center format, variances)
        g = gt[safe_match]                                # (N, 4)
        gcx, gcy, gw, gh = _corner_to_center(g)
        eps = 1e-12
        tx = (gcx - acx) / jnp.maximum(aw, eps) / var[0]
        ty = (gcy - acy) / jnp.maximum(ah, eps) / var[1]
        tw = jnp.log(jnp.maximum(gw / jnp.maximum(aw, eps), eps)) / var[2]
        th = jnp.log(jnp.maximum(gh / jnp.maximum(ah, eps), eps)) / var[3]
        t = jnp.concatenate([tx, ty, tw, th], axis=-1)    # (N, 4)
        mask = jnp.where(matched[:, None], 1.0, 0.0)
        return (t * mask).reshape(-1), \
            jnp.broadcast_to(mask, (N, 4)).reshape(-1), cls_t

    box_t, box_m, cls_t = jax.vmap(one_sample)(label, cls_pred)
    return box_t, box_m, cls_t


@register("_contrib_MultiBoxDetection", num_inputs=3,
          aliases=["MultiBoxDetection"], differentiable=False)
def MultiBoxDetection(cls_prob, loc_pred, anchor, *, clip=True,
                      threshold=0.01, background_id=0, nms_threshold=0.5,
                      force_suppress=False,
                      variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """Decode + per-class NMS (reference: multibox_detection.cc).

    Output (B, N, 6): rows [cls_id, score, xmin, ymin, xmax, ymax];
    suppressed / below-threshold rows have cls_id -1, sorted by score.
    """
    anchors = anchor.reshape(-1, 4)
    N = anchors.shape[0]
    var = jnp.asarray(variances, jnp.float32)
    acx, acy, aw, ah = _corner_to_center(anchors)

    def one_sample(cprob, lpred):
        loc = lpred.reshape(N, 4)
        cx = loc[:, 0:1] * var[0] * aw + acx
        cy = loc[:, 1:2] * var[1] * ah + acy
        w = jnp.exp(jnp.clip(loc[:, 2:3] * var[2], -10, 10)) * aw / 2
        h = jnp.exp(jnp.clip(loc[:, 3:4] * var[3], -10, 10)) * ah / 2
        boxes = jnp.concatenate([cx - w, cy - h, cx + w, cy + h], -1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)

        # best foreground class per anchor
        fg = jnp.concatenate(
            [cprob[:background_id], cprob[background_id + 1:]], axis=0)
        fg_ids = jnp.concatenate(
            [jnp.arange(background_id),
             jnp.arange(background_id + 1, cprob.shape[0])])
        best = jnp.argmax(fg, axis=0)                     # (N,)
        score = jnp.take_along_axis(fg, best[None, :], 0)[0]
        cls_id = fg_ids[best].astype(jnp.float32) - \
            jnp.where(fg_ids[best] > background_id, 1.0, 0.0)
        keep = score > threshold
        cls_id = jnp.where(keep, cls_id, -1.0)
        score = jnp.where(keep, score, 0.0)

        # sort by score descending; optional topk cutoff
        order = jnp.argsort(-score)
        cls_id = cls_id[order]
        score = score[order]
        boxes = boxes[order]
        if nms_topk > 0:
            idx = jnp.arange(N)
            cls_id = jnp.where(idx < nms_topk, cls_id, -1.0)

        iou = _iou_matrix(boxes, boxes)

        def nms_body(i, alive):
            # box i suppresses lower-scored overlapping boxes of its class
            same_cls = (cls_id == cls_id[i]) | bool(force_suppress)
            sup = (iou[i] > nms_threshold) & same_cls & \
                (jnp.arange(N) > i) & alive[i] & (cls_id[i] >= 0)
            return alive & ~sup

        alive = jnp.ones((N,), bool)
        alive = lax.fori_loop(0, N, nms_body, alive)
        cls_id = jnp.where(alive, cls_id, -1.0)
        return jnp.concatenate([cls_id[:, None], score[:, None], boxes],
                               axis=-1)

    return jax.vmap(one_sample)(cls_prob, loc_pred)
