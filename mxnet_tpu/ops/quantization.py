"""INT8 quantization operators.

Reference surface: ``src/operator/quantization/`` —
``_contrib_quantize`` / ``_contrib_quantize_v2`` / ``_contrib_dequantize`` /
``_contrib_requantize`` and the ``quantized_*`` compute ops
(``quantized_fully_connected.cc``, ``quantized_conv.cc``,
``quantized_pooling.cc``, ``quantized_flatten.cc``) — SURVEY.md 2.2
contrib/quantization row.

TPU-native redesign: the reference lowers these to cuDNN/oneDNN int8
primitives; here the int8 GEMM/conv lower to ``lax.dot_general`` /
``lax.conv_general_dilated`` with ``preferred_element_type=int32`` so XLA
drives the MXU in its native 8-bit multiply / 32-bit accumulate mode.
Quantize/dequantize are elementwise jnp that XLA fuses into the adjacent
op, so a quantize→gemm→dequantize sandwich is one kernel, not three.

Range convention (matches the reference's signed-int8 path): a tensor with
calibration range [min_r, max_r] uses the symmetric scale
``s = max(|min_r|, |max_r|) / 127`` and stores ``round(x / s)`` clipped to
[-127, 127]; int32 accumulators carry range ±(2^31-1)·s_a·s_b.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register

INT8_MAX = 127.0
INT32_MAX = 2147483647.0


def _sym_scale(min_r, max_r):
    """Symmetric int8 scale for a calibration range.

    A degenerate [0, 0] range (all-zero tensor — dead ReLU batch,
    zero-init param) gets scale 1/127 instead of 0: quantized values are
    still exactly 0, and downstream scale divisions (bias rescale,
    dequantize) stay finite instead of producing NaN/inf.
    """
    amax = jnp.maximum(jnp.abs(min_r), jnp.abs(max_r))
    return jnp.where(amax > 0, amax, 1.0) / INT8_MAX


@register("_contrib_quantize", num_inputs=3, num_outputs=3,
          differentiable=False, aliases=["quantize"])
def quantize(data, min_range, max_range, *, out_type: str = "int8"):
    """fp32 → int8 with an explicit calibration range (reference:
    quantize.cc).  Returns (qdata, min_output, max_output)."""
    if out_type != "int8":
        raise ValueError("only signed int8 quantization is supported "
                         "(uint8 has no MXU advantage on TPU)")
    mn = jnp.asarray(min_range, jnp.float32).reshape(())
    mx = jnp.asarray(max_range, jnp.float32).reshape(())
    scale = _sym_scale(mn, mx)
    q = jnp.clip(jnp.round(data / scale), -INT8_MAX, INT8_MAX)
    amax = scale * INT8_MAX
    return q.astype(jnp.int8), -amax, amax


@register("_contrib_quantize_v2", num_outputs=3, differentiable=False,
          aliases=["quantize_v2"])
def quantize_v2(data, *, out_type: str = "int8", min_calib_range=None,
                max_calib_range=None):
    """fp32 → int8; range from calibration if given, else from the data
    itself (reference: quantize_v2.cc)."""
    if min_calib_range is not None and max_calib_range is not None:
        mn = jnp.float32(min_calib_range)
        mx = jnp.float32(max_calib_range)
    else:
        mn = jnp.min(data).astype(jnp.float32)
        mx = jnp.max(data).astype(jnp.float32)
    return quantize(data, mn, mx, out_type=out_type)


@register("_contrib_dequantize", num_inputs=3, differentiable=False,
          aliases=["dequantize"])
def dequantize(qdata, min_range, max_range, *, out_type: str = "float32"):
    """int8/int32 → fp32 (reference: dequantize.cc)."""
    mn = jnp.asarray(min_range, jnp.float32).reshape(())
    mx = jnp.asarray(max_range, jnp.float32).reshape(())
    qmax = INT8_MAX if qdata.dtype == jnp.int8 else INT32_MAX
    scale = jnp.maximum(jnp.abs(mn), jnp.abs(mx)) / qmax
    return qdata.astype(jnp.float32) * scale


@register("_contrib_requantize", num_inputs=3, num_outputs=3,
          differentiable=False, aliases=["requantize"])
def requantize(qdata, min_range, max_range, *, min_calib_range=None,
               max_calib_range=None):
    """int32 → int8, narrowing to the calibrated (or observed) output range
    (reference: requantize.cc)."""
    mn = jnp.asarray(min_range, jnp.float32).reshape(())
    mx = jnp.asarray(max_range, jnp.float32).reshape(())
    in_scale = jnp.maximum(jnp.abs(mn), jnp.abs(mx)) / INT32_MAX
    real = qdata.astype(jnp.float32) * in_scale
    if min_calib_range is not None and max_calib_range is not None:
        omn = jnp.float32(min_calib_range)
        omx = jnp.float32(max_calib_range)
    else:
        omn = jnp.min(real)
        omx = jnp.max(real)
    out_scale = _sym_scale(omn, omx)
    q = jnp.clip(jnp.round(real / out_scale), -INT8_MAX, INT8_MAX)
    amax = out_scale * INT8_MAX
    return q.astype(jnp.int8), -amax, amax


def _int32_range(min_a, max_a, min_b, max_b):
    """Output range metadata for an int8×int8→int32 accumulation."""
    s = _sym_scale(min_a, max_a) * _sym_scale(min_b, max_b)
    amax = s * INT32_MAX
    return -amax, amax


def _rescale_bias(bias_q, min_bias, max_bias, out_scale):
    """int8 bias → int32-accumulator units (reference: the FC kernel's
    bias shift in quantized_fully_connected.cc)."""
    s_b = _sym_scale(jnp.asarray(min_bias, jnp.float32),
                     jnp.asarray(max_bias, jnp.float32))
    return jnp.round(bias_q.astype(jnp.float32) * (s_b / out_scale)
                     ).astype(jnp.int32)


@register("_contrib_quantized_fully_connected", num_inputs=9, num_outputs=3,
          differentiable=False, aliases=["quantized_fully_connected"])
def quantized_fully_connected(data, weight, bias, min_data, max_data,
                              min_weight, max_weight, min_bias, max_bias, *,
                              num_hidden: int = 0, no_bias: bool = False,
                              flatten: bool = True):
    """int8 FC: int8×int8 → int32 on the MXU
    (reference: quantized_fully_connected.cc).  Inputs follow the reference
    9-tensor convention; returns (out_int32, min_out, max_out)."""
    x = data.reshape(data.shape[0], -1) if flatten else data
    out = lax.dot_general(x, weight,
                          (((x.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    mn_d = jnp.asarray(min_data, jnp.float32).reshape(())
    mx_d = jnp.asarray(max_data, jnp.float32).reshape(())
    mn_w = jnp.asarray(min_weight, jnp.float32).reshape(())
    mx_w = jnp.asarray(max_weight, jnp.float32).reshape(())
    omn, omx = _int32_range(mn_d, mx_d, mn_w, mx_w)
    if not no_bias and bias is not None:
        out_scale = _sym_scale(mn_d, mx_d) * _sym_scale(mn_w, mx_w)
        out = out + _rescale_bias(bias, min_bias, max_bias, out_scale)
    return out, omn, omx


@register("_contrib_quantized_conv", num_inputs=9, num_outputs=3,
          differentiable=False, aliases=["quantized_conv"])
def quantized_conv(data, weight, bias, min_data, max_data, min_weight,
                   max_weight, min_bias, max_bias, *, kernel=(), stride=(),
                   dilate=(), pad=(), num_filter: int = 0,
                   num_group: int = 1, no_bias: bool = False,
                   layout: str = "NCHW"):
    """int8 conv: 8-bit multiply / 32-bit accumulate
    (reference: quantized_conv.cc)."""
    ndim = data.ndim - 2
    stride = tuple(stride) or (1,) * ndim
    dilate = tuple(dilate) or (1,) * ndim
    pad = tuple(pad) or (0,) * ndim
    out = lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    mn_d = jnp.asarray(min_data, jnp.float32).reshape(())
    mx_d = jnp.asarray(max_data, jnp.float32).reshape(())
    mn_w = jnp.asarray(min_weight, jnp.float32).reshape(())
    mx_w = jnp.asarray(max_weight, jnp.float32).reshape(())
    omn, omx = _int32_range(mn_d, mx_d, mn_w, mx_w)
    if not no_bias and bias is not None:
        out_scale = _sym_scale(mn_d, mx_d) * _sym_scale(mn_w, mx_w)
        b = _rescale_bias(bias, min_bias, max_bias, out_scale)
        out = out + b.reshape((1, -1) + (1,) * ndim)
    return out, omn, omx


@register("_contrib_quantized_pooling", num_inputs=3, num_outputs=3,
          differentiable=False, aliases=["quantized_pooling"])
def quantized_pooling(data, min_data, max_data, *, kernel=(), stride=(),
                      pad=(), pool_type: str = "max",
                      global_pool: bool = False):
    """Pooling straight on int8 — range is preserved
    (reference: quantized_pooling.cc)."""
    ndim = data.ndim - 2
    if global_pool:
        kernel = data.shape[2:]
        stride = (1,) * ndim
        pad = (0,) * ndim
    stride = tuple(stride) or (1,) * ndim
    pad = tuple(pad) or (0,) * ndim
    dims = (1, 1) + tuple(kernel)
    strides = (1, 1) + tuple(stride)
    padding = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)
    if pool_type == "max":
        # init value must carry the operand dtype (a bare python int
        # trips reduce_window's dtype check for int8 operands)
        out = lax.reduce_window(
            data, jnp.array(jnp.iinfo(jnp.int8).min, data.dtype), lax.max,
            dims, strides, padding)
    elif pool_type == "avg":
        s = lax.reduce_window(data.astype(jnp.int32),
                              jnp.array(0, jnp.int32), lax.add,
                              dims, strides, padding)
        n = 1
        for k in kernel:
            n *= int(k)
        out = (s // n).astype(jnp.int8)
    else:
        raise ValueError(f"unsupported quantized pool_type {pool_type!r}")
    return out, jnp.asarray(min_data, jnp.float32).reshape(()), \
        jnp.asarray(max_data, jnp.float32).reshape(())


@register("_contrib_quantized_flatten", num_inputs=3, num_outputs=3,
          differentiable=False, aliases=["quantized_flatten"])
def quantized_flatten(data, min_data, max_data):
    """Flatten on int8 (reference: quantized_flatten.cc)."""
    return (data.reshape(data.shape[0], -1),
            jnp.asarray(min_data, jnp.float32).reshape(()),
            jnp.asarray(max_data, jnp.float32).reshape(()))


@register("_contrib_quantized_act", num_inputs=3, num_outputs=3,
          differentiable=False, aliases=["quantized_act"])
def quantized_act(data, min_data, max_data, *, act_type: str = "relu"):
    """ReLU on int8: clamp at zero, range maps to [0, max]
    (reference: quantized_activation.cc)."""
    if act_type != "relu":
        raise ValueError("only relu is supported on the int8 path")
    mn = jnp.asarray(min_data, jnp.float32).reshape(())
    mx = jnp.asarray(max_data, jnp.float32).reshape(())
    return jnp.maximum(data, 0), jnp.zeros_like(mn), jnp.maximum(mx, 0.0)
