"""Operator library: importing this package registers every op.

Reference: ``src/operator/`` registration via NNVM_REGISTER_OP static
initializers; here registration runs at import of the submodules.
"""
from . import registry
from .registry import register, get_op, list_ops, invoke, OP_REGISTRY

from . import tensor      # noqa: F401  elemwise/broadcast/reduce/shape/index
from . import nn          # noqa: F401  Convolution/BatchNorm/RNN/...
from . import linalg      # noqa: F401  gemm/potrf/trsm
from . import optimizer_ops  # noqa: F401  fused sgd/adam/lamb updates
from . import contrib     # noqa: F401  transformer kernels, roialign, ...
from . import detection   # noqa: F401  SSD MultiBox prior/target/detection
from . import moe         # noqa: F401  MoE routing + expert FFN (GShard)
from . import quantization  # noqa: F401  int8 quantize/dequantize/qgemm
from . import pallas_kernels  # noqa: F401  flash attention (TPU/interpret)
from .. import random as _random_ops  # noqa: F401  sampling ops
