"""Linear-algebra operators (reference: src/operator/tensor/la_op.cc —
``_linalg_*`` family over LAPACK/cuSolver).  XLA provides all decompositions
natively on TPU."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


@register("_linalg_gemm", num_inputs=3, aliases=["linalg_gemm"])
def linalg_gemm(A, B, C, *, transpose_a: bool = False,
                transpose_b: bool = False, alpha: float = 1.0,
                beta: float = 1.0, axis: int = -2):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b) + beta * C


@register("_linalg_gemm2", num_inputs=2, aliases=["linalg_gemm2"])
def linalg_gemm2(A, B, *, transpose_a: bool = False, transpose_b: bool = False,
                 alpha: float = 1.0, axis: int = -2):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b)


@register("_linalg_potrf", aliases=["linalg_potrf"])
def linalg_potrf(A):
    """Cholesky factor (lower)."""
    return jnp.linalg.cholesky(A)


@register("_linalg_potri", aliases=["linalg_potri"])
def linalg_potri(A):
    """Inverse from Cholesky factor: inv(L L^T)."""
    n = A.shape[-1]
    eye = jnp.broadcast_to(jnp.eye(n, dtype=A.dtype), A.shape)
    linv = jax.scipy.linalg.solve_triangular(A, eye, lower=True)
    return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)


@register("_linalg_trsm", num_inputs=2, aliases=["linalg_trsm"])
def linalg_trsm(A, B, *, transpose: bool = False, rightside: bool = False,
                lower: bool = True, alpha: float = 1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    low = lower != transpose
    if rightside:
        x = jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(a, -1, -2), jnp.swapaxes(alpha * B, -1, -2),
            lower=not low)
        return jnp.swapaxes(x, -1, -2)
    return jax.scipy.linalg.solve_triangular(a, alpha * B, lower=low)


@register("_linalg_trmm", num_inputs=2, aliases=["linalg_trmm"])
def linalg_trmm(A, B, *, transpose: bool = False, rightside: bool = False,
                lower: bool = True, alpha: float = 1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    tri = jnp.tril(a) if lower != transpose else jnp.triu(a)
    return alpha * (jnp.matmul(B, tri) if rightside else jnp.matmul(tri, B))


@register("_linalg_syrk", aliases=["linalg_syrk"])
def linalg_syrk(A, *, transpose: bool = False, alpha: float = 1.0):
    a_t = jnp.swapaxes(A, -1, -2)
    return alpha * (jnp.matmul(a_t, A) if transpose else jnp.matmul(A, a_t))


@register("_linalg_sumlogdiag", aliases=["linalg_sumlogdiag"])
def linalg_sumlogdiag(A):
    return jnp.sum(jnp.log(jnp.diagonal(A, axis1=-2, axis2=-1)), axis=-1)


@register("_linalg_extractdiag", aliases=["linalg_extractdiag"])
def linalg_extractdiag(A, *, offset: int = 0):
    return jnp.diagonal(A, offset=offset, axis1=-2, axis2=-1)


@register("_linalg_makediag", aliases=["linalg_makediag"])
def linalg_makediag(A, *, offset: int = 0):
    return jnp.apply_along_axis(lambda v: jnp.diag(v, offset), -1, A)


@register("_linalg_det", aliases=["linalg_det"])
def linalg_det(A):
    return jnp.linalg.det(A)


@register("_linalg_slogdet", num_outputs=2, aliases=["linalg_slogdet"])
def linalg_slogdet(A):
    sign, logdet = jnp.linalg.slogdet(A)
    return sign, logdet


@register("_linalg_inverse", aliases=["linalg_inverse"])
def linalg_inverse(A):
    return jnp.linalg.inv(A)


@register("_linalg_gelqf", num_outputs=2, aliases=["linalg_gelqf"])
def linalg_gelqf(A):
    """LQ factorization (via QR of A^T)."""
    q, r = jnp.linalg.qr(jnp.swapaxes(A, -1, -2))
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


@register("_linalg_syevd", num_outputs=2, aliases=["linalg_syevd"])
def linalg_syevd(A):
    w, v = jnp.linalg.eigh(A)
    return jnp.swapaxes(v, -1, -2), w
