"""Compiled batched beam search for the Transformer (VERDICT r2 item 4).

The reference's Sockeye-facing surface implies decode THROUGHPUT: beam
search must be a compiled program, not a host loop.  This module runs the
whole search — incremental decoder with per-layer KV caches, beam
bookkeeping, early exit — as ONE ``jax.jit``-ed ``lax.while_loop`` over
(batch, beam), compiled once per (B, K, Ls, max_len) signature.

Design (TPU-first):
- static shapes everywhere: the target buffer is (B, K, max_len+1); the
  self-attention KV cache is written with ``dynamic_update_slice`` and
  masked by position, so XLA sees fixed shapes and keeps the matmuls on
  the MXU.
- the encoder runs once through the normal (hybridizable) path; the
  decoder is re-expressed functionally here over the SAME Parameter
  arrays, passed as program INPUTS (weight updates never force a
  retrace; ``refresh()`` re-snapshots after ``load_parameters``).
- beam ranking uses raw cumulative log-probs during the search and GNMT
  length normalization ``((5+len)/6)**alpha`` for the final pick
  (fairseq-style; the reference's per-step normalized pruning differs
  only on near-tie beams).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from .. import ndarray as nd
from ..base import MXNetError

__all__ = ["TransformerBeamDecoder"]

NEG_INF = -1e9


def _dense(x, w, b):
    return x @ w.T + b


def _ln(x, g, b, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * g + b


def _decode_step(params, H, x, caches_k, caches_v, t, mem_k, mem_v,
                 mem_mask):
    """One incremental decoder step.

    x: (BK, C) current-position embedding (scaled + positioned).
    caches: per-layer (BK, H, Tmax, D).  mem_k/v: per-layer
    (BK, H, Ls, D).  Returns (logits (BK, V), new caches).
    """
    BK, C = x.shape
    D = C // H
    Tmax = caches_k[0].shape[2]
    pos_ok = (jnp.arange(Tmax)[None, None, :] <= t)          # (1,1,Tmax)
    new_k, new_v = [], []
    for li, cp in enumerate(params["cells"]):
        # masked self-attention with KV cache (interleaved layout:
        # per head [q|k|v] — ops/contrib.py contract)
        qkv = _dense(x, cp["qkv_w"], cp["qkv_b"]).reshape(BK, H, 3, D)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        ck = lax.dynamic_update_slice(
            caches_k[li], k[:, :, None, :], (0, 0, t, 0))
        cv = lax.dynamic_update_slice(
            caches_v[li], v[:, :, None, :], (0, 0, t, 0))
        new_k.append(ck)
        new_v.append(cv)
        s = jnp.einsum("bhd,bhtd->bht", q / math.sqrt(D), ck)
        s = jnp.where(pos_ok, s, NEG_INF)
        att = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bht,bhtd->bhd", att, cv).reshape(BK, C)
        h = _dense(o, cp["so_w"], cp["so_b"])
        h = _ln(x + h, cp["sn_g"], cp["sn_b"])
        # cross attention over the (precomputed) encoder memory
        cq = _dense(h, cp["q_w"], cp["q_b"]).reshape(BK, H, D)
        cs = jnp.einsum("bhd,bhsd->bhs", cq / math.sqrt(D), mem_k[li])
        cs = cs + mem_mask                                   # (BK,1,Ls)
        catt = jax.nn.softmax(cs, axis=-1)
        co = jnp.einsum("bhs,bhsd->bhd", catt, mem_v[li]).reshape(BK, C)
        c = _dense(co, cp["co_w"], cp["co_b"])
        c = _ln(h + c, cp["cn_g"], cp["cn_b"])
        # post-norm relu FFN
        f = jax.nn.relu(_dense(c, cp["f1_w"], cp["f1_b"]))
        f = _dense(f, cp["f2_w"], cp["f2_b"])
        x = _ln(c + f, cp["fn_g"], cp["fn_b"])
    return _dense(x, params["proj_w"], params["proj_b"]), new_k, new_v


def _make_search(H, C, n_layers, B, K, Ls, max_len, bos, eos, alpha):
    D = C // H
    scale = math.sqrt(C)

    def search(params, mem, src_valid):
        # mem: (Ls, B, C); precompute per-layer cross K/V, expanded to
        # beams: (B*K, H, Ls, D)
        mem_k, mem_v = [], []
        for cp in params["cells"]:
            kv = _dense(mem, cp["kv_w"], cp["kv_b"])         # (Ls,B,2C)
            kv = kv.reshape(Ls, B, H, 2, D)
            k = kv[:, :, :, 0].transpose(1, 2, 0, 3)         # (B,H,Ls,D)
            v = kv[:, :, :, 1].transpose(1, 2, 0, 3)
            mem_k.append(jnp.repeat(k, K, axis=0))           # (BK,H,Ls,D)
            mem_v.append(jnp.repeat(v, K, axis=0))
        ok = jnp.arange(Ls)[None, :] < src_valid[:, None]    # (B, Ls)
        mem_mask = jnp.where(jnp.repeat(ok, K, axis=0), 0.0,
                             NEG_INF)[:, None, :]            # (BK,1,Ls)

        tokens0 = jnp.full((B, K, max_len + 1), eos, jnp.int32)
        tokens0 = tokens0.at[:, :, 0].set(bos)
        # only beam 0 live at t=0 (identical beams would duplicate)
        scores0 = jnp.full((B, K), NEG_INF, jnp.float32)
        scores0 = scores0.at[:, 0].set(0.0)
        fin0 = jnp.zeros((B, K), bool)
        len0 = jnp.full((B, K), max_len, jnp.int32)
        ck0 = tuple(jnp.zeros((B * K, H, max_len, D), jnp.float32)
                    for _ in range(n_layers))
        cv0 = tuple(jnp.zeros((B * K, H, max_len, D), jnp.float32)
                    for _ in range(n_layers))
        eos_only = jnp.where(jnp.arange(params["proj_b"].shape[0]) == eos,
                             0.0, NEG_INF)                   # (V,)

        def cond(carry):
            t, _tok, _sc, fin, _ln_, _ck, _cv = carry
            return jnp.logical_and(t < max_len,
                                   jnp.logical_not(fin.all()))

        def body(carry):
            t, tokens, scores, finished, lens, ck, cv = carry
            cur = lax.dynamic_slice(
                tokens, (0, 0, t), (B, K, 1))[..., 0]        # (B,K)
            x = params["tgt_embed"][cur.reshape(-1)] * scale + \
                lax.dynamic_slice(params["pos"], (t, 0), (1, C))[0]
            logits, nk, nv = _decode_step(
                params, H, x, list(ck), list(cv), t, mem_k, mem_v,
                mem_mask)
            V = logits.shape[-1]
            logp = jax.nn.log_softmax(logits.reshape(B, K, V), -1)
            # finished beams only propose EOS at zero cost
            logp = jnp.where(finished[:, :, None], eos_only[None, None],
                             logp)
            total = scores[:, :, None] + logp                # (B,K,V)
            top, idx = lax.top_k(total.reshape(B, K * V), K)
            parent = idx // V                                # (B,K)
            tok = (idx % V).astype(jnp.int32)
            # gather beam state by parent
            batch_ix = jnp.arange(B)[:, None]
            tokens = tokens[batch_ix, parent]
            tokens = lax.dynamic_update_slice(
                tokens, tok[:, :, None], (0, 0, t + 1))
            fin_p = finished[batch_ix, parent]
            lens_p = lens[batch_ix, parent]
            newly = jnp.logical_and(jnp.logical_not(fin_p), tok == eos)
            lens = jnp.where(newly, t + 1, lens_p)
            finished = jnp.logical_or(fin_p, tok == eos)
            flat_parent = (batch_ix * K + parent).reshape(-1)
            ck = tuple(c[flat_parent] for c in nk)
            cv = tuple(c[flat_parent] for c in nv)
            return (t + 1, tokens, top, finished, lens, ck, cv)

        t, tokens, scores, finished, lens, _ck, _cv = lax.while_loop(
            cond, body,
            (jnp.int32(0), tokens0, scores0, fin0, len0, ck0, cv0))
        lens = jnp.where(finished, lens, t)                  # ran off end
        lp = ((5.0 + lens.astype(jnp.float32)) / 6.0) ** alpha
        best = jnp.argmax(scores / lp, axis=1)               # (B,)
        return tokens[jnp.arange(B), best], lens[jnp.arange(B), best]

    return jax.jit(search)


class TransformerBeamDecoder:
    """Compiled batched beam search over a ``models.Transformer``."""

    def __init__(self, model):
        self.model = model
        self._progs = {}
        self._srcs = None
        self.refresh()
        self._srcs = [p.data()._data
                      for p in model.collect_params().values()]

    def refresh(self):
        """Re-snapshot parameter arrays (call after load_parameters).
        Compiled programs survive — weights are program inputs."""
        m = self.model
        g = lambda p: p.data()._data.astype(jnp.float32)  # noqa: E731
        cells = []
        for cell in m.decoder.cells:
            sa, ca, ffn = (cell.self_attention, cell.cross_attention,
                           cell.ffn)
            cells.append(dict(
                qkv_w=g(sa.qkv.weight), qkv_b=g(sa.qkv.bias),
                so_w=g(sa.out_proj.weight), so_b=g(sa.out_proj.bias),
                sn_g=g(cell.self_norm.gamma), sn_b=g(cell.self_norm.beta),
                q_w=g(ca.q_proj.weight), q_b=g(ca.q_proj.bias),
                kv_w=g(ca.kv_proj.weight), kv_b=g(ca.kv_proj.bias),
                co_w=g(ca.out_proj.weight), co_b=g(ca.out_proj.bias),
                cn_g=g(cell.cross_norm.gamma),
                cn_b=g(cell.cross_norm.beta),
                f1_w=g(ffn.ffn_1.weight), f1_b=g(ffn.ffn_1.bias),
                f2_w=g(ffn.ffn_2.weight), f2_b=g(ffn.ffn_2.bias),
                fn_g=g(ffn.layer_norm.gamma), fn_b=g(ffn.layer_norm.beta),
            ))
        self.params = {
            "tgt_embed": g(m.tgt_embed.weight),
            "pos": m.decoder.pos_embed.data()._data.astype(jnp.float32),
            "proj_w": g(m.proj.weight), "proj_b": g(m.proj.bias),
            "cells": cells,
        }

    def _maybe_refresh(self):
        """Auto-refresh when any source Parameter buffer was replaced
        (trainer.step/set_data/load_parameters rebind arrays; identity
        comparison catches it with zero copies on the hot path)."""
        srcs = [p.data()._data
                for p in self.model.collect_params().values()]
        if getattr(self, "_srcs", None) is None or \
                len(srcs) != len(self._srcs) or \
                any(a is not b for a, b in zip(srcs, self._srcs)):
            self.refresh()
            self._srcs = srcs

    def __call__(self, src, src_valid=None, bos=2, eos=3, beam_size=4,
                 max_decode_len=32, alpha=0.6):
        """Beam-search decode.  Returns (B, max_decode_len+1) int32 ids
        (BOS first; positions past EOS hold EOS)."""
        self._maybe_refresh()
        m = self.model
        n_pos = int(self.params["pos"].shape[0])
        if int(max_decode_len) > n_pos:
            # the decode loop reads pos[t] for t in [0, max_decode_len-1];
            # beyond the table lax.dynamic_slice would silently clamp the
            # start index and reuse the last position embedding for every
            # further step — wrong decodes with no error
            raise MXNetError(
                f"max_decode_len={max_decode_len} exceeds the model's "
                f"positional table ({n_pos} positions); rebuild the model "
                f"with max_length >= {int(max_decode_len)} or decode "
                f"shorter sequences")
        B, Ls = src.shape
        from .. import autograd
        with autograd.pause(train_mode=False):
            mem = m.encode(src, src_valid)                   # (Ls, B, C)
        sv = (src_valid._data.astype(jnp.int32) if src_valid is not None
              else jnp.full((B,), Ls, jnp.int32))
        key = (B, int(beam_size), Ls, int(max_decode_len), int(bos),
               int(eos), float(alpha))
        prog = self._progs.get(key)
        if prog is None:
            prog = self._progs[key] = _make_search(
                m._num_heads, m._units, len(self.params["cells"]), B,
                int(beam_size), Ls, int(max_decode_len), int(bos),
                int(eos), float(alpha))
        ids, _lens = prog(self.params, mem._data.astype(jnp.float32), sv)
        return nd.NDArray(ids)
