"""In-tree model families.

Vision models live in gluon.model_zoo.vision (reference layout); BERT and
the NMT transformer lived in GluonNLP/Sockeye for the reference and are
in-tree here since they are baseline configs (BASELINE.md configs 3-5).
"""
from . import transformer_blocks
from . import bert
from . import transformer
from .bert import (BERTEncoder, BERTModel, BERTForPretrain,
                   BERTPretrainLoss, BERTForQA,
                   BERTClassifier, bert_12_768_12, bert_24_1024_16,
                   get_bert_model)
from .transformer import (Transformer, TransformerEncoder,
                          TransformerDecoder, transformer_base,
                          transformer_big, SmoothedSoftmaxCELoss)
from .transformer_blocks import TransformerDecoderLM

__all__ = ["BERTEncoder", "BERTModel", "BERTForPretrain",
           "BERTPretrainLoss", "BERTForQA",
           "BERTClassifier", "bert_12_768_12", "bert_24_1024_16",
           "get_bert_model", "Transformer", "TransformerEncoder",
           "TransformerDecoder", "transformer_base", "transformer_big",
           "SmoothedSoftmaxCELoss", "TransformerDecoderLM"]
