"""Shared transformer building blocks (GluonNLP-parity layers).

The reference core ships only the fused attention matmul kernels
(``src/operator/contrib/transformer.cc``); the model-level blocks lived in
GluonNLP.  Here both live in-tree: these HybridBlocks call the same
``_contrib_interleaved_matmul_*`` ops, so the attention math hits batched
MXU GEMMs, and under ``hybridize()``/pjit the whole cell fuses into one
XLA program.  For long sequences the same API can route to the Pallas
flash-attention kernel (ops/pallas_kernels.py) via ``use_flash``.
"""
from __future__ import annotations

import math

from ..base import MXNetError
from ..gluon import nn
from ..gluon.block import HybridBlock

__all__ = ["PositionwiseFFN", "MultiHeadSelfAttention",
           "MultiHeadAttention", "TransformerEncoderCell",
           "TransformerDecoderCell"]


class PositionwiseFFN(HybridBlock):
    """FFN(x) = W2 act(W1 x) with residual+LN (GluonNLP BERT layout)."""

    def __init__(self, units, hidden_size, dropout=0.0, activation="gelu",
                 layer_norm_eps=1e-5, pre_norm=False, **kwargs):
        super().__init__(**kwargs)
        self._pre_norm = pre_norm
        with self.name_scope():
            self.ffn_1 = nn.Dense(hidden_size, in_units=units,
                                  flatten=False, prefix="ffn_1_")
            self.ffn_2 = nn.Dense(units, in_units=hidden_size,
                                  flatten=False, prefix="ffn_2_")
            self.layer_norm = nn.LayerNorm(in_channels=units,
                                           epsilon=layer_norm_eps)
            self.dropout_layer = nn.Dropout(dropout)
        self._activation = activation

    def _act(self, F, x):
        if self._activation == "gelu":
            return F._contrib_gelu_erf(x)
        if self._activation == "gelu_tanh":
            return F._contrib_gelu_tanh(x)
        return F.Activation(x, act_type=self._activation)

    def hybrid_forward(self, F, x):
        residual = x
        if self._pre_norm:
            x = self.layer_norm(x)
        out = self.ffn_1(x)
        out = self._act(F, out)
        out = self.ffn_2(out)
        out = self.dropout_layer(out)
        out = out + residual
        if not self._pre_norm:
            out = self.layer_norm(out)
        return out


class MultiHeadSelfAttention(HybridBlock):
    """Self-attention over (L, B, C) via the interleaved qkv kernels
    (reference op: _contrib_interleaved_matmul_selfatt_qk/valatt).

    ``use_flash=True`` routes the qk→softmax→valatt chain to the fused
    Pallas flash-attention kernel (ops/pallas_kernels.py) whenever the
    mask is expressible as key valid-lengths (+ optional causal), i.e.
    ``mask is None``; an explicit additive ``mask`` falls back to the
    dense path.  The flash path has no attention-prob dropout (the score
    matrix never materializes); dropout is applied to the attention
    output instead.

    When to flip it (measured, BERT-large on one v5e chip, r3 kernel —
    bf16 MXU dots + tuned 512-wide blocks): at L=512 flash now edges out
    XLA's fused dense attention on step time (fwd+bwd ~6.4ms vs ~7.1ms
    per layer at B=8) and wins decisively at L=2048 (~6.8ms vs ~11.5ms
    at the same token count).  Flash also keeps its MEMORY advantage:
    at L=2048 the dense path OOMs a 16GB chip even at batch 1 (O(L^2)
    fp32 scores) while flash trains fine.  Default remains dense for
    L<=128-style short sequences; set use_flash=True from L~512 up,
    optionally combined with ring-attention context parallelism
    (parallel/ring_attention.py) beyond a single chip's length budget.
    """

    def __init__(self, units, num_heads, dropout=0.0, use_flash=False,
                 causal=False, window=None, **kwargs):
        super().__init__(**kwargs)
        if units % num_heads:
            raise MXNetError(f"units {units} not divisible by heads "
                             f"{num_heads}")
        if causal and not use_flash:
            raise MXNetError(
                "causal=True requires use_flash=True; on the dense path "
                "pass an explicit additive causal mask instead")
        if window is not None:
            if not (use_flash and causal):
                raise MXNetError(
                    "window (sliding-window attention) requires "
                    "use_flash=True and causal=True")
            if int(window) < 1:
                raise MXNetError(f"window must be >= 1, got {window}")
        self._units = units
        self._heads = num_heads
        self._use_flash = use_flash
        self._causal = causal
        self._window = -1 if window is None else int(window)
        with self.name_scope():
            self.qkv = nn.Dense(3 * units, in_units=units, flatten=False,
                                prefix="qkv_")
            self.out_proj = nn.Dense(units, in_units=units, flatten=False,
                                     prefix="out_proj_")
            self.dropout_layer = nn.Dropout(dropout)

    def hybrid_forward(self, F, x, mask=None, valid_length=None):
        # x: (L, B, C). qkv: (L, B, 3C) interleaved per head [q|k|v]
        qkv = self.qkv(x)
        if self._use_flash and mask is None:
            if valid_length is None:
                out = F.flash_selfatt_nomask(qkv, heads=self._heads,
                                             causal=self._causal,
                                             window=self._window)
            else:
                out = F.flash_selfatt(qkv, valid_length,
                                      heads=self._heads,
                                      causal=self._causal,
                                      window=self._window)
            return self.out_proj(self.dropout_layer(out))
        if self._window > 0:
            raise MXNetError(
                "window (sliding-window attention) is only honored on "
                "the flash path (mask=None); passing an explicit mask "
                "would silently drop the window — fold the window into "
                "the mask instead")
        if valid_length is not None:
            raise MXNetError(
                "valid_length is only consumed by the flash path "
                "(use_flash=True, mask=None); the dense path needs an "
                "explicit additive mask — it would otherwise be silently "
                "ignored")
        scores = F._contrib_interleaved_matmul_selfatt_qk(
            qkv, heads=self._heads)            # (B*H, L, L)
        if mask is not None:
            scores = scores + mask
        att = F.softmax(scores, axis=-1)
        att = self.dropout_layer(att)
        out = F._contrib_interleaved_matmul_selfatt_valatt(
            qkv, att, heads=self._heads)       # (L, B, C)
        return self.out_proj(out)


class MultiHeadAttention(HybridBlock):
    """Cross-attention: q from decoder (L_q,B,C), kv from memory
    (L_kv,B,C) via the encdec interleaved kernels."""

    def __init__(self, units, num_heads, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._heads = num_heads
        with self.name_scope():
            self.q_proj = nn.Dense(units, in_units=units, flatten=False,
                                   prefix="q_proj_")
            self.kv_proj = nn.Dense(2 * units, in_units=units,
                                    flatten=False, prefix="kv_proj_")
            self.out_proj = nn.Dense(units, in_units=units, flatten=False,
                                     prefix="out_proj_")
            self.dropout_layer = nn.Dropout(dropout)

    def hybrid_forward(self, F, x, mem, mask=None):
        q = self.q_proj(x)
        kv = self.kv_proj(mem)
        scores = F._contrib_interleaved_matmul_encdec_qk(
            q, kv, heads=self._heads)          # (B*H, L_q, L_kv)
        if mask is not None:
            scores = scores + mask
        att = F.softmax(scores, axis=-1)
        att = self.dropout_layer(att)
        out = F._contrib_interleaved_matmul_encdec_valatt(
            kv, att, heads=self._heads)
        return self.out_proj(out)


class TransformerEncoderCell(HybridBlock):
    """Post-norm transformer encoder layer (BERT layout)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 activation="gelu", layer_norm_eps=1e-5, pre_norm=False,
                 use_flash=False, **kwargs):
        super().__init__(**kwargs)
        self._pre_norm = pre_norm
        with self.name_scope():
            self.attention = MultiHeadSelfAttention(units, num_heads,
                                                    dropout,
                                                    use_flash=use_flash)
            self.attn_norm = nn.LayerNorm(in_channels=units,
                                          epsilon=layer_norm_eps)
            self.dropout_layer = nn.Dropout(dropout)
            self.ffn = PositionwiseFFN(units, hidden_size, dropout,
                                       activation, layer_norm_eps,
                                       pre_norm)

    def hybrid_forward(self, F, x, mask=None, valid_length=None):
        residual = x
        h = self.attn_norm(x) if self._pre_norm else x
        h = self.attention(h, mask, valid_length)
        h = self.dropout_layer(h)
        h = h + residual
        if not self._pre_norm:
            h = self.attn_norm(h)
        return self.ffn(h)


class TransformerDecoderCell(HybridBlock):
    """Decoder layer: masked self-att, cross-att, FFN."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 activation="relu", layer_norm_eps=1e-5, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.self_attention = MultiHeadSelfAttention(units, num_heads,
                                                         dropout)
            self.self_norm = nn.LayerNorm(in_channels=units,
                                          epsilon=layer_norm_eps)
            self.cross_attention = MultiHeadAttention(units, num_heads,
                                                      dropout)
            self.cross_norm = nn.LayerNorm(in_channels=units,
                                           epsilon=layer_norm_eps)
            self.dropout_layer = nn.Dropout(dropout)
            self.ffn = PositionwiseFFN(units, hidden_size, dropout,
                                       activation, layer_norm_eps)

    def hybrid_forward(self, F, x, mem, self_mask=None, mem_mask=None):
        h = self.self_attention(x, self_mask)
        h = self.self_norm(x + self.dropout_layer(h))
        c = self.cross_attention(h, mem, mem_mask)
        c = self.cross_norm(h + self.dropout_layer(c))
        return self.ffn(c)
