"""Shared transformer building blocks (GluonNLP-parity layers).

The reference core ships only the fused attention matmul kernels
(``src/operator/contrib/transformer.cc``); the model-level blocks lived in
GluonNLP.  Here both live in-tree: these HybridBlocks call the same
``_contrib_interleaved_matmul_*`` ops, so the attention math hits batched
MXU GEMMs, and under ``hybridize()``/pjit the whole cell fuses into one
XLA program.  For long sequences the same API can route to the Pallas
flash-attention kernel (ops/pallas_kernels.py) via ``use_flash``.
"""
from __future__ import annotations

import math

import numpy as np

from ..base import MXNetError
from ..gluon import nn
from ..gluon.block import HybridBlock

__all__ = ["PositionwiseFFN", "MultiHeadSelfAttention",
           "MultiHeadAttention", "TransformerEncoderCell",
           "TransformerDecoderCell", "TransformerDecoderLM",
           "paged_lm_params", "paged_prefill", "paged_decode_step",
           "paged_verify", "paged_verify_batch"]


class PositionwiseFFN(HybridBlock):
    """FFN(x) = W2 act(W1 x) with residual+LN (GluonNLP BERT layout)."""

    def __init__(self, units, hidden_size, dropout=0.0, activation="gelu",
                 layer_norm_eps=1e-5, pre_norm=False, **kwargs):
        super().__init__(**kwargs)
        self._pre_norm = pre_norm
        with self.name_scope():
            self.ffn_1 = nn.Dense(hidden_size, in_units=units,
                                  flatten=False, prefix="ffn_1_")
            self.ffn_2 = nn.Dense(units, in_units=hidden_size,
                                  flatten=False, prefix="ffn_2_")
            self.layer_norm = nn.LayerNorm(in_channels=units,
                                           epsilon=layer_norm_eps)
            self.dropout_layer = nn.Dropout(dropout)
        self._activation = activation

    def _act(self, F, x):
        if self._activation == "gelu":
            return F._contrib_gelu_erf(x)
        if self._activation == "gelu_tanh":
            return F._contrib_gelu_tanh(x)
        return F.Activation(x, act_type=self._activation)

    def hybrid_forward(self, F, x):
        residual = x
        if self._pre_norm:
            x = self.layer_norm(x)
        out = self.ffn_1(x)
        out = self._act(F, out)
        out = self.ffn_2(out)
        out = self.dropout_layer(out)
        out = out + residual
        if not self._pre_norm:
            out = self.layer_norm(out)
        return out


class MultiHeadSelfAttention(HybridBlock):
    """Self-attention over (L, B, C) via the interleaved qkv kernels
    (reference op: _contrib_interleaved_matmul_selfatt_qk/valatt).

    ``use_flash=True`` routes the qk→softmax→valatt chain to the fused
    Pallas flash-attention kernel (ops/pallas_kernels.py) whenever the
    mask is expressible as key valid-lengths (+ optional causal), i.e.
    ``mask is None``; an explicit additive ``mask`` falls back to the
    dense path.  The flash path has no attention-prob dropout (the score
    matrix never materializes); dropout is applied to the attention
    output instead.

    When to flip it (measured, BERT-large on one v5e chip, r3 kernel —
    bf16 MXU dots + tuned 512-wide blocks): at L=512 flash now edges out
    XLA's fused dense attention on step time (fwd+bwd ~6.4ms vs ~7.1ms
    per layer at B=8) and wins decisively at L=2048 (~6.8ms vs ~11.5ms
    at the same token count).  Flash also keeps its MEMORY advantage:
    at L=2048 the dense path OOMs a 16GB chip even at batch 1 (O(L^2)
    fp32 scores) while flash trains fine.  Default remains dense for
    L<=128-style short sequences; set use_flash=True from L~512 up,
    optionally combined with ring-attention context parallelism
    (parallel/ring_attention.py) beyond a single chip's length budget.
    """

    def __init__(self, units, num_heads, dropout=0.0, use_flash=False,
                 causal=False, window=None, **kwargs):
        super().__init__(**kwargs)
        if units % num_heads:
            raise MXNetError(f"units {units} not divisible by heads "
                             f"{num_heads}")
        if causal and not use_flash:
            raise MXNetError(
                "causal=True requires use_flash=True; on the dense path "
                "pass an explicit additive causal mask instead")
        if window is not None:
            if not (use_flash and causal):
                raise MXNetError(
                    "window (sliding-window attention) requires "
                    "use_flash=True and causal=True")
            if int(window) < 1:
                raise MXNetError(f"window must be >= 1, got {window}")
        self._units = units
        self._heads = num_heads
        self._use_flash = use_flash
        self._causal = causal
        self._window = -1 if window is None else int(window)
        with self.name_scope():
            self.qkv = nn.Dense(3 * units, in_units=units, flatten=False,
                                prefix="qkv_")
            self.out_proj = nn.Dense(units, in_units=units, flatten=False,
                                     prefix="out_proj_")
            self.dropout_layer = nn.Dropout(dropout)

    def hybrid_forward(self, F, x, mask=None, valid_length=None):
        # x: (L, B, C). qkv: (L, B, 3C) interleaved per head [q|k|v]
        qkv = self.qkv(x)
        if self._use_flash and mask is None:
            if valid_length is None:
                out = F.flash_selfatt_nomask(qkv, heads=self._heads,
                                             causal=self._causal,
                                             window=self._window)
            else:
                out = F.flash_selfatt(qkv, valid_length,
                                      heads=self._heads,
                                      causal=self._causal,
                                      window=self._window)
            return self.out_proj(self.dropout_layer(out))
        if self._window > 0:
            raise MXNetError(
                "window (sliding-window attention) is only honored on "
                "the flash path (mask=None); passing an explicit mask "
                "would silently drop the window — fold the window into "
                "the mask instead")
        if valid_length is not None:
            raise MXNetError(
                "valid_length is only consumed by the flash path "
                "(use_flash=True, mask=None); the dense path needs an "
                "explicit additive mask — it would otherwise be silently "
                "ignored")
        scores = F._contrib_interleaved_matmul_selfatt_qk(
            qkv, heads=self._heads)            # (B*H, L, L)
        if mask is not None:
            scores = scores + mask
        att = F.softmax(scores, axis=-1)
        att = self.dropout_layer(att)
        out = F._contrib_interleaved_matmul_selfatt_valatt(
            qkv, att, heads=self._heads)       # (L, B, C)
        return self.out_proj(out)


class MultiHeadAttention(HybridBlock):
    """Cross-attention: q from decoder (L_q,B,C), kv from memory
    (L_kv,B,C) via the encdec interleaved kernels."""

    def __init__(self, units, num_heads, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._heads = num_heads
        with self.name_scope():
            self.q_proj = nn.Dense(units, in_units=units, flatten=False,
                                   prefix="q_proj_")
            self.kv_proj = nn.Dense(2 * units, in_units=units,
                                    flatten=False, prefix="kv_proj_")
            self.out_proj = nn.Dense(units, in_units=units, flatten=False,
                                     prefix="out_proj_")
            self.dropout_layer = nn.Dropout(dropout)

    def hybrid_forward(self, F, x, mem, mask=None):
        q = self.q_proj(x)
        kv = self.kv_proj(mem)
        scores = F._contrib_interleaved_matmul_encdec_qk(
            q, kv, heads=self._heads)          # (B*H, L_q, L_kv)
        if mask is not None:
            scores = scores + mask
        att = F.softmax(scores, axis=-1)
        att = self.dropout_layer(att)
        out = F._contrib_interleaved_matmul_encdec_valatt(
            kv, att, heads=self._heads)
        return self.out_proj(out)


class TransformerEncoderCell(HybridBlock):
    """Post-norm transformer encoder layer (BERT layout)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 activation="gelu", layer_norm_eps=1e-5, pre_norm=False,
                 use_flash=False, **kwargs):
        super().__init__(**kwargs)
        self._pre_norm = pre_norm
        with self.name_scope():
            self.attention = MultiHeadSelfAttention(units, num_heads,
                                                    dropout,
                                                    use_flash=use_flash)
            self.attn_norm = nn.LayerNorm(in_channels=units,
                                          epsilon=layer_norm_eps)
            self.dropout_layer = nn.Dropout(dropout)
            self.ffn = PositionwiseFFN(units, hidden_size, dropout,
                                       activation, layer_norm_eps,
                                       pre_norm)

    def hybrid_forward(self, F, x, mask=None, valid_length=None):
        residual = x
        h = self.attn_norm(x) if self._pre_norm else x
        h = self.attention(h, mask, valid_length)
        h = self.dropout_layer(h)
        h = h + residual
        if not self._pre_norm:
            h = self.attn_norm(h)
        return self.ffn(h)


class TransformerDecoderCell(HybridBlock):
    """Decoder layer: masked self-att, cross-att, FFN."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 activation="relu", layer_norm_eps=1e-5, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.self_attention = MultiHeadSelfAttention(units, num_heads,
                                                         dropout)
            self.self_norm = nn.LayerNorm(in_channels=units,
                                          epsilon=layer_norm_eps)
            self.cross_attention = MultiHeadAttention(units, num_heads,
                                                      dropout)
            self.cross_norm = nn.LayerNorm(in_channels=units,
                                           epsilon=layer_norm_eps)
            self.dropout_layer = nn.Dropout(dropout)
            self.ffn = PositionwiseFFN(units, hidden_size, dropout,
                                       activation, layer_norm_eps)

    def hybrid_forward(self, F, x, mem, self_mask=None, mem_mask=None):
        h = self.self_attention(x, self_mask)
        h = self.self_norm(x + self.dropout_layer(h))
        c = self.cross_attention(h, mem, mem_mask)
        c = self.cross_norm(h + self.dropout_layer(c))
        return self.ffn(c)


# ---------------------------------------------------------------------------
# decoder-only LM + paged decode-mode forward (serving decode engine)
# ---------------------------------------------------------------------------
def _sinusoid_table(max_len, units):
    """Shared sinusoidal position table (also consumed by
    models/transformer.py — ONE copy of the formula)."""
    pos = np.arange(max_len)[:, None]
    dim = np.arange(units)[None, :]
    angle = pos / np.power(10000, (2 * (dim // 2)) / units)
    table = np.zeros((max_len, units), dtype=np.float32)
    table[:, 0::2] = np.sin(angle[:, 0::2])
    table[:, 1::2] = np.cos(angle[:, 1::2])
    return table


NEG_INF = -1e9


class TransformerDecoderLM(HybridBlock):
    """Decoder-only causal LM (GPT layout): embedding + sinusoid
    positions, pre-norm self-attention cells, final LayerNorm, vocab
    projection.

    Two forwards share the SAME parameters:

    - the hybridizable training/teacher-forcing forward here —
      ``lm(tokens (B, L)) -> logits (B, L, V)`` with an additive causal
      mask on the dense attention path;
    - the serving *decode-mode* forward — the pure-jax
      :func:`paged_prefill` / :func:`paged_decode_step` pair below,
      which threads K/V through the paged cache pool
      (``serving.kv_cache``) instead of rematerializing the whole
      prefix each step.  ``paged_lm_params(lm)`` snapshots the
      parameter arrays into the dict those functions consume.
    """

    def __init__(self, vocab_size, units=64, hidden_size=128,
                 num_layers=2, num_heads=2, max_length=128, dropout=0.0,
                 activation="relu", layer_norm_eps=1e-5, **kwargs):
        super().__init__(**kwargs)
        if units % num_heads:
            raise MXNetError(f"units {units} not divisible by heads "
                             f"{num_heads}")
        self.vocab_size = int(vocab_size)
        self.units = int(units)
        self.num_heads = int(num_heads)
        self.num_layers = int(num_layers)
        self.head_dim = self.units // self.num_heads
        self.max_context = int(max_length)
        self._activation = activation
        self._eps = layer_norm_eps
        with self.name_scope():
            self.embed = nn.Embedding(vocab_size, units)
            self.pos_embed = self.params.get_constant(
                "pos_embed", _sinusoid_table(max_length, units))
            self.dropout_layer = nn.Dropout(dropout)
            self.cells = nn.HybridSequential()
            for _ in range(num_layers):
                self.cells.add(TransformerEncoderCell(
                    units, hidden_size, num_heads, dropout,
                    activation=activation, layer_norm_eps=layer_norm_eps,
                    pre_norm=True))
            self.final_norm = nn.LayerNorm(in_channels=units,
                                           epsilon=layer_norm_eps)
            self.proj = nn.Dense(vocab_size, in_units=units,
                                 flatten=False)

    def hybrid_forward(self, F, tokens, pos_embed=None):
        # tokens: (B, L) int ids -> logits (B, L, V)
        from .. import ndarray as nd
        B, L = tokens.shape
        x = self.embed(tokens) * math.sqrt(self.units)      # (B, L, C)
        x = F.transpose(x, axes=(1, 0, 2))                  # (L, B, C)
        x = x + pos_embed.slice_axis(axis=0, begin=0,
                                     end=L).expand_dims(1)
        x = self.dropout_layer(x)
        steps = nd.arange(L)
        ok = F.broadcast_lesser_equal(steps.reshape((1, L)),
                                      steps.reshape((L, 1)))
        mask = (1.0 - ok) * NEG_INF                         # (L, L) causal
        for cell in self.cells:
            x = cell(x, mask)
        x = self.final_norm(x)
        logits = self.proj(x)                               # (L, B, V)
        return F.transpose(logits, axes=(1, 0, 2))

    def decode_meta(self, eos_id=None, draft=None, spec_k=None):
        """The decode-capable metadata block a serving/deploy manifest
        carries (``deploy.export_stablehlo(decode=...)``): everything an
        external runtime needs to size the paged KV cache and drive the
        step loop.

        ``draft`` (another :class:`TransformerDecoderLM`, or a plain
        dims dict) ships the speculative-decoding draft model's cache
        sizing next to the target's, and ``spec_k`` the proposal depth
        the deployment was tuned for (docs/serving.md §9) — so an
        external runtime can pre-size BOTH pools and the verify-program
        width before loading weights."""
        meta = {"vocab_size": self.vocab_size,
                "num_layers": self.num_layers,
                "num_heads": self.num_heads,
                "head_dim": self.head_dim,
                "max_context": self.max_context}
        if eos_id is not None:
            meta["eos_id"] = int(eos_id)
        if draft is not None:
            meta["draft"] = dict(draft) if isinstance(draft, dict) \
                else draft.decode_meta()
        if spec_k is not None:
            meta["spec_k"] = int(spec_k)
        return meta


def paged_lm_params(lm):
    """Snapshot a :class:`TransformerDecoderLM`'s parameters into the
    flat jnp dict :func:`paged_prefill` / :func:`paged_decode_step`
    consume.  Arrays are snapshots: later training does not mutate a
    served copy (re-snapshot to publish new weights), and weights enter
    compiled programs as INPUTS, so a refresh never retraces."""
    import jax.numpy as jnp

    def g(p):
        return p.data()._data.astype(jnp.float32)

    cells = []
    for cell in lm.cells:
        att, ffn = cell.attention, cell.ffn
        cells.append(dict(
            n1_g=g(cell.attn_norm.gamma), n1_b=g(cell.attn_norm.beta),
            qkv_w=g(att.qkv.weight), qkv_b=g(att.qkv.bias),
            o_w=g(att.out_proj.weight), o_b=g(att.out_proj.bias),
            n2_g=g(ffn.layer_norm.gamma), n2_b=g(ffn.layer_norm.beta),
            f1_w=g(ffn.ffn_1.weight), f1_b=g(ffn.ffn_1.bias),
            f2_w=g(ffn.ffn_2.weight), f2_b=g(ffn.ffn_2.bias),
        ))
    return {
        "embed": g(lm.embed.weight),
        "pos": lm.pos_embed.data()._data.astype(jnp.float32),
        "fn_g": g(lm.final_norm.gamma), "fn_b": g(lm.final_norm.beta),
        "proj_w": g(lm.proj.weight), "proj_b": g(lm.proj.bias),
        "cells": cells,
    }


def _f_ln(x, gamma, beta, eps=1e-5):
    import jax.numpy as jnp
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * gamma + beta


def _f_act(x, activation):
    import jax
    if activation == "relu":
        return jax.nn.relu(x)
    if activation in ("gelu", "gelu_erf"):
        return jax.nn.gelu(x, approximate=False)
    if activation == "gelu_tanh":
        return jax.nn.gelu(x, approximate=True)
    raise MXNetError(f"paged decode forward: unsupported activation "
                     f"{activation!r}")


def _f_ffn(x, cp, activation):
    h = _f_act(x @ cp["f1_w"].T + cp["f1_b"], activation)
    return h @ cp["f2_w"].T + cp["f2_b"]


def paged_prefill(params, tokens, length, block_table, k_pages, v_pages,
                  *, num_heads, page_size, activation="relu",
                  layer_norm_eps=1e-5):
    """Prefill ONE sequence and write its K/V into cache pages.

    ``tokens``: (1, L_bucket) int32, padded past ``length`` (a scalar);
    ``block_table``: (pages_per_seq,) int32 physical pages (null page 0
    in unused slots); ``k_pages``/``v_pages``: the full
    (layers, pool_pages, page_size, heads, head_dim) pools.  Attention
    over the fresh prompt is plain causal+padding-masked softmax (the
    prefix IS the whole context — no cache read yet); K/V of positions
    past ``length`` are routed to the null page.  Returns
    ``(last-token logits (V,), k_pages, v_pages)``.
    """
    import jax.numpy as jnp
    H = num_heads
    L = tokens.shape[1]
    C = params["embed"].shape[1]
    D = C // H
    x = params["embed"][tokens[0]] * math.sqrt(C) \
        + params["pos"][:L]                                 # (L, C)
    pos_idx = jnp.arange(L)
    valid = pos_idx < length                                # (L,)
    page_idx = jnp.where(valid, block_table[pos_idx // page_size], 0)
    slot_idx = pos_idx % page_size
    # causal + padding: key j visible to query i iff j <= i and j valid
    mask = (pos_idx[None, :] <= pos_idx[:, None]) \
        & valid[None, :]                                    # (L, L)
    for li, cp in enumerate(params["cells"]):
        h = _f_ln(x, cp["n1_g"], cp["n1_b"], layer_norm_eps)
        qkv = (h @ cp["qkv_w"].T + cp["qkv_b"]).reshape(L, H, 3, D)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        k_pages = k_pages.at[li, page_idx, slot_idx].set(
            k.astype(k_pages.dtype))
        v_pages = v_pages.at[li, page_idx, slot_idx].set(
            v.astype(v_pages.dtype))
        s = jnp.einsum("ihd,jhd->hij", q, k) / math.sqrt(D)
        s = jnp.where(mask[None], s, NEG_INF)
        p = jnp.exp(s - jnp.max(s, -1, keepdims=True))
        p = p / jnp.sum(p, -1, keepdims=True)
        o = jnp.einsum("hij,jhd->ihd", p, v).reshape(L, C)
        x = x + (o @ cp["o_w"].T + cp["o_b"])
        x = x + _f_ffn(_f_ln(x, cp["n2_g"], cp["n2_b"], layer_norm_eps),
                       cp, activation)
    x_last = x[length - 1]                                  # (C,)
    x_last = _f_ln(x_last, params["fn_g"], params["fn_b"],
                   layer_norm_eps)
    return (x_last @ params["proj_w"].T + params["proj_b"],
            k_pages, v_pages)


def paged_decode_step(params, tokens, positions, block_tables, k_pages,
                      v_pages, *, num_heads, page_size,
                      activation="relu", layer_norm_eps=1e-5,
                      attention_impl="jax"):
    """One decode step for the whole (fixed-size) decode batch.

    ``tokens``: (B,) int32 current token per slot; ``positions``: (B,)
    int32 write position (== context length so far); ``block_tables``:
    (B, pages_per_seq) int32.  Inactive slots carry token 0, position
    0, and an all-null block table — their K/V writes land in the null
    page and their logits are garbage the engine never reads.  Each
    layer writes the new token's K/V through the block table, then
    attends over the ragged paged context with the Pallas kernel
    (``attention_impl="pallas"``, TPU) or the pure-jax reference
    (``"jax"``, the CPU serving path).  Returns
    ``(logits (B, V), k_pages, v_pages)``.
    """
    import jax.numpy as jnp

    from ..ops import pallas_kernels as pk
    H = num_heads
    B = tokens.shape[0]
    C = params["embed"].shape[1]
    D = C // H
    x = params["embed"][tokens] * math.sqrt(C) \
        + params["pos"][positions]                          # (B, C)
    page = jnp.take_along_axis(
        block_tables, (positions // page_size)[:, None], axis=1)[:, 0]
    slot = positions % page_size
    ctx = positions + 1                                     # incl. new tok
    for li, cp in enumerate(params["cells"]):
        h = _f_ln(x, cp["n1_g"], cp["n1_b"], layer_norm_eps)
        qkv = (h @ cp["qkv_w"].T + cp["qkv_b"]).reshape(B, H, 3, D)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        k_pages = k_pages.at[li, page, slot].set(k.astype(k_pages.dtype))
        v_pages = v_pages.at[li, page, slot].set(v.astype(v_pages.dtype))
        if attention_impl == "pallas":
            o = pk.ragged_paged_attention(
                q, k_pages[li], v_pages[li], block_tables, ctx)
        else:
            o = pk.ragged_paged_attention_reference(
                q, k_pages[li], v_pages[li], block_tables, ctx)
        x = x + (o.reshape(B, C) @ cp["o_w"].T + cp["o_b"])
        x = x + _f_ffn(_f_ln(x, cp["n2_g"], cp["n2_b"], layer_norm_eps),
                       cp, activation)
    x = _f_ln(x, params["fn_g"], params["fn_b"], layer_norm_eps)
    return x @ params["proj_w"].T + params["proj_b"], k_pages, v_pages


def paged_verify(params, tokens, start, length, block_table, k_pages,
                 v_pages, *, num_heads, page_size, activation="relu",
                 layer_norm_eps=1e-5, attention_impl="jax"):
    """Multi-token window forward over a paged context: the ragged
    verification shape of speculative decoding, and the tail prefill of
    a prefix-cache hit (docs/serving.md §9).

    ``tokens``: (1, W_bucket) int32 window, padded past ``length``;
    ``start``: scalar global position of ``tokens[0, 0]`` (K/V of
    positions ``< start`` already sit in cache pages); ``block_table``:
    (pages_per_seq,) int32.  Writes K/V for the ``length`` valid window
    positions through the block table (padded positions route to the
    null page) and attends each window token causally over the FULL
    paged context up to itself — the prefill/multi-token path of
    ``ragged_paged_attention`` ("Ragged Paged Attention", PAPERS.md).
    Returns ``(logits (W_bucket, V), k_pages, v_pages)``; rows past
    ``length`` are zeros-in/garbage-out and must not be read.

    Equivalences the decode engine leans on: with ``start == 0`` and
    ``length == L`` this is :func:`paged_prefill` over a paged read
    path; with ``W == 1`` it recovers the last-token logits of an
    already-cached prefix; with the speculation window
    ``[last_sampled, draft_1..draft_k]`` it verifies all k+1 positions
    in ONE program call.
    """
    import jax.numpy as jnp

    from ..ops import pallas_kernels as pk
    H = num_heads
    W = tokens.shape[1]
    C = params["embed"].shape[1]
    D = C // H
    P = block_table.shape[0]
    offs = jnp.arange(W)
    pos = start + offs
    valid = offs < length                                   # (W,)
    max_pos = params["pos"].shape[0]
    x = params["embed"][tokens[0]] * math.sqrt(C) \
        + params["pos"][jnp.minimum(pos, max_pos - 1)]      # (W, C)
    page_idx = jnp.where(
        valid, block_table[jnp.minimum(pos // page_size, P - 1)], 0)
    slot_idx = pos % page_size
    starts = jnp.reshape(start, (1,)).astype(jnp.int32)
    lengths = jnp.reshape(length, (1,)).astype(jnp.int32)
    for li, cp in enumerate(params["cells"]):
        h = _f_ln(x, cp["n1_g"], cp["n1_b"], layer_norm_eps)
        qkv = (h @ cp["qkv_w"].T + cp["qkv_b"]).reshape(W, H, 3, D)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        k_pages = k_pages.at[li, page_idx, slot_idx].set(
            k.astype(k_pages.dtype))
        v_pages = v_pages.at[li, page_idx, slot_idx].set(
            v.astype(v_pages.dtype))
        if attention_impl == "pallas":
            o = pk.ragged_paged_verify(
                q[None], k_pages[li], v_pages[li], block_table[None],
                starts, lengths)[0]
        else:
            o = pk.ragged_paged_verify_reference(
                q[None], k_pages[li], v_pages[li], block_table[None],
                starts, lengths)[0]
        x = x + (o.reshape(W, C) @ cp["o_w"].T + cp["o_b"])
        x = x + _f_ffn(_f_ln(x, cp["n2_g"], cp["n2_b"], layer_norm_eps),
                       cp, activation)
    x = _f_ln(x, params["fn_g"], params["fn_b"], layer_norm_eps)
    return x @ params["proj_w"].T + params["proj_b"], k_pages, v_pages


def paged_verify_batch(params, tokens, starts, lengths, block_tables,
                       k_pages, v_pages, *, num_heads, page_size,
                       activation="relu", layer_norm_eps=1e-5,
                       attention_impl="jax"):
    """Batched :func:`paged_verify`: one fixed-shape program verifies
    every running sequence's speculation window in ONE device call —
    the ragged multi-token decode shape (docs/serving.md §9).

    ``tokens``: (B, W) int32 windows; ``starts``/``lengths``: (B,)
    int32 per-slot window origin and valid width (0 = inactive slot:
    null writes, zero rows); ``block_tables``: (B, pages_per_seq).
    Returns ``(logits (B, W, V), k_pages, v_pages)``; rows past a
    slot's ``lengths`` are garbage the engine never reads.
    """
    import jax.numpy as jnp

    from ..ops import pallas_kernels as pk
    H = num_heads
    B, W = tokens.shape
    C = params["embed"].shape[1]
    D = C // H
    P = block_tables.shape[1]
    offs = jnp.arange(W)[None, :]
    pos = starts[:, None] + offs                            # (B, W)
    valid = offs < lengths[:, None]                         # (B, W)
    max_pos = params["pos"].shape[0]
    x = params["embed"][tokens] * math.sqrt(C) \
        + params["pos"][jnp.minimum(pos, max_pos - 1)]      # (B, W, C)
    page_idx = jnp.where(
        valid,
        jnp.take_along_axis(block_tables,
                            jnp.minimum(pos // page_size, P - 1),
                            axis=1), 0)                     # (B, W)
    slot_idx = pos % page_size
    for li, cp in enumerate(params["cells"]):
        h = _f_ln(x, cp["n1_g"], cp["n1_b"], layer_norm_eps)
        qkv = (h @ cp["qkv_w"].T + cp["qkv_b"]).reshape(B, W, H, 3, D)
        q, k, v = qkv[:, :, :, 0], qkv[:, :, :, 1], qkv[:, :, :, 2]
        k_pages = k_pages.at[li, page_idx, slot_idx].set(
            k.astype(k_pages.dtype))
        v_pages = v_pages.at[li, page_idx, slot_idx].set(
            v.astype(v_pages.dtype))
        if attention_impl == "pallas":
            o = pk.ragged_paged_verify(
                q, k_pages[li], v_pages[li], block_tables, starts,
                lengths)
        else:
            o = pk.ragged_paged_verify_reference(
                q, k_pages[li], v_pages[li], block_tables, starts,
                lengths)
        x = x + (o.reshape(B, W, C) @ cp["o_w"].T + cp["o_b"])
        x = x + _f_ffn(_f_ln(x, cp["n2_g"], cp["n2_b"], layer_norm_eps),
                       cp, activation)
    x = _f_ln(x, params["fn_g"], params["fn_b"], layer_norm_eps)
    return x @ params["proj_w"].T + params["proj_b"], k_pages, v_pages
