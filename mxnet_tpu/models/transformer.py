"""Transformer seq2seq for NMT (Sockeye / transformer-big parity —
BASELINE.md config 4).

Encoder-decoder with sinusoidal positions, label smoothing helper, greedy
and beam-search decoding.  Decoding uses the bucketed compile-cache model
(SURVEY.md §2.4 P8): each (L_src, L_tgt) signature compiles once.
"""
from __future__ import annotations

import math

import numpy as np

from ..base import MXNetError
from .. import ndarray as nd
from ..gluon import nn
from ..gluon.block import HybridBlock
from .transformer_blocks import TransformerEncoderCell, \
    TransformerDecoderCell, _sinusoid_table

__all__ = ["TransformerEncoder", "TransformerDecoder", "Transformer",
           "transformer_big", "transformer_base",
           "SmoothedSoftmaxCELoss"]

NEG_INF = -1e9


class TransformerEncoder(HybridBlock):
    def __init__(self, units=512, hidden_size=2048, num_layers=6,
                 num_heads=8, dropout=0.1, max_length=1024, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._num_heads = num_heads
        with self.name_scope():
            self.pos_embed = self.params.get_constant(
                "pos_embed", _sinusoid_table(max_length, units))
            self.dropout_layer = nn.Dropout(dropout)
            self.cells = nn.HybridSequential()
            for _ in range(num_layers):
                self.cells.add(TransformerEncoderCell(
                    units, hidden_size, num_heads, dropout,
                    activation="relu"))

    def hybrid_forward(self, F, x, mask=None, pos_embed=None):
        # x: (L, B, C)
        L = x.shape[0]
        x = x * math.sqrt(self._units)
        x = x + pos_embed.slice_axis(axis=0, begin=0, end=L).expand_dims(1)
        x = self.dropout_layer(x)
        for cell in self.cells:
            x = cell(x, mask)
        return x


class TransformerDecoder(HybridBlock):
    def __init__(self, units=512, hidden_size=2048, num_layers=6,
                 num_heads=8, dropout=0.1, max_length=1024, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._num_heads = num_heads
        with self.name_scope():
            self.pos_embed = self.params.get_constant(
                "pos_embed", _sinusoid_table(max_length, units))
            self.dropout_layer = nn.Dropout(dropout)
            self.cells = nn.HybridSequential()
            for _ in range(num_layers):
                self.cells.add(TransformerDecoderCell(
                    units, hidden_size, num_heads, dropout,
                    activation="relu"))

    def hybrid_forward(self, F, x, mem, self_mask=None, mem_mask=None,
                       pos_embed=None):
        L = x.shape[0]
        x = x * math.sqrt(self._units)
        x = x + pos_embed.slice_axis(axis=0, begin=0, end=L).expand_dims(1)
        x = self.dropout_layer(x)
        for cell in self.cells:
            x = cell(x, mem, self_mask, mem_mask)
        return x


class Transformer(HybridBlock):
    """Full encoder-decoder with tied source/target embeddings option.

    Call: ``model(src (B, Ls), tgt (B, Lt), src_valid, tgt_valid)`` →
    logits (B, Lt, V_tgt).
    """

    def __init__(self, src_vocab_size, tgt_vocab_size=None, units=512,
                 hidden_size=2048, num_layers=6, num_heads=8, dropout=0.1,
                 max_length=1024, tie_weights=False, **kwargs):
        super().__init__(**kwargs)
        tgt_vocab_size = tgt_vocab_size or src_vocab_size
        self._units = units
        self._num_heads = num_heads
        with self.name_scope():
            self.src_embed = nn.Embedding(src_vocab_size, units)
            if tie_weights and tgt_vocab_size == src_vocab_size:
                self.tgt_embed = self.src_embed
            else:
                self.tgt_embed = nn.Embedding(tgt_vocab_size, units)
            self.encoder = TransformerEncoder(units, hidden_size,
                                              num_layers, num_heads,
                                              dropout, max_length)
            self.decoder = TransformerDecoder(units, hidden_size,
                                              num_layers, num_heads,
                                              dropout, max_length)
            self.proj = nn.Dense(tgt_vocab_size, in_units=units,
                                 flatten=False)

    # ---------------------------------------------------------------- masks
    def _pad_mask(self, F, valid_length, L_q, L_k):
        """additive (B*H, L_q, L_k) padding mask from (B,) lengths."""
        steps = nd.arange(L_k)
        ok = F.broadcast_lesser(steps.reshape((1, L_k)),
                                valid_length.reshape((-1, 1))
                                .astype("float32"))
        mask = (1.0 - ok) * NEG_INF                     # (B, L_k)
        mask = mask.reshape((-1, 1, 1, L_k)).broadcast_to(
            (mask.shape[0], self._num_heads, L_q, L_k))
        return mask.reshape((-1, L_q, L_k))

    def _causal_mask(self, F, L, ref):
        tri = np.triu(np.full((L, L), NEG_INF, dtype=np.float32), k=1)
        return nd.array(tri, ctx=ref.context)

    def encode(self, src, src_valid=None):
        F = nd
        x = self.src_embed(src).swapaxes(0, 1)
        mask = None
        if src_valid is not None:
            mask = self._pad_mask(F, src_valid, src.shape[1], src.shape[1])
        return self.encoder(x, mask)

    def decode_logits(self, mem, tgt, src_valid=None):
        F = nd
        Lt = tgt.shape[1]
        y = self.tgt_embed(tgt).swapaxes(0, 1)
        self_mask = self._causal_mask(F, Lt, tgt)
        mem_mask = None
        if src_valid is not None:
            mem_mask = self._pad_mask(F, src_valid, Lt, mem.shape[0])
        out = self.decoder(y, mem, self_mask, mem_mask)
        return self.proj(out.swapaxes(0, 1))

    def hybrid_forward(self, F, src, tgt, src_valid=None, tgt_valid=None):
        mem = self.encode(src, src_valid)
        return self.decode_logits(mem, tgt, src_valid)

    # ------------------------------------------------------------- decoding
    def greedy_decode(self, src, src_valid=None, bos=2, eos=3,
                      max_decode_len=32):
        """Greedy autoregressive decode; returns (B, <=max_len) ids."""
        B = src.shape[0]
        mem = self.encode(src, src_valid)
        tgt = nd.full((B, 1), bos, dtype="int32")
        finished = np.zeros((B,), dtype=bool)
        for _ in range(max_decode_len):
            logits = self.decode_logits(mem, tgt, src_valid)
            nxt = logits.slice_axis(axis=1, begin=-1, end=None) \
                .squeeze(axis=1).argmax(axis=-1).astype("int32")
            nxt_np = nxt.asnumpy()
            finished |= (nxt_np == eos)
            tgt = nd.op.concat(tgt, nxt.reshape((B, 1)), dim=1)
            if finished.all():
                break
        return tgt

    def beam_search(self, src, src_valid=None, bos=2, eos=3, beam_size=4,
                    max_decode_len=32, alpha=0.6):
        """Length-normalized beam search (Sockeye-style), COMPILED: the
        whole batched search (incremental KV-cache decoder + beam
        bookkeeping) is one jitted lax.while_loop program
        (models/decoding.py).  Returns (B, max_decode_len+1) ids."""
        from .decoding import TransformerBeamDecoder
        dec = getattr(self, "_beam_decoder", None)
        if dec is None:
            dec = self._beam_decoder = TransformerBeamDecoder(self)
        return dec(src, src_valid, bos=bos, eos=eos, beam_size=beam_size,
                   max_decode_len=max_decode_len, alpha=alpha)

    def beam_search_host(self, src, src_valid=None, bos=2, eos=3,
                         beam_size=4, max_decode_len=32, alpha=0.6):
        """Legacy host-side beam search (per-sentence python loop); kept
        as the readable oracle the compiled search is tested against."""
        B = src.shape[0]
        if B != 1:
            return nd.op.concat(*[
                self.beam_search_host(
                    src.slice_axis(axis=0, begin=i, end=i + 1),
                    None if src_valid is None else
                    src_valid.slice_axis(axis=0, begin=i, end=i + 1),
                    bos, eos, beam_size, max_decode_len, alpha)
                for i in range(B)], dim=0)
        mem = self.encode(src, src_valid)          # (Ls, 1, C)
        beams = [([bos], 0.0, False)]
        for _ in range(max_decode_len):
            if all(done for _, _, done in beams):
                break
            candidates = []
            for seq, score, done in beams:
                if done:
                    candidates.append((seq, score, True))
                    continue
                tgt = nd.array(np.array([seq], dtype=np.int32),
                               dtype="int32")
                logits = self.decode_logits(mem, tgt, src_valid)
                logp = nd.op.log_softmax(
                    logits.slice_axis(axis=1, begin=-1, end=None)
                    .squeeze(axis=1), axis=-1).asnumpy()[0]
                top = np.argsort(-logp)[:beam_size]
                for t in top:
                    candidates.append((seq + [int(t)],
                                       score + float(logp[t]),
                                       int(t) == eos))
            # length-normalized scores
            def lp(s):
                return ((5 + len(s)) / 6.0) ** alpha
            candidates.sort(key=lambda c: -(c[1] / lp(c[0])))
            beams = candidates[:beam_size]
        best = max(beams, key=lambda c: c[1] / (((5 + len(c[0])) / 6.0)
                                                ** alpha))
        return nd.array(np.array([best[0]], dtype=np.int32), dtype="int32")


class SmoothedSoftmaxCELoss(HybridBlock):
    """Label-smoothed cross entropy (Sockeye/transformer training)."""

    def __init__(self, smoothing=0.1, axis=-1, **kwargs):
        super().__init__(**kwargs)
        self._eps = smoothing
        self._axis = axis

    def hybrid_forward(self, F, pred, label, valid_length=None):
        V = pred.shape[-1]
        logp = F.log_softmax(pred, axis=self._axis)
        nll = -F.pick(logp, label, axis=self._axis, keepdims=False)
        smooth = -logp.mean(axis=self._axis)
        loss = (1 - self._eps) * nll + self._eps * smooth
        if valid_length is not None:
            L = loss.shape[1]
            steps = nd.arange(L)
            mask = F.broadcast_lesser(
                steps.reshape((1, L)),
                valid_length.reshape((-1, 1)).astype("float32"))
            loss = loss * mask
            return loss.sum(axis=1) / valid_length.astype("float32")
        return loss.mean(axis=1)


def transformer_base(src_vocab_size, tgt_vocab_size=None, **kw):
    cfg = dict(units=512, hidden_size=2048, num_layers=6, num_heads=8)
    cfg.update(kw)
    return Transformer(src_vocab_size, tgt_vocab_size, **cfg)


def transformer_big(src_vocab_size, tgt_vocab_size=None, **kw):
    """WMT14 En-De transformer-big (BASELINE config 4)."""
    cfg = dict(units=1024, hidden_size=4096, num_layers=6, num_heads=16)
    cfg.update(kw)
    return Transformer(src_vocab_size, tgt_vocab_size, **cfg)
