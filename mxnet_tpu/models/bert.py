"""BERT — the flagship model family (GluonNLP scripts/bert parity).

Reference chain: the fused attention kernels live in the core
(``src/operator/contrib/transformer.cc``); the model lived in GluonNLP
(``bert_12_768_12`` / ``bert_24_1024_16``).  This in-tree build supplies
BERTModel + pretrain (MLM/NSP) and SQuAD heads as HybridBlocks; under
``hybridize()`` or the pjit path (mxnet_tpu.parallel) the whole encoder
compiles to one XLA program with attention on batched MXU GEMMs.

Internal layout is (L, B, C) time-major — the interleaved attention
kernels' contract — with (B, L) int token inputs at the API boundary,
matching the GluonNLP call signature ``model(inputs, token_types,
valid_length)``.
"""
from __future__ import annotations

import math

import numpy as np

from ..base import MXNetError
from .. import ndarray as nd
from ..gluon import nn
from ..gluon.block import HybridBlock
from .transformer_blocks import TransformerEncoderCell

__all__ = ["BERTEncoder", "BERTModel", "BERTForPretrain", "BERTForQA",
           "BERTClassifier", "bert_12_768_12", "bert_24_1024_16",
           "get_bert_model"]

NEG_INF = -1e9


class BERTEncoder(HybridBlock):
    """Stack of transformer encoder cells (gelu, post-norm)."""

    def __init__(self, units=768, hidden_size=3072, num_layers=12,
                 num_heads=12, dropout=0.1, max_length=512,
                 layer_norm_eps=1e-12, use_flash=False, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._num_heads = num_heads
        self._max_length = max_length
        with self.name_scope():
            self.position_weight = self.params.get(
                "position_weight", shape=(max_length, units),
                init="normal")
            self.layer_norm = nn.LayerNorm(in_channels=units,
                                           epsilon=layer_norm_eps)
            self.dropout_layer = nn.Dropout(dropout)
            self.transformer_cells = nn.HybridSequential()
            for _ in range(num_layers):
                self.transformer_cells.add(TransformerEncoderCell(
                    units, hidden_size, num_heads, dropout,
                    activation="gelu", layer_norm_eps=layer_norm_eps,
                    use_flash=use_flash))

    def hybrid_forward(self, F, x, mask=None, valid_length=None,
                       position_weight=None):
        # x: (L, B, C)
        L = x.shape[0]
        pos = position_weight.slice_axis(axis=0, begin=0, end=L)
        x = x + pos.expand_dims(1)
        x = self.dropout_layer(self.layer_norm(x))
        for cell in self.transformer_cells:
            x = cell(x, mask, valid_length)
        return x


class BERTModel(HybridBlock):
    """Embeddings + encoder + pooler (GluonNLP BERTModel parity).

    Call: ``model(inputs, token_types, valid_length)`` with (B, L) int32.
    Returns (sequence_output (B, L, C), pooled_output (B, C)).
    """

    def __init__(self, units=768, hidden_size=3072, num_layers=12,
                 num_heads=12, vocab_size=30522, token_type_vocab_size=2,
                 max_length=512, dropout=0.1, layer_norm_eps=1e-12,
                 use_pooler=True, use_flash=False, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._num_heads = num_heads
        self._use_pooler = use_pooler
        self._use_flash = use_flash
        with self.name_scope():
            self.word_embed = nn.Embedding(vocab_size, units,
                                           weight_initializer="normal")
            self.token_type_embed = nn.Embedding(token_type_vocab_size,
                                                 units,
                                                 weight_initializer="normal")
            self.encoder = BERTEncoder(units, hidden_size, num_layers,
                                       num_heads, dropout, max_length,
                                       layer_norm_eps,
                                       use_flash=use_flash)
            if use_pooler:
                self.pooler = nn.Dense(units, in_units=units,
                                       activation="tanh", flatten=False)

    def _make_mask(self, F, valid_length, L):
        # additive mask (B*H, L, L): 0 where key < valid_length else -inf
        steps = nd.arange(L, ctx=valid_length.context)          # (L,)
        keys_ok = F.broadcast_lesser(
            steps.reshape((1, L)),
            valid_length.reshape((-1, 1)).astype("float32"))    # (B, L)
        mask = (1.0 - keys_ok) * NEG_INF                        # (B, L)
        mask = mask.reshape((-1, 1, 1, L))                      # (B,1,1,L)
        mask = mask.broadcast_to((mask.shape[0], self._num_heads, L, L))
        return mask.reshape((-1, L, L))                         # (B*H,L,L)

    def hybrid_forward(self, F, inputs, token_types=None, valid_length=None):
        B, L = inputs.shape
        emb = self.word_embed(inputs)
        if token_types is not None:
            emb = emb + self.token_type_embed(token_types)
        x = emb.swapaxes(0, 1)                                  # (L, B, C)
        if self._use_flash:
            # padding rides the flash kernel's lengths vector; no O(L^2)
            # mask is ever materialized
            out = self.encoder(x, None, valid_length=valid_length)
        else:
            mask = None
            if valid_length is not None:
                mask = self._make_mask(F, valid_length, L)
            out = self.encoder(x, mask)                         # (L, B, C)
        seq = out.swapaxes(0, 1)                                # (B, L, C)
        if not self._use_pooler:
            return seq
        pooled = self.pooler(seq.slice_axis(axis=1, begin=0, end=1)
                             .squeeze(axis=1))
        return seq, pooled


class BERTForPretrain(HybridBlock):
    """MLM + NSP heads over BERTModel (GluonNLP BERTForPretrain)."""

    def __init__(self, bert: BERTModel, vocab_size=None, **kwargs):
        super().__init__(**kwargs)
        units = bert._units
        self._vocab_size = vocab_size or \
            bert.word_embed._input_dim
        with self.name_scope():
            self.bert = bert
            self.mlm_dense = nn.Dense(units, in_units=units,
                                      flatten=False)
            self.mlm_norm = nn.LayerNorm(in_channels=units, epsilon=1e-12)
            self.mlm_decoder = nn.Dense(self._vocab_size, in_units=units,
                                        flatten=False)
            self.nsp_classifier = nn.Dense(2, in_units=units)

    def hybrid_forward(self, F, inputs, token_types, valid_length,
                       masked_positions):
        seq, pooled = self.bert(inputs, token_types, valid_length)
        # gather the masked positions: (B, M, C)
        gathered = _gather_positions(F, seq, masked_positions)
        h = self.mlm_dense(gathered)
        h = F._contrib_gelu_erf(h)
        h = self.mlm_norm(h)
        mlm_scores = self.mlm_decoder(h)          # (B, M, V)
        nsp_scores = self.nsp_classifier(pooled)  # (B, 2)
        return mlm_scores, nsp_scores


class BERTPretrainLoss(HybridBlock):
    """MLM+NSP loss fused into the traced graph (GluonNLP's pretraining
    script computes these losses eagerly; on TPU every eager op pays a
    dispatch round trip, so the loss belongs inside the hybridized program
    — one forward program, one backward program for the whole step).
    """

    def __init__(self, pretrain: "BERTForPretrain", **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.pretrain = pretrain

    def hybrid_forward(self, F, inputs, token_types, valid_length,
                       masked_positions, mlm_labels, nsp_labels):
        mlm_scores, nsp_scores = self.pretrain(
            inputs, token_types, valid_length, masked_positions)
        mlm_lp = F.log_softmax(mlm_scores.astype("float32"), axis=-1)
        nsp_lp = F.log_softmax(nsp_scores.astype("float32"), axis=-1)
        mlm_loss = 0.0 - F.pick(mlm_lp, mlm_labels, axis=-1).mean()
        nsp_loss = 0.0 - F.pick(nsp_lp, nsp_labels, axis=-1).mean()
        return mlm_loss + nsp_loss


def _gather_positions(F, seq, positions):
    """seq (B, L, C), positions (B, M) -> (B, M, C)."""
    B, L, C = seq.shape
    M = positions.shape[1]
    flat = seq.reshape((B * L, C))
    offset = nd.arange(B, ctx=seq.context).reshape((B, 1)) * L
    idx = (positions.astype("float32") + offset).reshape((-1,))
    out = F.take(flat, idx.astype("int32"), axis=0)
    return out.reshape((B, M, C))


class BERTClassifier(HybridBlock):
    """Sentence-pair classification head (GluonNLP BERTClassifier)."""

    def __init__(self, bert: BERTModel, num_classes=2, dropout=0.1,
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.bert = bert
            self.classifier = nn.HybridSequential()
            self.classifier.add(nn.Dropout(dropout))
            self.classifier.add(nn.Dense(num_classes,
                                         in_units=bert._units))

    def hybrid_forward(self, F, inputs, token_types, valid_length=None):
        _, pooled = self.bert(inputs, token_types, valid_length)
        return self.classifier(pooled)


class BERTForQA(HybridBlock):
    """SQuAD span head (GluonNLP BertForQA): (B, L, 2) start/end logits."""

    def __init__(self, bert: BERTModel, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.bert = bert
            self.span_classifier = nn.Dense(2, in_units=bert._units,
                                            flatten=False)

    def hybrid_forward(self, F, inputs, token_types, valid_length=None):
        seq, _ = self.bert(inputs, token_types, valid_length)
        scores = self.span_classifier(seq)        # (B, L, 2)
        return scores


_BERT_CONFIGS = {
    "bert_12_768_12": dict(units=768, hidden_size=3072, num_layers=12,
                           num_heads=12),
    "bert_24_1024_16": dict(units=1024, hidden_size=4096, num_layers=24,
                            num_heads=16),
}


def get_bert_model(model_name="bert_12_768_12", vocab_size=30522,
                   dropout=0.1, max_length=512, use_pooler=True, **kwargs):
    if model_name not in _BERT_CONFIGS:
        raise MXNetError(f"unknown bert config {model_name!r}; "
                         f"known: {sorted(_BERT_CONFIGS)}")
    cfg = dict(_BERT_CONFIGS[model_name])
    cfg.update(kwargs)
    return BERTModel(vocab_size=vocab_size, dropout=dropout,
                     max_length=max_length, use_pooler=use_pooler, **cfg)


def bert_12_768_12(**kwargs):
    """BERT-base (GluonNLP name)."""
    return get_bert_model("bert_12_768_12", **kwargs)


def bert_24_1024_16(**kwargs):
    """BERT-large (GluonNLP name) — the north-star pretrain config."""
    return get_bert_model("bert_24_1024_16", **kwargs)
