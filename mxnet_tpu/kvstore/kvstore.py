"""KVStore: multi-device gradient aggregation & weight sync.

Reference surface: ``python/mxnet/kvstore/kvstore.py`` + ``src/kvstore/``
(`KVStoreLocal`, `CommDevice`, `KVStoreNCCL`) — SURVEY.md §2.1 KVStore row,
§2.4 P1/P2/P5/P6, §5.8.

TPU-native redesign (not a translation):

- ``'local'`` / ``'device'``: single-process reduce across per-context
  copies.  The reference reduces on CPU ('local') or via GPU P2P
  ('device'); here both are one ``jax.device_put`` + add chain differing
  only in where the reduction lands.
- ``'xla'``: the NCCL/dist tier replacement — push/pull/pushpull lower to
  ONE compiled XLA collective program (``shard_map`` + ``lax.psum``) over a
  1-d device mesh, so on real hardware the reduce rides ICI without host
  round-trips.  Small keys are fused into buckets (reference:
  ``MXNET_KVSTORE_BIGARRAY_BOUND`` fusion in KVStoreNCCL).
- 2-bit gradient compression with error-feedback residual (reference:
  ``src/kvstore/gradient_compression.cc``) applies to every tier's push.
- int8/fp8 blockwise gradient compression (``mxnet_tpu.quantize``;
  EQuARX, PAPERS.md): on the ``'xla'`` tier quant/dequant runs INSIDE
  the jitted collective — each device quantizes its shard (+ the
  error-feedback residual), all-gathers only the 1-byte payload and
  per-block f32 scales, and accumulates in f32 — so compressed bytes
  are what actually crosses chips.  Enable per store via
  ``set_gradient_compression({'type': 'int8', ...})`` or process-wide
  via ``MXNET_KVSTORE_GRAD_COMPRESSION``.  ``kvstore.wire.bytes``
  counts interconnect traffic next to the logical
  ``kvstore.push.bytes``; their ratio is the live compression factor.
"""
from __future__ import annotations

import functools
from collections import OrderedDict

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError, get_env
from ..context import cpu
from .. import faults as _faults
from ..ndarray import NDArray
from .. import optimizer as opt
from .. import quantize as qz
from .. import runtime_metrics as _rm
from .base import KVStoreBase

__all__ = ["KVStore", "create"]


from ..util import as_list as _as_list


def _nd_bytes(vals) -> int:
    """LOGICAL payload size of a list of NDArrays (shape x itemsize;
    sparse and exotic values count 0 rather than densifying just to be
    measured).  This is the application-level gradient volume feeding
    ``kvstore.push.bytes`` / ``kvstore.pull.bytes`` — NOT wire traffic:
    under gradient compression the interconnect moves the (smaller)
    compressed representation, counted by ``kvstore.wire.bytes``
    (docs/observability.md)."""
    total = 0
    for v in vals:
        try:
            n = 1
            for s in v.shape:
                n *= int(s)
            total += n * np.dtype(v.dtype).itemsize
        except Exception:   # noqa: BLE001
            pass
    return total


def _normalize(key, value):
    """-> list of (str_key, [NDArray per device]) pairs."""
    keys = _as_list(key)
    if len(keys) == 1 and not (isinstance(value, (list, tuple))
                               and value and isinstance(value[0],
                                                        (list, tuple))):
        vals = [_as_list(value)]
    else:
        vals = [_as_list(v) for v in value]
    if len(keys) != len(vals):
        raise MXNetError(
            f"kvstore: {len(keys)} keys but {len(vals)} value lists")
    return [(str(k), list(v)) for k, v in zip(keys, vals)]


class _TwoBitCompressor:
    """2-bit sign compression with error feedback
    (reference: gradient_compression.cc)."""

    def __init__(self, threshold=0.5):
        self.threshold = float(threshold)
        self._residual = {}

    def compress(self, key, idx, grad_data):
        thr = self.threshold
        res = self._residual.get((key, idx))
        if res is None:
            res = jnp.zeros_like(grad_data)
        g = grad_data + res
        q = jnp.where(g >= thr, thr, 0.0) + jnp.where(g <= -thr, -thr, 0.0)
        q = q.astype(grad_data.dtype)
        self._residual[(key, idx)] = g - q
        return q

    def wire_bytes(self, vals) -> int:
        # host-side sign simulation: nothing compressed actually
        # crosses a wire here, so account the logical volume
        return _nd_bytes(vals)


class _QuantCompressor:
    """int8/fp8 blockwise compression (``mxnet_tpu.quantize``) for the
    per-key host tiers: a quantize -> dequantize round trip with an
    error-feedback residual per (key, device copy) — the value-level
    twin of the fused in-collective path the ``'xla'`` tier runs."""

    def __init__(self, spec: qz.CompressionSpec):
        self.spec = spec
        self._residual = {}
        self._step = 0          # stochastic-rounding key stream

    def compress(self, key, idx, grad_data):
        spec = self.spec
        res = self._residual.get((key, idx))
        if res is None or res.shape != grad_data.shape:
            res = jnp.zeros(grad_data.shape, jnp.float32)
        rkey = None
        if spec.stochastic:
            self._step += 1
            rkey = jax.random.fold_in(
                jax.random.PRNGKey(self._step), idx)
        payload, scales, new_res = qz.quantize_with_feedback(
            grad_data, res, spec, key=rkey)
        self._residual[(key, idx)] = new_res
        return qz.dequantize(payload, scales, grad_data.shape,
                             grad_data.dtype)

    def wire_bytes(self, vals) -> int:
        total = 0
        for v in vals:
            n = 1
            for s in v.shape:
                n *= int(s)
            total += qz.wire_bytes(n, self.spec)
        return total


class KVStore(KVStoreBase):
    """Classic imperative API: init / push / pull / pushpull.

    Subclasses supply ``_reduce`` (aggregate per-device copies) — everything
    else (storage, updater, compression, broadcast) is shared.
    """

    CAPABILITIES = (KVStoreBase.OPTIMIZER,)

    def __init__(self):
        self._store: "OrderedDict[str, NDArray]" = OrderedDict()
        self._updater = None
        self._optimizer = None
        self._compressor = None

    # ------------------------------------------------------------ identity
    @property
    def type(self):
        return self._TYPE

    # ---------------------------------------------------------------- init
    def init(self, key, value):
        for k, vals in _normalize(key, value):
            if k in self._store:
                raise MXNetError(f"kvstore: key {k!r} already initialized")
            self._store[k] = self._pin(vals[0])

    def _pin(self, value: NDArray) -> NDArray:
        """Where the master copy of a key lives ('local': host cpu).

        Always a fresh NDArray wrapper: ``as_in_context`` returns ``self``
        for a same-context value, and aliasing the caller's array would let
        pushes overwrite live weights.
        """
        return value.as_in_context(cpu(0)).copy()

    # ---------------------------------------------------------------- push
    def push(self, key, value, priority=0):
        _faults.inject("kvstore.push")
        for k, vals in _normalize(key, value):
            if _rm._ENABLED:
                _rm.KV_PUSH.inc()
                _rm.KV_PUSH_BYTES.inc(_nd_bytes(vals))
                self._count_wire(vals)
            self._push_one(k, vals)

    def _count_wire(self, vals):
        """Wire-traffic accounting for one push: logical bytes when
        uncompressed, the compressed representation's size under
        gradient compression.  The 'xla' tier overrides this — its
        fused collective accounts per bucket instead."""
        if self._compressor is not None:
            _rm.KV_WIRE_BYTES.inc(self._compressor.wire_bytes(vals))
        else:
            _rm.KV_WIRE_BYTES.inc(_nd_bytes(vals))

    def _push_one(self, k, vals):
        if k not in self._store:
            raise MXNetError(f"kvstore: push to uninitialized key {k!r}")
        vals = self._maybe_compress(k, vals)
        merged = self._reduce(k, vals)
        stored = self._store[k]
        if self._updater is not None:
            self._updater(int(k) if k.isdigit() else k,
                          merged.as_in_context(stored.context), stored)
        else:
            stored._set_data(merged.as_in_context(stored.context)._data
                             .astype(stored._data.dtype))

    def _maybe_compress(self, k, vals):
        if self._compressor is None:
            return vals
        return [NDArray(self._compressor.compress(k, i, v._data),
                        ctx=v.context) for i, v in enumerate(vals)]

    # ---------------------------------------------------------------- pull
    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        _faults.inject("kvstore.pull")
        if out is None:
            raise MXNetError("kvstore.pull requires out=")
        for k, outs in _normalize(key, out):
            if k not in self._store:
                raise MXNetError(f"kvstore: pull of uninitialized key {k!r}")
            stored = self._store[k]
            if _rm._ENABLED:
                _rm.KV_PULL.inc()
                _rm.KV_PULL_BYTES.inc(_nd_bytes(outs))
            for o in outs:
                stored.copyto(o)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        # dense framework storage: row_ids select rows of the dense value
        if out is None or row_ids is None:
            raise MXNetError("row_sparse_pull requires out= and row_ids=")
        for (k, outs), rids in zip(_normalize(key, out),
                                   _normalize(key, row_ids)):
            stored = self._store[k]
            for o, r in zip(outs, rids):
                rows = jnp.take(stored._data, r._data.astype(jnp.int32),
                                axis=0)
                o._set_data(jax.device_put(
                    rows.astype(o._data.dtype),
                    o.context.jax_device()))

    # ------------------------------------------------------------ pushpull
    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out=out, priority=priority)

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out=out, priority=priority)

    # ------------------------------------------------------------ optimizer
    def set_optimizer(self, optimizer):
        if not self.is_capable(KVStoreBase.OPTIMIZER):
            raise MXNetError(
                f"kvstore type {self.type!r} cannot run the optimizer "
                f"(update_on_kvstore unsupported)")
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def set_gradient_compression(self, compression_params):
        """Enable gradient compression on every subsequent push.

        - ``{'type': '2bit', 'threshold': t}`` — reference sign
          compression with error feedback (host-side simulation);
        - ``{'type': 'int8'|'fp8', 'block': ..., 'stochastic': ...,
          'error_feedback': ...}`` — blockwise quantization
          (``mxnet_tpu.quantize.CompressionSpec``); also accepted as a
          spec string (``'int8:block=64'``) or a ``CompressionSpec``.
          On the ``'xla'`` tier quant/dequant runs inside the jitted
          collective, so only compressed payloads cross chips.
        """
        if compression_params is None:
            self._compressor = None         # disable (e.g. override an
            return                          # env-default compression)
        if isinstance(compression_params, qz.CompressionSpec):
            self._compressor = _QuantCompressor(compression_params)
            return
        if isinstance(compression_params, str):
            spec = qz.CompressionSpec.parse(compression_params)
            self._compressor = None if spec is None \
                else _QuantCompressor(spec)
            return
        params = dict(compression_params)
        ctype = params.pop("type", "2bit")
        if ctype == "2bit":
            self._compressor = _TwoBitCompressor(
                params.pop("threshold", 0.5))
            if params:
                raise MXNetError(f"unknown compression params {params}")
            return
        if ctype in ("int8", "fp8"):
            self._compressor = _QuantCompressor(
                qz.CompressionSpec.parse(dict(params, type=ctype)))
            return
        raise MXNetError(f"unsupported compression type {ctype!r}")

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    # ------------------------------------------------------------- reduce
    def _reduce(self, k, vals) -> NDArray:
        raise NotImplementedError


@KVStoreBase.register
class Local(KVStore):
    """Reduce on host CPU (reference: KVStoreLocal / CommCPU)."""

    _TYPE = "local"

    def _reduce(self, k, vals):
        dev = cpu(0).jax_device()
        acc = jax.device_put(vals[0]._data, dev)
        for v in vals[1:]:
            acc = acc + jax.device_put(v._data, dev)
        return NDArray(acc, ctx=cpu(0))


@KVStoreBase.register
class Device(KVStore):
    """Reduce on the first value's device (reference: CommDevice P2P)."""

    _TYPE = "device"

    def _pin(self, value):
        return value.copy()

    def _reduce(self, k, vals):
        dev = vals[0]._data.device
        acc = vals[0]._data
        for v in vals[1:]:
            acc = acc + jax.device_put(v._data, dev)
        return NDArray(acc, ctx=vals[0].context)


@KVStoreBase.register
class XLA(KVStore):
    """Allreduce as one compiled XLA collective over the device mesh.

    The north-star ``kvstore('xla')`` tier (SURVEY §5.8): per-device copies
    are assembled into a sharded global array (zero copies — shards stay on
    their devices), a cached ``jit(shard_map(psum))`` program reduces over
    the 'dev' axis on ICI, and the replicated result is read back from
    per-device shards.  Keys smaller than MXNET_KVSTORE_BIGARRAY_BOUND are
    fused into one bucket per dtype (reference: NCCL small-grad fusion).
    """

    _TYPE = "xla"
    CAPABILITIES = ()

    def __init__(self):
        super().__init__()
        self._fn_cache = {}
        self._mesh_cache = {}
        # error-feedback residuals of the quantized fused collective,
        # keyed by (dtype, bucket key tuple, total): one per-device
        # rounding-error vector per bucket, sharded over the mesh
        self._ef_residuals = {}
        self._quant_step = 0
        self.bigarray_bound = int(get_env("MXNET_KVSTORE_BIGARRAY_BOUND",
                                          1 << 19))

    def _pin(self, value):
        return value.copy()

    def _count_wire(self, vals):
        # the fused collective accounts wire bytes per bucket (it knows
        # what actually crosses); counting here too would double it
        pass

    def _maybe_compress(self, k, vals):
        # int8/fp8 compression happens INSIDE the fused collective —
        # the host-side value round trip would quantize twice.  Every
        # multi-copy reduce on this tier (classic push/_push_one
        # included) lands in _fused_allreduce, which applies the quant
        # spec there; a single-copy key skips both, correctly — it has
        # no interconnect hop to compress (and set_optimizer is
        # rejected on this tier, so the updater fallback is
        # unreachable).
        if isinstance(self._compressor, _QuantCompressor):
            return vals
        return super()._maybe_compress(k, vals)

    # single-key reduce (used by push when called per key)
    def _reduce(self, k, vals):
        if len(vals) == 1:
            return vals[0]
        reduced = self._fused_allreduce([(k, vals)])
        return reduced[k][0]

    def pushpull(self, key, value, out=None, priority=0):
        """Batched fused path: aggregates ALL keys in as few collective
        launches as possible, then writes results straight into ``out``
        shards (no master-copy round trip)."""
        pairs = _normalize(key, value)
        for k, _ in pairs:
            if k not in self._store:
                raise MXNetError(
                    f"kvstore: push to uninitialized key {k!r}")
        if any(len(v) == 1 for _, v in pairs) or self._updater is not None \
                or isinstance(self._compressor, _TwoBitCompressor):
            # degenerate / host-compressed path: classic push+pull via
            # the store (which carries its own push/pull accounting
            # and fault sites); int8/fp8 quantization stays ON the
            # fused path below — it runs inside the jitted collective
            return super().pushpull(key, value, out, priority)
        # the fused XLA collective call site: a chaos plan kills or
        # stalls the whole bucketed allreduce launch here
        _faults.inject("kvstore.pushpull")
        if _rm._ENABLED:
            for _k, vals in pairs:
                _rm.KV_PUSH.inc()
                _rm.KV_PUSH_BYTES.inc(_nd_bytes(vals))
        reduced = self._fused_allreduce(pairs)
        for k, _ in pairs:
            per_dev = reduced[k]
            self._store[k]._set_data(
                per_dev[0]._data.astype(self._store[k]._data.dtype))
        if out is not None:
            for k, outs in _normalize(key, out):
                per_dev = reduced[k]
                if _rm._ENABLED:
                    _rm.KV_PULL.inc()
                    _rm.KV_PULL_BYTES.inc(_nd_bytes(outs))
                for o, r in zip(outs, per_dev):
                    o._set_data(r._data.astype(o._data.dtype))

    # ------------------------------------------------------------ internals
    def _sharding(self, devices):
        """Cached (mesh, input sharding) per device tuple — Mesh
        construction is host-side work that must stay off the step path."""
        cached = self._mesh_cache.get(devices)
        if cached is None:
            mesh = Mesh(np.array(devices), ("dev",))
            cached = (mesh, NamedSharding(mesh, P("dev")))
            self._mesh_cache[devices] = cached
        return cached

    def _allreduce_fn(self, devices, size, dtype):
        cache_key = (devices, size, dtype)
        fn = self._fn_cache.get(cache_key)
        if fn is None:
            mesh, _ = self._sharding(devices)
            from .._jax_compat import shard_map
            body = shard_map(lambda x: lax.psum(x, "dev"), mesh=mesh,
                             in_specs=P("dev"), out_specs=P())
            fn = jax.jit(body,
                         out_shardings=NamedSharding(mesh, P()))
            self._fn_cache[cache_key] = fn
        return fn

    def _quant_allreduce_fn(self, devices, size, dtype, spec):
        """ONE compiled program per (topology, bucket, dtype, spec):
        error-feedback quantize + all-gather of the compressed payload
        + f32 dequant-accumulate, all inside the jitted shard_map body
        so XLA fuses quant/dequant into the collective and only
        compressed bytes cross chips."""
        cache_key = ("quant", devices, size, dtype, spec.key())
        fn = self._fn_cache.get(cache_key)
        if fn is None:
            mesh, _ = self._sharding(devices)
            from .._jax_compat import shard_map
            if spec.stochastic:
                def body(x, res, k):
                    rkey = jax.random.fold_in(k, lax.axis_index("dev"))
                    return qz.allreduce_sum(x, res, spec, "dev",
                                            key=rkey)
                in_specs = (P("dev"), P("dev"), P())
            else:
                def body(x, res):
                    return qz.allreduce_sum(x, res, spec, "dev")
                in_specs = (P("dev"), P("dev"))
            # out_specs P("dev") for the sum too: every device returns
            # its own (identical, via the symmetric all_gather) copy,
            # which sidesteps shard_map's static replication check and
            # hands back exactly the per-device layout the shard
            # splitter reads (addressable_shards[d] = full sum)
            sm = shard_map(body, mesh=mesh, in_specs=in_specs,
                           out_specs=(P("dev"), P("dev")))
            fn = jax.jit(sm, out_shardings=(
                NamedSharding(mesh, P("dev")),
                NamedSharding(mesh, P("dev"))))
            self._fn_cache[cache_key] = fn
        return fn

    def _fused_allreduce(self, pairs):
        """pairs: [(key, [NDArray per device])] -> {key: [NDArray per dev]}.

        Groups keys by dtype, packs small ones into shared buckets, runs
        one psum per bucket, and splits results back out of the replicated
        per-device shards.
        """
        ndev = len(pairs[0][1])
        devices = tuple(v._data.device for v in pairs[0][1])
        if len(set(devices)) != ndev:
            raise MXNetError(
                "kvstore('xla'): per-key copies must live on distinct "
                f"devices, got {devices}")
        by_dtype = OrderedDict()
        for k, vals in pairs:
            if len(vals) != ndev:
                raise MXNetError(
                    f"kvstore('xla'): key {k!r} has {len(vals)} copies, "
                    f"expected {ndev}")
            by_dtype.setdefault(str(vals[0]._data.dtype), []).append(
                (k, vals))

        results = {}
        for dtype, group in by_dtype.items():
            buckets, cur, cur_elems = [], [], 0
            for k, vals in group:
                n = int(np.prod(vals[0].shape)) if vals[0].shape else 1
                if n >= self.bigarray_bound:
                    buckets.append([(k, vals, n)])
                    continue
                cur.append((k, vals, n))
                cur_elems += n
                if cur_elems >= self.bigarray_bound:
                    buckets.append(cur)
                    cur, cur_elems = [], 0
            if cur:
                buckets.append(cur)
            quant_spec = self._compressor.spec \
                if isinstance(self._compressor, _QuantCompressor) \
                and jnp.issubdtype(jnp.dtype(dtype), jnp.floating) \
                else None
            for bucket in buckets:
                total = sum(n for _, _, n in bucket)
                shards = []
                for d in range(ndev):
                    flats = [vals[d]._data.reshape(-1)
                             for _, vals, _ in bucket]
                    shards.append(flats[0] if len(flats) == 1
                                  else jnp.concatenate(flats))
                _, in_sharding = self._sharding(devices)
                mesh_arr = jax.make_array_from_single_device_arrays(
                    (ndev * total,), in_sharding, shards)
                if quant_spec is not None:
                    res_key = (dtype, tuple(k for k, _, _ in bucket),
                               total)
                    res = self._ef_residuals.get(res_key)
                    if res is None:
                        res = jax.device_put(
                            jnp.zeros((ndev * total,), jnp.float32),
                            in_sharding)
                    fn = self._quant_allreduce_fn(
                        devices, total, dtype, quant_spec)
                    # bucket totals are NOT request-scoped: they derive
                    # from the training job's fixed key set (one
                    # program per (topology, bucket, dtype, spec),
                    # cached in _fn_cache — same contract as the
                    # uncompressed _allreduce_fn path)
                    if quant_spec.stochastic:
                        self._quant_step += 1
                        # mxlint: disable=recompile-churn
                        out, new_res = fn(
                            mesh_arr, res,
                            jax.random.PRNGKey(self._quant_step))
                    else:
                        # mxlint: disable=recompile-churn
                        out, new_res = fn(mesh_arr, res)
                    self._ef_residuals[res_key] = new_res
                    if _rm._ENABLED:
                        _rm.KV_WIRE_BYTES.inc(
                            ndev * qz.wire_bytes(total, quant_spec))
                else:
                    out = self._allreduce_fn(devices, total,
                                             dtype)(mesh_arr)
                    if _rm._ENABLED:
                        _rm.KV_WIRE_BYTES.inc(
                            ndev * total * jnp.dtype(dtype).itemsize)
                per_dev_full = [s.data for s in out.addressable_shards]
                # addressable_shards order follows device order in mesh
                offset = 0
                for k, vals, n in bucket:
                    outs = []
                    for d in range(ndev):
                        seg = lax.dynamic_slice_in_dim(
                            per_dev_full[d], offset, n)
                        outs.append(NDArray(
                            seg.reshape(vals[d].shape),
                            ctx=vals[d].context))
                    results[k] = outs
                    offset += n
        return results


# 'nccl' scripts get the ICI tier transparently (reference: KVStoreNCCL)
KVStoreBase.register_alias("nccl", XLA)


@KVStoreBase.register
class DistSync(KVStore):
    """Multi-process synchronous tier (reference: KVStoreDist dist_sync).

    The reference runs a parameter-server control plane over DCN; here the
    process group is bootstrapped by ``parallel.dist.initialize`` (env
    protocol from tools/launch.py) and a push reduces first locally across
    this process's device copies, then across processes.  Rank/num_workers
    mirror the reference worker identity API.
    """

    _TYPE = "dist_sync"

    def __init__(self):
        super().__init__()
        from ..parallel import dist
        self._dist = dist
        dist.initialize()   # no-op when standalone / already joined

    def init(self, key, value):
        # rank 0's value is authoritative (reference: KVStoreDist —
        # server stores rank-0 init), else workers whose initial weights
        # differ would train on divergent parameters forever
        super().init(key, value)
        if self._dist.is_initialized():
            for k, _vals in _normalize(key, value):
                stored = self._store[k]
                stored._set_data(
                    self._dist.broadcast_host(stored, root=0)._data)

    @property
    def rank(self):
        return self._dist.rank() if self._dist.is_initialized() else 0

    @property
    def num_workers(self):
        return self._dist.size() if self._dist.is_initialized() else 1

    def _reduce(self, k, vals):
        # intra-process reduce (device copies) ...
        dev = cpu(0).jax_device()
        acc = jax.device_put(vals[0]._data, dev)
        for v in vals[1:]:
            acc = acc + jax.device_put(v._data, dev)
        # ... then inter-process reduce over the group
        return self._dist.allreduce_host(NDArray(acc, ctx=cpu(0)))


KVStoreBase.register_alias("dist_sync", DistSync)
KVStoreBase.register_alias("dist", DistSync)
KVStoreBase.register_alias("dist_device_sync", DistSync)


def create(name="local") -> KVStore:
    """Factory (reference: kvstore.create / KVStoreBase registry).

    ``dist_async`` (reference: KVStoreDist async push + server-side
    optimizer) is **documented-unsupported** on TPU by design, not an
    omission: asynchronous, per-key eventually-consistent updates assume
    a parameter-server topology with CPU-side optimizers.  On a TPU pod
    the same scale point is served by the synchronous ``'xla'``/
    ``'dist_sync'`` tiers, whose allreduce rides ICI/DCN collectives
    inside the compiled step — faster than a PS round trip, with none of
    the staleness.  Use ``'dist_sync'`` (or raw
    ``parallel.ShardedTrainer`` over a multi-host mesh).
    """
    if not isinstance(name, str):
        raise MXNetError("kvstore name must be a string")
    if name.lower() in ("dist_async", "dist_device_async"):
        raise MXNetError(
            f"kvstore type {name!r} is intentionally unsupported on this "
            f"framework: asynchronous parameter-server SGD assumes "
            f"CPU-side per-key optimizers and tolerates gradient "
            f"staleness; on TPU the synchronous 'xla'/'dist_sync' tiers "
            f"(ICI/DCN allreduce compiled into the step) cover the same "
            f"scale without staleness.  Use 'dist_sync' instead.  See "
            f"kvstore.create.__doc__.")
    klass = KVStoreBase.kv_registry.get(name.lower())
    if klass is None:
        raise MXNetError(
            f"unknown kvstore type {name!r}; registered: "
            f"{sorted(KVStoreBase.kv_registry)}")
    store = klass()
    # process-wide default gradient compression: every created store
    # starts compressed (set_gradient_compression still overrides)
    env_spec = qz.CompressionSpec.from_env()
    if env_spec is not None:
        store.set_gradient_compression(env_spec)
    return store
