"""Multi-device / multi-node communication (reference:
python/mxnet/kvstore/; SURVEY.md §2.1 KVStore row, §5.8)."""
from .base import KVStoreBase
from .kvstore import KVStore, create

__all__ = ["KVStoreBase", "KVStore", "create"]
