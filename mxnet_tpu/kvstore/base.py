"""KVStoreBase plugin registry (reference: python/mxnet/kvstore/base.py).

The reference's v1.7+ plugin surface: external communication backends
(Horovod/BytePS-style) register a subclass under a name and ``create()``
dispatches to it.  Here the built-in tiers ('local', 'device', 'xla') are
registered through the same mechanism, so the registry is exercised by the
framework itself — SURVEY.md §2.4 P6.
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["KVStoreBase"]


class KVStoreBase:
    """Abstract communication backend.

    Subclasses implement the v1.7+ minimal surface (``broadcast``,
    ``pushpull``) and declare capabilities; the classic ``KVStore`` API
    (init/push/pull) is layered on top in kvstore.py.
    """

    kv_registry = {}

    # capability names (reference: KVStoreBase.OPTIMIZER)
    OPTIMIZER = "optimizer"

    # ------------------------------------------------------------ registry
    @staticmethod
    def register(klass):
        """Class decorator: register under the lowercase class name."""
        if not issubclass(klass, KVStoreBase):
            raise MXNetError(f"{klass!r} must subclass KVStoreBase")
        name = klass.__name__.lower()
        KVStoreBase.kv_registry[name] = klass
        return klass

    @staticmethod
    def register_alias(name, klass):
        KVStoreBase.kv_registry[name.lower()] = klass

    # ------------------------------------------------------- v1.7+ surface
    def broadcast(self, key, value, out, priority=0):
        """Initialize ``key`` with ``value`` and broadcast into ``out``."""
        raise NotImplementedError

    def pushpull(self, key, value, out=None, priority=0):
        """Aggregate ``value`` across devices/workers; write into ``out``."""
        raise NotImplementedError

    @classmethod
    def is_capable(cls, capability):
        return capability in getattr(cls, "CAPABILITIES", ())

    # ------------------------------------------------------------- identity
    @property
    def type(self):
        return type(self).__name__.lower()

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1
