"""``mx.np``: the NumPy-compatible array namespace.

Reference: ``python/mxnet/numpy/`` (SURVEY.md 2.2 ndarray row) — a
NumPy-semantics API (true broadcasting, zero-size and 0-d shapes, numpy
promotion rules) next to the legacy ``mx.nd`` namespace.

TPU-native redesign: the reference needed a parallel operator stack
(``_np_*`` kernels) because legacy MXNet ops had non-numpy semantics.
Here the array IS jax-backed, and ``jax.numpy`` already implements NumPy's
semantics exactly — so ``mx.np`` is a *generated veneer*: each function
unwraps NDArray→jax.Array, calls the ``jax.numpy`` twin, and re-wraps.
One source of truth for numerics; differentiable and jittable for free:
under ``autograd.record()`` each call routes through the op dispatcher
(``ops.registry.invoke``) so a TapeNode is attached exactly as for
``mx.nd`` ops — models written in ``mx.np`` train like Gluon models
(reference parity: GluonNLP-era models train on ``mx.np``).  Metadata
functions (``shape``, ``result_type``, …) stay tape-free.
"""
from __future__ import annotations

import builtins as _builtins
import sys as _sys
import types as _types

import numpy as _onp
import jax as _jax
import jax.numpy as _jnp

from ..base import MXNetError
from ..ndarray import NDArray

ndarray = NDArray   # mx.np.ndarray is the same runtime array type

# dtype / constant re-exports (reference: mxnet.numpy exposes numpy dtypes)
float16 = _onp.float16
float32 = _onp.float32
float64 = _onp.float64
bfloat16 = _jnp.bfloat16
int8 = _onp.int8
int16 = _onp.int16
int32 = _onp.int32
int64 = _onp.int64
uint8 = _onp.uint8
bool_ = _onp.bool_
pi = _onp.pi
e = _onp.e
euler_gamma = _onp.euler_gamma
inf = _onp.inf
nan = _onp.nan
newaxis = None
dtype = _onp.dtype


def _unwrap(x):
    # NB: use _builtins.any — this module's globals later gain a generated
    # `any` (the numpy reduction), which would shadow the builtin here
    if isinstance(x, NDArray):
        return x._data
    if isinstance(x, (list, tuple)) and _builtins.any(
            isinstance(e, NDArray) for e in x):
        return type(x)(_unwrap(e) for e in x)
    return x


def _rebuild_seq(typ, items):
    """Rebuild list/tuple/NamedTuple results (jnp.linalg returns
    NamedTuple types like EighResult, whose ctor takes *fields)."""
    if hasattr(typ, "_fields"):
        return typ._make(items)
    return typ(items)


def _wrap_out(out):
    if isinstance(out, (list, tuple)):
        return _rebuild_seq(type(out), [_wrap_out(o) for o in out])
    if hasattr(out, "dtype") and hasattr(out, "shape"):
        return NDArray(_jnp.asarray(out))
    return out


# metadata/introspection functions: python-value outputs, never taped
_NO_TAPE = frozenset({
    "shape", "ndim", "size", "result_type", "promote_types", "can_cast",
    "may_share_memory", "shares_memory", "isscalar", "iscomplexobj",
    "isrealobj",
})


class _Slot:
    """Placeholder for an NDArray leaf inside a call's (args, kwargs)
    template (see _invoke_recorded)."""
    __slots__ = ("i",)

    def __init__(self, i):
        self.i = i

    def __repr__(self):                 # stable across calls (cache keys)
        return f"<arr{self.i}>"


def _invoke_recorded(jfn, name, args, kwargs):
    """Route one np call through the op dispatcher so the autograd tape
    records it (same TapeNode machinery as every mx.nd op)."""
    from ..ops.registry import LightOpDef, invoke

    leaves = []

    def scan(x):
        if isinstance(x, NDArray):
            leaves.append(x)
            return _Slot(len(leaves) - 1)
        if isinstance(x, (list, tuple)):
            return type(x)(scan(e) for e in x)
        return x

    t_args = tuple(scan(a) for a in args)
    t_kwargs = {k: scan(v) for k, v in kwargs.items()}
    if not leaves:
        return None                     # nothing to tape: use eager path
    out_meta = {}

    def fn(*arrays):
        def fill(x):
            if isinstance(x, _Slot):
                return arrays[x.i]
            if isinstance(x, (list, tuple)):
                return type(x)(fill(e) for e in x)
            return x

        out = jfn(*[fill(a) for a in t_args],
                  **{k: fill(v) for k, v in t_kwargs.items()})
        if isinstance(out, (list, tuple)):
            out_meta["n"], out_meta["type"] = len(out), type(out)
            return tuple(out)
        out_meta["n"], out_meta["type"] = 1, None
        return out

    # Constants baked into the closure must be part of the bulk-replay
    # cache identity: two calls differing only in a scalar (multiply(x,3)
    # vs multiply(x,5)) would otherwise share a compiled backward and the
    # second would silently reuse the first's constant.  Array-valued
    # constants have no stable cheap repr — disable bulk keying for those.
    op_name = f"np.{name}"
    no_bulk = False
    if t_kwargs or _builtins.any(not isinstance(a, _Slot) for a in t_args):
        consts = (t_args, tuple(sorted(t_kwargs.items())))
        if _builtins.any(
                hasattr(c, "shape") and hasattr(c, "dtype")
                for c in _jax.tree_util.tree_leaves(consts)):
            no_bulk = True
        else:
            op_name = f"np.{name}/{repr(consts)}"
    opdef = LightOpDef(op_name, fn, len(leaves),
                       lambda kw: out_meta["n"])
    if no_bulk:
        opdef.no_bulk_key = True
    outs = invoke(opdef, leaves, {})
    if out_meta["type"] is not None:
        outs = outs if isinstance(outs, list) else [outs]
        return _rebuild_seq(out_meta["type"], outs)
    return outs


def _make(jfn, name):
    taped = name not in _NO_TAPE

    def f(*args, **kwargs):
        if taped:
            from .. import autograd
            if autograd.is_recording():
                try:
                    out = _invoke_recorded(jfn, name, args, kwargs)
                except MXNetError:
                    raise
                except Exception as exc:
                    raise MXNetError(f"np.{name}: {exc}") from exc
                if out is not None:
                    return out
        args = tuple(_unwrap(a) for a in args)
        kwargs = {k: _unwrap(v) for k, v in kwargs.items()}
        try:
            out = jfn(*args, **kwargs)
        except Exception as exc:
            raise MXNetError(f"np.{name}: {exc}") from exc
        return _wrap_out(out)

    f.__name__ = name
    f.__qualname__ = name
    f.__doc__ = (f"NumPy-semantics ``{name}`` (delegates to "
                 f"jax.numpy.{name}; see numpy docs).  Differentiable: "
                 f"records on the autograd tape under record().")
    return f


# Functions lifted verbatim from jax.numpy (numpy semantics by
# construction).  Grouped as the reference's mxnet/numpy modules do.
_FUNCS = [
    # creation
    "array", "asarray", "zeros", "ones", "full", "empty", "zeros_like",
    "ones_like", "full_like", "empty_like", "arange", "linspace",
    "logspace", "eye", "identity", "tri", "tril", "triu", "diag",
    "diagflat", "meshgrid", "indices", "fromfunction",
    # manipulation
    "reshape", "ravel", "transpose", "swapaxes", "moveaxis", "rollaxis",
    "expand_dims", "squeeze", "concatenate", "stack", "vstack", "hstack",
    "dstack", "column_stack", "split", "array_split", "hsplit", "vsplit",
    "dsplit", "tile", "repeat", "flip", "fliplr", "flipud", "roll",
    "rot90", "broadcast_to", "broadcast_arrays", "atleast_1d",
    "atleast_2d", "atleast_3d", "insert", "delete", "append", "pad",
    "trim_zeros", "unique",
    # math
    "add", "subtract", "multiply", "divide", "true_divide", "floor_divide",
    "power", "float_power", "mod", "remainder", "fmod", "divmod", "negative",
    "positive", "reciprocal", "abs", "absolute", "fabs", "sign", "rint",
    "exp", "exp2", "expm1", "log", "log2", "log10", "log1p", "sqrt", "cbrt",
    "square", "sin", "cos", "tan", "arcsin", "arccos", "arctan", "arctan2",
    "sinh", "cosh", "tanh", "arcsinh", "arccosh", "arctanh", "hypot",
    "degrees", "radians", "deg2rad", "rad2deg", "floor", "ceil", "trunc",
    "round", "around", "clip", "maximum", "minimum", "fmax", "fmin",
    "nan_to_num", "real", "imag", "conj", "conjugate", "angle", "i0",
    "sinc", "gcd", "lcm", "heaviside", "copysign", "frexp", "ldexp",
    "interp", "convolve", "correlate", "cross", "trapezoid", "ediff1d",
    "gradient", "diff", "cumsum", "cumprod", "nancumsum", "nancumprod",
    # NB "fix" omitted: deprecated in jax (alias of trunc)
    # reductions
    "sum", "prod", "mean", "std", "var", "min", "max", "amin", "amax",
    "nansum", "nanprod", "nanmean", "nanstd", "nanvar", "nanmin", "nanmax",
    "argmin", "argmax", "nanargmin", "nanargmax", "ptp", "median",
    "average", "percentile", "quantile", "count_nonzero", "any", "all",
    # sorting / searching
    "sort", "argsort", "partition", "argpartition", "searchsorted",
    "nonzero", "flatnonzero", "argwhere", "where", "extract", "take",
    "take_along_axis", "choose", "compress", "select", "digitize",
    # logic / comparison
    "equal", "not_equal", "greater", "greater_equal", "less", "less_equal",
    "logical_and", "logical_or", "logical_xor", "logical_not", "isfinite",
    "isinf", "isnan", "isneginf", "isposinf", "isclose", "allclose",
    "array_equal", "array_equiv", "signbit",
    # linear algebra (top-level)
    "dot", "vdot", "inner", "outer", "matmul", "tensordot", "einsum",
    "kron", "trace",
    # bit ops
    "bitwise_and", "bitwise_or", "bitwise_xor", "invert", "left_shift",
    "right_shift",
    # stats
    "histogram", "histogram2d", "histogram_bin_edges", "bincount", "cov",
    "corrcoef",
    # sets
    "intersect1d", "union1d", "setdiff1d", "setxor1d", "isin",
    # misc
    "shape", "ndim", "size", "copy", "result_type", "promote_types",
    "can_cast", "may_share_memory", "shares_memory", "iscomplexobj",
    "isrealobj", "isscalar", "vander", "unravel_index", "ravel_multi_index",
    "tril_indices", "triu_indices", "diag_indices",
]

_g = globals()
for _name in _FUNCS:
    _j = getattr(_jnp, _name, None)
    if _j is not None and _name not in _g:
        _g[_name] = _make(_j, _name)


# ---------------------------------------------------------------------------
# np.random / np.linalg / np.fft submodules
# ---------------------------------------------------------------------------

def _make_random():
    mod = _types.ModuleType(__name__ + ".random")
    mod.__doc__ = ("NumPy-style sampling over the framework PRNG "
                   "(mx.random.seed controls it; threefry keys under the "
                   "hood — reference: mxnet/numpy/random.py)")
    from .. import random as _mxrand

    def _key():
        return _mxrand.next_key()

    def uniform(low=0.0, high=1.0, size=None, dtype=None):
        shape = _norm_size(size)
        return NDArray(_jax.random.uniform(
            _key(), shape, minval=low, maxval=high,
            dtype=_jnp.dtype(dtype or "float32")))

    def normal(loc=0.0, scale=1.0, size=None, dtype=None):
        shape = _norm_size(size)
        return NDArray(_jax.random.normal(
            _key(), shape, dtype=_jnp.dtype(dtype or "float32"))
            * scale + loc)

    def randn(*shape):
        return normal(size=shape or ())

    def rand(*shape):
        return uniform(size=shape or ())

    def randint(low, high=None, size=None, dtype="int32"):
        if high is None:
            low, high = 0, low
        shape = _norm_size(size)
        return NDArray(_jax.random.randint(_key(), shape, low, high,
                                           dtype=_jnp.dtype(dtype)))

    def choice(a, size=None, replace=True, p=None):
        shape = _norm_size(size)
        a_arr = _unwrap(a)
        if isinstance(a_arr, int):
            a_arr = _jnp.arange(a_arr)
        return NDArray(_jax.random.choice(_key(), a_arr, shape, replace,
                                          _unwrap(p)))

    def permutation(x):
        if isinstance(x, int):
            return NDArray(_jax.random.permutation(_key(), x))
        return NDArray(_jax.random.permutation(_key(), _unwrap(x)))

    def shuffle(x):
        if not isinstance(x, NDArray):
            raise MXNetError("np.random.shuffle expects an ndarray")
        x._set_data(_jax.random.permutation(_key(), x._data))

    def seed(s):
        _mxrand.seed(s)

    def exponential(scale=1.0, size=None):
        shape = _norm_size(size)
        return NDArray(_jax.random.exponential(_key(), shape) * scale)

    def gamma(shape_param, scale=1.0, size=None):
        shp = _norm_size(size)
        return NDArray(_jax.random.gamma(_key(), shape_param, shp) * scale)

    def beta(a, b, size=None):
        shp = _norm_size(size)
        return NDArray(_jax.random.beta(_key(), a, b, shp))

    def binomial(n, p, size=None):
        shp = _norm_size(size)
        return NDArray(_jax.random.binomial(_key(), n, p, shape=shp))

    def multinomial(n, pvals, size=None):
        pv = _unwrap(pvals)
        shp = _norm_size(size)
        draws = _jax.random.categorical(
            _key(), _jnp.log(_jnp.asarray(pv)), shape=shp + (n,))
        counts = _jax.vmap(lambda d: _jnp.bincount(
            d, length=len(pv)))(draws.reshape(-1, n))
        return NDArray(counts.reshape(shp + (len(pv),)))

    for fn in (uniform, normal, randn, rand, randint, choice, permutation,
               shuffle, seed, exponential, gamma, beta, binomial,
               multinomial):
        setattr(mod, fn.__name__, fn)
    return mod


def _norm_size(size):
    if size is None:
        return ()
    if isinstance(size, int):
        return (size,)
    return tuple(size)


def _make_linalg():
    mod = _types.ModuleType(__name__ + ".linalg")
    mod.__doc__ = "numpy.linalg semantics via jax.numpy.linalg."
    for name in ("norm", "inv", "pinv", "det", "slogdet", "cholesky",
                 "qr", "svd", "eig", "eigh", "eigvals", "eigvalsh",
                 "solve", "lstsq", "matrix_rank", "matrix_power",
                 "tensorsolve", "tensorinv", "multi_dot"):
        jfn = getattr(_jnp.linalg, name, None)
        if jfn is not None:
            setattr(mod, name, _make(jfn, f"linalg.{name}"))
    return mod


def _make_fft():
    mod = _types.ModuleType(__name__ + ".fft")
    mod.__doc__ = "numpy.fft semantics via jax.numpy.fft."
    for name in ("fft", "ifft", "fft2", "ifft2", "fftn", "ifftn", "rfft",
                 "irfft", "rfft2", "irfft2", "rfftn", "irfftn", "fftfreq",
                 "rfftfreq", "fftshift", "ifftshift"):
        jfn = getattr(_jnp.fft, name, None)
        if jfn is not None:
            setattr(mod, name, _make(jfn, f"fft.{name}"))
    return mod


random = _make_random()
linalg = _make_linalg()
fft = _make_fft()
_sys.modules[random.__name__] = random
_sys.modules[linalg.__name__] = linalg
_sys.modules[fft.__name__] = fft
