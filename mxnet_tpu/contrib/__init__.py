"""contrib: experimental / auxiliary subsystems
(reference: ``python/mxnet/contrib/`` — SURVEY.md 2.2 contrib row).
"""
from . import amp

__all__ = ["amp"]
