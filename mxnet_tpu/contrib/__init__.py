"""contrib: experimental / auxiliary subsystems
(reference: ``python/mxnet/contrib/`` — SURVEY.md 2.2 contrib row).
"""
from . import amp
from . import quantization
from . import onnx
from . import text

__all__ = ["amp", "quantization", "onnx", "text"]
