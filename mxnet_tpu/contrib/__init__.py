"""contrib: experimental / auxiliary subsystems
(reference: ``python/mxnet/contrib/`` — SURVEY.md 2.2 contrib row).
"""
from . import amp
from . import quantization
from . import onnx
from . import text
from . import tensorboard

__all__ = ["amp", "quantization", "onnx", "text", "tensorboard"]
