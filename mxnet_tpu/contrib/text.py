"""Text utilities: vocabulary + token embeddings.

Reference surface: ``python/mxnet/contrib/text/`` —
``vocab.Vocabulary``, ``embedding.TokenEmbedding``/``CustomEmbedding``,
``utils.count_tokens_from_str``.  Pretrained-embedding downloads
(GloVe/fastText) need egress this build doesn't have; the file-backed
``CustomEmbedding`` covers the same API with local vectors.
"""
from __future__ import annotations

import re
from collections import Counter
from typing import List, Optional

import numpy as np

from ..base import MXNetError

__all__ = ["count_tokens_from_str", "Vocabulary", "CustomEmbedding",
           "get_pretrained_file_names"]


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Token frequency Counter from raw text (reference:
    text.utils.count_tokens_from_str)."""
    source_str = re.sub(
        f"[{re.escape(token_delim)}{re.escape(seq_delim)}]+", " ",
        source_str)
    if to_lower:
        source_str = source_str.lower()
    counter = counter_to_update if counter_to_update is not None \
        else Counter()
    counter.update(t for t in source_str.split(" ") if t)
    return counter


class Vocabulary:
    """Indexed vocabulary with reserved tokens (reference:
    text.vocab.Vocabulary)."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise MXNetError("min_freq must be >= 1")
        reserved_tokens = list(reserved_tokens or [])
        if unknown_token in reserved_tokens:
            raise MXNetError("unknown_token must not be reserved")
        if len(set(reserved_tokens)) != len(reserved_tokens):
            raise MXNetError("reserved_tokens must be unique")
        self._unknown_token = unknown_token
        self._reserved_tokens = reserved_tokens
        self._idx_to_token = [unknown_token] + reserved_tokens
        if counter is not None:
            pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
            if most_freq_count is not None:
                pairs = pairs[:most_freq_count]
            for tok, freq in pairs:
                if freq >= min_freq and tok not in self._idx_to_token[
                        :1 + len(reserved_tokens)]:
                    self._idx_to_token.append(tok)
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def idx_to_token(self) -> List[str]:
        return list(self._idx_to_token)

    @property
    def token_to_idx(self):
        return dict(self._token_to_idx)

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return list(self._reserved_tokens)

    def to_indices(self, tokens):
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        out = [self._token_to_idx.get(t, 0) for t in toks]
        return out[0] if single else out

    def to_tokens(self, indices):
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        out = []
        for i in idxs:
            if not 0 <= i < len(self._idx_to_token):
                raise MXNetError(f"token index {i} out of range")
            out.append(self._idx_to_token[i])
        return out[0] if single else out


class CustomEmbedding:
    """Token embedding loaded from a local vector file: one line per
    token, ``token v1 v2 ... vD`` (reference: text.embedding
    .CustomEmbedding)."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 encoding="utf8", vocabulary: Optional[Vocabulary] = None,
                 init_unknown_vec=None):
        tokens, vecs = [], []
        with open(pretrained_file_path, encoding=encoding) as f:
            for line in f:
                parts = line.rstrip().split(elem_delim)
                if len(parts) < 2:
                    continue
                tokens.append(parts[0])
                vecs.append(np.asarray([float(x) for x in parts[1:]],
                                       np.float32))
        if not tokens:
            raise MXNetError(f"no vectors found in {pretrained_file_path}")
        dim = len(vecs[0])
        for t, v in zip(tokens, vecs):
            if len(v) != dim:
                raise MXNetError(
                    f"inconsistent vector length for token {t!r}")
        self._vec_len = dim
        file_map = dict(zip(tokens, vecs))
        if vocabulary is None:
            vocabulary = Vocabulary(Counter(tokens))
        self._vocab = vocabulary
        unk = (init_unknown_vec or (lambda d: np.zeros(d, np.float32)))(dim)
        table = [np.asarray(unk, np.float32)]
        for tok in vocabulary.idx_to_token[1:]:
            table.append(file_map.get(tok, np.asarray(unk, np.float32)))
        self._idx_to_vec = np.stack(table)

    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        from .. import ndarray as nd
        return nd.array(self._idx_to_vec)

    def __len__(self):
        return len(self._vocab)

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        from .. import ndarray as nd
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        t2i = self._vocab._token_to_idx
        idxs = []
        for t in toks:
            i = t2i.get(t)
            if i is None and lower_case_backup:
                i = t2i.get(t.lower())
            idxs.append(0 if i is None else i)
        out = self._idx_to_vec[idxs]
        return nd.array(out[0] if single else out)

    def to_indices(self, tokens):
        return self._vocab.to_indices(tokens)

    def to_tokens(self, indices):
        return self._vocab.to_tokens(indices)


def get_pretrained_file_names(embedding_name=None):
    """Reference: text.embedding.get_pretrained_file_names — the download
    catalog needs network egress this build doesn't have."""
    raise MXNetError(
        "pretrained embedding downloads are unavailable (no network "
        "egress); load local vectors with contrib.text.CustomEmbedding")
