"""INT8 post-training quantization frontend.

Reference surface: ``python/mxnet/contrib/quantization.py`` —
``quantize_model`` (symbolic graph pass), ``quantize_net`` (Gluon),
naive min/max and KL-divergence ("entropy") calibration
(``_get_optimal_threshold``, ``_LayerOutputCollector``) — SURVEY.md 2.2
contrib row; op layer in ops/quantization.py.

TPU-native notes: quantized compute runs int8×int8→int32 on the MXU
(ops/quantization.py); the quantize/dequantize sandwich around each layer
is elementwise jnp that XLA fuses away, so a quantized layer is a single
fused kernel.  Only signed int8 is supported (uint8 buys nothing on TPU).
"""
from __future__ import annotations

import fnmatch
import logging
from collections import OrderedDict

import numpy as np

from ..base import MXNetError

__all__ = ["quantize_net", "quantize_model", "quantize_graph",
           "CalibrationCollector", "calib_graph"]


# ---------------------------------------------------------------------------
# Calibration
# ---------------------------------------------------------------------------

def _get_optimal_threshold(hist, hist_edges, num_quantized_bins=255):
    """KL-divergence threshold search (reference:
    quantization.py _get_optimal_threshold / _smooth_distribution).

    ``hist`` is a symmetric histogram of absolute activations around 0.
    Returns the |threshold| minimizing KL(P || Q) between the clipped fp32
    distribution P and its num_quantized_bins-level quantization Q.
    """
    num_bins = len(hist)
    zero_bin = num_bins // 2
    thresholds = []
    divergences = []
    # candidate thresholds: growing symmetric windows around the zero bin
    for i in range(num_quantized_bins // 2 + 1, zero_bin + 1):
        p_start, p_stop = zero_bin - i, zero_bin + i + 1
        threshold = hist_edges[p_stop]
        sliced = hist[p_start:p_stop].astype(np.float64)
        p = sliced.copy()
        # outliers are clipped into the boundary bins
        p[0] += hist[:p_start].sum()
        p[-1] += hist[p_stop:].sum()
        is_nonzero = p != 0
        # quantize the window into num_quantized_bins buckets
        num_merged = len(sliced) // num_quantized_bins
        q = np.zeros(len(p), np.float64)
        for j in range(num_quantized_bins):
            start = j * num_merged
            stop = (j + 1) * num_merged if j != num_quantized_bins - 1 \
                else len(sliced)
            seg = sliced[start:stop]
            nz = (seg != 0).sum()
            if nz:
                q[start:stop] = np.where(seg != 0, seg.sum() / nz, 0.0)
        p /= max(p.sum(), 1e-12)
        qs = q.sum()
        if qs == 0:
            continue
        q /= qs
        q[q == 0] = 1e-10
        kl = float(np.sum(p[is_nonzero]
                          * np.log(p[is_nonzero] / q[is_nonzero])))
        thresholds.append(float(threshold))
        divergences.append(kl)
    if not thresholds:
        return float(hist_edges[-1])
    return thresholds[int(np.argmin(divergences))]


class CalibrationCollector:
    """Accumulates per-tensor calibration statistics across batches
    (reference: _LayerOutputMinMaxCollector / _LayerHistogramCollector)."""

    def __init__(self, mode="naive", num_bins=8001):
        if mode not in ("naive", "entropy"):
            raise MXNetError(f"calib_mode must be naive|entropy, got {mode}")
        self.mode = mode
        self.num_bins = num_bins
        self.min_max = OrderedDict()        # name -> (min, max)
        self.hists = OrderedDict()          # name -> (hist, edges)

    def collect(self, name, arr):
        a = np.asarray(arr, np.float32)
        mn, mx = float(a.min()), float(a.max())
        old = self.min_max.get(name)
        if old is not None:
            mn, mx = min(mn, old[0]), max(mx, old[1])
        self.min_max[name] = (mn, mx)
        if self.mode == "entropy":
            amax = max(abs(mn), abs(mx), 1e-8)
            prev = self.hists.get(name)
            if prev is not None and prev[1][-1] >= amax:
                hist, edges = np.histogram(a, bins=prev[1])
                self.hists[name] = (prev[0] + hist, prev[1])
            else:
                edges = np.linspace(-amax, amax, self.num_bins + 1)
                hist, _ = np.histogram(a, bins=edges)
                if prev is not None:
                    # re-bin the old histogram into the wider range
                    centers = (prev[1][:-1] + prev[1][1:]) / 2
                    rebin, _ = np.histogram(centers, bins=edges,
                                            weights=prev[0])
                    hist = hist + rebin.astype(hist.dtype)
                self.hists[name] = (hist, edges)

    def ranges(self):
        """Final calibration ranges per collected tensor."""
        out = OrderedDict()
        for name, (mn, mx) in self.min_max.items():
            if self.mode == "entropy":
                hist, edges = self.hists[name]
                t = _get_optimal_threshold(hist, edges)
                out[name] = (-t, t)
            else:
                out[name] = (mn, mx)
        return out


# ---------------------------------------------------------------------------
# Gluon path: quantize_net
# ---------------------------------------------------------------------------

def _quantize_param(p):
    """Quantize one fp32 parameter offline → (int8 NDArray, min, max)."""
    from .. import ndarray as nd
    data = p.data() if hasattr(p, "data") else p
    q, mn, mx = nd.quantize_v2(data.astype("float32"))
    return q, mn, mx


def _make_quantized_blocks():
    """Defer gluon import to avoid a cycle at package import time."""
    from ..gluon.block import HybridBlock

    class QuantizedDense(HybridBlock):
        """int8 replacement for nn.Dense built by quantize_net
        (reference: the quantized_fully_connected subgraph)."""

        def __init__(self, dense, calib_range, **kwargs):
            super().__init__(**kwargs)
            from .. import ndarray as nd
            self._units = dense._units
            self._flatten = dense._flatten
            self._activation = dense._activation
            self._calib = calib_range      # None = dynamic per-batch range
            self._qweight, self._wmin, self._wmax = \
                _quantize_param(dense.weight)
            if dense.bias is not None:
                self._qbias, self._bmin, self._bmax = \
                    _quantize_param(dense.bias)
            else:
                self._qbias = None

        def hybrid_forward(self, F, x):
            from .. import ndarray as nd
            if self._calib is not None:
                qx, xmn, xmx = nd.quantize_v2(
                    x, min_calib_range=self._calib[0],
                    max_calib_range=self._calib[1])
            else:
                qx, xmn, xmx = nd.quantize_v2(x)
            if self._qbias is not None:
                out32, omn, omx = nd.quantized_fully_connected(
                    qx, self._qweight, self._qbias, xmn, xmx,
                    self._wmin, self._wmax, self._bmin, self._bmax,
                    num_hidden=self._units, flatten=self._flatten)
            else:
                out32, omn, omx = nd.quantized_fully_connected(
                    qx, self._qweight, None, xmn, xmx,
                    self._wmin, self._wmax, None, None,
                    num_hidden=self._units, flatten=self._flatten,
                    no_bias=True)
            out = nd.dequantize(out32, omn, omx)
            if self._activation is not None:
                out = nd.Activation(out, act_type=self._activation)
            return out

    class QuantizedConv(HybridBlock):
        """int8 replacement for nn.Conv2D/Conv1D/Conv3D
        (reference: the quantized_conv subgraph)."""

        def __init__(self, conv, calib_range, **kwargs):
            super().__init__(**kwargs)
            self._kernel = conv._kernel
            self._strides = conv._strides
            self._padding = conv._padding
            self._dilation = conv._dilation
            self._groups = conv._groups
            self._channels = conv._channels
            self._activation = conv._activation
            self._calib = calib_range
            self._qweight, self._wmin, self._wmax = \
                _quantize_param(conv.weight)
            if conv.bias is not None:
                self._qbias, self._bmin, self._bmax = \
                    _quantize_param(conv.bias)
            else:
                self._qbias = None

        def hybrid_forward(self, F, x):
            from .. import ndarray as nd
            if self._calib is not None:
                qx, xmn, xmx = nd.quantize_v2(
                    x, min_calib_range=self._calib[0],
                    max_calib_range=self._calib[1])
            else:
                qx, xmn, xmx = nd.quantize_v2(x)
            args = dict(kernel=self._kernel, stride=self._strides,
                        dilate=self._dilation, pad=self._padding,
                        num_filter=self._channels, num_group=self._groups)
            if self._qbias is not None:
                out32, omn, omx = nd.quantized_conv(
                    qx, self._qweight, self._qbias, xmn, xmx,
                    self._wmin, self._wmax, self._bmin, self._bmax, **args)
            else:
                out32, omn, omx = nd.quantized_conv(
                    qx, self._qweight, None, xmn, xmx,
                    self._wmin, self._wmax, None, None,
                    no_bias=True, **args)
            out = nd.dequantize(out32, omn, omx)
            if self._activation is not None:
                out = nd.Activation(out, act_type=self._activation)
            return out

    return QuantizedDense, QuantizedConv


def _walk_candidates(block, exclude_layers, exclude_layers_match, prefix=""):
    """Yield (parent, child_key, attr_name, layer, path) for every
    quantizable layer (Dense / forward Conv)."""
    from ..gluon import nn
    for key, child in list(block._children.items()):
        path = f"{prefix}{key}"
        is_dense = isinstance(child, nn.Dense)
        is_conv = isinstance(child, (nn.Conv1D, nn.Conv2D, nn.Conv3D))
        if is_dense or is_conv:
            name = child.name
            if exclude_layers and name in exclude_layers:
                continue
            if exclude_layers_match and any(
                    fnmatch.fnmatch(name, pat) or pat in name
                    for pat in exclude_layers_match):
                continue
            attr = None
            for k, v in block.__dict__.items():
                if v is child:
                    attr = k
                    break
            yield block, key, attr, child, path
        else:
            yield from _walk_candidates(child, exclude_layers,
                                        exclude_layers_match, path + ".")


def quantize_net(network, quantized_dtype="int8", quantize_mode="full",
                 exclude_layers=None, exclude_layers_match=None,
                 calib_data=None, data_shapes=None, calib_mode="none",
                 num_calib_batches=None, ctx=None, logger=None):
    """Quantize a Gluon network in place-of (reference: quantize_net).

    calib_mode:
      'none'    — dynamic: every batch computes its own input ranges.
      'naive'   — min/max over ``calib_data`` batches.
      'entropy' — KL-optimal thresholds over ``calib_data`` batches.
    Returns the same network object with Dense/Conv children swapped for
    int8 blocks; the original blocks' fp32 weights are quantized offline.
    """
    if quantized_dtype != "int8":
        raise MXNetError("only int8 is supported (TPU has no uint8 path)")
    logger = logger or logging.getLogger(__name__)
    QuantizedDense, QuantizedConv = _make_quantized_blocks()
    from ..gluon import nn

    cands = list(_walk_candidates(network, exclude_layers,
                                  exclude_layers_match))
    if not cands:
        raise MXNetError("quantize_net: no quantizable Dense/Conv layers "
                         "found (or all excluded)")

    calib_ranges = {}
    if calib_mode in ("naive", "entropy"):
        if calib_data is None:
            raise MXNetError(f"calib_mode={calib_mode!r} needs calib_data")
        collector = CalibrationCollector(mode=calib_mode)
        handles = []
        for _, _, _, layer, path in cands:
            def mk(path):
                def pre_hook(blk, args):
                    collector.collect(path, args[0].asnumpy())
                return pre_hook
            layer._forward_pre_hooks.append(mk(path))
            handles.append(layer)
        try:
            for i, batch in enumerate(calib_data):
                if num_calib_batches is not None and i >= num_calib_batches:
                    break
                data = batch[0] if isinstance(batch, (list, tuple)) else batch
                network(data)
        finally:
            for layer in handles:
                layer._forward_pre_hooks.pop()
        calib_ranges = collector.ranges()
        logger.info("calibrated %d tensors (%s)", len(calib_ranges),
                    calib_mode)
    elif calib_mode != "none":
        raise MXNetError(f"unknown calib_mode {calib_mode!r}")

    n = 0
    for parent, key, attr, layer, path in cands:
        crange = calib_ranges.get(path)
        if isinstance(layer, nn.Dense):
            qblock = QuantizedDense(layer, crange)
        else:
            qblock = QuantizedConv(layer, crange)
        parent._children[key] = qblock
        if attr is not None:
            parent.__dict__[attr] = qblock
        n += 1
    logger.info("quantized %d layers", n)
    return network


# ---------------------------------------------------------------------------
# Symbolic path: quantize_model / quantize_graph
# ---------------------------------------------------------------------------

_QUANTIZABLE = {"FullyConnected": "_contrib_quantized_fully_connected",
                "Convolution": "_contrib_quantized_conv"}


def quantize_graph(sym, excluded_sym_names=(), calib_ranges=None):
    """Rewrite a Symbol graph: each FullyConnected/Convolution becomes a
    quantize→quantized-op→dequantize sandwich (reference: the C++
    QuantizeGraph pass driven from quantize_model).

    Returns (qsym, needed_param_transforms) where the latter maps
    ``weight_name -> base_name`` for every weight/bias variable that
    ``quantize_params`` must convert to int8 + range scalars.
    """
    from ..ops.registry import get_op
    from ..symbol.symbol import Symbol, _SymNode, var

    calib_ranges = calib_ranges or {}
    excluded = set(excluded_sym_names)
    mapping = {}                      # id(old node) -> new node
    param_transforms = {}

    def mapped(entry):
        node, idx = entry
        return (mapping[id(node)], idx)

    for node in sym._topo():
        if node.is_variable:
            mapping[id(node)] = node
            continue
        new_inputs = [mapped(e) for e in node.inputs]
        opname = node.op.name
        if opname in _QUANTIZABLE and node.name not in excluded:
            qop = get_op(_QUANTIZABLE[opname])
            data_e = new_inputs[0]
            weight_e = new_inputs[1]
            no_bias = bool(node.kwargs.get("no_bias", False))
            bias_e = None if no_bias or len(new_inputs) < 3 \
                else new_inputs[2]
            if not weight_e[0].is_variable or (
                    bias_e is not None and not bias_e[0].is_variable):
                # weight produced by another op — leave the node fp32
                mapping[id(node)] = _SymNode(node.op, new_inputs,
                                             dict(node.kwargs), node.name,
                                             node.num_outputs)
                continue
            # offline-quantized weight/bias variables
            wname = weight_e[0].name
            param_transforms[wname] = wname
            qw = var(wname + "_quantize")._outputs[0][0]
            wmn = var(wname + "_min")._outputs[0][0]
            wmx = var(wname + "_max")._outputs[0][0]
            if bias_e is not None:
                bname = bias_e[0].name
                param_transforms[bname] = bname
                qb = var(bname + "_quantize")._outputs[0][0]
                bmn = var(bname + "_min")._outputs[0][0]
                bmx = var(bname + "_max")._outputs[0][0]
            # runtime-quantized data input
            qkw = {}
            crange = calib_ranges.get(node.name)
            if crange is not None:
                qkw = {"min_calib_range": float(crange[0]),
                       "max_calib_range": float(crange[1])}
            qdata = _SymNode(get_op("_contrib_quantize_v2"), [data_e], qkw,
                             node.name + "_quantize", 3)
            qinputs = [(qdata, 0),
                       (qw, 0),
                       (qb, 0) if bias_e is not None else (qdata, 0),
                       (qdata, 1), (qdata, 2), (wmn, 0), (wmx, 0)]
            qkwargs = dict(node.kwargs)
            if bias_e is not None:
                qinputs += [(bmn, 0), (bmx, 0)]
            else:
                qinputs += [(qdata, 1), (qdata, 2)]
                qkwargs["no_bias"] = True
            qnode = _SymNode(qop, qinputs, qkwargs,
                             "quantized_" + node.name, 3)
            deq = _SymNode(get_op("_contrib_dequantize"),
                           [(qnode, 0), (qnode, 1), (qnode, 2)], {},
                           node.name, 1)
            mapping[id(node)] = deq
        else:
            mapping[id(node)] = _SymNode(node.op, new_inputs,
                                         dict(node.kwargs), node.name,
                                         node.num_outputs)
    qsym = Symbol([mapped(e) for e in sym._outputs])
    return qsym, param_transforms


def quantize_params(qsym, arg_params):
    """Produce the quantized arg dict for a rewritten graph (reference:
    quantize_params): every ``X_quantize`` variable gets int8 data plus
    ``X_min``/``X_max`` scalars; untouched fp32 params pass through."""
    needed = set(qsym.list_arguments())
    out = {}
    for name, value in arg_params.items():
        if name + "_quantize" in needed:
            q, mn, mx = _quantize_param(value)
            out[name + "_quantize"] = q
            out[name + "_min"] = mn
            out[name + "_max"] = mx
        elif name in needed:
            out[name] = value
    return out


def calib_graph(sym, arg_params, aux_params, calib_data, data_names=("data",),
                calib_mode="naive", num_calib_batches=None):
    """Collect per-quantizable-node input ranges by evaluating the fp32
    graph's internals over calibration batches (reference: the
    collect_layer_output step of quantize_model)."""
    collector = CalibrationCollector(mode=calib_mode)
    internals = sym.get_internals()
    out_names = internals.list_outputs()
    # which internal outputs feed quantizable nodes, keyed by consumer name
    wanted = {}                      # internal output index -> node name
    topo = sym._topo()
    index_of = {}
    k = 0
    for n in topo:
        for i in range(n.num_outputs):
            index_of[(id(n), i)] = k
            k += 1
    for node in topo:
        if not node.is_variable and node.op.name in _QUANTIZABLE:
            src, si = node.inputs[0]
            wanted[index_of[(id(src), si)]] = node.name
    for bi, batch in enumerate(calib_data):
        if num_calib_batches is not None and bi >= num_calib_batches:
            break
        if not isinstance(batch, (list, tuple)):
            batch = (batch,)
        feed = dict(arg_params)
        feed.update(aux_params or {})
        feed.update(dict(zip(data_names, batch)))
        outs = internals.eval(**feed)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        for idx, consumer in wanted.items():
            collector.collect(consumer, outs[idx].asnumpy())
    return collector.ranges()


def quantize_model(sym, arg_params, aux_params=None, data_names=("data",),
                   ctx=None, excluded_sym_names=None, calib_mode="none",
                   calib_data=None, num_calib_examples=None,
                   num_calib_batches=None, quantized_dtype="int8",
                   logger=None):
    """Quantize a symbolic model (reference: contrib.quantization
    .quantize_model).  Returns (qsym, qarg_params, aux_params)."""
    if quantized_dtype != "int8":
        raise MXNetError("only int8 is supported (TPU has no uint8 path)")
    aux_params = aux_params or {}
    calib_ranges = None
    if calib_mode in ("naive", "entropy"):
        if calib_data is None:
            raise MXNetError(f"calib_mode={calib_mode!r} needs calib_data")
        calib_ranges = calib_graph(sym, arg_params, aux_params, calib_data,
                                   data_names, calib_mode,
                                   num_calib_batches)
    elif calib_mode != "none":
        raise MXNetError(f"unknown calib_mode {calib_mode!r}")
    qsym, _ = quantize_graph(sym, excluded_sym_names or (), calib_ranges)
    qargs = quantize_params(qsym, arg_params)
    return qsym, qargs, aux_params
