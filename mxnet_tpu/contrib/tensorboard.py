"""TensorBoard event-file logging (mxboard equivalent).

Reference surface: upstream MXNet delegates TensorBoard logging to the
external ``mxboard`` package (``python/mxnet/contrib/tensorboard.py`` is
a thin ``LogMetricsCallback``) — SURVEY.md §5.5 "TensorBoard via external
mxboard (event-file writer); not in-repo".  This build has no egress, so
the writer is self-contained: TFRecord framing (length + masked CRC32C)
around hand-schemed ``Event``/``Summary`` protobufs, encoded with the
shared wire codec from ``contrib.onnx._proto`` — no tensorflow /
tensorboard / protoc dependency.  Files are readable by any stock
TensorBoard.

API mirrors mxboard's ``SummaryWriter``:

    with SummaryWriter(logdir="./logs") as sw:
        sw.add_scalar("loss", 0.5, global_step=1)
        sw.add_histogram("weights", nd_or_np_array, global_step=1)
        sw.add_image("sample", hwc_uint8_array, global_step=1)
        sw.add_text("note", "hello", global_step=1)

plus the upstream in-repo ``LogMetricsCallback`` for ``Module.fit``-style
batch-end callbacks.
"""
from __future__ import annotations

import os
import socket
import struct
import time
from typing import Optional

import numpy as np

from .onnx._proto import SCHEMAS, decode, encode

__all__ = ["SummaryWriter", "LogMetricsCallback", "read_events"]

# TF event.proto / summary.proto field numbers (stable public wire
# contract).  Names are prefixed TF* where they would collide with the
# ONNX messages sharing the codec's schema registry.
SCHEMAS.update({
    "Event": {
        "wall_time": (1, "double"),
        "step": (2, "int"),
        "file_version": (3, "str"),
        "summary": (5, "msg:Summary"),
    },
    "Summary": {
        "value": (1, "rep_msg:SummaryValue"),
    },
    "SummaryValue": {
        "tag": (1, "str"),
        "simple_value": (2, "float"),
        "image": (4, "msg:SummaryImage"),
        "histo": (5, "msg:HistogramProto"),
        "tensor": (8, "msg:TFTensorProto"),
        "metadata": (9, "msg:SummaryMetadata"),
    },
    "SummaryImage": {
        "height": (1, "int"),
        "width": (2, "int"),
        "colorspace": (3, "int"),
        "encoded_image_string": (4, "bytes"),
    },
    "HistogramProto": {
        "min": (1, "double"),
        "max": (2, "double"),
        "num": (3, "double"),
        "sum": (4, "double"),
        "sum_squares": (5, "double"),
        "bucket_limit": (6, "rep_double"),
        "bucket": (7, "rep_double"),
    },
    "SummaryMetadata": {
        "plugin_data": (1, "msg:PluginData"),
        "display_name": (2, "str"),
    },
    "PluginData": {
        "plugin_name": (1, "str"),
        "content": (2, "bytes"),
    },
    "TFTensorProto": {
        "dtype": (1, "int"),           # DataType enum; DT_STRING = 7
        "tensor_shape": (2, "msg:TFTensorShapeProto"),
        "string_val": (8, "rep_bytes"),
    },
    "TFTensorShapeProto": {
        "dim": (2, "rep_msg:TFTensorShapeDim"),
    },
    "TFTensorShapeDim": {
        "size": (1, "int"),
        "name": (2, "str"),
    },
})

_DT_STRING = 7


# --------------------------------------------------------------------------
# CRC32C (Castagnoli) — table-driven; TFRecord framing masks it.
# --------------------------------------------------------------------------

def _make_crc32c_table():
    poly = 0x82F63B78  # reflected Castagnoli polynomial
    table = []
    for n in range(256):
        crc = n
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        table.append(crc)
    return table


_CRC_TABLE = _make_crc32c_table()


def _crc32c(data: bytes, crc: int = 0) -> int:
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# --------------------------------------------------------------------------
# Summary builders (dict messages for the shared codec)
# --------------------------------------------------------------------------

def _histogram_msg(values: np.ndarray, bins: int = 30) -> dict:
    v = np.asarray(values, np.float64).ravel()
    if v.size == 0:
        v = np.zeros((1,), np.float64)
    counts, edges = np.histogram(v, bins=bins)
    return {"min": float(v.min()), "max": float(v.max()),
            "num": float(v.size), "sum": float(v.sum()),
            "sum_squares": float((v * v).sum()),
            "bucket_limit": list(edges[1:]),
            "bucket": [float(c) for c in counts]}


def _image_msg(img: np.ndarray) -> dict:
    from ..image.image import imencode
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if arr.dtype != np.uint8:
        a = arr.astype(np.float64)
        lo, hi = a.min(), a.max()
        if hi > lo:
            a = (a - lo) / (hi - lo)
        arr = (np.clip(a, 0.0, 1.0) * 255.0 + 0.5).astype(np.uint8)
    h, w, c = arr.shape
    return {"height": h, "width": w, "colorspace": c,
            "encoded_image_string": imencode(arr, ".png")}


def _text_msg(text: str) -> dict:
    # the "text" plugin reads a rank-1 DT_STRING tensor
    return {"tensor": {"dtype": _DT_STRING,
                       "tensor_shape": {"dim": [{"size": 1}]},
                       "string_val": [text.encode("utf-8")]},
            "metadata": {"plugin_data": {"plugin_name": "text"}}}


def _event(values=None, step: Optional[int] = None,
           file_version: Optional[str] = None) -> bytes:
    ev = {"wall_time": time.time()}
    if step is not None:
        ev["step"] = int(step)
    if file_version is not None:
        ev["file_version"] = file_version
    if values:
        ev["summary"] = {"value": values}
    return encode("Event", ev)


# --------------------------------------------------------------------------
# Writer
# --------------------------------------------------------------------------

_WRITER_SEQ = [0]


class SummaryWriter:
    """Writes TensorBoard event files (mxboard.SummaryWriter surface)."""

    def __init__(self, logdir, flush_secs=120, filename_suffix=""):
        self._logdir = str(logdir)
        os.makedirs(self._logdir, exist_ok=True)
        # pid + per-process counter keep two writers created in the same
        # wall-clock second from clobbering each other's file
        _WRITER_SEQ[0] += 1
        fname = "events.out.tfevents.%010d.%s.%d.%d%s" % (
            int(time.time()), socket.gethostname(), os.getpid(),
            _WRITER_SEQ[0], filename_suffix)
        self._path = os.path.join(self._logdir, fname)
        self._file = open(self._path, "wb")
        self._flush_secs = flush_secs
        self._last_flush = time.time()
        self._write_event(_event(file_version="brain.Event:2"))
        self.flush()

    # -- record framing ---------------------------------------------------
    def _write_event(self, event: bytes):
        if self._file is None:
            raise ValueError("SummaryWriter is closed")
        header = struct.pack("<Q", len(event))
        self._file.write(header)
        self._file.write(struct.pack("<I", _masked_crc(header)))
        self._file.write(event)
        self._file.write(struct.pack("<I", _masked_crc(event)))
        if time.time() - self._last_flush >= self._flush_secs:
            self.flush()

    @staticmethod
    def _to_numpy(values):
        if hasattr(values, "asnumpy"):
            return values.asnumpy()
        return np.asarray(values)

    # -- public API -------------------------------------------------------
    def add_scalar(self, tag, value, global_step=None):
        if hasattr(value, "asscalar"):
            value = value.asscalar()
        self._write_event(_event(
            [{"tag": tag, "simple_value": float(value)}], step=global_step))

    def add_histogram(self, tag, values, global_step=None, bins=30):
        self._write_event(_event(
            [{"tag": tag,
              "histo": _histogram_msg(self._to_numpy(values), bins)}],
            step=global_step))

    def add_image(self, tag, image, global_step=None):
        """`image`: HWC (or HW) uint8 / float array or NDArray.  Float
        images are min-max normalized (constant images clamp to [0,1])."""
        self._write_event(_event(
            [{"tag": tag, "image": _image_msg(self._to_numpy(image))}],
            step=global_step))

    def add_text(self, tag, text, global_step=None):
        self._write_event(_event(
            [dict(_text_msg(str(text)), tag=tag)], step=global_step))

    def flush(self):
        if self._file is not None:
            self._file.flush()
            self._last_flush = time.time()

    def close(self):
        if self._file is not None:
            self.flush()
            self._file.close()
            self._file = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def get_logdir(self):
        return self._logdir


class LogMetricsCallback:
    """Batch-end callback streaming `eval_metric` to TensorBoard
    (reference: ``python/mxnet/contrib/tensorboard.LogMetricsCallback``).
    """

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.summary_writer = SummaryWriter(logging_dir)
        self._step = 0

    def __call__(self, param):
        if param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)
            self.summary_writer.add_scalar(name, value, self._step)
        self._step += 1


# --------------------------------------------------------------------------
# Reader (round-trip verification + offline inspection without TB).
# --------------------------------------------------------------------------

def read_events(path):
    """Parse an event file back into dicts (verifies CRCs).

    Returns a list of ``{"wall_time", "step", "file_version", "values"}``
    where ``values`` maps tag → scalar float / ``{"histo": ...}`` /
    ``{"image": (h, w, c, png_bytes)}`` / ``{"text": str}``.
    """
    events = []
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    while pos < len(data):
        header = data[pos:pos + 8]
        (length,) = struct.unpack("<Q", header)
        (hcrc,) = struct.unpack("<I", data[pos + 8:pos + 12])
        if hcrc != _masked_crc(header):
            raise ValueError("event file corrupt: bad header crc")
        payload = data[pos + 12:pos + 12 + length]
        (pcrc,) = struct.unpack("<I",
                                data[pos + 12 + length:pos + 16 + length])
        if pcrc != _masked_crc(payload):
            raise ValueError("event file corrupt: bad payload crc")
        pos += 16 + length

        raw = decode("Event", payload)
        ev = {"wall_time": raw.get("wall_time"), "step": raw.get("step"),
              "file_version": raw.get("file_version"), "values": {}}
        for val in raw.get("summary", {}).get("value", []):
            tag = val.get("tag")
            if tag is None:
                continue
            if "simple_value" in val:
                ev["values"][tag] = val["simple_value"]
            elif "histo" in val:
                ev["values"][tag] = {"histo": val["histo"]}
            elif "image" in val:
                im = val["image"]
                ev["values"][tag] = {"image": (
                    im.get("height"), im.get("width"), im.get("colorspace"),
                    im.get("encoded_image_string", b""))}
            elif "tensor" in val:
                sv = val["tensor"].get("string_val", [b""])
                ev["values"][tag] = {"text": sv[0].decode("utf-8")}
        events.append(ev)
    return events
