"""ONNX interop (reference: ``python/mxnet/contrib/onnx`` — mx2onnx
export + onnx2mx import).  The protobuf wire format is implemented
in-tree (``_proto.py``) because the ``onnx`` pip package is not part of
this build; files produced here follow onnx.proto3 IR v8 / opset 13 and
are readable by the real onnx tooling for the supported op subset.
"""
from .mx2onnx import export_model
from .onnx2mx import import_model

# reference alias: mx.contrib.onnx.onnx_net / get_model naming
import_to_gluon = None          # gluon import arrives via SymbolBlock

__all__ = ["export_model", "import_model"]
