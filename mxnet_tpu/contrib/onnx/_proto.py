"""Minimal protobuf wire-format codec (schema-driven, write+read).

Primary consumer is the ONNX message subset below; ``contrib.tensorboard``
registers the TF ``Event``/``Summary`` schemas into the same registry and
reuses the codec for event files.

Reference surface: ``python/mxnet/contrib/onnx`` depends on the ``onnx``
pip package for ModelProto serialization; that package is not available in
this build, so the wire format (proto3) is implemented directly — varint /
64-bit / length-delimited / 32-bit field encodings over a declarative
schema of the ONNX messages we emit and read (onnx/onnx.proto3, IR v8).

Messages are plain dicts; repeated fields are lists.  The decoder accepts
both packed and unpacked repeated scalars, skips unknown fields, and is
therefore compatible with files produced by the real onnx library for the
message subset used here.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Tuple

# --------------------------------------------------------------------------
# ONNX enums
# --------------------------------------------------------------------------

# TensorProto.DataType
FLOAT, UINT8, INT8, UINT16, INT16, INT32, INT64 = 1, 2, 3, 4, 5, 6, 7
STRING, BOOL, FLOAT16, DOUBLE, UINT32, UINT64 = 8, 9, 10, 11, 12, 13
BFLOAT16 = 16

NP_TO_ONNX = {"float32": FLOAT, "float64": DOUBLE, "float16": FLOAT16,
              "int8": INT8, "uint8": UINT8, "int32": INT32, "int64": INT64,
              "bool": BOOL, "bfloat16": BFLOAT16}
ONNX_TO_NP = {v: k for k, v in NP_TO_ONNX.items()}

# AttributeProto.AttributeType
A_FLOAT, A_INT, A_STRING, A_TENSOR = 1, 2, 3, 4
A_FLOATS, A_INTS, A_STRINGS = 6, 7, 8

# --------------------------------------------------------------------------
# Schemas: field name -> (field_number, kind)
# kind: 'int' varint | 'float' 32-bit | 'str' | 'bytes' | 'msg:<Name>'
#       prefix 'rep_' marks repeated fields
# --------------------------------------------------------------------------

SCHEMAS: Dict[str, Dict[str, Tuple[int, str]]] = {
    "ModelProto": {
        "ir_version": (1, "int"),
        "producer_name": (2, "str"),
        "producer_version": (3, "str"),
        "domain": (4, "str"),
        "model_version": (5, "int"),
        "doc_string": (6, "str"),
        "graph": (7, "msg:GraphProto"),
        "opset_import": (8, "rep_msg:OperatorSetIdProto"),
    },
    "OperatorSetIdProto": {
        "domain": (1, "str"),
        "version": (2, "int"),
    },
    "GraphProto": {
        "node": (1, "rep_msg:NodeProto"),
        "name": (2, "str"),
        "initializer": (5, "rep_msg:TensorProto"),
        "doc_string": (10, "str"),
        "input": (11, "rep_msg:ValueInfoProto"),
        "output": (12, "rep_msg:ValueInfoProto"),
        "value_info": (13, "rep_msg:ValueInfoProto"),
    },
    "NodeProto": {
        "input": (1, "rep_str"),
        "output": (2, "rep_str"),
        "name": (3, "str"),
        "op_type": (4, "str"),
        "attribute": (5, "rep_msg:AttributeProto"),
        "doc_string": (6, "str"),
        "domain": (7, "str"),
    },
    "AttributeProto": {
        "name": (1, "str"),
        "f": (2, "float"),
        "i": (3, "int"),
        "s": (4, "bytes"),
        "t": (5, "msg:TensorProto"),
        "floats": (7, "rep_float"),
        "ints": (8, "rep_int"),
        "strings": (9, "rep_bytes"),
        "type": (20, "int"),
    },
    "TensorProto": {
        "dims": (1, "rep_int"),
        "data_type": (2, "int"),
        "float_data": (4, "rep_float"),
        "int32_data": (5, "rep_int"),
        "int64_data": (7, "rep_int"),
        "name": (8, "str"),
        "raw_data": (9, "bytes"),
    },
    "ValueInfoProto": {
        "name": (1, "str"),
        "type": (2, "msg:TypeProto"),
        "doc_string": (3, "str"),
    },
    "TypeProto": {
        "tensor_type": (1, "msg:TensorTypeProto"),
    },
    "TensorTypeProto": {          # TypeProto.Tensor
        "elem_type": (1, "int"),
        "shape": (2, "msg:TensorShapeProto"),
    },
    "TensorShapeProto": {
        "dim": (1, "rep_msg:Dimension"),
    },
    "Dimension": {                # TensorShapeProto.Dimension
        "dim_value": (1, "int"),
        "dim_param": (2, "str"),
    },
}

_WIRE_VARINT, _WIRE_64, _WIRE_LEN, _WIRE_32 = 0, 1, 2, 5


# --------------------------------------------------------------------------
# Encoding
# --------------------------------------------------------------------------

def _varint(value: int) -> bytes:
    if value < 0:
        value += 1 << 64                      # two's complement, 10 bytes
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _enc_scalar(field: int, kind: str, v) -> bytes:
    if kind == "int":
        return _tag(field, _WIRE_VARINT) + _varint(int(v))
    if kind == "float":
        return _tag(field, _WIRE_32) + struct.pack("<f", float(v))
    if kind == "double":
        return _tag(field, _WIRE_64) + struct.pack("<d", float(v))
    if kind == "str":
        b = v.encode("utf-8") if isinstance(v, str) else bytes(v)
        return _tag(field, _WIRE_LEN) + _varint(len(b)) + b
    if kind == "bytes":
        b = bytes(v)
        return _tag(field, _WIRE_LEN) + _varint(len(b)) + b
    raise ValueError(f"unknown scalar kind {kind!r}")


def encode(msg_name: str, obj: dict) -> bytes:
    schema = SCHEMAS[msg_name]
    out = bytearray()
    for fname, value in obj.items():
        if value is None:
            continue
        if fname not in schema:
            raise KeyError(f"{msg_name} has no field {fname!r}")
        field, kind = schema[fname]
        if kind.startswith("rep_msg:"):
            sub = kind.split(":", 1)[1]
            for item in value:
                body = encode(sub, item)
                out += _tag(field, _WIRE_LEN) + _varint(len(body)) + body
        elif kind.startswith("msg:"):
            sub = kind.split(":", 1)[1]
            body = encode(sub, value)
            out += _tag(field, _WIRE_LEN) + _varint(len(body)) + body
        elif kind == "rep_int":                # packed
            body = b"".join(_varint(int(x)) for x in value)
            out += _tag(field, _WIRE_LEN) + _varint(len(body)) + body
        elif kind == "rep_float":              # packed
            body = b"".join(struct.pack("<f", float(x)) for x in value)
            out += _tag(field, _WIRE_LEN) + _varint(len(body)) + body
        elif kind == "rep_double":             # packed
            body = b"".join(struct.pack("<d", float(x)) for x in value)
            out += _tag(field, _WIRE_LEN) + _varint(len(body)) + body
        elif kind in ("rep_str", "rep_bytes"):
            for item in value:
                out += _enc_scalar(field, kind[4:], item)
        else:
            out += _enc_scalar(field, kind, value)
    return bytes(out)


# --------------------------------------------------------------------------
# Decoding
# --------------------------------------------------------------------------

def _read_varint(data: bytes, pos: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    if result >= 1 << 63:                     # negative int64
        result -= 1 << 64
    return result, pos


def decode(msg_name: str, data: bytes) -> dict:
    schema = SCHEMAS[msg_name]
    by_num = {num: (fname, kind) for fname, (num, kind) in schema.items()}
    obj: dict = {}
    pos = 0
    n = len(data)
    while pos < n:
        tag, pos = _read_varint(data, pos)
        field, wire = tag >> 3, tag & 7
        if wire == _WIRE_VARINT:
            value, pos = _read_varint(data, pos)
            raw = ("varint", value)
        elif wire == _WIRE_64:
            raw = ("f64", struct.unpack_from("<d", data, pos)[0])
            pos += 8
        elif wire == _WIRE_LEN:
            ln, pos = _read_varint(data, pos)
            raw = ("len", bytes(data[pos:pos + ln]))
            pos += ln
        elif wire == _WIRE_32:
            raw = ("f32", struct.unpack_from("<f", data, pos)[0])
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        if field not in by_num:
            continue                           # unknown field: skip
        fname, kind = by_num[field]
        _store(obj, fname, kind, raw)
    return obj


def _store(obj, fname, kind, raw):
    wire_kind, value = raw
    if kind.startswith("rep_msg:"):
        obj.setdefault(fname, []).append(decode(kind.split(":", 1)[1], value))
    elif kind.startswith("msg:"):
        obj[fname] = decode(kind.split(":", 1)[1], value)
    elif kind == "rep_int":
        lst = obj.setdefault(fname, [])
        if wire_kind == "len":                 # packed
            pos = 0
            while pos < len(value):
                v, pos = _read_varint(value, pos)
                lst.append(v)
        else:
            lst.append(value)
    elif kind == "rep_float":
        lst = obj.setdefault(fname, [])
        if wire_kind == "len":                 # packed
            lst.extend(struct.unpack(f"<{len(value) // 4}f", value))
        else:
            lst.append(value)
    elif kind == "rep_double":
        lst = obj.setdefault(fname, [])
        if wire_kind == "len":                 # packed
            lst.extend(struct.unpack(f"<{len(value) // 8}d", value))
        else:
            lst.append(value)
    elif kind == "rep_str":
        obj.setdefault(fname, []).append(value.decode("utf-8"))
    elif kind == "rep_bytes":
        obj.setdefault(fname, []).append(value)
    elif kind == "int":
        obj[fname] = value
    elif kind in ("float", "double"):
        obj[fname] = value
    elif kind == "str":
        obj[fname] = value.decode("utf-8")
    elif kind == "bytes":
        obj[fname] = value
    else:
        raise ValueError(f"unknown kind {kind!r}")


# --------------------------------------------------------------------------
# Tensor helpers
# --------------------------------------------------------------------------

def tensor_from_numpy(name: str, arr) -> dict:
    import numpy as np
    a = np.ascontiguousarray(arr)
    dt = NP_TO_ONNX.get(str(a.dtype))
    if dt is None:
        a = a.astype(np.float32)
        dt = FLOAT
    return {"name": name, "dims": list(a.shape), "data_type": dt,
            "raw_data": a.tobytes()}


def tensor_to_numpy(t: dict):
    import numpy as np
    dims = t.get("dims", [])
    dt = t.get("data_type", FLOAT)
    np_dtype = ONNX_TO_NP.get(dt, "float32")
    if "raw_data" in t and t["raw_data"]:
        if np_dtype == "bfloat16":
            import jax.numpy as jnp
            return np.asarray(
                jnp.asarray(
                    np.frombuffer(t["raw_data"], np.uint16).reshape(dims)
                ).view(jnp.bfloat16))
        return np.frombuffer(t["raw_data"], np_dtype).reshape(dims).copy()
    if t.get("float_data"):
        return np.asarray(t["float_data"], np.float32).reshape(dims)
    if t.get("int64_data"):
        return np.asarray(t["int64_data"], np.int64).reshape(dims)
    if t.get("int32_data"):
        return np.asarray(t["int32_data"], np_dtype if "int" in np_dtype
                          else np.int32).reshape(dims)
    return np.zeros(dims, np_dtype)
