"""Symbol graph → ONNX ModelProto export.

Reference surface: ``python/mxnet/contrib/onnx/mx2onnx/export_model.py``
(op converter registry + ``export_model``).  Serialization rides the
self-contained codec in ``_proto.py`` instead of the onnx pip package.
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from . import _proto as P

OPSET_VERSION = 13
_CONVERTERS = {}


def register_converter(*op_names):
    def deco(fn):
        for n in op_names:
            _CONVERTERS[n] = fn
        return fn
    return deco


class _Ctx:
    """Per-export state: extra initializers and generated nodes."""

    def __init__(self, params):
        self.params = params
        self.extra_inits = []
        self.counter = [0]

    def const(self, value, dtype, hint):
        name = f"_const_{hint}_{self.counter[0]}"
        self.counter[0] += 1
        self.extra_inits.append(
            P.tensor_from_numpy(name, np.asarray(value, dtype)))
        return name


def _node(op_type, inputs, outputs, name, **attrs):
    a = []
    for k, v in attrs.items():
        if v is None:
            continue
        if isinstance(v, float):
            a.append({"name": k, "type": P.A_FLOAT, "f": v})
        elif isinstance(v, bool) or isinstance(v, int):
            a.append({"name": k, "type": P.A_INT, "i": int(v)})
        elif isinstance(v, str):
            a.append({"name": k, "type": P.A_STRING, "s": v.encode()})
        elif isinstance(v, (list, tuple)):
            if v and isinstance(v[0], float):
                a.append({"name": k, "type": P.A_FLOATS,
                          "floats": [float(x) for x in v]})
            else:
                a.append({"name": k, "type": P.A_INTS,
                          "ints": [int(x) for x in v]})
        else:
            raise MXNetError(f"unsupported attr {k}={v!r}")
    return {"op_type": op_type, "input": list(inputs),
            "output": list(outputs), "name": name, "attribute": a}


# --------------------------------------------------------------------------
# Converters: (ctx, node_name, kwargs, input_names, out_name) -> [NodeProto]
# --------------------------------------------------------------------------

@register_converter("FullyConnected")
def _fc(ctx, name, kw, ins, out):
    nodes = []
    data = ins[0]
    if kw.get("flatten", True):
        nodes.append(_node("Flatten", [data], [name + "_flat"],
                           name + "_flat", axis=1))
        data = name + "_flat"
    gemm_in = [data, ins[1]] + (ins[2:3] if not kw.get("no_bias") else [])
    nodes.append(_node("Gemm", gemm_in, [out], name,
                       alpha=1.0, beta=1.0, transA=0, transB=1))
    return nodes


@register_converter("Convolution")
def _conv(ctx, name, kw, ins, out):
    kernel = list(kw.get("kernel", ()))
    nd = len(kernel)
    stride = list(kw.get("stride", ())) or [1] * nd
    dilate = list(kw.get("dilate", ())) or [1] * nd
    pad = list(kw.get("pad", ())) or [0] * nd
    return [_node("Conv", list(ins), [out], name, kernel_shape=kernel,
                  strides=stride, dilations=dilate, pads=pad + pad,
                  group=int(kw.get("num_group", 1)))]


@register_converter("Pooling")
def _pool(ctx, name, kw, ins, out):
    ptype = kw.get("pool_type", "max")
    if ptype not in ("max", "avg"):
        raise MXNetError(f"onnx export: unsupported pool_type {ptype!r}")
    if kw.get("pooling_convention", "valid") != "valid":
        raise MXNetError("onnx export: pooling_convention='full' (ceil "
                         "semantics) has no converter")
    if kw.get("global_pool"):
        op = "GlobalMaxPool" if ptype == "max" else "GlobalAveragePool"
        return [_node(op, list(ins), [out], name)]
    kernel = list(kw.get("kernel", ()))
    nd = len(kernel)
    stride = list(kw.get("stride", ())) or [1] * nd
    pad = list(kw.get("pad", ())) or [0] * nd
    op = "MaxPool" if ptype == "max" else "AveragePool"
    attrs = dict(kernel_shape=kernel, strides=stride, pads=pad + pad)
    if ptype == "avg":
        attrs["count_include_pad"] = int(kw.get("count_include_pad", True))
    return [_node(op, list(ins), [out], name, **attrs)]


_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softrelu": "Softplus", "softsign": "Softsign"}


@register_converter("Activation")
def _act(ctx, name, kw, ins, out):
    act = kw.get("act_type", "relu")
    if act not in _ACT:
        raise MXNetError(f"onnx export: unsupported act_type {act!r}")
    return [_node(_ACT[act], list(ins), [out], name)]


@register_converter("relu")
def _relu(ctx, name, kw, ins, out):
    return [_node("Relu", list(ins), [out], name)]


@register_converter("sigmoid")
def _sigmoid(ctx, name, kw, ins, out):
    return [_node("Sigmoid", list(ins), [out], name)]


@register_converter("tanh")
def _tanh(ctx, name, kw, ins, out):
    return [_node("Tanh", list(ins), [out], name)]


@register_converter("BatchNorm")
def _bn(ctx, name, kw, ins, out):
    ins = list(ins)
    if kw.get("fix_gamma", True):
        # fix_gamma forces scale=1 at compute time (ops/nn.py BatchNorm);
        # the exported graph must bake that in, not the stored gamma values
        gamma = ctx.params.get(ins[1])
        size = (int(np.prod(gamma.shape)) if gamma is not None else None)
        if size is None:
            raise MXNetError(
                f"onnx export: BatchNorm {name!r} has fix_gamma=True but "
                f"gamma {ins[1]!r} is not a bound param")
        ins[1] = ctx.const(np.ones(size, np.float32), np.float32,
                           "fixed_gamma")
    return [_node("BatchNormalization", ins, [out], name,
                  epsilon=float(kw.get("eps", 1e-3)),
                  momentum=float(kw.get("momentum", 0.9)))]


@register_converter("LayerNorm")
def _ln(ctx, name, kw, ins, out):
    return [_node("LayerNormalization", list(ins), [out], name,
                  axis=int(kw.get("axis", -1)),
                  epsilon=float(kw.get("eps", 1e-5)))]


@register_converter("Flatten")
def _flatten(ctx, name, kw, ins, out):
    return [_node("Flatten", list(ins), [out], name, axis=1)]


@register_converter("reshape", "Reshape")
def _reshape(ctx, name, kw, ins, out):
    shape = list(kw.get("shape", ()))
    if any(s in (-2, -3, -4) for s in shape):
        raise MXNetError("onnx export: reshape special codes -2/-3/-4 have "
                         "no ONNX equivalent")
    # MXNet's 0 = copy-dim matches ONNX Reshape's 0 (allowzero=0 default)
    sname = ctx.const(shape, np.int64, "shape")
    return [_node("Reshape", [ins[0], sname], [out], name)]


@register_converter("concat")
def _concat(ctx, name, kw, ins, out):
    return [_node("Concat", list(ins), [out], name,
                  axis=int(kw.get("dim", 1)))]


@register_converter("Dropout")
def _dropout(ctx, name, kw, ins, out):
    rname = ctx.const(float(kw.get("p", 0.5)), np.float32, "ratio")
    return [_node("Dropout", [ins[0], rname], [out], name)]


@register_converter("softmax")
def _softmax(ctx, name, kw, ins, out):
    return [_node("Softmax", list(ins), [out], name,
                  axis=int(kw.get("axis", -1)))]


@register_converter("log_softmax")
def _log_softmax(ctx, name, kw, ins, out):
    return [_node("LogSoftmax", list(ins), [out], name,
                  axis=int(kw.get("axis", -1)))]


@register_converter("transpose")
def _transpose(ctx, name, kw, ins, out):
    axes = list(kw.get("axes", ()))
    return [_node("Transpose", list(ins), [out], name,
                  perm=axes or None)]


@register_converter("Embedding")
def _embedding(ctx, name, kw, ins, out):
    # mx: (indices, weight) -> onnx Gather(weight, indices)
    return [_node("Gather", [ins[1], ins[0]], [out], name, axis=0)]


_BINOP = {"elemwise_add": "Add", "broadcast_add": "Add",
          "elemwise_sub": "Sub", "broadcast_sub": "Sub",
          "elemwise_mul": "Mul", "broadcast_mul": "Mul",
          "elemwise_div": "Div", "broadcast_div": "Div",
          "dot": "MatMul"}

for _mx, _ox in _BINOP.items():
    def _mk(_ox):
        def cv(ctx, name, kw, ins, out):
            return [_node(_ox, list(ins), [out], name)]
        return cv
    register_converter(_mx)(_mk(_ox))

_SCALAR_OP = {"_plus_scalar": "Add", "_minus_scalar": "Sub",
              "_mul_scalar": "Mul", "_div_scalar": "Div"}

for _mx, _ox in _SCALAR_OP.items():
    def _mks(_ox):
        def cv(ctx, name, kw, ins, out):
            s = ctx.const(float(kw.get("scalar", 0.0)), np.float32, "scalar")
            return [_node(_ox, [ins[0], s], [out], name)]
        return cv
    register_converter(_mx)(_mks(_ox))


# --------------------------------------------------------------------------
# export_model
# --------------------------------------------------------------------------

def _out_name(node, idx, n_outputs):
    return node.name if n_outputs == 1 else f"{node.name}_out{idx}"


def export_model(sym, params, input_shapes=None, input_dtypes="float32",
                 onnx_file_path="model.onnx", verbose=False):
    """Serialize a Symbol + params to an ONNX file (reference:
    onnx_mxnet.export_model).  Returns the file path."""
    params = {k.split(":", 1)[-1]: v for k, v in (params or {}).items()}
    arg_names = set(sym.list_inputs())
    data_inputs = [n for n in sym.list_inputs() if n not in params]
    if isinstance(input_shapes, dict):
        shape_map = dict(input_shapes)
    else:
        shape_map = dict(zip(data_inputs, input_shapes or []))
    if isinstance(input_dtypes, str):
        dtype_map = {n: input_dtypes for n in data_inputs}
    else:
        dtype_map = dict(zip(data_inputs, input_dtypes))

    ctx = _Ctx(params)
    nodes, inits, graph_inputs = [], [], []
    for name in data_inputs:
        shape = shape_map.get(name)
        dims = [{"dim_value": int(s)} for s in (shape or ())]
        graph_inputs.append({
            "name": name,
            "type": {"tensor_type": {
                "elem_type": P.NP_TO_ONNX.get(
                    str(dtype_map.get(name, "float32")), P.FLOAT),
                "shape": {"dim": dims}}}})
    for name in sorted(p for p in arg_names if p in params):
        arr = params[name]
        inits.append(P.tensor_from_numpy(
            name, arr.asnumpy() if hasattr(arr, "asnumpy") else arr))

    out_names = []
    for node in sym._topo():
        if node.is_variable:
            if node.name not in params and node.name not in set(data_inputs):
                raise MXNetError(
                    f"onnx export: free variable {node.name!r} has no "
                    f"shape/param binding")
            continue
        opname = node.op.name
        conv = _CONVERTERS.get(opname)
        if conv is None:
            for alias in node.op.aliases:
                conv = _CONVERTERS.get(alias)
                if conv is not None:
                    break
        if conv is None:
            raise MXNetError(
                f"onnx export: no converter for operator {opname!r}")
        ins = [_out_name(src, i, src.num_outputs) if not src.is_variable
               else src.name for src, i in node.inputs]
        out = _out_name(node, 0, node.num_outputs)
        nodes.extend(conv(ctx, node.name, dict(node.kwargs), ins, out))

    for n, i in sym._outputs:
        out_names.append(_out_name(n, i, n.num_outputs) if not n.is_variable
                         else n.name)
    graph = {
        "node": nodes,
        "name": "mxnet_tpu_graph",
        "initializer": inits + ctx.extra_inits,
        "input": graph_inputs,
        "output": [{"name": o, "type": {"tensor_type": {
            "elem_type": P.FLOAT, "shape": {"dim": []}}}}
            for o in out_names],
    }
    model = {
        "ir_version": 8,
        "producer_name": "mxnet_tpu",
        "producer_version": "2.0",
        "opset_import": [{"domain": "", "version": OPSET_VERSION}],
        "graph": graph,
    }
    blob = P.encode("ModelProto", model)
    with open(onnx_file_path, "wb") as f:
        f.write(blob)
    if verbose:
        print(f"exported {len(nodes)} nodes, {len(inits)} params "
              f"-> {onnx_file_path}")
    return onnx_file_path
