"""ONNX ModelProto → Symbol graph import.

Reference surface: ``python/mxnet/contrib/onnx/onnx2mx/import_model.py``
(``import_model`` returning ``(sym, arg_params, aux_params)``).
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from . import _proto as P

_IMPORTERS = {}


def register_importer(*op_types):
    def deco(fn):
        for t in op_types:
            _IMPORTERS[t] = fn
        return fn
    return deco


def _attrs(node):
    out = {}
    for a in node.get("attribute", []):
        t = a.get("type")
        if t == P.A_FLOAT:
            out[a["name"]] = a.get("f", 0.0)
        elif t == P.A_INT:
            out[a["name"]] = a.get("i", 0)
        elif t == P.A_STRING:
            out[a["name"]] = a.get("s", b"").decode()
        elif t == P.A_FLOATS:
            out[a["name"]] = list(a.get("floats", []))
        elif t == P.A_INTS:
            out[a["name"]] = list(a.get("ints", []))
        elif t == P.A_TENSOR:
            out[a["name"]] = P.tensor_to_numpy(a["t"])
    return out


# Importer signature: (sym_mod, inputs, attrs, consts, name) -> Symbol
# ``consts`` maps input name -> numpy value for initializer-backed inputs.

@register_importer("Gemm")
def _gemm(sym, ins, at, consts, name):
    if at.get("transA"):
        raise MXNetError("onnx import: Gemm transA unsupported")
    alpha = float(at.get("alpha", 1.0))
    beta = float(at.get("beta", 1.0))
    data, weight = ins[0], ins[1]
    if not at.get("transB", 0):
        weight = sym.transpose(weight)
    if alpha == 1.0 and beta == 1.0:
        args = [data, weight] + (list(ins[2:3]) if len(ins) > 2 else [])
        return sym.FullyConnected(*args, num_hidden=0,
                                  no_bias=len(ins) < 3, flatten=False,
                                  name=name)
    out = sym.FullyConnected(data, weight, num_hidden=0, no_bias=True,
                             flatten=False, name=name + "_mm")
    if alpha != 1.0:
        out = out * alpha
    if len(ins) > 2 and beta != 0.0:
        bias = ins[2] if beta == 1.0 else ins[2] * beta
        out = sym.broadcast_add(out, bias, name=name)
    return out


@register_importer("Conv")
def _conv(sym, ins, at, consts, name):
    kernel = tuple(at.get("kernel_shape", ()))
    nd = len(kernel)
    pads = at.get("pads", [0] * (2 * nd))
    if pads[:nd] != pads[nd:]:
        raise MXNetError("onnx import: asymmetric Conv pads unsupported")
    return sym.Convolution(*ins, kernel=kernel,
                           stride=tuple(at.get("strides", ())) or (1,) * nd,
                           dilate=tuple(at.get("dilations", ())) or
                           (1,) * nd,
                           pad=tuple(pads[:nd]),
                           num_filter=0,
                           num_group=int(at.get("group", 1)),
                           no_bias=len(ins) < 3, name=name)


@register_importer("MaxPool", "AveragePool")
def _pool(sym, ins, at, consts, name):
    kernel = tuple(at.get("kernel_shape", ()))
    nd = len(kernel)
    pads = at.get("pads", [0] * (2 * nd))
    if pads[:nd] != pads[nd:]:
        raise MXNetError("onnx import: asymmetric pool pads unsupported")
    kw = {}
    if at["_ptype"] == "avg":
        # ONNX default count_include_pad=0; MXNet default is True
        kw["count_include_pad"] = bool(at.get("count_include_pad", 0))
    return sym.Pooling(ins[0], kernel=kernel, pool_type=at["_ptype"],
                       stride=tuple(at.get("strides", ())) or (1,) * nd,
                       pad=tuple(pads[:nd]), name=name, **kw)


@register_importer("GlobalMaxPool", "GlobalAveragePool")
def _gpool(sym, ins, at, consts, name):
    return sym.Pooling(ins[0], global_pool=True, pool_type=at["_ptype"],
                       kernel=(), name=name)


@register_importer("BatchNormalization")
def _bn(sym, ins, at, consts, name):
    return sym.BatchNorm(*ins, eps=float(at.get("epsilon", 1e-5)),
                         momentum=float(at.get("momentum", 0.9)),
                         fix_gamma=False, use_global_stats=True, name=name)


@register_importer("LayerNormalization")
def _ln(sym, ins, at, consts, name):
    return sym.LayerNorm(*ins, axis=int(at.get("axis", -1)),
                         eps=float(at.get("epsilon", 1e-5)), name=name)


@register_importer("Relu")
def _relu(sym, ins, at, consts, name):
    return sym.Activation(ins[0], act_type="relu", name=name)


@register_importer("Sigmoid")
def _sig(sym, ins, at, consts, name):
    return sym.Activation(ins[0], act_type="sigmoid", name=name)


@register_importer("Tanh")
def _tanh(sym, ins, at, consts, name):
    return sym.Activation(ins[0], act_type="tanh", name=name)


@register_importer("Softplus")
def _softplus(sym, ins, at, consts, name):
    return sym.Activation(ins[0], act_type="softrelu", name=name)


@register_importer("Flatten")
def _flat(sym, ins, at, consts, name):
    return sym.Flatten(ins[0], name=name)


@register_importer("Reshape")
def _reshape(sym, ins, at, consts, name):
    shape = consts.get("__in1__")
    if shape is None:
        raise MXNetError("onnx import: dynamic Reshape shape unsupported")
    return sym.reshape(ins[0], shape=tuple(int(s) for s in shape),
                       name=name)


@register_importer("Concat")
def _concat(sym, ins, at, consts, name):
    return sym.concat(*ins, dim=int(at.get("axis", 1)), name=name)


@register_importer("Dropout")
def _dropout(sym, ins, at, consts, name):
    ratio = at.get("ratio")
    if ratio is None:
        r = consts.get("__in1__")
        ratio = float(r) if r is not None else 0.5
    return sym.Dropout(ins[0], p=float(ratio), name=name)


@register_importer("Softmax")
def _softmax(sym, ins, at, consts, name):
    return sym.softmax(ins[0], axis=int(at.get("axis", -1)), name=name)


@register_importer("LogSoftmax")
def _logsoftmax(sym, ins, at, consts, name):
    return sym.log_softmax(ins[0], axis=int(at.get("axis", -1)), name=name)


@register_importer("Transpose")
def _transpose(sym, ins, at, consts, name):
    return sym.transpose(ins[0], axes=tuple(at.get("perm", ())), name=name)


@register_importer("Gather")
def _gather(sym, ins, at, consts, name):
    return sym.take(ins[0], ins[1], axis=int(at.get("axis", 0)), name=name)


@register_importer("MatMul")
def _matmul(sym, ins, at, consts, name):
    return sym.dot(ins[0], ins[1], name=name)


for _ox, _mx in (("Add", "broadcast_add"), ("Sub", "broadcast_sub"),
                 ("Mul", "broadcast_mul"), ("Div", "broadcast_div")):
    def _mkbin(_mx):
        def imp(sym, ins, at, consts, name):
            return getattr(sym, _mx)(ins[0], ins[1], name=name)
        return imp
    register_importer(_ox)(_mkbin(_mx))


def import_model(model_file):
    """Load an ONNX file → (sym, arg_params, aux_params) (reference:
    onnx_mxnet.import_model)."""
    import mxnet_tpu.symbol as sym_mod
    from ... import nd

    with open(model_file, "rb") as f:
        model = P.decode("ModelProto", f.read())
    graph = model.get("graph", {})
    inits = {t["name"]: P.tensor_to_numpy(t)
             for t in graph.get("initializer", [])}
    tensors = {}                               # onnx name -> Symbol
    for vi in graph.get("input", []):
        if vi["name"] not in inits:
            tensors[vi["name"]] = sym_mod.var(vi["name"])

    arg_params, aux_params = {}, {}
    used_const = set()

    def as_sym(onnx_name):
        if onnx_name in tensors:
            return tensors[onnx_name]
        if onnx_name in inits:
            arg_params[onnx_name] = nd.array(
                np.ascontiguousarray(inits[onnx_name]))
            tensors[onnx_name] = sym_mod.var(onnx_name)
            used_const.add(onnx_name)
            return tensors[onnx_name]
        raise MXNetError(f"onnx import: undefined tensor {onnx_name!r}")

    for node in graph.get("node", []):
        op = node["op_type"]
        imp = _IMPORTERS.get(op)
        if imp is None:
            raise MXNetError(f"onnx import: no importer for {op!r}")
        at = _attrs(node)
        at["_op_type"] = op
        if "Pool" in op:
            at["_ptype"] = "max" if "Max" in op else "avg"
        raw_ins = node.get("input", [])
        consts = {}
        for i, n in enumerate(raw_ins):
            if n in inits:
                consts[f"__in{i}__"] = inits[n]
        # shape/ratio style const inputs are consumed as attrs, not args
        if op in ("Reshape", "Dropout") and len(raw_ins) > 1:
            ins = [as_sym(raw_ins[0])]
        else:
            ins = [as_sym(n) for n in raw_ins]
        name = node.get("name") or f"{op.lower()}_{len(tensors)}"
        out_sym = imp(sym_mod, ins, at, consts, name)
        outs = node.get("output", [])
        if op == "BatchNormalization" and len(raw_ins) >= 5:
            for aux_in in raw_ins[3:5]:
                if aux_in in arg_params:
                    aux_params[aux_in] = arg_params.pop(aux_in)
        for i, o in enumerate(outs):
            tensors[o] = out_sym[i] if len(outs) > 1 else out_sym

    out_syms = [tensors[o["name"]] for o in graph.get("output", [])]
    final = out_syms[0] if len(out_syms) == 1 else sym_mod.Group(out_syms)
    return final, arg_params, aux_params
