"""Automatic Mixed Precision.

Reference surface: ``python/mxnet/contrib/amp/amp.py`` — ``amp.init()``
patches the generated op namespaces so MXU-bound ops execute in the target
dtype, numerically-sensitive ops in fp32; ``init_trainer``/``scale_loss``
add dynamic loss scaling; optimizer ``multi_precision`` keeps fp32 master
weights (optimizer/optimizer.py create_state_multi_precision).

TPU-native redesign: target dtype defaults to **bfloat16** (the MXU's
native input type).  The patching wraps the registry-generated frontends in
``mx.nd``/``mx.sym`` (and their ``.op`` submodules), so eager, hybridized
(CachedOp traces through the patched frontends), and symbolic paths all see
the same rewrite.  Casts are jnp ``astype`` — XLA fuses them into the
adjacent matmul, so the rewrite costs no extra HBM traffic.
"""
from __future__ import annotations

import contextlib
import logging
from typing import Dict

from ...base import MXNetError
from . import lists
from .loss_scaler import LossScaler

__all__ = ["init", "init_trainer", "scale_loss", "unscale",
           "convert_hybrid_block", "list_lp16_ops", "list_fp32_ops"]

_amp_state: Dict = {"initialized": False, "target_dtype": None,
                    "originals": {}}


def list_lp16_ops(target_dtype="bfloat16"):
    return list(lists.TARGET_DTYPE_OPS)


def list_fp32_ops(target_dtype="bfloat16"):
    return list(lists.FP32_OPS)


def _wrap_cast(fn, dtype, float_only=True):
    """Wrap a frontend: cast array inputs to `dtype` before dispatch."""
    from ...ndarray import NDArray
    from ...symbol import Symbol
    from ... import ndarray as nd_mod

    def _cast(a):
        if isinstance(a, NDArray):
            if not float_only or str(a.dtype).startswith(("float", "bfloat")):
                if str(a.dtype) != dtype:
                    return nd_mod.amp_cast(a, dtype=dtype)
            return a
        if isinstance(a, Symbol):
            from ...ops.registry import get_op
            from ...symbol.symbol import invoke_symbolic
            return invoke_symbolic(get_op("amp_cast"), (a,),
                                   {"dtype": dtype})
        if isinstance(a, (list, tuple)):
            return type(a)(_cast(x) for x in a)
        return a

    def wrapped(*args, **kwargs):
        return fn(*tuple(_cast(a) for a in args), **kwargs)

    wrapped.__name__ = getattr(fn, "__name__", "amp_wrapped")
    wrapped.__doc__ = fn.__doc__
    wrapped._amp_original = fn
    return wrapped


def _wrap_widest(fn):
    """Wrap a multi-input frontend: unify input dtypes to the widest."""
    from ...ndarray import NDArray
    from ... import ndarray as nd_mod
    import numpy as np

    def wrapped(*args, **kwargs):
        arrs = [a for a in args if isinstance(a, NDArray)]
        if len(arrs) > 1:
            widest = str(np.result_type(*[np.dtype(str(a.dtype))
                                          for a in arrs]))
            args = tuple(nd_mod.amp_cast(a, dtype=widest)
                         if isinstance(a, NDArray) and
                         str(a.dtype) != widest else a for a in args)
        return fn(*args, **kwargs)

    wrapped.__name__ = getattr(fn, "__name__", "amp_wrapped")
    wrapped._amp_original = fn
    return wrapped


def _patch_targets():
    """The namespaces holding generated frontends."""
    from ... import ndarray as nd_mod
    from ... import symbol as sym_mod
    return [nd_mod, nd_mod.op, sym_mod, sym_mod.op]


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Enable AMP by patching the op namespaces (reference: amp.init).

    target_dtype: 'bfloat16' (TPU-native default) or 'float16'.
    target_precision_ops / fp32_ops: override the default lists.
    """
    if _amp_state["initialized"]:
        if _amp_state["target_dtype"] != target_dtype:
            raise MXNetError(
                f"amp.init already called with "
                f"{_amp_state['target_dtype']!r}")
        return
    if target_dtype not in ("bfloat16", "float16"):
        raise MXNetError("target_dtype must be bfloat16 or float16")
    lp_ops = list(target_precision_ops if target_precision_ops is not None
                  else lists.TARGET_DTYPE_OPS)
    f32_ops = list(fp32_ops if fp32_ops is not None else lists.FP32_OPS)
    if conditional_fp32_ops:
        f32_ops += [name for name, _, _ in conditional_fp32_ops]
    overlap = set(lp_ops) & set(f32_ops)
    if overlap:
        raise MXNetError(f"ops in both lists: {sorted(overlap)}")

    targets = _patch_targets()
    originals = {}
    for names, wrapper in ((lp_ops, lambda f: _wrap_cast(f, target_dtype)),
                           (f32_ops, lambda f: _wrap_cast(f, "float32")),
                           (lists.WIDEST_TYPE_CASTS,
                            lambda f: _wrap_widest(f))):
        for opname in names:
            for mod in targets:
                fn = getattr(mod, opname, None)
                if fn is None or hasattr(fn, "_amp_original"):
                    continue
                originals[(id(mod), opname)] = (mod, opname, fn)
                setattr(mod, opname, wrapper(fn))
    _amp_state.update(initialized=True, target_dtype=target_dtype,
                      originals=originals)
    logging.info("AMP initialized (target dtype %s)", target_dtype)


def _deinit():
    """Undo init() — test hook; the reference has no public equivalent."""
    for mod, opname, fn in _amp_state["originals"].values():
        setattr(mod, opname, fn)
    _amp_state.update(initialized=False, target_dtype=None, originals={})


def init_trainer(trainer):
    """Attach a dynamic LossScaler and overflow-skipping step to a Gluon
    Trainer (reference: amp.init_trainer)."""
    from ...gluon.trainer import Trainer
    if not isinstance(trainer, Trainer):
        raise MXNetError("init_trainer expects a gluon Trainer")
    if getattr(trainer, "_amp_loss_scaler", None) is not None:
        return trainer
    scaler = LossScaler()
    trainer._amp_loss_scaler = scaler
    trainer._amp_original_step = trainer.step

    def amp_step(batch_size, ignore_stale_grad=False):
        if scaler.has_overflow(trainer._params):
            scaler.update_scale(True)
            logging.warning("AMP: gradient overflow, skipping step "
                            "(loss scale -> %g)", scaler.loss_scale)
            trainer._scale = 1.0
            return
        trainer._amp_original_step(batch_size, ignore_stale_grad)
        scaler.update_scale(False)
        trainer._scale = 1.0

    trainer.step = amp_step
    return trainer


@contextlib.contextmanager
def scale_loss(loss, trainer):
    """``with amp.scale_loss(loss, trainer) as l: l.backward()`` —
    multiplies the loss by the current scale and arranges for the next
    ``trainer.step`` to divide gradients back down (via Trainer._scale)."""
    from ... import autograd
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        raise MXNetError("call amp.init_trainer(trainer) first")
    trainer._scale = 1.0 / scaler.loss_scale
    # scale inside a record scope so the multiply lands on the tape even
    # when the caller invokes scale_loss outside `with autograd.record()`
    with autograd.record():
        if isinstance(loss, (list, tuple)):
            scaled = [l * scaler.loss_scale for l in loss]
        else:
            scaled = loss * scaler.loss_scale
    yield scaled


def unscale(trainer):
    """Divide gradients by the loss scale in place (reference:
    amp.unscale) — for gradient clipping between backward and step."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        raise MXNetError("call amp.init_trainer(trainer) first")
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        if p.grad_req == "null":
            continue
        for g in p.list_grad():
            g._set_data(g._data * inv)
    trainer._scale = 1.0


def convert_hybrid_block(block, target_dtype="bfloat16"):
    """Cast a HybridBlock's parameters to the target dtype for pure
    low-precision inference (reference: amp.convert_hybrid_block).
    For training, prefer amp.init() + multi_precision optimizers."""
    block.cast(target_dtype)
    return block
