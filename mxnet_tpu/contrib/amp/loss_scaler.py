"""Dynamic loss scaling (reference: contrib/amp/loss_scaler.py).

On TPU the working reduced dtype is bfloat16, whose exponent range matches
fp32 — gradients rarely underflow — but the scaler is kept
reference-compatible (and required when target_dtype='float16').
"""
from __future__ import annotations

from ... import ndarray as nd

__all__ = ["LossScaler"]


class LossScaler:
    """Doubling/halving dynamic scaler (reference: LossScaler)."""

    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000, tolerance=0.05):
        self.loss_scale = float(init_scale)
        self._scale_factor = float(scale_factor)
        self._scale_window = int(scale_window)
        self._unskipped = 0
        self._total_steps = 0
        self._skipped = 0

    def has_overflow(self, params) -> bool:
        """One fused finite-check over every gradient array; a single
        scalar readback (reference: multi_all_finite)."""
        grads = []
        for p in params:
            if getattr(p, "grad_req", "write") == "null":
                continue
            grads.extend(p.list_grad())
        if not grads:
            return False
        ok = nd.all_finite(*[g for g in grads])
        return bool(ok.asnumpy()[0] == 0.0)

    def update_scale(self, overflow: bool):
        self._total_steps += 1
        if overflow:
            self.loss_scale = max(1.0, self.loss_scale / self._scale_factor)
            self._unskipped = 0
            self._skipped += 1
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self.loss_scale *= self._scale_factor
                self._unskipped = 0

    @property
    def stats(self):
        return {"loss_scale": self.loss_scale,
                "steps": self._total_steps, "skipped": self._skipped}
