"""Automatic Mixed Precision (reference: ``python/mxnet/contrib/amp``)."""
from .amp import (init, init_trainer, scale_loss, unscale,
                  convert_hybrid_block, list_lp16_ops, list_fp32_ops)
from .loss_scaler import LossScaler
from . import lists

__all__ = ["init", "init_trainer", "scale_loss", "unscale",
           "convert_hybrid_block", "list_lp16_ops", "list_fp32_ops",
           "LossScaler", "lists"]
