"""AMP op classification lists.

Reference: ``python/mxnet/contrib/amp/lists/symbol_fp16.py`` — the
allow/deny lists deciding which ops run in reduced precision.

TPU-native note: the target dtype is **bfloat16** (MXU-native; same
exponent range as fp32, so the fp16 overflow pathology the reference's
lists guard against is far milder) — but the structure is kept so loss
scaling and the op classification remain reference-shaped, and fp16 can be
selected explicitly.
"""

# MXU-bound ops: the FLOPs live here — run in the target (bf16) dtype.
TARGET_DTYPE_OPS = [
    "FullyConnected",
    "Convolution",
    "Deconvolution",
    "dot",
    "batch_dot",
    "RNN",
    "_contrib_interleaved_matmul_selfatt_qk",
    "_contrib_interleaved_matmul_selfatt_valatt",
    "_contrib_interleaved_matmul_encdec_qk",
    "_contrib_interleaved_matmul_encdec_valatt",
]

# Numerically sensitive ops: always fp32 (reductions, exp/log families,
# losses, normalizations that divide by small variances).
FP32_OPS = [
    "softmax",
    "log_softmax",
    "softmin",
    "SoftmaxActivation",
    "SoftmaxOutput",
    "softmax_cross_entropy",
    "CTCLoss",
    "BatchNorm",
    "LayerNorm",
    "InstanceNorm",
    "GroupNorm",
    "L2Normalization",
    "LRN",
    "norm",
    "exp",
    "log",
    "log2",
    "log10",
    "expm1",
    "log1p",
    "mean",
    "sum",
    "erfinv",
    "reciprocal",
    "rsqrt",
    "rcbrt",
    "smooth_l1",
]

# Multi-input elementwise ops whose inputs must agree: cast to the widest
# input dtype (reference: WIDEST_TYPE_CASTS).
WIDEST_TYPE_CASTS = [
    "broadcast_add",
    "broadcast_sub",
    "broadcast_mul",
    "broadcast_div",
    "elemwise_add",
    "elemwise_sub",
    "elemwise_mul",
    "elemwise_div",
    "add_n",
    "concat",
    "where",
]
