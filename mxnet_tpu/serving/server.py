"""In-process model server: bounded queues, worker pool, backpressure
(docs/serving.md §4).

``predict()`` is synchronous from the caller's side; underneath,
admitted requests land in a bounded per-model queue, a worker pool
coalesces them into shape-bucketed batches (``DynamicBatcher``) and the
caller's thread wakes when its slice of the batch output is ready.
Backpressure is explicit: when queue depth sits at/above the
load-shedding watermark, admission fails *immediately* with
:class:`ServerOverloadedError` carrying a retry-after hint — the
serving-tier contract that callers see bounded latency or a cheap
reject, never an unbounded queue (reference: MXNet Model Server's
worker queues; the Gemma-on-TPU serving comparison's batching policy,
PAPERS.md).
"""
from __future__ import annotations

import itertools
import logging
import threading
import time
from collections import OrderedDict, deque

import numpy as np

from .. import engine, runtime_metrics as _rm, tracing as _tr
from ..base import MXNetError
from .batcher import DynamicBatcher
from .config import ServingConfig
from .repository import ModelRepository

__all__ = ["ModelServer", "ServerOverloadedError"]

_LOG = logging.getLogger("mxnet_tpu")
_SERVER_SEQ = itertools.count(1)


class ServerOverloadedError(MXNetError):
    """Request shed by the backpressure bounds.  ``retry_after_ms`` is
    the server's backoff hint (an HTTP frontend maps this to 429 +
    Retry-After); the message names which bound actually tripped so
    operators tune the right knob."""

    def __init__(self, model, retry_after_ms, reason):
        self.model = model
        self.retry_after_ms = retry_after_ms
        super().__init__(
            f"server overloaded: {reason} for model {model!r}; "
            f"retry after {retry_after_ms}ms")


class _Request:
    __slots__ = ("entry", "inputs", "rows", "event", "result", "error",
                 "t_enq", "trace", "queue_span")

    def __init__(self, entry, inputs, rows):
        self.entry = entry
        self.inputs = inputs
        self.rows = rows
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.t_enq = time.monotonic()
        # tracing: the request's TraceContext (None when untraced) and
        # its queue-wait span — started in the caller's thread at
        # enqueue, ended in whichever worker pops it (Span.end is
        # idempotent, so the timeout-withdrawal race is benign)
        self.trace = None
        self.queue_span = _tr._NOOP


class ModelServer:
    """Dynamic-batching server over a :class:`ModelRepository`.

    >>> repo = ModelRepository()
    >>> repo.load_artifact("lenet", "lenet.shlo")
    >>> with ModelServer(repo) as srv:
    ...     out = srv.predict("lenet", batch_of_images)

    Requests resolve their model entry at admission, so
    ``repository.swap`` hot-swaps versions without draining: in-flight
    requests finish on the old version, new admissions see the new one.
    """

    def __init__(self, repository=None, config=None, autostart=True,
                 name=None):
        self.repository = repository or ModelRepository()
        self.config = config or ServingConfig()
        self.batcher = DynamicBatcher(self.config)
        self.name = name or f"server{next(_SERVER_SEQ)}"
        self._evict_subscribed = False
        # engine.make_condition: plain Condition normally; lock-order
        # recording under MXNET_ENGINE_SANITIZE=1 (the serving tests
        # double as race tests in CI's sanity_lint job)
        self._cond = engine.make_condition("serving.ModelServer._cond")
        self._queues = OrderedDict()    # entry.uid -> (entry, deque)
        self._decoders = OrderedDict()  # entry.uid -> DecodeEngine
        # serializes decode-engine CONSTRUCTION (KV-pool allocation +
        # adapter bind) without holding _cond: two first-generate()
        # racers must not both run setup() on one shared adapter
        self._decoder_build = engine.make_lock(
            "serving.ModelServer._decoder_build")
        self._depth = 0
        self._inflight = 0              # admitted, popped, not finished
        self._started = False
        self._stopping = False
        self._workers = []
        self._stats = {"requests": 0, "completed": 0, "shed": 0,
                       "batches": 0, "errors": 0}
        if autostart:
            self.start()

    # ----------------------------------------------------------- lifecycle
    def start(self):
        with self._cond:
            if self._started:
                return self
            self._started = True
            self._stopping = False
            # retired versions must not pin compiled programs for the
            # process lifetime (hot-swap deploy loops); unsubscribed at
            # stop() so the repository never pins a dead server.  Flag
            # and subscription flip atomically under _cond (a racing
            # stop() must observe both or neither); the nested
            # repository lock is safe — the server->repository
            # acquisition order is one-way (the repository never calls
            # back into the server)
            if not self._evict_subscribed:
                self.repository.subscribe_unload(self._on_unload)
                self._evict_subscribed = True
        with self._cond:
            self._workers = [
                threading.Thread(target=self._worker_loop,
                                 name=f"mxnet-serving-{i}", daemon=True)
                for i in range(self.config.num_workers)]
        for t in self._workers:
            t.start()
        return self

    def stop(self, drain=True, timeout=None):
        """Shut down the worker pool.  ``drain=True`` (default) stops
        admission, lets workers finish every queued request, then joins;
        ``drain=False`` fails queued requests immediately.

        Returns True once the pool is down.  With a ``timeout``, a
        worker stuck in a dispatch can outlive the join — then the
        server STAYS in the stopping state (so a later ``start()``
        cannot spawn a second pool next to the orphan) and stop()
        returns False; call it again to finish the shutdown."""
        with self._cond:
            if not self._started:
                return True
            self._stopping = True
            if not drain:
                for _entry, q in self._queues.values():
                    for req in q:
                        req.error = MXNetError(
                            "ModelServer stopped before this request "
                            "was dispatched")
                        req.event.set()
                    q.clear()
                self._set_depth(0)
            self._cond.notify_all()
        # one total budget, not one per worker
        deadline = None if timeout is None else time.monotonic() + timeout
        for t in self._workers:
            t.join(None if deadline is None
                   else max(0.0, deadline - time.monotonic()))
        alive = [t for t in self._workers if t.is_alive()]
        if alive:
            return False
        # decode engines go down with the worker pool; outstanding
        # generate() calls fail with finish_reason="stopped"
        with self._cond:
            decoders = dict(self._decoders)
            self._decoders.clear()
        stuck = {}
        for uid, eng in decoders.items():
            if not eng.stop(timeout=None if deadline is None
                            else max(0.0, deadline - time.monotonic())):
                stuck[uid] = eng
        if stuck:
            # same contract as a stuck worker: keep the references so a
            # later stop() can finish the job, stay in the stopping
            # state, report failure — never leak a live step loop
            with self._cond:
                self._decoders.update(stuck)
            return False
        with self._cond:
            self._started = False
            self._workers = []
            if self._evict_subscribed:
                self.repository.unsubscribe_unload(self._on_unload)
                self._evict_subscribed = False
        return True

    def _on_unload(self, entry):
        """Repository unload hook: drop the batcher's cached programs
        AND stop/drop the entry's decode engine (its KV pool must not
        pin device memory for a retired version)."""
        self.batcher.evict(entry)
        with self._cond:
            eng = self._decoders.pop(entry.uid, None)
        if eng is not None:
            eng.stop()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop(drain=exc == (None, None, None))
        return False

    @property
    def started(self):
        return self._started

    # -------------------------------------------------------------- predict
    def predict(self, model, *inputs, timeout=None):
        """Run one inference request; blocks until its slice of a
        coalesced batch is ready.  Inputs are batch-major NDArray /
        numpy arrays validated against the model's serving signature;
        returns numpy (one array, or a tuple for multi-output models).

        With ``MXNET_TRACE=1`` the request carries one trace identity
        end to end: admission, queue wait, the (shared) batch-assembly
        span with its bucket outcome, and execute — and the latency
        histogram records the trace id as its exemplar, so a p99 links
        to the exact trace behind it (docs/observability.md).
        """
        with _tr.trace("serving.predict", model=model) as root:
            return self._predict_impl(model, inputs, timeout, root)

    def _predict_impl(self, model, inputs, timeout, root):
        from .. import deploy
        entry = self.repository.get(model)
        if entry.decode_model is not None:
            raise MXNetError(
                f"serving predict({model!r}): decoder entry — "
                f"autoregressive models serve through generate()")
        np_inputs = tuple(
            np.asarray(x.asnumpy()) if hasattr(x, "asnumpy")
            else np.asarray(x) for x in inputs)
        deploy.validate_inputs(entry.manifest, np_inputs,
                               where=f"serving predict({model!r})")
        if not np_inputs or np_inputs[0].ndim < 1:
            raise MXNetError(
                f"serving predict({model!r}): inputs must be batch-major "
                f"arrays with a leading batch dimension")
        rows = np_inputs[0].shape[0]
        cap = entry.max_rows(self.config.max_batch_size)
        if rows < 1 or rows > cap:
            raise MXNetError(
                f"serving predict({model!r}): request batch of {rows} "
                f"rows outside [1, {cap}] (max_batch_size="
                f"{self.config.max_batch_size}, "
                f"exported batch={entry.fixed_batch})")

        req = _Request(entry, np_inputs, rows)
        req.trace = root.context
        admit = _tr.span("serving.admit", parent=req.trace, rows=rows)
        try:
            with self._cond:
                if not self._started or self._stopping:
                    raise MXNetError(
                        "ModelServer is not accepting requests "
                        "(not started, or shutting down)")
                # two-level backpressure: the watermark bounds the
                # WAITING queue; queue_depth additionally bounds total
                # outstanding work (queued + in-flight), so a slow
                # model cannot pile up unbounded
                # dispatched-but-unfinished requests
                reason = None
                if self._depth >= self.config.shed_watermark:
                    reason = (f"queue depth {self._depth} >= shed "
                              f"watermark {self.config.shed_watermark}")
                elif self._depth + self._inflight \
                        >= self.config.queue_depth:
                    reason = (f"outstanding work {self._depth} queued "
                              f"+ {self._inflight} in flight >= "
                              f"queue_depth {self.config.queue_depth}")
                if reason is not None:
                    self._stats["shed"] += 1
                    if _rm._ENABLED:
                        _rm.SERVING_SHED.inc(model=model)
                    admit.set_tag("shed", reason)
                    raise ServerOverloadedError(
                        model, self.config.retry_after_ms, reason)
                slot = self._queues.get(entry.uid)
                if slot is None:
                    slot = (entry, deque())
                    self._queues[entry.uid] = slot
                slot[1].append(req)
                self._set_depth(self._depth + 1)
                self._stats["requests"] += 1
                if _rm._ENABLED:
                    _rm.SERVING_REQUESTS.inc(model=model)
                req.queue_span = _tr.span("serving.queue_wait",
                                          parent=req.trace,
                                          depth=self._depth)
                self._cond.notify_all()
        except ServerOverloadedError:
            # flight recorder: an overloaded replica dumps its recent
            # traces + debug state ONCE per debounce window (the
            # callable defers the state walk until a dump really
            # happens) — called after _cond is released
            _tr.record_incident("serving.shed", self.debug_state)
            raise
        finally:
            admit.end()

        if not req.event.wait(timeout):
            # withdraw an abandoned request so it neither occupies
            # bounded-queue depth (pushing admissions into the shed
            # watermark) nor burns device time computing a result
            # nobody will read; if a worker popped it meanwhile, let
            # that batch complete — the result is simply dropped
            with self._cond:
                slot = self._queues.get(entry.uid)
                if slot is not None and req in slot[1]:
                    slot[1].remove(req)
                    if not slot[1]:
                        self._queues.pop(entry.uid, None)
                    self._set_depth(self._depth - 1)
            req.queue_span.end(error="timeout")
            raise MXNetError(
                f"serving predict({model!r}): no result within "
                f"{timeout}s (queue depth {self._depth})")
        if req.error is not None:
            raise req.error
        return req.result if len(req.result) > 1 else req.result[0]

    # ------------------------------------------------------------- generate
    def _decoder_engine(self, entry):
        """The (lazily created) decode engine of a decoder entry.  One
        engine per entry uid: a hot-swap makes later generate() calls
        resolve the new version's entry and spin up ITS engine, while
        in-flight sequences finish on the old one (the predict-path
        admission contract applied to engines)."""
        from .decode import DecodeEngine
        not_accepting = MXNetError(
            "ModelServer is not accepting requests "
            "(not started, or shutting down)")
        with self._cond:
            if not self._started or self._stopping:
                raise not_accepting
            eng = self._decoders.get(entry.uid)
        if eng is None:
            # engine construction is HEAVY (device KV-pool allocation +
            # adapter bind) — build under the dedicated build lock, NOT
            # _cond, so predict() admissions never stall behind a first
            # generate() and two racers cannot both run setup() on the
            # shared adapter (a losing racer's setup would zero the
            # winner's live KV pool)
            with self._decoder_build:
                with self._cond:
                    if not self._started or self._stopping:
                        raise not_accepting
                    eng = self._decoders.get(entry.uid)
                if eng is None:
                    fresh = DecodeEngine(entry.decode_model, self.config,
                                         model_name=entry.name)
                    reject = False
                    with self._cond:
                        if not self._started or self._stopping:
                            reject = True
                        else:
                            self._decoders[entry.uid] = fresh
                            eng = fresh
                    if reject:
                        fresh.stop()        # unbinds the adapter again
                        raise not_accepting
        eng.start()
        # close the start-vs-stop race: a concurrent stop()/unload that
        # cleared the map between our insert and start() has already
        # "stopped" an engine with no thread — the one we just started
        # would leak; stop it and reject
        with self._cond:
            tracked = self._decoders.get(entry.uid) is eng
        if not tracked:
            eng.stop()
            raise not_accepting
        return eng

    def generate(self, model, prompt, *, max_new_tokens=None,
                 eos_id=None, on_token=None, timeout=None):
        """Autoregressive generation through the continuous-batching
        decode engine (docs/serving.md §6).

        ``prompt`` is a 1-D int sequence; returns the generated ids as
        int32 (EOS included when hit).  ``on_token(token_id)`` streams
        every sampled token from the engine thread as it lands —
        time-to-first-token is one prefill away regardless of how many
        other sequences are mid-generation, because the engine admits
        new sequences every STEP, not every request.  Concurrent
        ``generate()`` calls of mixed lengths share the fixed-shape
        decode batch; a short request admitted mid-flight finishes
        ahead of a longer one admitted earlier.

        With ``MXNET_TRACE=1`` the request is one trace end to end:
        admission, queue wait, prefill, every Nth decode step, and
        eviction, with KV-page counts as tags (docs/observability.md).
        """
        with _tr.trace("serving.generate", model=model) as root:
            entry = self.repository.get(model)
            if entry.decode_model is None:
                extra = ""
                if entry.decode_meta is not None:
                    extra = (" (the artifact manifest carries decode "
                             "metadata, but artifact entries serve "
                             "predict() only — register the block with "
                             "add_decoder for in-process generation)")
                raise MXNetError(
                    f"serving generate({model!r}): not a decoder entry "
                    f"— register the model with "
                    f"ModelRepository.add_decoder{extra}")
            eng = self._decoder_engine(entry)
            # pass the (already made) sampling decision down: a
            # sampled-out request must NOT re-enter head sampling in
            # the engine and root a fragment trace
            seq = eng.submit(prompt, max_new_tokens=max_new_tokens,
                             eos_id=eos_id, on_token=on_token,
                             _trace_ctx=root.context)
            return eng.result(seq, timeout=timeout)

    def decode_stats(self, model):
        """The decode engine's scheduler/pool counters for ``model``
        (steps, generated tokens, admissions/evictions, KV-pool
        occupancy, compiled programs vs bound)."""
        entry = self.repository.get(model)
        with self._cond:
            eng = self._decoders.get(entry.uid)
        if eng is None:
            raise MXNetError(
                f"decode_stats({model!r}): no decode engine yet "
                f"(generate() creates it lazily)")
        return eng.stats()

    # -------------------------------------------------------------- prewarm
    def prewarm(self, model, version=None):
        """Compile/load ALL shape buckets of (model, version) through
        this server's program cache before they can meet traffic — the
        zero-cold-start half of the hot-swap contract
        (docs/serving.md §5)::

            repo.load_artifact("m", path, activate=False)   # stage
            srv.prewarm("m", version=2)                     # warm
            repo.swap("m", 2)                               # cutover

        After a prewarmed swap no request ever waits on an XLA compile:
        every bucket's program is already in the batcher's memory cache
        (deserialized from the persistent compile cache when
        ``MXNET_COMPILE_CACHE_DIR`` is set, freshly compiled otherwise).
        Returns the repository's summary dict."""
        return self.repository.prewarm(
            model, version, batcher=self.batcher,
            max_batch_size=self.config.max_batch_size)

    # ---------------------------------------------------------------- stats
    def stats(self):
        """Plain-dict serving counters (always on, independent of the
        runtime-metrics switch)."""
        with self._cond:
            out = dict(self._stats)
            out["queue_depth"] = self._depth
            out["inflight"] = self._inflight
        out["bucket_hits"] = self.batcher.bucket_hits
        out["bucket_disk_hits"] = self.batcher.bucket_disk_hits
        out["bucket_misses"] = self.batcher.bucket_misses
        out["programs"] = self.batcher.programs()
        return out

    def debug_state(self):
        """Deep, JSON-serializable snapshot of the serving stack for
        the flight recorder: per-model queue depths and head ages,
        in-flight counts, per-engine decode state (running sequences
        with their block-table occupancy), program-cache sizes, the
        repository's version map, and tracer counters.  Dumped
        automatically on overload incidents
        (:func:`mxnet_tpu.tracing.record_incident`) and on demand by
        ``tools/diagnose.py``."""
        now = time.monotonic()
        with self._cond:
            queues = []
            for entry, q in self._queues.values():
                queues.append({
                    "model": entry.name, "version": entry.version,
                    "depth": len(q),
                    "head_age_s": None if not q
                    else round(now - q[0].t_enq, 6)})
            decoders = dict(self._decoders)
            state = {
                "server": self.name,
                "started": self._started,
                "stopping": self._stopping,
                "workers": len(self._workers),
                "queue_depth": self._depth,
                "inflight": self._inflight,
                "stats": dict(self._stats),
                "queues": queues,
            }
        # engine/batcher/repository snapshots go through THEIR locks
        # only after _cond is released (one-way acquisition order)
        state["decoders"] = {str(uid): eng.debug_state()
                             for uid, eng in decoders.items()}
        state["batcher"] = {
            "programs": self.batcher.programs(),
            "bucket_hits": self.batcher.bucket_hits,
            "bucket_disk_hits": self.batcher.bucket_disk_hits,
            "bucket_misses": self.batcher.bucket_misses,
        }
        state["repository"] = self.repository.debug_state()
        state["tracer"] = _tr.TRACER.stats()
        return state

    # -------------------------------------------------------------- workers
    def _set_depth(self, depth):
        # mxlint: disable=lock-discipline (contract: callers hold
        # self._cond — every call site is inside `with self._cond`)
        self._depth = depth
        if _rm._ENABLED:
            _rm.SERVING_QUEUE_DEPTH.set(depth, server=self.name)
            _rm.SERVING_QUEUE_PEAK.set_max(depth, server=self.name)

    def _next_batch(self):
        """Block until a batch is ready to dispatch (or shutdown drain
        is complete).  Returns ``(entry, [requests])`` or None.

        A queue is *ripe* once it holds a full batch or its head request
        has aged past ``max_latency_us`` (always, during shutdown
        drain).  The ripe queue with the oldest head dispatches first so
        no model starves; when nothing is ripe yet, wait only until the
        earliest forming-batch deadline — a full batch for one model
        never sits behind another model's hold window.
        """
        max_latency_s = self.config.max_latency_us / 1e6
        with self._cond:
            while True:
                ripe, earliest = None, None
                for uid, (entry, q) in self._queues.items():
                    if not q:
                        continue
                    cap = entry.max_rows(self.config.max_batch_size)
                    deadline = q[0].t_enq + max_latency_s
                    now = time.monotonic()
                    if self._stopping or now >= deadline \
                            or sum(r.rows for r in q) >= cap:
                        if ripe is None or q[0].t_enq < ripe[1][0].t_enq:
                            ripe = (entry, q)
                    elif earliest is None or deadline < earliest:
                        earliest = deadline
                if ripe is None:
                    if earliest is not None:
                        # hold forming batches open for more work, then
                        # re-evaluate (new arrivals notify)
                        self._cond.wait(
                            max(0.0, earliest - time.monotonic()))
                        continue
                    if self._stopping:
                        return None
                    # idle: block until an enqueue/stop notifies (every
                    # state change that creates work calls notify_all)
                    self._cond.wait()
                    continue
                entry, q = ripe
                cap = entry.max_rows(self.config.max_batch_size)
                reqs, rows = [], 0
                while q and rows + q[0].rows <= cap:
                    r = q.popleft()
                    reqs.append(r)
                    rows += r.rows
                if not q:
                    self._queues.pop(entry.uid, None)
                self._set_depth(self._depth - len(reqs))
                self._inflight += len(reqs)
                return entry, reqs

    def _worker_loop(self):
        while True:
            batch = self._next_batch()
            if batch is None:
                return
            entry, reqs = batch
            # queue-wait spans end at the pop (outside _cond — the
            # tracer lock is never taken while a serving lock is held)
            for r in reqs:
                r.queue_span.end()
            # ONE batch-assembly span shared by every coalesced
            # request: it lives in the first sampled request's trace
            # and is copied (same interval, same tags) into the others
            # after dispatch — chrome-trace has no multi-parent links,
            # so each trace gets a complete private timeline instead
            home = next((r.trace for r in reqs if r.trace is not None),
                        None)
            bspan = _tr.span("serving.batch", parent=home,
                             model=entry.name, requests=len(reqs))

            def _share_batch_span():
                # copy the (ended) shared span into the OTHER coalesced
                # traces — must run BEFORE any r.event.set(): a woken
                # caller completes its root, after which the copy would
                # be dropped as a straggler
                if bspan.sampled:
                    for r in reqs:
                        if r.trace is not None \
                                and r.trace.trace_id != bspan.trace_id:
                            _tr.record_span(
                                "serving.batch", r.trace, bspan.t0,
                                bspan.t1 or bspan.t0,
                                dict(bspan.tags or {},
                                     shared_with=bspan.trace_id))

            try:
                with bspan:
                    results = self.batcher.run_batch(
                        entry, [r.inputs for r in reqs])
            except Exception as e:        # noqa: BLE001 — fail the batch
                # also log it: a caller that already timed out will
                # never read req.error, and a compile failure must not
                # be diagnosable only as caller-side timeouts
                _LOG.warning("serving: batch of %d request(s) for "
                             "%s:%s failed: %s", len(reqs), entry.name,
                             entry.version, e)
                _share_batch_span()       # bspan ended by the with-exit
                with self._cond:
                    self._stats["errors"] += len(reqs)
                    self._inflight -= len(reqs)
                    self._cond.notify_all()
                for r in reqs:
                    r.error = e
                    r.event.set()
                continue
            _share_batch_span()
            done = time.monotonic()
            with self._cond:
                self._stats["batches"] += 1
                self._stats["completed"] += len(reqs)
                self._inflight -= len(reqs)
                self._cond.notify_all()
            for r, out in zip(reqs, results):
                r.result = out
                if _rm._ENABLED:
                    _rm.SERVING_REQUEST_SECONDS.observe(
                        done - r.t_enq, model=entry.name,
                        exemplar=None if r.trace is None
                        else r.trace.trace_id)
                r.event.set()
