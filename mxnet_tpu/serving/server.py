"""In-process model server: bounded queues, worker pool, backpressure
(docs/serving.md §4).

``predict()`` is synchronous from the caller's side; underneath,
admitted requests land in a bounded per-model queue, a worker pool
coalesces them into shape-bucketed batches (``DynamicBatcher``) and the
caller's thread wakes when its slice of the batch output is ready.
Backpressure is explicit: when queue depth sits at/above the
load-shedding watermark, admission fails *immediately* with
:class:`ServerOverloadedError` carrying a retry-after hint — the
serving-tier contract that callers see bounded latency or a cheap
reject, never an unbounded queue (reference: MXNet Model Server's
worker queues; the Gemma-on-TPU serving comparison's batching policy,
PAPERS.md).
"""
from __future__ import annotations

import itertools
import logging
import threading
import time
from collections import OrderedDict, deque

import numpy as np

from .. import engine, runtime_metrics as _rm, tracing as _tr
from ..base import MXNetError, entropy_rng
from .admission import AdmissionController
from .batcher import DynamicBatcher
from .config import ServingConfig
from .repository import ModelRepository
from .resilience import (CircuitBreaker, Deadline, DeadlineExceededError,
                         ServerOverloadedError, retry_call)

__all__ = ["ModelServer", "ServerOverloadedError",
           "DeadlineExceededError"]

_LOG = logging.getLogger("mxnet_tpu")
_SERVER_SEQ = itertools.count(1)


class _Request:
    __slots__ = ("entry", "inputs", "rows", "event", "result", "error",
                 "t_enq", "trace", "queue_span", "deadline")

    def __init__(self, entry, inputs, rows, deadline=None):
        self.entry = entry
        self.inputs = inputs
        self.rows = rows
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.t_enq = time.monotonic()
        # end-to-end deadline (resilience.Deadline; may be unbounded):
        # fixed at admission, consulted at batch assembly and by the
        # retry policy — a request can never outlive its timeout just
        # because it made it into a batch
        self.deadline = deadline or Deadline()
        # tracing: the request's TraceContext (None when untraced) and
        # its queue-wait span — started in the caller's thread at
        # enqueue, ended in whichever worker pops it (Span.end is
        # idempotent, so the timeout-withdrawal race is benign)
        self.trace = None
        self.queue_span = _tr._NOOP


class ModelServer:
    """Dynamic-batching server over a :class:`ModelRepository`.

    >>> repo = ModelRepository()
    >>> repo.load_artifact("lenet", "lenet.shlo")
    >>> with ModelServer(repo) as srv:
    ...     out = srv.predict("lenet", batch_of_images)

    Requests resolve their model entry at admission, so
    ``repository.swap`` hot-swaps versions without draining: in-flight
    requests finish on the old version, new admissions see the new one.
    """

    def __init__(self, repository=None, config=None, autostart=True,
                 name=None):
        self.repository = repository or ModelRepository()
        self.config = config or ServingConfig()
        self.batcher = DynamicBatcher(self.config)
        self.name = name or f"server{next(_SERVER_SEQ)}"
        self._evict_subscribed = False
        # engine.make_condition: plain Condition normally; lock-order
        # recording under MXNET_ENGINE_SANITIZE=1 (the serving tests
        # double as race tests in CI's sanity_lint job)
        self._cond = engine.make_condition("serving.ModelServer._cond")
        self._queues = OrderedDict()    # entry.uid -> (entry, deque)
        self._decoders = OrderedDict()  # entry.uid -> DecodeEngine
        # serializes decode-engine CONSTRUCTION (KV-pool allocation +
        # adapter bind) without holding _cond: two first-generate()
        # racers must not both run setup() on one shared adapter
        self._decoder_build = engine.make_lock(
            "serving.ModelServer._decoder_build")
        # replica layer (docs/serving.md §10): with config.replicas > 1
        # each entry serves through a lazily built ReplicaSet instead
        # of the shared batcher / single decode engine.  Same build
        # discipline as decoders: construction (N prewarms) runs under
        # its own lock, never under _cond
        self._replica_sets = OrderedDict()  # entry.uid -> ReplicaSet
        self._replica_build = engine.make_lock(
            "serving.ModelServer._replica_build")
        self._depth = 0
        self._inflight = 0              # admitted, popped, not finished
        self._started = False
        self._stopping = False
        self._workers = []
        # per-model-version circuit breakers (entry.uid -> breaker),
        # created lazily at first admission; a hot-swap naturally gets
        # a FRESH breaker because the new version is a new uid.  The
        # retired set mirrors the batcher's: a worker finishing an
        # in-flight batch for an unloaded entry must not resurrect its
        # breaker into the map (nothing would ever evict it again)
        self._breakers = {}
        self._retired_uids = set()
        # jitter source for retry backoff — instance-owned so tests can
        # inject a seeded one; entropy-seeded by default so N replicas
        # hitting one backend failure do NOT retry in lockstep (the
        # thundering herd jitter exists to break up)
        self._retry_rng = entropy_rng()
        # tiered admission gate (docs/serving.md §11), built from
        # config.tenant_tiers; None = gate off, zero per-request cost
        self._admission = AdmissionController.from_config(self.config)
        self._stats = {"requests": 0, "completed": 0, "shed": 0,
                       "batches": 0, "errors": 0, "retries": 0,
                       "deadline_exceeded": 0, "bisected": 0,
                       "circuit_open_rejects": 0, "tenant_sheds": 0}
        engine.watch_races(self)
        if autostart:
            self.start()

    # ----------------------------------------------------------- lifecycle
    def start(self):
        with self._cond:
            if self._started:
                return self
            self._started = True
            self._stopping = False
            # retired versions must not pin compiled programs for the
            # process lifetime (hot-swap deploy loops); unsubscribed at
            # stop() so the repository never pins a dead server.  Flag
            # and subscription flip atomically under _cond (a racing
            # stop() must observe both or neither); the nested
            # repository lock is safe — the server->repository
            # acquisition order is one-way (the repository never calls
            # back into the server)
            if not self._evict_subscribed:
                self.repository.subscribe_unload(self._on_unload)
                self._evict_subscribed = True
        with self._cond:
            self._workers = [
                engine.make_thread(self._worker_loop,
                                   name=f"mxnet-serving-{i}",
                                   owner=f"ModelServer({self.name})")
                for i in range(self.config.num_workers)]
        for t in self._workers:
            t.start()
        return self

    def stop(self, drain=True, timeout=None):
        """Shut down the worker pool.  ``drain=True`` (default) stops
        admission, lets workers finish every queued request, then joins;
        ``drain=False`` fails queued requests immediately.

        Returns True once the pool is down.  With a ``timeout``, a
        worker stuck in a dispatch can outlive the join — then the
        server STAYS in the stopping state (so a later ``start()``
        cannot spawn a second pool next to the orphan) and stop()
        returns False; call it again to finish the shutdown."""
        with self._cond:
            if not self._started:
                return True
            self._stopping = True
            if not drain:
                for _entry, q in self._queues.values():
                    for req in q:
                        req.error = MXNetError(
                            "ModelServer stopped before this request "
                            "was dispatched")
                        req.event.set()
                    q.clear()
                self._set_depth(0)
            self._cond.notify_all()
        # one total budget, not one per worker
        deadline = None if timeout is None else time.monotonic() + timeout
        for t in self._workers:
            t.join(None if deadline is None
                   else max(0.0, deadline - time.monotonic()))
        alive = [t for t in self._workers if t.is_alive()]
        if alive:
            return False
        # decode engines and replica sets go down with the worker pool;
        # outstanding generate() calls fail with finish_reason="stopped"
        with self._cond:
            decoders = dict(self._decoders)
            self._decoders.clear()
            rsets = dict(self._replica_sets)
            self._replica_sets.clear()
        stuck = {}
        for uid, eng in decoders.items():
            if not eng.stop(timeout=None if deadline is None
                            else max(0.0, deadline - time.monotonic())):
                stuck[uid] = eng
        stuck_sets = {}
        for uid, rset in rsets.items():
            if not rset.stop(timeout=None if deadline is None
                             else max(0.0,
                                      deadline - time.monotonic())):
                stuck_sets[uid] = rset
        if stuck or stuck_sets:
            # same contract as a stuck worker: keep the references so a
            # later stop() can finish the job, stay in the stopping
            # state, report failure — never leak a live step loop
            with self._cond:
                self._decoders.update(stuck)
                self._replica_sets.update(stuck_sets)
            return False
        with self._cond:
            self._started = False
            self._workers = []
            if self._evict_subscribed:
                self.repository.unsubscribe_unload(self._on_unload)
                self._evict_subscribed = False
        return True

    def _on_unload(self, entry):
        """Repository unload hook: drop the batcher's cached programs,
        the version's circuit breaker (a retired uid's error history
        must not pin memory across hot-swap churn), AND stop/drop the
        entry's decode engine and replica set (their KV pools and
        per-replica program caches must not pin device memory for a
        retired version)."""
        self.batcher.evict(entry)
        with self._cond:
            eng = self._decoders.pop(entry.uid, None)
            rset = self._replica_sets.pop(entry.uid, None)
            self._breakers.pop(entry.uid, None)
            self._retired_uids.add(entry.uid)
        if eng is not None:
            eng.stop()
        if rset is not None:
            rset.stop()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop(drain=exc == (None, None, None))
        return False

    @property
    def started(self):
        return self._started

    # ------------------------------------------------------------ breakers
    def _breaker(self, entry):
        """The (lazily created) circuit breaker of one model VERSION.
        Keyed on entry.uid: a hot-swapped version starts with a fresh,
        closed circuit, and a rolled-back version's error history dies
        with its uid.  A RETIRED uid (unloaded mid-flight) gets an
        ephemeral breaker that is never stored — the unload hook has
        already run, so re-inserting would leak it forever."""
        with self._cond:
            br = self._breakers.get(entry.uid)
            if br is None:
                br = CircuitBreaker(
                    self.config.circuit_window,
                    self.config.circuit_threshold,
                    self.config.circuit_cooldown_ms,
                    model=entry.name, version=entry.version)
                if entry.uid not in self._retired_uids:
                    self._breakers[entry.uid] = br
        return br

    def _admit_circuit(self, entry):
        """Breaker gate at admission; counts the reject as a shed (to a
        caller an open circuit IS an overload — back off and retry),
        with the same observability every other shed gets: an admit
        span tagged with the shed reason (parented to the ambient
        predict/generate root) and a debounced serving.shed incident
        dump."""
        try:
            self._breaker(entry).admit()
        except ServerOverloadedError as e:
            with self._cond:
                self._stats["shed"] += 1
                self._stats["circuit_open_rejects"] += 1
            if _rm._ENABLED:
                _rm.SERVING_SHED.inc(model=entry.name)
            sp = _tr.span("serving.admit")
            sp.set_tag("shed", str(e))
            sp.end()
            _tr.record_incident("serving.shed", self.debug_state)
            raise

    def _admit_tenant(self, entry, tenant):
        """Tenant-tier gate (docs/serving.md §11): quota token bucket
        plus priority shedding under overload — low tiers shed first.
        Runs AFTER the circuit gate and BEFORE the watermark check so
        a shed tenant never touches the bounded queue.  No-op when
        ``config.tenant_tiers`` is unset.  Observability mirrors every
        other shed: stats, serving.shed metric, tagged admit span,
        debounced incident dump."""
        if self._admission is None:
            return
        # instantaneous queue fraction, read without _cond — a stale
        # snapshot only skews the pressure one request, and the gate
        # must not nest the controller's lock inside the server's
        load = self._depth / float(max(1, self.config.shed_watermark))
        try:
            self._admission.check(tenant, model=entry.name, load=load)
        except ServerOverloadedError as e:
            with self._cond:
                self._stats["shed"] += 1
                self._stats["tenant_sheds"] += 1
            if _rm._ENABLED:
                _rm.SERVING_SHED.inc(model=entry.name)
            sp = _tr.span("serving.admit")
            sp.set_tag("shed", str(e))
            sp.set_tag("tenant", "" if tenant is None else str(tenant))
            sp.end()
            _tr.record_incident("serving.shed", self.debug_state)
            raise

    def admission_controller(self):
        """The tiered :class:`~mxnet_tpu.serving.admission.
        AdmissionController` (None when ``config.tenant_tiers`` is
        unset) — the autoscaler publishes SLO pressure into it, tests
        and ``tools/diagnose.py`` read its stats."""
        return self._admission

    # -------------------------------------------------------------- predict
    def predict(self, model, *inputs, timeout=None, tenant=None):
        """Run one inference request; blocks until its slice of a
        coalesced batch is ready.  Inputs are batch-major NDArray /
        numpy arrays validated against the model's serving signature;
        returns numpy (one array, or a tuple for multi-output models).

        ``timeout`` (default ``config.deadline_default``) is the
        request's END-TO-END deadline, not just the queue wait: it is
        fixed at admission and carried through queue -> batch assembly
        -> execute, an expired request is cancelled before it consumes
        a batch slot, and the caller gets
        :class:`~mxnet_tpu.serving.resilience.DeadlineExceededError`
        within one scheduling quantum of the deadline — never a hang
        (docs/serving.md §8).

        ``tenant`` ("name" or "name:tier") routes the request through
        the tiered admission gate when ``config.tenant_tiers`` is set
        (docs/serving.md §11); None rides the default tier with no
        quota.

        With ``MXNET_TRACE=1`` the request carries one trace identity
        end to end: admission, queue wait, the (shared) batch-assembly
        span with its bucket outcome, and execute — and the latency
        histogram records the trace id as its exemplar, so a p99 links
        to the exact trace behind it (docs/observability.md).
        """
        with _tr.trace("serving.predict", model=model) as root:
            return self._predict_impl(model, inputs, timeout, root,
                                      tenant)

    def _predict_impl(self, model, inputs, timeout, root, tenant=None):
        from .. import deploy
        entry = self.repository.get(model)
        if entry.decode_model is not None:
            raise MXNetError(
                f"serving predict({model!r}): decoder entry — "
                f"autoregressive models serve through generate()")
        np_inputs = tuple(
            np.asarray(x.asnumpy()) if hasattr(x, "asnumpy")
            else np.asarray(x) for x in inputs)
        deploy.validate_inputs(entry.manifest, np_inputs,
                               where=f"serving predict({model!r})")
        if not np_inputs or np_inputs[0].ndim < 1:
            raise MXNetError(
                f"serving predict({model!r}): inputs must be batch-major "
                f"arrays with a leading batch dimension")
        rows = np_inputs[0].shape[0]
        cap = entry.max_rows(self.config.max_batch_size)
        if rows < 1 or rows > cap:
            raise MXNetError(
                f"serving predict({model!r}): request batch of {rows} "
                f"rows outside [1, {cap}] (max_batch_size="
                f"{self.config.max_batch_size}, "
                f"exported batch={entry.fixed_batch})")
        if timeout is None:
            timeout = self.config.deadline_default
        deadline = Deadline.start(timeout)
        # circuit gate AFTER validation (a malformed request says
        # nothing about version health) and BEFORE queueing (an open
        # circuit must shed instantly, not after a queue wait); the
        # tenant-tier gate follows the same rule
        self._admit_circuit(entry)
        self._admit_tenant(entry, tenant)

        req = _Request(entry, np_inputs, rows, deadline=deadline)
        req.trace = root.context
        admit = _tr.span("serving.admit", parent=req.trace, rows=rows)
        try:
            with self._cond:
                if not self._started or self._stopping:
                    raise MXNetError(
                        "ModelServer is not accepting requests "
                        "(not started, or shutting down)")
                # two-level backpressure: the watermark bounds the
                # WAITING queue; queue_depth additionally bounds total
                # outstanding work (queued + in-flight), so a slow
                # model cannot pile up unbounded
                # dispatched-but-unfinished requests
                reason = None
                if self._depth >= self.config.shed_watermark:
                    reason = (f"queue depth {self._depth} >= shed "
                              f"watermark {self.config.shed_watermark}")
                elif self._depth + self._inflight \
                        >= self.config.queue_depth:
                    reason = (f"outstanding work {self._depth} queued "
                              f"+ {self._inflight} in flight >= "
                              f"queue_depth {self.config.queue_depth}")
                if reason is not None:
                    self._stats["shed"] += 1
                    if _rm._ENABLED:
                        _rm.SERVING_SHED.inc(model=model)
                    admit.set_tag("shed", reason)
                    raise ServerOverloadedError(
                        model, self.config.retry_after_ms, reason)
                slot = self._queues.get(entry.uid)
                if slot is None:
                    slot = (entry, deque())
                    self._queues[entry.uid] = slot
                slot[1].append(req)
                self._set_depth(self._depth + 1)
                self._stats["requests"] += 1
                if _rm._ENABLED:
                    _rm.SERVING_REQUESTS.inc(model=model)
                req.queue_span = _tr.span("serving.queue_wait",
                                          parent=req.trace,
                                          depth=self._depth)
                self._cond.notify_all()
        except ServerOverloadedError:
            # flight recorder: an overloaded replica dumps its recent
            # traces + debug state ONCE per debounce window (the
            # callable defers the state walk until a dump really
            # happens) — called after _cond is released
            _tr.record_incident("serving.shed", self.debug_state)
            raise
        finally:
            admit.end()

        if not req.event.wait(deadline.remaining()):
            # withdraw an abandoned request so it neither occupies
            # bounded-queue depth (pushing admissions into the shed
            # watermark) nor burns device time computing a result
            # nobody will read; if a worker popped it meanwhile, let
            # that batch complete — the result is simply dropped.
            # Count the expiry only when WE withdrew it: a popped
            # request is counted by the worker instead (executed, or
            # expired at batch assembly) — never twice.
            withdrawn = False
            with self._cond:
                slot = self._queues.get(entry.uid)
                if slot is not None and req in slot[1]:
                    slot[1].remove(req)
                    if not slot[1]:
                        self._queues.pop(entry.uid, None)
                    self._set_depth(self._depth - 1)
                    withdrawn = True
                if withdrawn:
                    self._stats["deadline_exceeded"] += 1
            if withdrawn and _rm._ENABLED:
                _rm.SERVING_DEADLINE_EXCEEDED.inc(model=model)
            req.queue_span.end(error="timeout")
            raise DeadlineExceededError(
                f"serving predict({model!r})", timeout,
                f"queue depth {self._depth}")
        if req.error is not None:
            raise req.error
        return req.result if len(req.result) > 1 else req.result[0]

    # ------------------------------------------------------------- replicas
    def _replicated(self, entry):
        """Whether this entry serves through a ReplicaSet.  The
        single-replica configuration keeps the pre-replica path
        byte-for-byte (shared batcher / one decode engine), so
        replicas=1 cannot regress anything."""
        return self.config.replicas > 1

    def _replica_devices(self, entry):
        """Best-effort device placement for one entry's replicas:
        disjoint groups of the visible devices when they cover the
        replica count, shared devices otherwise (the CPU/test
        topology).  Function entries get no placement — there is no
        device work to place."""
        if entry.kind in ("function", "decoder"):
            return None
        try:
            from ..parallel.placement import replica_groups
            return replica_groups(self.config.replicas,
                                  oversubscribe=None)
        except Exception as e:      # noqa: BLE001 — placement optional
            _LOG.warning(
                "serving: replica placement unavailable for %s (%s); "
                "replicas share default placement", entry.name, e)
            return None

    def _replica_set(self, entry):
        """The (lazily built) ReplicaSet of one entry uid.  Build —
        which prewarms every replica — runs under the dedicated build
        lock so admissions never stall behind it, with the same
        start-vs-stop re-check discipline as decode engines."""
        from .replica import ReplicaSet
        not_accepting = MXNetError(
            "ModelServer is not accepting requests "
            "(not started, or shutting down)")
        with self._cond:
            if not self._started or self._stopping:
                raise not_accepting
            rset = self._replica_sets.get(entry.uid)
        if rset is not None:
            return rset
        with self._replica_build:
            with self._cond:
                if not self._started or self._stopping:
                    raise not_accepting
                rset = self._replica_sets.get(entry.uid)
            if rset is not None:
                return rset
            fresh = ReplicaSet(entry, self.config,
                               devices=self._replica_devices(entry))
            reject = False
            with self._cond:
                if not self._started or self._stopping \
                        or entry.uid in self._retired_uids:
                    reject = True
                else:
                    self._replica_sets[entry.uid] = fresh
            if reject:
                fresh.stop()
                raise not_accepting
            # close the build-vs-unload race the decode engines also
            # guard: an unload that popped the map between our insert
            # and here has already "stopped" a set it never saw — stop
            # the orphan and reject rather than leak its threads
            with self._cond:
                tracked = self._replica_sets.get(entry.uid) is fresh
            if not tracked:
                fresh.stop()
                raise not_accepting
            return fresh

    def replica_set(self, model, version=None):
        """The :class:`~mxnet_tpu.serving.replica.ReplicaSet` serving
        (model, version) — built (every replica prewarmed) on first
        use.  This is the autoscaler's actuation handle
        (docs/serving.md §11): ``Autoscaler(server.replica_set("m"),
        ...)``.  Raises unless ``config.replicas`` > 1."""
        entry = self.repository._resolve(model, version)
        if not self._replicated(entry):
            raise MXNetError(
                f"replica_set({model!r}): config.replicas="
                f"{self.config.replicas} — the replica layer needs "
                f"replicas > 1 (docs/serving.md §10)")
        return self._replica_set(entry)

    def _execute_batch(self, entry, inputs, deadline):
        """One batch execution: through the entry's ReplicaSet
        (least-loaded healthy replica, deadline-preserving failover)
        when replicas are configured, else the shared batcher."""
        if self._replicated(entry):
            return self._replica_set(entry).run_batch(
                inputs, deadline=deadline)
        return self.batcher.run_batch(entry, inputs, deadline=deadline)

    # ------------------------------------------------------------- generate
    def _decoder_engine(self, entry):
        """The (lazily created) decode engine of a decoder entry.  One
        engine per entry uid: a hot-swap makes later generate() calls
        resolve the new version's entry and spin up ITS engine, while
        in-flight sequences finish on the old one (the predict-path
        admission contract applied to engines)."""
        from .decode import DecodeEngine
        not_accepting = MXNetError(
            "ModelServer is not accepting requests "
            "(not started, or shutting down)")
        with self._cond:
            if not self._started or self._stopping:
                raise not_accepting
            eng = self._decoders.get(entry.uid)
        if eng is None:
            # engine construction is HEAVY (device KV-pool allocation +
            # adapter bind) — build under the dedicated build lock, NOT
            # _cond, so predict() admissions never stall behind a first
            # generate() and two racers cannot both run setup() on the
            # shared adapter (a losing racer's setup would zero the
            # winner's live KV pool)
            with self._decoder_build:
                with self._cond:
                    if not self._started or self._stopping:
                        raise not_accepting
                    eng = self._decoders.get(entry.uid)
                if eng is None:
                    # speculative draft: the entry's own attachment
                    # wins; else MXNET_SERVING_SPEC_DRAFT names a
                    # repository decoder entry whose decode model
                    # drafts for everyone.  Every engine gets its OWN
                    # adapter over the named entry's LM — an adapter
                    # binds one live engine (its pool/programs are
                    # engine state), so sharing the entry's adapter
                    # across N targets would reject the second one
                    draft = entry.draft_model
                    if draft is None and self.config.spec_k \
                            and self.config.spec_draft \
                            and self.config.spec_draft != entry.name:
                        from .decode import PagedLMAdapter
                        draft = self.repository.get(
                            self.config.spec_draft).decode_model
                        if isinstance(draft, PagedLMAdapter):
                            draft = PagedLMAdapter(
                                draft.lm,
                                attention_impl=draft.attention_impl)
                    fresh = DecodeEngine(entry.decode_model, self.config,
                                         model_name=entry.name,
                                         draft=draft)
                    reject = False
                    with self._cond:
                        if not self._started or self._stopping:
                            reject = True
                        else:
                            self._decoders[entry.uid] = fresh
                            eng = fresh
                    if reject:
                        fresh.stop()        # unbinds the adapter again
                        raise not_accepting
        eng.start()
        # close the start-vs-stop race: a concurrent stop()/unload that
        # cleared the map between our insert and start() has already
        # "stopped" an engine with no thread — the one we just started
        # would leak; stop it and reject
        with self._cond:
            tracked = self._decoders.get(entry.uid) is eng
        if not tracked:
            eng.stop()
            raise not_accepting
        return eng

    def generate(self, model, prompt, *, max_new_tokens=None,
                 eos_id=None, on_token=None, timeout=None,
                 tenant=None):
        """Autoregressive generation through the continuous-batching
        decode engine (docs/serving.md §6).

        ``prompt`` is a 1-D int sequence; returns the generated ids as
        int32 (EOS included when hit).  ``on_token(token_id)`` streams
        every sampled token from the engine thread as it lands —
        time-to-first-token is one prefill away regardless of how many
        other sequences are mid-generation, because the engine admits
        new sequences every STEP, not every request.  Concurrent
        ``generate()`` calls of mixed lengths share the fixed-shape
        decode batch; a short request admitted mid-flight finishes
        ahead of a longer one admitted earlier.

        ``timeout`` (default ``config.deadline_default``) is the
        END-TO-END deadline: carried into the engine's waiting queue
        (an expired waiting sequence is cancelled before it consumes a
        decode slot or KV pages) and checked every step while running
        (an expired running sequence is evicted with its pages
        reclaimed), so a request can never outlive its timeout inside
        the decode batch (docs/serving.md §8).

        ``tenant`` ("name" or "name:tier") routes the request through
        the tiered admission gate when ``config.tenant_tiers`` is set
        (docs/serving.md §11); None rides the default tier with no
        quota.

        With ``MXNET_TRACE=1`` the request is one trace end to end:
        admission, queue wait, prefill, every Nth decode step, and
        eviction, with KV-page counts as tags (docs/observability.md).
        """
        with _tr.trace("serving.generate", model=model) as root:
            entry = self.repository.get(model)
            if entry.decode_model is None:
                extra = ""
                if entry.decode_meta is not None:
                    extra = (" (the artifact manifest carries decode "
                             "metadata, but artifact entries serve "
                             "predict() only — register the block with "
                             "add_decoder for in-process generation)")
                raise MXNetError(
                    f"serving generate({model!r}): not a decoder entry "
                    f"— register the model with "
                    f"ModelRepository.add_decoder{extra}")
            if timeout is None:
                timeout = self.config.deadline_default
            self._admit_circuit(entry)
            self._admit_tenant(entry, tenant)
            if self._replicated(entry):
                # replica path (docs/serving.md §10): the set routes
                # to the least-loaded healthy replica's engine and
                # fails a dead replica's sequence over to a sibling as
                # a fresh request under this SAME deadline.  Health
                # lives in the per-replica breakers — the version-level
                # breaker stays admission-only here (a version is as
                # healthy as its replicas; a fully-dark set sheds as
                # ServerOverloadedError from the router).
                return self._replica_set(entry).generate(
                    prompt, max_new_tokens=max_new_tokens,
                    eos_id=eos_id, on_token=on_token, timeout=timeout,
                    _trace_ctx=root.context)
            eng = self._decoder_engine(entry)
            # pass the (already made) sampling decision down: a
            # sampled-out request must NOT re-enter head sampling in
            # the engine and root a fragment trace
            seq = eng.submit(prompt, max_new_tokens=max_new_tokens,
                             eos_id=eos_id, on_token=on_token,
                             timeout=timeout, _trace_ctx=root.context)
            breaker = self._breaker(entry)
            try:
                out = eng.result(seq, timeout=timeout)
            except Exception:
                # execute outcomes only: a step failure / quarantine is
                # version health, a cancel/deadline/shed is not
                if seq.finish_reason in ("error", "quarantined"):
                    breaker.record(False)
                raise
            breaker.record(True)
            return out

    def decode_stats(self, model):
        """The decode engine's scheduler/pool counters for ``model``
        (steps, generated tokens, admissions/evictions, KV-pool
        occupancy, compiled programs vs bound).  With replicas
        configured, one entry per replica id."""
        entry = self.repository.get(model)
        with self._cond:
            eng = self._decoders.get(entry.uid)
            rset = self._replica_sets.get(entry.uid)
        if rset is not None:
            return rset.decode_stats()
        if eng is None:
            raise MXNetError(
                f"decode_stats({model!r}): no decode engine yet "
                f"(generate() creates it lazily)")
        return eng.stats()

    # -------------------------------------------------------------- prewarm
    def prewarm(self, model, version=None):
        """Compile/load ALL shape buckets of (model, version) through
        this server's program cache before they can meet traffic — the
        zero-cold-start half of the hot-swap contract
        (docs/serving.md §5)::

            repo.load_artifact("m", path, activate=False)   # stage
            srv.prewarm("m", version=2)                     # warm
            repo.swap("m", 2)                               # cutover

        After a prewarmed swap no request ever waits on an XLA compile:
        every bucket's program is already in the batcher's memory cache
        (deserialized from the persistent compile cache when
        ``MXNET_COMPILE_CACHE_DIR`` is set, freshly compiled otherwise).
        Returns the repository's summary dict.

        With replicas configured, prewarming builds the whole
        ReplicaSet instead — EVERY replica's program cache is built
        and executed before any of them is routable, so the staged
        version's swap admits traffic against N warm replicas."""
        entry = self.repository._resolve(model, version)
        if self._replicated(entry):
            rset = self._replica_set(entry)
            return {"model": model, "version": entry.version,
                    "replicas": rset.replicas(),
                    "stats": rset.stats()}
        return self.repository.prewarm(
            model, version, batcher=self.batcher,
            max_batch_size=self.config.max_batch_size)

    # ---------------------------------------------------------------- stats
    def stats(self):
        """Plain-dict serving counters (always on, independent of the
        runtime-metrics switch)."""
        with self._cond:
            out = dict(self._stats)
            out["queue_depth"] = self._depth
            out["inflight"] = self._inflight
        out["bucket_hits"] = self.batcher.bucket_hits
        out["bucket_disk_hits"] = self.batcher.bucket_disk_hits
        out["bucket_misses"] = self.batcher.bucket_misses
        out["programs"] = self.batcher.programs()
        with self._cond:
            rsets = dict(self._replica_sets)
        if rsets:
            # keyed by model name; when TWO versions of one model are
            # live (staged prewarm during a hot-swap window) the later
            # uid disambiguates as "name@vN" instead of silently
            # shadowing the serving version's counters
            sets = {}
            for rset in rsets.values():
                key = rset.name
                if key in sets:
                    key = f"{rset.name}@v{rset.entry.version}"
                sets[key] = rset.stats()
            out["replica_sets"] = sets
        if self._admission is not None:
            out["admission"] = self._admission.stats()
        return out

    def debug_state(self):
        """Deep, JSON-serializable snapshot of the serving stack for
        the flight recorder: per-model queue depths and head ages,
        in-flight counts, per-engine decode state (running sequences
        with their block-table occupancy), program-cache sizes, the
        repository's version map, and tracer counters.  Dumped
        automatically on overload incidents
        (:func:`mxnet_tpu.tracing.record_incident`) and on demand by
        ``tools/diagnose.py``."""
        now = time.monotonic()
        with self._cond:
            queues = []
            for entry, q in self._queues.values():
                queues.append({
                    "model": entry.name, "version": entry.version,
                    "depth": len(q),
                    "head_age_s": None if not q
                    else round(now - q[0].t_enq, 6)})
            decoders = dict(self._decoders)
            rsets = dict(self._replica_sets)
            state = {
                "server": self.name,
                "started": self._started,
                "stopping": self._stopping,
                "workers": len(self._workers),
                "queue_depth": self._depth,
                "inflight": self._inflight,
                "stats": dict(self._stats),
                "queues": queues,
            }
            breakers = dict(self._breakers)
        # engine/batcher/repository snapshots go through THEIR locks
        # only after _cond is released (one-way acquisition order)
        state["decoders"] = {str(uid): eng.debug_state()
                             for uid, eng in decoders.items()}
        state["replica_sets"] = {str(uid): rset.debug_state()
                                 for uid, rset in rsets.items()}
        state["circuits"] = {str(uid): br.debug_state()
                             for uid, br in breakers.items()}
        state["batcher"] = {
            "programs": self.batcher.programs(),
            "bucket_hits": self.batcher.bucket_hits,
            "bucket_disk_hits": self.batcher.bucket_disk_hits,
            "bucket_misses": self.batcher.bucket_misses,
        }
        if self._admission is not None:
            state["admission"] = self._admission.debug_state()
        state["repository"] = self.repository.debug_state()
        state["tracer"] = _tr.TRACER.stats()
        return state

    # -------------------------------------------------------------- workers
    def _set_depth(self, depth):
        # mxlint: disable=lock-discipline (contract: callers hold
        # self._cond — every call site is inside `with self._cond`)
        self._depth = depth
        if _rm._ENABLED:
            _rm.SERVING_QUEUE_DEPTH.set(depth, server=self.name)
            _rm.SERVING_QUEUE_PEAK.set_max(depth, server=self.name)

    def _next_batch(self):
        """Block until a batch is ready to dispatch (or shutdown drain
        is complete).  Returns ``(entry, [requests], [expired])`` or
        None.

        A queue is *ripe* once it holds a full batch or its head request
        has aged past ``max_latency_us`` (always, during shutdown
        drain).  The ripe queue with the oldest head dispatches first so
        no model starves; when nothing is ripe yet, wait only until the
        earliest forming-batch deadline — a full batch for one model
        never sits behind another model's hold window.

        Requests whose end-to-end deadline already expired are split
        out at the pop (the deadline contract: a dead request must not
        consume a batch slot or device time) — the worker fails them
        with ``DeadlineExceededError`` without dispatching them.
        """
        max_latency_s = self.config.max_latency_us / 1e6
        with self._cond:
            while True:
                ripe, earliest = None, None
                for uid, (entry, q) in self._queues.items():
                    if not q:
                        continue
                    cap = entry.max_rows(self.config.max_batch_size)
                    deadline = q[0].t_enq + max_latency_s
                    now = time.monotonic()
                    if self._stopping or now >= deadline \
                            or sum(r.rows for r in q) >= cap \
                            or any(r.deadline.expired(now) for r in q):
                        if ripe is None or q[0].t_enq < ripe[1][0].t_enq:
                            ripe = (entry, q)
                    elif earliest is None or deadline < earliest:
                        earliest = deadline
                if ripe is None:
                    if earliest is not None:
                        # hold forming batches open for more work, then
                        # re-evaluate (new arrivals notify)
                        self._cond.wait(
                            max(0.0, earliest - time.monotonic()))
                        continue
                    if self._stopping:
                        return None
                    # idle: block until an enqueue/stop notifies (every
                    # state change that creates work calls notify_all)
                    # mxlint: disable=deadline-soundness (contract:
                    # idle park — the queues are empty, so no admitted
                    # request's deadline is burning)
                    self._cond.wait()
                    continue
                entry, q = ripe
                cap = entry.max_rows(self.config.max_batch_size)
                reqs, expired, rows = [], [], 0
                now = time.monotonic()
                while q and rows + q[0].rows <= cap:
                    r = q.popleft()
                    if r.deadline.expired(now):
                        expired.append(r)   # no slot for the dead
                        continue
                    reqs.append(r)
                    rows += r.rows
                if not q:
                    self._queues.pop(entry.uid, None)
                self._set_depth(self._depth - len(reqs) - len(expired))
                self._inflight += len(reqs)
                if expired:
                    self._stats["deadline_exceeded"] += len(expired)
                return entry, reqs, expired

    def _fail_expired(self, entry, expired):
        """Fail requests whose deadline passed before batch assembly
        (popped but never dispatched — the other half of the deadline
        contract next to the caller-side withdrawal)."""
        for r in expired:
            r.queue_span.end(error="deadline")
            if _rm._ENABLED:
                _rm.SERVING_DEADLINE_EXCEEDED.inc(model=entry.name)
            r.error = DeadlineExceededError(
                f"serving predict({entry.name!r})", r.deadline.timeout,
                "deadline expired in queue, request cancelled before "
                "batch assembly")
            r.event.set()

    def _group_deadline(self, reqs):
        """The tightest member deadline — the retry policy must not
        sleep past the first caller's budget."""
        times = [r.deadline.t for r in reqs if r.deadline.t is not None]
        return Deadline(min(times)) if times else Deadline()

    def _note_retry(self, entry, attempt, exc):
        with self._cond:
            self._stats["retries"] += 1
        if _rm._ENABLED:
            _rm.SERVING_RETRIES.inc(model=entry.name)
        _LOG.warning("serving: transient failure for %s:%s (retry "
                     "%d/%d): %s", entry.name, entry.version, attempt,
                     self.config.retry_max, exc)

    def _dispatch_group(self, entry, reqs):
        """Execute one request group with bounded transient retries;
        on persistent failure BISECT so one poisoned request fails
        alone instead of killing its coalesced batchmates.  Returns
        ``(succeeded_requests, [(failed_request, error), ...])``;
        results are assigned onto the requests, events are NOT set
        (the worker publishes outcomes after breaker accounting)."""
        group_deadline = self._group_deadline(reqs)
        try:
            results = retry_call(
                lambda: self._execute_batch(
                    entry, [r.inputs for r in reqs], group_deadline),
                retries=self.config.retry_max,
                backoff_ms=self.config.retry_backoff_ms,
                deadline=group_deadline,
                rng=self._retry_rng,
                on_retry=lambda n, e: self._note_retry(entry, n, e))
        except DeadlineExceededError as e:
            # a group-deadline expiry (wedged bucket build, or the
            # retry budget burned against the tightest member) says
            # nothing about a poisoned request — don't bisect or count
            # it as one.  Fail the members whose own budget is gone
            # and re-dispatch the rest under their looser deadlines
            # (program_for raises only after the group deadline truly
            # expired, so at least one member leaves on every pass).
            alive, gone = [], []
            for r in reqs:
                (gone if r.deadline.expired() else alive).append(r)
            gone = [(r, e) for r in gone]
            if not alive or not gone:   # no-gone: unknown raise site —
                return [], gone + [(r, e) for r in alive]  # never loop
            ok, bad = self._dispatch_group(entry, alive)
            return ok, bad + gone
        except Exception as e:      # noqa: BLE001 — isolate the poison
            if len(reqs) == 1:
                # also log it: a caller that already timed out will
                # never read req.error, and a compile failure must not
                # be diagnosable only as caller-side timeouts
                _LOG.warning("serving: request for %s:%s failed: %s",
                             entry.name, entry.version, e)
                return [], [(reqs[0], e)]
            _LOG.warning("serving: batch of %d request(s) for %s:%s "
                         "failed (%s); bisecting to isolate the "
                         "poisoned request", len(reqs), entry.name,
                         entry.version, e)
            with self._cond:
                self._stats["bisected"] += 1
            _tr.tag("bisected", len(reqs))
            mid = len(reqs) // 2
            ok_lo, bad_lo = self._dispatch_group(entry, reqs[:mid])
            ok_hi, bad_hi = self._dispatch_group(entry, reqs[mid:])
            return ok_lo + ok_hi, bad_lo + bad_hi
        with self._cond:
            self._stats["batches"] += 1
        for r, out in zip(reqs, results):
            r.result = out
        return list(reqs), []

    def _worker_loop(self):
        while True:
            batch = self._next_batch()
            if batch is None:
                return
            entry, reqs, expired = batch
            self._fail_expired(entry, expired)
            if not reqs:
                continue
            # queue-wait spans end at the pop (outside _cond — the
            # tracer lock is never taken while a serving lock is held)
            for r in reqs:
                r.queue_span.end()
            # ONE batch-assembly span shared by every coalesced
            # request: it lives in the first sampled request's trace
            # and is copied (same interval, same tags) into the others
            # after dispatch — chrome-trace has no multi-parent links,
            # so each trace gets a complete private timeline instead
            home = next((r.trace for r in reqs if r.trace is not None),
                        None)
            bspan = _tr.span("serving.batch", parent=home,
                             model=entry.name, requests=len(reqs))

            def _share_batch_span():
                # copy the (ended) shared span into the OTHER coalesced
                # traces — must run BEFORE any r.event.set(): a woken
                # caller completes its root, after which the copy would
                # be dropped as a straggler
                if bspan.sampled:
                    for r in reqs:
                        if r.trace is not None \
                                and r.trace.trace_id != bspan.trace_id:
                            _tr.record_span(
                                "serving.batch", r.trace, bspan.t0,
                                bspan.t1 or bspan.t0,
                                dict(bspan.tags or {},
                                     shared_with=bspan.trace_id))

            with bspan:
                ok, bad = self._dispatch_group(entry, reqs)
                if bad:
                    # failures no longer propagate out of the dispatch
                    # (retry/bisection contains them) — tag the shared
                    # batch span the way an escaping exception used to
                    bspan.set_tag("error", type(bad[0][1]).__name__)
                    bspan.set_tag("failed_requests", len(bad))
            _share_batch_span()           # bspan ended by the with-exit
            done = time.monotonic()
            breaker = self._breaker(entry)
            n_deadline = sum(1 for _r, e in bad
                             if isinstance(e, DeadlineExceededError))
            with self._cond:
                self._stats["completed"] += len(ok)
                self._stats["errors"] += len(bad)
                self._stats["deadline_exceeded"] += n_deadline
                self._inflight -= len(reqs)
                self._cond.notify_all()
            # publish outcomes AFTER the shared bookkeeping: breaker
            # records execute outcomes only (expired requests above
            # never reached the model and say nothing about health —
            # and neither does a deadline that expired waiting on a
            # bucket build, so those skip the breaker too)
            for r, e in bad:
                if isinstance(e, DeadlineExceededError):
                    if _rm._ENABLED:
                        _rm.SERVING_DEADLINE_EXCEEDED.inc(
                            model=entry.name)
                else:
                    breaker.record(False)
                r.error = e
                r.event.set()
            for r in ok:
                breaker.record(True)
                if _rm._ENABLED:
                    _rm.SERVING_REQUEST_SECONDS.observe(
                        done - r.t_enq, model=entry.name,
                        exemplar=None if r.trace is None
                        else r.trace.trace_id)
                r.event.set()
