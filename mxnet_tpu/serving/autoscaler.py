"""Traffic plane, part 2: SLO-driven autoscaling of a ReplicaSet
(docs/serving.md §11).

PR 13 shipped the actuators — ``ReplicaSet.add_replica`` /
``remove_replica``: prewarm-gated, drain-gated, safe under load — and
nothing drove them.  This module is the missing control loop:

- **sensors**: the signals ALREADY in :mod:`~mxnet_tpu.runtime_metrics`
  — ``serving.queue.depth``, windowed p99 of the TTFT and request
  latency histograms (bucket-count deltas per control tick, so a burst
  an hour ago cannot pin today's quantile), and the replica state map;
- **targets** (:class:`SLOTargets`): declared TTFT/latency p99 bounds
  plus a queue-depth high watermark — the contract the controller
  defends, and what :func:`traffic.summarize` scores;
- **policy** (:class:`Autoscaler`): hysteresis (N consecutive breach
  ticks before scale-up, a longer idle streak before scale-down),
  per-direction cooldowns, a max-replica budget, and a prewarm-aware
  scale-up lead — bringing a replica up takes a measured prewarm
  time, so the breach streak required before acting SHRINKS by the
  ticks that prewarm will consume (capacity must start building before
  the SLO is fully lost, not after);
- **accountability**: every decision — hold included — increments
  ``serving.autoscale.decisions{model,action}``, publishes
  ``serving.autoscale.replicas_target``, and non-hold decisions root an
  ``autoscale.decide`` trace with the sensor readings as tags; the
  last decisions ring feeds ``tools/diagnose.py``;
- **overload coupling**: each tick publishes its pressure reading into
  the :class:`~mxnet_tpu.serving.admission.AdmissionController`, so
  tier-ordered shedding reacts to the same SLO sensors that drive
  scaling;
- **chaos**: the ``autoscale.decide`` fault site fires before each
  actuation — an injected failure (e.g. a scale-up whose prewarm
  dies) must leave the loop alive, counted, and backing off.
"""
from __future__ import annotations

import math
import threading
import time
from collections import deque

from .. import engine as _engine
from .. import faults
from .. import runtime_metrics as _rm
from .. import tracing as _tr
from ..base import MXNetError, get_env
from .replica import HEALTHY

__all__ = ["SLOTargets", "AutoscalerConfig", "RuntimeMetricsSource",
           "Autoscaler"]


class SLOTargets:
    """Declared serving SLOs: p99 TTFT (generate) and p99 end-to-end
    latency (predict) in milliseconds, plus the queue-depth high
    watermark that signals saturation before latency does.  ``None``
    disables a target.  ``queue_low`` (default ``queue_high / 4``) is
    the scale-DOWN band — asymmetric on purpose, the hysteresis gap."""

    def __init__(self, ttft_p99_ms=None, latency_p99_ms=None,
                 queue_high=None, queue_low=None):
        def pick(value, env, typ=float):
            if value is None:
                value = get_env(env, typ=typ)
            return None if value is None else typ(value)

        self.ttft_p99_ms = pick(
            ttft_p99_ms, "MXNET_SERVING_AUTOSCALE_SLO_TTFT_P99_MS")
        self.latency_p99_ms = pick(
            latency_p99_ms, "MXNET_SERVING_AUTOSCALE_SLO_LATENCY_P99_MS")
        self.queue_high = pick(
            queue_high, "MXNET_SERVING_AUTOSCALE_QUEUE_HIGH", typ=int)
        if self.queue_high is not None and self.queue_high < 1:
            raise MXNetError("SLOTargets: queue_high must be >= 1")
        if queue_low is None and self.queue_high is not None:
            queue_low = max(1, self.queue_high // 4)
        self.queue_low = None if queue_low is None else int(queue_low)
        for name in ("ttft_p99_ms", "latency_p99_ms"):
            v = getattr(self, name)
            if v is not None and v <= 0:
                raise MXNetError(f"SLOTargets: {name} must be > 0")
        if self.queue_low is not None and self.queue_high is not None \
                and self.queue_low > self.queue_high:
            raise MXNetError(
                f"SLOTargets: queue_low ({self.queue_low}) above "
                f"queue_high ({self.queue_high}) — the hysteresis band "
                f"would invert")
        if self.ttft_p99_ms is None and self.latency_p99_ms is None \
                and self.queue_high is None:
            raise MXNetError(
                "SLOTargets: declare at least one target (ttft_p99_ms, "
                "latency_p99_ms, or queue_high)")

    def __repr__(self):
        return (f"SLOTargets(ttft_p99_ms={self.ttft_p99_ms}, "
                f"latency_p99_ms={self.latency_p99_ms}, "
                f"queue_high={self.queue_high}, "
                f"queue_low={self.queue_low})")


class AutoscalerConfig:
    """Control-loop policy (``MXNET_SERVING_AUTOSCALE_*`` defaults).

    - ``min_replicas`` / ``max_replicas``: the replica budget;
    - ``interval_s``: control period (the loop thread's tick);
    - ``breach_ticks``: consecutive breach ticks before scale-up
      (minus the prewarm lead, below); ``idle_ticks``: consecutive
      idle ticks before scale-down (longer — scaling down is cheap to
      delay, expensive to regret);
    - ``cooldown_up_s`` / ``cooldown_down_s``: per-direction refractory
      periods after ANY replica-count change, so one burst cannot
      staircase the fleet;
    - ``prewarm_lead_s``: initial estimate of one ``add_replica``
      prewarm (refined by an EWMA of measured prewarms).  The breach
      streak required before scaling up shrinks by
      ``prewarm / interval`` ticks — the lead time capacity needs to
      exist by the time the hysteresis window would have ended;
    - ``drain_timeout_s``: bound on a scale-down drain.
    """

    def __init__(self, min_replicas=None, max_replicas=None,
                 interval_s=None, breach_ticks=None, idle_ticks=None,
                 cooldown_up_s=None, cooldown_down_s=None,
                 prewarm_lead_s=None, drain_timeout_s=30.0,
                 scale_down_margin=0.5):
        def pick(value, env, typ=int):
            if value is None:
                value = get_env(env, typ=typ)
            return None if value is None else typ(value)

        def pick_s(value, env):
            # ctor args carry SECONDS; the env knobs are declared in
            # milliseconds, so only the env path converts
            if value is not None:
                return float(value)
            v = get_env(env, typ=float)
            return None if v is None else v / 1e3

        self.min_replicas = pick(min_replicas,
                                 "MXNET_SERVING_AUTOSCALE_MIN")
        self.max_replicas = pick(max_replicas,
                                 "MXNET_SERVING_AUTOSCALE_MAX")
        self.interval_s = pick_s(interval_s,
                                 "MXNET_SERVING_AUTOSCALE_INTERVAL_MS")
        self.breach_ticks = pick(breach_ticks,
                                 "MXNET_SERVING_AUTOSCALE_BREACH_TICKS")
        self.idle_ticks = pick(idle_ticks,
                               "MXNET_SERVING_AUTOSCALE_IDLE_TICKS")
        self.cooldown_up_s = pick_s(
            cooldown_up_s, "MXNET_SERVING_AUTOSCALE_COOLDOWN_UP_MS")
        self.cooldown_down_s = pick_s(
            cooldown_down_s, "MXNET_SERVING_AUTOSCALE_COOLDOWN_DOWN_MS")
        self.prewarm_lead_s = pick_s(
            prewarm_lead_s, "MXNET_SERVING_AUTOSCALE_PREWARM_LEAD_MS")
        self.drain_timeout_s = float(drain_timeout_s)
        self.scale_down_margin = float(scale_down_margin)
        if self.min_replicas < 1:
            raise MXNetError(
                "AutoscalerConfig: min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise MXNetError(
                f"AutoscalerConfig: max_replicas "
                f"({self.max_replicas}) below min_replicas "
                f"({self.min_replicas})")
        if self.interval_s <= 0:
            raise MXNetError(
                "AutoscalerConfig: interval must be > 0")
        if self.breach_ticks < 1 or self.idle_ticks < 1:
            raise MXNetError(
                "AutoscalerConfig: breach_ticks and idle_ticks must "
                "be >= 1")
        if self.cooldown_up_s < 0 or self.cooldown_down_s < 0 \
                or self.prewarm_lead_s < 0:
            raise MXNetError(
                "AutoscalerConfig: cooldowns and prewarm lead must "
                "be >= 0")
        if not 0.0 < self.scale_down_margin <= 1.0:
            raise MXNetError(
                "AutoscalerConfig: scale_down_margin must be in (0, 1]")

    def __repr__(self):
        return (f"AutoscalerConfig(min={self.min_replicas}, "
                f"max={self.max_replicas}, "
                f"interval_s={self.interval_s}, "
                f"breach_ticks={self.breach_ticks}, "
                f"idle_ticks={self.idle_ticks}, "
                f"cooldown_up_s={self.cooldown_up_s}, "
                f"cooldown_down_s={self.cooldown_down_s}, "
                f"prewarm_lead_s={self.prewarm_lead_s})")


def _quantile_from_counts(buckets, counts, q):
    """Prometheus-style interpolated quantile over one window's bucket
    counts (the delta between two cumulative snapshots).  NaN when the
    window saw nothing."""
    total = sum(counts)
    if total <= 0:
        return float("nan")
    rank = q * total
    cum = 0.0
    lo = 0.0
    for i, b in enumerate(buckets):
        prev = cum
        cum += counts[i]
        if cum >= rank:
            frac = 0.0 if counts[i] == 0 else (rank - prev) / counts[i]
            return lo + (b - lo) * frac
        lo = b
    return buckets[-1]


class RuntimeMetricsSource:
    """The production sensor: reads the instruments the serving stack
    already publishes.  Queue depth comes from the
    ``serving.queue.depth`` gauge (labeled by server name); TTFT and
    latency p99 are WINDOWED — each :meth:`sample` diffs the
    histograms' cumulative bucket counts against the previous sample,
    so the quantile describes the last control interval, not the
    process lifetime.  Histogram reads aggregate across the model's
    replica series: replica-path engines observe under
    ``model="name/rid"`` while a direct engine uses ``model="name"``,
    and the controller defends the SET's tail, so both are summed into
    one distribution.  Not thread-safe: owned by one control loop
    (tests substitute any object with a compatible ``sample()``)."""

    def __init__(self, server_name, model):
        self.server_name = str(server_name)
        self.model = str(model)
        self._prev = {}

    def _fleet_counts(self, hist):
        prefix = self.model + "/"
        names = [m for m in hist.label_values("model")
                 if m == self.model or m.startswith(prefix)]
        counts = [0] * (len(hist.buckets) + 1)
        for m in names:
            for i, c in enumerate(hist.bucket_counts(model=m)):
                counts[i] += c
        return counts

    def _windowed_p99(self, hist):
        counts = self._fleet_counts(hist)
        prev = self._prev.get(hist.name)
        self._prev[hist.name] = counts
        if prev is None:
            delta = counts
        else:
            delta = [c - p for c, p in zip(counts, prev)]
        return _quantile_from_counts(hist.buckets, delta, 0.99)

    def sample(self):
        return {
            "queue_depth": _rm.SERVING_QUEUE_DEPTH.value(
                server=self.server_name),
            "ttft_p99_s": self._windowed_p99(
                _rm.SERVING_DECODE_TTFT_SECONDS),
            "latency_p99_s": self._windowed_p99(
                _rm.SERVING_REQUEST_SECONDS),
        }


class Autoscaler:
    """SLO-defending replica controller over one
    :class:`~mxnet_tpu.serving.replica.ReplicaSet`.

    ``tick()`` runs one sense -> decide -> actuate cycle (tests drive
    it directly with a fake source and clock); :meth:`start` runs it on
    a daemon thread every ``config.interval_s``.  Actuation happens
    OUTSIDE the controller lock — ``add_replica`` blocks through a
    prewarm and must not freeze state readers meanwhile.

    Decision grammar (the ``action`` label of
    ``serving.autoscale.decisions``): ``up`` / ``down`` (actuated),
    ``hold`` (no change), ``blocked`` (breach sustained but the
    max-replica budget or a live cooldown refused it), ``error`` (the
    actuator raised — injected ``autoscale.decide`` chaos or a real
    prewarm failure; the loop stays alive and backs off by the up
    cooldown)."""

    def __init__(self, replica_set, slo=None, config=None, *,
                 source=None, admission=None, server_name=None,
                 clock=time.monotonic):
        self.rset = replica_set
        self.model = replica_set.name
        self.slo = slo or SLOTargets()
        self.config = config or AutoscalerConfig()
        if source is None:
            if server_name is None:
                raise MXNetError(
                    "Autoscaler: pass server_name= (the ModelServer's "
                    ".name, which labels serving.queue.depth) or an "
                    "explicit source=")
            source = RuntimeMetricsSource(server_name, self.model)
        self.source = source
        self.admission = admission
        self.clock = clock
        # engine.make_lock (not a bare threading.Lock) so the sanitizer
        # sees it in lock-order and lockset tracking
        self._lock = _engine.make_lock("serving.Autoscaler._lock")
        self._breach_streak = 0
        self._idle_streak = 0
        self._last_up = None            # clock stamps of last actuation
        self._last_change = None
        self._prewarm_s = self.config.prewarm_lead_s
        self._target = None
        self._decisions = deque(maxlen=32)
        # holds dominate a quiet loop and evict the interesting rows,
        # so actuations (up/down/blocked/error) keep their own ledger
        self._actuations = deque(maxlen=32)
        self._stats = {"ticks": 0, "up": 0, "down": 0, "hold": 0,
                       "blocked": 0, "error": 0}
        self._stop_evt = threading.Event()
        self._thread = None
        self._in_tick = False
        _engine.watch_races(self)

    # ------------------------------------------------------------- sensing
    def _pressure(self, depth, ttft_s, lat_s):
        """Worst breach ratio across declared targets, in [0, 1] —
        published to the admission controller so tier shedding tracks
        the same sensors."""
        ratios = [0.0]
        if self.slo.queue_high:
            ratios.append(depth / float(self.slo.queue_high))
        if self.slo.ttft_p99_ms and not math.isnan(ttft_s):
            ratios.append(1e3 * ttft_s / self.slo.ttft_p99_ms)
        if self.slo.latency_p99_ms and not math.isnan(lat_s):
            ratios.append(1e3 * lat_s / self.slo.latency_p99_ms)
        return min(1.0, max(ratios))

    def _breaches(self, depth, ttft_s, lat_s):
        out = []
        if self.slo.queue_high is not None \
                and depth >= self.slo.queue_high:
            out.append(f"queue depth {depth:.0f} >= "
                       f"{self.slo.queue_high}")
        if self.slo.ttft_p99_ms is not None and not math.isnan(ttft_s) \
                and 1e3 * ttft_s > self.slo.ttft_p99_ms:
            out.append(f"ttft p99 {1e3 * ttft_s:.1f}ms > "
                       f"{self.slo.ttft_p99_ms}ms")
        if self.slo.latency_p99_ms is not None \
                and not math.isnan(lat_s) \
                and 1e3 * lat_s > self.slo.latency_p99_ms:
            out.append(f"latency p99 {1e3 * lat_s:.1f}ms > "
                       f"{self.slo.latency_p99_ms}ms")
        return out

    def _is_idle(self, depth, ttft_s, lat_s):
        m = self.config.scale_down_margin
        if self.slo.queue_low is not None and depth > self.slo.queue_low:
            return False
        if self.slo.ttft_p99_ms is not None and not math.isnan(ttft_s) \
                and 1e3 * ttft_s > m * self.slo.ttft_p99_ms:
            return False
        if self.slo.latency_p99_ms is not None \
                and not math.isnan(lat_s) \
                and 1e3 * lat_s > m * self.slo.latency_p99_ms:
            return False
        return True

    # ------------------------------------------------------------ deciding
    def tick(self, now=None):
        """One control cycle; returns the decision record (or None when
        another tick is already in flight)."""
        now = self.clock() if now is None else now
        with self._lock:
            if self._in_tick:
                return None
            self._in_tick = True
        try:
            return self._tick_locked_out(now)
        finally:
            with self._lock:
                self._in_tick = False

    def _tick_locked_out(self, now):
        cfg = self.config
        sample = self.source.sample()

        def _f(key, default):
            v = sample.get(key, default)
            return default if v is None else float(v)

        depth = _f("queue_depth", 0.0)
        ttft_s = _f("ttft_p99_s", float("nan"))
        lat_s = _f("latency_p99_s", float("nan"))
        states = self.rset.replicas()
        total = len(states)
        healthy = sum(1 for s in states.values() if s == HEALTHY)
        breaches = self._breaches(depth, ttft_s, lat_s)
        idle = not breaches and self._is_idle(depth, ttft_s, lat_s)
        pressure = self._pressure(depth, ttft_s, lat_s)
        if self.admission is not None:
            self.admission.update_pressure(pressure, now=now)

        with self._lock:
            self._stats["ticks"] += 1
            self._breach_streak = self._breach_streak + 1 if breaches \
                else 0
            self._idle_streak = self._idle_streak + 1 if idle else 0
            breach_streak, idle_streak = self._breach_streak, \
                self._idle_streak
            # prewarm-aware lead: the ticks a prewarm will consume are
            # ticks the hysteresis window cannot afford to wait
            lead_ticks = int(math.ceil(
                self._prewarm_s / cfg.interval_s)) \
                if self._prewarm_s > 0 else 0
            need_ticks = max(1, cfg.breach_ticks - lead_ticks)
            in_up_cd = self._last_up is not None \
                and now - self._last_up < cfg.cooldown_up_s
            in_down_cd = self._last_change is not None \
                and now - self._last_change < cfg.cooldown_down_s

        action, reason = "hold", "within SLO band"
        if breaches:
            reason = "; ".join(breaches) \
                + f" (streak {breach_streak}/{need_ticks})"
            if breach_streak >= need_ticks:
                if total >= cfg.max_replicas:
                    action = "blocked"
                    reason += (f"; at max-replica budget "
                               f"({cfg.max_replicas})")
                elif in_up_cd:
                    action = "blocked"
                    reason += "; in scale-up cooldown"
                else:
                    action = "up"
        elif idle and idle_streak >= cfg.idle_ticks \
                and total > cfg.min_replicas:
            if in_down_cd:
                action = "blocked"
                reason = (f"idle streak {idle_streak} but in "
                          f"scale-down cooldown")
            else:
                action = "down"
                reason = (f"idle {idle_streak} ticks (queue "
                          f"{depth:.0f}, margin "
                          f"{cfg.scale_down_margin})")

        target = total
        error = None
        if action == "up":
            target = total + 1
            try:
                faults.inject("autoscale.decide")
                t0 = time.monotonic()
                rid = self.rset.add_replica()
                prewarm_s = time.monotonic() - t0
                with self._lock:
                    self._prewarm_s = prewarm_s \
                        if self._prewarm_s == 0 \
                        else 0.5 * self._prewarm_s + 0.5 * prewarm_s
                reason = (f"added {rid} (prewarm {prewarm_s:.3f}s): "
                          f"{reason}")
            except MXNetError as e:
                action, error = "error", e
                target = total
                reason = f"scale-up failed: {e}"
            stamp_up = True
        elif action == "down":
            target = total - 1
            victim = self._pick_victim(states)
            try:
                faults.inject("autoscale.decide")
                if victim is None:
                    raise MXNetError(
                        f"Autoscaler({self.model}): no healthy replica "
                        f"to drain (states {states})")
                self.rset.remove_replica(
                    victim, timeout=cfg.drain_timeout_s)
                reason = f"drained {victim}: {reason}"
            except MXNetError as e:
                action, error = "error", e
                target = total
                reason = f"scale-down failed: {e}"
            stamp_up = False
        else:
            stamp_up = None

        with self._lock:
            if action in ("up", "down") or error is not None:
                # an error backs off like the actuation it failed —
                # a dead actuator must not be hammered every tick
                self._last_change = now
                if stamp_up or error is not None:
                    self._last_up = now
                self._breach_streak = 0
                self._idle_streak = 0
            self._target = target
            self._stats[action] += 1
            record = {"t": now, "action": action, "reason": reason,
                      "replicas": total, "healthy": healthy,
                      "target": target, "queue_depth": depth,
                      "ttft_p99_s": None if math.isnan(ttft_s)
                      else round(ttft_s, 6),
                      "latency_p99_s": None if math.isnan(lat_s)
                      else round(lat_s, 6),
                      "pressure": round(pressure, 4)}
            self._decisions.append(record)
            if action != "hold":
                self._actuations.append(record)

        if _rm._ENABLED:
            _rm.SERVING_AUTOSCALE_DECISIONS.inc(
                model=self.model, action=action)
            _rm.SERVING_AUTOSCALE_REPLICAS_TARGET.set(
                target, model=self.model)
        if action != "hold":
            with _tr.trace("autoscale.decide", model=self.model,
                           action=action) as root:
                root.set_tag("reason", reason)
                root.set_tag("replicas", total)
                root.set_tag("target", target)
                root.set_tag("queue_depth", depth)
                root.set_tag("pressure", round(pressure, 4))
        return record

    def _pick_victim(self, states):
        """Healthy replica with the least in-flight work (ties: the
        newest rid) — the cheapest drain."""
        healthy = [rid for rid, s in states.items() if s == HEALTHY]
        if len(healthy) < 2:
            return None
        per = self.rset.stats()["replicas"]
        return min(healthy,
                   key=lambda r: (per.get(r, {}).get("inflight", 0),
                                  -_rid_ord(r)))

    # ------------------------------------------------------------ lifecycle
    def start(self):
        """Run the control loop on a daemon thread every
        ``config.interval_s`` until :meth:`stop`."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop_evt.clear()
            self._thread = _engine.make_thread(
                self._loop, name=f"mxnet-autoscale-{self.model}",
                owner=f"Autoscaler({self.model})")
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop_evt.wait(self.config.interval_s):
            try:
                self.tick()
            except MXNetError:
                # tick() already demoted actuator failures to counted
                # "error" decisions; anything landing here is a sensor
                # failure — the loop must outlive it
                continue

    def stop(self, timeout=5.0):
        self._stop_evt.set()
        with self._lock:
            th = self._thread
            self._thread = None
        if th is not None:
            th.join(timeout)
        return True

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # ------------------------------------------------------------- state
    def target(self):
        with self._lock:
            return self._target

    def last_decisions(self, n=8):
        with self._lock:
            return list(self._decisions)[-n:]

    def last_actuations(self, n=8):
        """The most recent NON-hold decisions (up/down/blocked/error)
        — survives long quiet stretches that evict them from
        :meth:`last_decisions`."""
        with self._lock:
            return list(self._actuations)[-n:]

    def stats(self):
        with self._lock:
            out = dict(self._stats)
            out["prewarm_estimate_s"] = round(self._prewarm_s, 6)
            out["target"] = self._target
            out["breach_streak"] = self._breach_streak
            out["idle_streak"] = self._idle_streak
        return out

    def debug_state(self):
        state = self.stats()
        state.update(model=self.model, slo=repr(self.slo),
                     config=repr(self.config),
                     replicas=self.rset.replicas(),
                     decisions=self.last_decisions(8),
                     actuations=self.last_actuations(8))
        if self.admission is not None:
            state["admission_pressure"] = self.admission.pressure()
        return state

    def __repr__(self):
        return (f"Autoscaler({self.model}, {self.slo}, "
                f"replicas={self.rset.replicas()})")


def _rid_ord(rid):
    """Numeric suffix of a replica id ('r2' -> 2) for tie-breaks."""
    digits = "".join(ch for ch in str(rid) if ch.isdigit())
    return int(digits) if digits else 0
