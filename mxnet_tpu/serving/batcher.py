"""Shape-bucketed dynamic batching (docs/serving.md §3).

Concurrent ``predict()`` calls of ragged batch sizes coalesce into one
dispatched batch per model: request rows are concatenated along axis 0
and padded up to the next power-of-two **bucket**, so any mix of N
request shapes reaches the compiler as at most ``ceil(log2(max)) + 1``
distinct program shapes (the Ragged-Paged-Attention / TPU-serving
insight that compiled-program reuse, not the kernel, is where the win
lives — PAPERS.md).  Each bucket's program is built once and cached;
``serving.bucket.cache{event=mem_hit|disk_hit|miss}`` counts lookups.
The invariant: **misses == freshly COMPILED programs** — a disk_hit is
an executable deserialized from the persistent compile cache
(``mxnet_tpu.compile_cache``), so the in-memory program count equals
misses + disk hits, and a warm-cache server restart shows zero misses.

Outputs must be batch-major (axis 0 = rows, the manifest contract);
padded rows are sliced off and per-request slices handed back, so a
ragged final batch un-pads exactly.
"""
from __future__ import annotations

import threading

import numpy as np

from .. import engine, faults as _faults, runtime_metrics as _rm, \
    tracing as _tr
from ..base import MXNetError
from .resilience import DeadlineExceededError

__all__ = ["DynamicBatcher", "next_bucket", "bucket_set", "pad_batch",
           "unpad_outputs"]


def next_bucket(rows, max_batch):
    """Smallest power of two >= rows, capped at max_batch (the cap
    itself is the last bucket even when it is not a power of two), so
    the bucket set is {1, 2, 4, ..., max_batch}."""
    if rows < 1:
        raise MXNetError(f"next_bucket: rows must be >= 1, got {rows}")
    if rows >= max_batch:
        return max_batch
    b = 1
    while b < rows:
        b <<= 1
    return min(b, max_batch)


def bucket_set(max_batch):
    """Every bucket :func:`next_bucket` can produce for ``max_batch``,
    ascending — the ONE definition of the bucket policy shared by
    prewarm (all-buckets warm-up) and ``export_stablehlo(precompile=)``
    (shipped executables), so neither can drift from what serving
    actually dispatches."""
    buckets, b = [], 1
    while b < max_batch:
        buckets.append(b)
        b <<= 1
    buckets.append(max_batch)       # the cap is always the last bucket
    return buckets


def pad_batch(request_inputs, bucket_rows):
    """Concatenate per-request input tuples along axis 0 and zero-pad to
    ``bucket_rows``.

    ``request_inputs``: list of tuples of numpy arrays (one tuple per
    request, batch-major).  Returns ``(padded_inputs, offsets)`` where
    ``offsets[i]`` is the row offset of request i (``offsets[-1]`` is
    the real row total).
    """
    n_in = len(request_inputs[0])
    offsets = [0]
    for req in request_inputs:
        offsets.append(offsets[-1] + req[0].shape[0])
    total = offsets[-1]
    if total > bucket_rows:
        raise MXNetError(
            f"pad_batch: {total} rows exceed bucket of {bucket_rows}")
    padded = []
    for pos in range(n_in):
        parts = [req[pos] for req in request_inputs]
        cat = parts[0] if len(parts) == 1 else np.concatenate(parts, 0)
        if total < bucket_rows:
            pad = np.zeros((bucket_rows - total,) + cat.shape[1:],
                           dtype=cat.dtype)
            cat = np.concatenate([cat, pad], 0)
        padded.append(cat)
    return tuple(padded), offsets


def unpad_outputs(outputs, offsets):
    """Split batch-major outputs back into per-request tuples, dropping
    padding rows (everything past ``offsets[-1]``)."""
    total = offsets[-1]
    # ONE device-to-host transfer per output, not one per request
    host = []
    for out in outputs:
        arr = np.asarray(out)
        if arr.ndim < 1 or arr.shape[0] < total:
            raise MXNetError(
                f"serving outputs must be batch-major: output of "
                f"shape {arr.shape} cannot be split across "
                f"{total} request rows")
        host.append(arr)
    return [tuple(arr[offsets[i]:offsets[i + 1]] for arr in host)
            for i in range(len(offsets) - 1)]


class DynamicBatcher:
    """Executes coalesced batches through a per-(entry, bucket) program
    cache.  Stateless with respect to queuing — the ModelServer worker
    pool decides *what* to coalesce; this decides *how* it runs."""

    def __init__(self, config, device=None):
        self.config = config
        # replica placement (docs/serving.md §10): when set, programs
        # build AND execute under jax.default_device(device) so each
        # replica's batcher lands on its own device group; None (the
        # default, and the whole non-replica path) changes nothing
        self.device = device
        self._lock = engine.make_lock("serving.DynamicBatcher._lock")
        self._progs = {}            # (entry.uid, bucket) -> callable
        self._building = {}         # key -> Event (in-flight builds)
        self._retired = set()       # uids evicted; never re-cache these
        self.bucket_hits = 0        # in-memory program reused
        self.bucket_disk_hits = 0   # deserialized from the compile cache
        self.bucket_misses = 0      # freshly compiled

    def _placed(self):
        """Context placing builds/executes on this batcher's device
        (no-op without one — fakes and the single-replica path never
        import jax here)."""
        import contextlib
        if self.device is None:
            return contextlib.nullcontext()
        import jax
        return jax.default_device(self.device)

    # ------------------------------------------------------------- cache
    def program_for(self, entry, bucket_rows, deadline=None):
        """The cached program for one (entry, bucket) — built (compiled
        or deserialized from the persistent compile cache) on first
        lookup.  The build runs OUTSIDE the batcher lock: an XLA
        compile can take seconds, and holding the lock through it would
        stall every other model's mem-hit lookups.  Concurrent lookups
        of the SAME key wait on the builder instead of compiling twice,
        so misses stay == compiled programs.

        ``deadline`` (a :class:`~.resilience.Deadline`) bounds the
        builder wait: a wedged builder (the ``serving.compile`` stall
        fault) must surface as ``DeadlineExceededError`` within the
        request's budget, not hang the worker forever — the §8
        no-silent-hangs contract.  Deadline-less callers (prewarm,
        tests) keep the unbounded wait."""
        key = (entry.uid, bucket_rows)
        while True:
            with self._lock:
                prog = self._progs.get(key)
                if prog is not None:
                    self.bucket_hits += 1
                    if _rm._ENABLED:
                        _rm.SERVING_BUCKET_CACHE.inc(event="mem_hit")
                    _tr.tag("bucket_outcome", "mem_hit")
                    return prog
                pending = self._building.get(key)
                if pending is None:
                    self._building[key] = threading.Event()
                    break               # this thread builds
            # builder done (or failed): recheck.  wait(None) is the
            # unbounded legacy wait for deadline-less callers.
            remaining = None if deadline is None else deadline.remaining()
            if not pending.wait(remaining) and deadline is not None \
                    and deadline.expired():
                raise DeadlineExceededError(
                    f"serving program build ({entry.name!r}, bucket "
                    f"{bucket_rows})", deadline.timeout,
                    "another thread's bucket build did not complete "
                    "within the request deadline")
        try:
            # chaos site: a transient compile/build failure — the
            # worker-level retry policy re-enters program_for, and the
            # waiter-wake contract below hands the build to a retrier
            _faults.inject("serving.compile")
            with self._placed():
                prog = entry.make_program(bucket_rows)
        except BaseException:
            # wake waiters so one of them retries as the next builder
            with self._lock:
                self._building.pop(key).set()
            raise
        with self._lock:
            # three-way label: a program deserialized from the
            # persistent compile cache (entry.make_program marks it) is
            # a disk_hit, not a miss — misses stay == compiled programs
            if getattr(prog, "_mx_from_disk_cache", False):
                self.bucket_disk_hits += 1
                event = "disk_hit"
            else:
                self.bucket_misses += 1
                event = "miss"
            if _rm._ENABLED:
                _rm.SERVING_BUCKET_CACHE.inc(event=event)
            _tr.tag("bucket_outcome", event)
            # a batch admitted before unload can dispatch after evict():
            # run it, but never re-cache under a retired uid (no future
            # unload event would ever clear it again)
            if entry.uid not in self._retired:
                self._progs[key] = prog
            self._building.pop(key).set()
        return prog

    def programs(self, entry=None):
        """Cached program count (per entry, or total)."""
        with self._lock:
            if entry is None:
                return len(self._progs)
            return sum(1 for uid, _ in self._progs if uid == entry.uid)

    def evict(self, entry):
        """Drop cached programs of an unloaded entry and bar the uid
        from re-caching (in-flight batches may still dispatch it once).
        """
        with self._lock:
            self._retired.add(entry.uid)
            for key in [k for k in self._progs if k[0] == entry.uid]:
                del self._progs[key]

    # ---------------------------------------------------------- dispatch
    def bucket_for(self, entry, rows):
        if entry.dynamic_batch:
            return next_bucket(rows, self.config.max_batch_size)
        # static artifact: every dispatch pads to the exported batch
        if entry.fixed_batch is None:
            raise MXNetError(
                f"model {entry.name!r}: static signature without a "
                f"batch dimension cannot be batch-served")
        return entry.fixed_batch

    def run_batch(self, entry, request_inputs, deadline=None):
        """Pad, execute, sync, un-pad one coalesced batch.  Returns the
        list of per-request output tuples.  ``deadline`` bounds the
        bucket-program build wait (see :meth:`program_for`)."""
        rows = sum(req[0].shape[0] for req in request_inputs)
        bucket = self.bucket_for(entry, rows)
        # annotate whatever span the dispatching worker entered (the
        # shared batch-assembly span) — no handle threading needed
        _tr.tag("bucket", bucket)
        _tr.tag("rows", rows)
        padded, offsets = pad_batch(request_inputs, bucket)
        prog = self.program_for(entry, bucket, deadline=deadline)
        with _tr.span("serving.execute", bucket=bucket, rows=rows):
            # chaos site: device-execute fail/delay/stall — what the
            # serving retry + bisection + deadline machinery absorbs
            _faults.inject("serving.execute")
            with self._placed():
                outs = prog(*padded)
            # bounded sync point: block on THIS batch (async errors
            # surface here, engine rethrow-at-sync-point contract)
            engine.sync_outputs(outs, site="serving")
        if _rm._ENABLED:
            _rm.SERVING_BATCHES.inc(model=entry.name)
            _rm.SERVING_BATCH_OCCUPANCY.observe(rows / bucket)
        return unpad_outputs(outs, offsets)
