"""Serving policy knobs (docs/serving.md).

Defaults come from the ``MXNET_SERVING_*`` environment variables
(declared in ``base.py``, documented in ``docs/env_vars.md``);
constructor arguments override per server.
"""
from __future__ import annotations

from ..base import MXNetError, get_env

__all__ = ["ServingConfig"]


class ServingConfig:
    """Batching + backpressure policy for one :class:`ModelServer`.

    - ``max_batch_size``: row cap per coalesced batch; shape buckets are
      powers of two up to it, so at most ``ceil(log2(max_batch))+1``
      programs compile per model signature.
    - ``max_latency_us``: how long the batcher holds the first request
      of a forming batch waiting for more work (the latency half of the
      batching policy).
    - two-level backpressure: ``shed_watermark`` (<= queue_depth,
      default equal to it) bounds the WAITING queue — at/above it
      admission sheds with ``ServerOverloadedError(retry_after_ms)``;
      ``queue_depth`` additionally bounds total outstanding work
      (queued + dispatched-but-unfinished), so a slow model cannot
      pile up unbounded in-flight batches.
    - ``num_workers``: dispatch threads forming and executing batches.

    Decode-engine knobs (autoregressive ``generate()``, docs/serving.md
    §6): ``decode_page_size`` tokens per KV page,
    ``decode_pool_pages`` total preallocated pages (incl. the null
    page), ``decode_max_batch`` sequence slots in the fixed-shape
    decode step, ``decode_max_new_tokens`` default generation cap.

    Decode optimizations (docs/serving.md §9): ``prefix_cache``
    enables copy-on-write KV page sharing (a prompt whose prefix is
    cached skips that prefill) with ``prefix_cache_pages`` capping
    cache-held pages (0 = bounded by the pool alone); ``spec_k`` > 0
    enables speculative decoding — a draft model proposes up to k
    tokens per sequence, the target verifies them in one call —
    with ``spec_draft`` naming the repository entry whose decode
    model serves as the default draft.

    Replica knobs (docs/serving.md §10): ``replicas`` > 1 serves each
    model version through a :class:`~mxnet_tpu.serving.replica.
    ReplicaSet` — N data-parallel replicas on disjoint device groups,
    least-loaded routing among HEALTHY replicas, failover under the
    original deadline, prewarm-gated rolling recovery.  Health policy:
    ``replica_heartbeat_ms`` beat interval,
    ``replica_heartbeat_window_ms`` staleness bound past which a
    replica is unroutable, ``replica_failure_threshold`` consecutive
    typed failures that trip its breaker without filling the windowed
    error rate.

    Admission knobs (docs/serving.md §11): ``tenant_tiers`` spec
    string ('name=priority[/quota_rps[/burst]]', comma-separated)
    enables the per-tenant admission gate — quota token buckets plus
    priority shedding under overload, lowest tier first starting at
    pressure ``admission_shed_start``.  None (default) disables it.

    Resilience knobs (docs/serving.md §8): ``deadline_default``
    seconds applied when a call passes no timeout (None = unbounded),
    ``retry_max`` transient-failure re-executions with
    ``retry_backoff_ms`` jittered exponential backoff, and the
    per-model-version circuit breaker (``circuit_window`` sliding
    outcomes, trip at ``circuit_threshold`` error rate, shed for
    ``circuit_cooldown_ms`` before the half-open probe;
    ``circuit_window=0`` disables).
    """

    def __init__(self, max_batch_size=None, max_latency_us=None,
                 queue_depth=None, shed_watermark=None, num_workers=None,
                 retry_after_ms=None, decode_page_size=None,
                 decode_pool_pages=None, decode_max_batch=None,
                 decode_max_new_tokens=None, deadline_default=None,
                 retry_max=None, retry_backoff_ms=None,
                 circuit_window=None, circuit_threshold=None,
                 circuit_cooldown_ms=None, prefix_cache=None,
                 prefix_cache_pages=None, spec_k=None, spec_draft=None,
                 replicas=None, replica_heartbeat_ms=None,
                 replica_heartbeat_window_ms=None,
                 replica_failure_threshold=None, tenant_tiers=None,
                 admission_shed_start=None):
        def pick(value, env, typ=int):
            if value is None:
                value = get_env(env, typ=typ)
            return None if value is None else typ(value)

        self.max_batch_size = pick(max_batch_size,
                                   "MXNET_SERVING_MAX_BATCH")
        self.max_latency_us = pick(max_latency_us,
                                   "MXNET_SERVING_MAX_LATENCY_US")
        self.queue_depth = pick(queue_depth, "MXNET_SERVING_QUEUE_DEPTH")
        self.shed_watermark = pick(shed_watermark,
                                   "MXNET_SERVING_SHED_WATERMARK")
        if self.shed_watermark is None:
            self.shed_watermark = self.queue_depth
        self.num_workers = pick(num_workers, "MXNET_SERVING_WORKERS")
        self.retry_after_ms = pick(retry_after_ms,
                                   "MXNET_SERVING_RETRY_AFTER_MS")
        self.decode_page_size = pick(decode_page_size,
                                     "MXNET_SERVING_DECODE_PAGE_SIZE")
        self.decode_pool_pages = pick(decode_pool_pages,
                                      "MXNET_SERVING_DECODE_POOL_PAGES")
        self.decode_max_batch = pick(decode_max_batch,
                                     "MXNET_SERVING_DECODE_MAX_BATCH")
        self.decode_max_new_tokens = pick(
            decode_max_new_tokens, "MXNET_SERVING_DECODE_MAX_NEW_TOKENS")
        # decode optimizations (docs/serving.md §9)
        self.prefix_cache = bool(pick(prefix_cache,
                                      "MXNET_SERVING_PREFIX_CACHE"))
        self.prefix_cache_pages = pick(prefix_cache_pages,
                                       "MXNET_SERVING_PREFIX_CACHE_PAGES")
        self.spec_k = pick(spec_k, "MXNET_SERVING_SPEC_K")
        self.spec_draft = spec_draft if spec_draft is not None \
            else get_env("MXNET_SERVING_SPEC_DRAFT", typ=str)
        # resilience policy (docs/serving.md §8)
        self.deadline_default = pick(deadline_default,
                                     "MXNET_SERVING_DEADLINE_DEFAULT",
                                     typ=float)
        self.retry_max = pick(retry_max, "MXNET_SERVING_RETRY_MAX")
        self.retry_backoff_ms = pick(retry_backoff_ms,
                                     "MXNET_SERVING_RETRY_BACKOFF_MS",
                                     typ=float)
        self.circuit_window = pick(circuit_window,
                                   "MXNET_SERVING_CIRCUIT_WINDOW")
        self.circuit_threshold = pick(circuit_threshold,
                                      "MXNET_SERVING_CIRCUIT_THRESHOLD",
                                      typ=float)
        self.circuit_cooldown_ms = pick(
            circuit_cooldown_ms, "MXNET_SERVING_CIRCUIT_COOLDOWN_MS",
            typ=float)
        # replica layer (docs/serving.md §10)
        self.replicas = pick(replicas, "MXNET_SERVING_REPLICAS")
        self.replica_heartbeat_ms = pick(
            replica_heartbeat_ms, "MXNET_SERVING_REPLICA_HEARTBEAT_MS",
            typ=float)
        self.replica_heartbeat_window_ms = pick(
            replica_heartbeat_window_ms,
            "MXNET_SERVING_REPLICA_HEARTBEAT_WINDOW_MS", typ=float)
        self.replica_failure_threshold = pick(
            replica_failure_threshold,
            "MXNET_SERVING_REPLICA_FAILURE_THRESHOLD")
        # tiered admission (docs/serving.md §11)
        self.tenant_tiers = tenant_tiers if tenant_tiers is not None \
            else get_env("MXNET_SERVING_TENANT_TIERS", typ=str)
        self.admission_shed_start = pick(
            admission_shed_start, "MXNET_SERVING_ADMISSION_SHED_START",
            typ=float)

        if self.max_batch_size < 1:
            raise MXNetError("ServingConfig: max_batch_size must be >= 1")
        if self.queue_depth < 1:
            raise MXNetError("ServingConfig: queue_depth must be >= 1")
        if not 1 <= self.shed_watermark <= self.queue_depth:
            raise MXNetError(
                f"ServingConfig: shed_watermark must be in "
                f"[1, queue_depth={self.queue_depth}], "
                f"got {self.shed_watermark}")
        if self.num_workers < 1:
            raise MXNetError("ServingConfig: num_workers must be >= 1")
        if self.max_latency_us < 0:
            raise MXNetError(
                "ServingConfig: max_latency_us must be >= 0")
        if self.retry_after_ms < 0:
            raise MXNetError(
                "ServingConfig: retry_after_ms must be >= 0")
        if self.decode_page_size < 1:
            raise MXNetError(
                "ServingConfig: decode_page_size must be >= 1")
        if self.decode_pool_pages < 2:
            raise MXNetError(
                "ServingConfig: decode_pool_pages must be >= 2 (page 0 "
                "is the reserved null page)")
        if self.decode_max_batch < 1:
            raise MXNetError(
                "ServingConfig: decode_max_batch must be >= 1")
        if self.decode_max_new_tokens < 1:
            raise MXNetError(
                "ServingConfig: decode_max_new_tokens must be >= 1")
        if self.prefix_cache_pages < 0:
            raise MXNetError(
                "ServingConfig: prefix_cache_pages must be >= 0 "
                "(0 = bounded by the KV pool alone)")
        if self.spec_k < 0:
            raise MXNetError(
                "ServingConfig: spec_k must be >= 0 (0 disables "
                "speculative decoding)")
        if self.deadline_default is not None \
                and self.deadline_default <= 0:
            raise MXNetError(
                "ServingConfig: deadline_default must be > 0 seconds "
                "(or None for no deadline)")
        if self.retry_max < 0:
            raise MXNetError("ServingConfig: retry_max must be >= 0")
        if self.retry_backoff_ms < 0:
            raise MXNetError(
                "ServingConfig: retry_backoff_ms must be >= 0")
        if self.circuit_window < 0:
            raise MXNetError(
                "ServingConfig: circuit_window must be >= 0 "
                "(0 disables the breaker)")
        if not 0.0 < self.circuit_threshold <= 1.0:
            raise MXNetError(
                "ServingConfig: circuit_threshold must be in (0, 1]")
        if self.circuit_cooldown_ms < 0:
            raise MXNetError(
                "ServingConfig: circuit_cooldown_ms must be >= 0")
        if self.replicas < 1:
            raise MXNetError("ServingConfig: replicas must be >= 1")
        if self.replica_heartbeat_ms <= 0:
            raise MXNetError(
                "ServingConfig: replica_heartbeat_ms must be > 0")
        if self.replica_heartbeat_window_ms <= self.replica_heartbeat_ms:
            raise MXNetError(
                f"ServingConfig: replica_heartbeat_window_ms "
                f"({self.replica_heartbeat_window_ms}) must exceed the "
                f"beat interval ({self.replica_heartbeat_ms}) — a "
                f"window under one beat marks every replica dead")
        if self.replica_failure_threshold < 0:
            raise MXNetError(
                "ServingConfig: replica_failure_threshold must be >= 0 "
                "(0 = windowed error rate only)")
        if not 0.0 <= self.admission_shed_start <= 1.0:
            raise MXNetError(
                "ServingConfig: admission_shed_start must be in [0, 1]")

    def __repr__(self):
        return (f"ServingConfig(max_batch_size={self.max_batch_size}, "
                f"max_latency_us={self.max_latency_us}, "
                f"queue_depth={self.queue_depth}, "
                f"shed_watermark={self.shed_watermark}, "
                f"num_workers={self.num_workers}, "
                f"retry_after_ms={self.retry_after_ms}, "
                f"decode_page_size={self.decode_page_size}, "
                f"decode_pool_pages={self.decode_pool_pages}, "
                f"decode_max_batch={self.decode_max_batch}, "
                f"decode_max_new_tokens={self.decode_max_new_tokens}, "
                f"prefix_cache={self.prefix_cache}, "
                f"prefix_cache_pages={self.prefix_cache_pages}, "
                f"spec_k={self.spec_k}, "
                f"spec_draft={self.spec_draft!r}, "
                f"deadline_default={self.deadline_default}, "
                f"retry_max={self.retry_max}, "
                f"retry_backoff_ms={self.retry_backoff_ms}, "
                f"circuit_window={self.circuit_window}, "
                f"circuit_threshold={self.circuit_threshold}, "
                f"circuit_cooldown_ms={self.circuit_cooldown_ms}, "
                f"replicas={self.replicas}, "
                f"replica_heartbeat_ms={self.replica_heartbeat_ms}, "
                f"replica_heartbeat_window_ms="
                f"{self.replica_heartbeat_window_ms}, "
                f"replica_failure_threshold="
                f"{self.replica_failure_threshold}, "
                f"tenant_tiers={self.tenant_tiers!r}, "
                f"admission_shed_start={self.admission_shed_start})")
