"""Multi-replica serving on the device mesh (docs/serving.md §10).

One model version, N replicas: a :class:`ReplicaSet` places N
data-parallel copies of a (possibly tensor-sharded) model on disjoint
device groups of the mesh (``parallel.placement.replica_groups``) and
routes each request to the least-loaded HEALTHY replica.  The replica
is the unit of throughput *and* availability — the production shape
"TensorFlow: A system for large-scale machine learning" (PAPERS.md)
motivates: replicate across groups, shard within one — and a replica
layer is only worth having if a dead replica degrades goodput instead
of correctness, so the failure machinery ships inside this module,
not around it:

- **Per-replica execution state.**  A predict replica owns its own
  :class:`~mxnet_tpu.serving.batcher.DynamicBatcher` (per-replica
  program cache, pinned to the replica's device); a decode replica
  owns its own :class:`~mxnet_tpu.serving.decode.DecodeEngine` with a
  private KV pool.  Programs still deduplicate through the persistent
  compile cache — replica K compiles nothing the content-addressed
  AOT store already holds, so replica count never multiplies cold
  compiles beyond the one miss that populates the store.
- **Health.**  Each replica runs a heartbeat thread (interval
  ``replica_heartbeat_ms``); every beat also sweeps the set, so a
  stalled sibling is detected within one beat even with zero traffic.
  A heartbeat older than ``replica_heartbeat_window_ms`` or
  ``replica_failure_threshold`` consecutive typed execute failures
  (the per-replica :class:`~mxnet_tpu.serving.resilience.
  CircuitBreaker`'s fast trip rule) marks the replica UNHEALTHY —
  unroutable, shedding its load onto siblings.
- **Failover.**  A retryable failure on one replica re-dispatches to
  a sibling under the request's ORIGINAL end-to-end deadline; since
  every replica runs the same program on the same inputs, the result
  is byte-identical either way (asserted by the chaos smoke against a
  fault-free single-replica twin).  Decode sequences on a dead
  replica are quarantined leak-free by the engine's §8 path and
  re-admitted here as FRESH requests on a sibling while the retry
  budget and deadline allow.
- **Rolling recovery.**  A rejoining replica (heartbeats resumed, or
  an explicit :meth:`ReplicaSet.restart` / :meth:`add_replica`) must
  re-pass **prewarm** — every shape bucket built and executed once —
  before it becomes routable, the same admission gate hot-swap uses,
  so replica add/remove/rejoin under load never serves a cold
  program.  :meth:`remove_replica` drains (unroutable, in-flight
  finishes) before stopping.

Chaos sites (``MXNET_FAULTS``): ``replica.<rid>.execute`` (dispatch),
``replica.<rid>.heartbeat`` (beat loop — ``stall`` is the dead-worker
shape), and ``replica.<rid>.decode.{prefill,step,verify,
prefix_lookup}`` (the engine's §8 sites, replica-scoped), so the
whole ladder — kill -> detect -> reroute -> recover -> rejoin — runs
deterministically in CI (``bench_serving.py --replicas N --faults``).
Observability: ``serving.replica.{state,requests,failovers,
heartbeat_age}`` metrics plus a ``replica=<rid>`` tag on every
dispatched request's span.
"""
from __future__ import annotations

import itertools
import logging
import threading
import time
from collections import OrderedDict

import numpy as np

from .. import engine as _engine, faults as _faults, \
    runtime_metrics as _rm, tracing as _tr
from ..base import MXNetError
from .batcher import DynamicBatcher
from .repository import prewarm_buckets, synth_inputs
from .resilience import (CircuitBreaker, Deadline,
                         DeadlineExceededError, ServerOverloadedError,
                         is_transient)

__all__ = ["Replica", "ReplicaSet", "STARTING", "PREWARMING", "HEALTHY",
           "UNHEALTHY", "DRAINING", "STOPPED"]

_LOG = logging.getLogger("mxnet_tpu")

# generate(_trace_ctx=...) default: "no caller decision" — mapped to
# the decode engine's own _AMBIENT sentinel at submit
_UNSET = object()

# replica lifecycle states (gauge codes in serving.replica.state)
STARTING, PREWARMING, HEALTHY = "starting", "prewarming", "healthy"
UNHEALTHY, DRAINING, STOPPED = "unhealthy", "draining", "stopped"
_STATE_CODE = {STARTING: 0, PREWARMING: 1, HEALTHY: 2, UNHEALTHY: 3,
               DRAINING: 4, STOPPED: 5}


class Replica:
    """One replica's identity + execution resources.

    Pure data holder for scheduling purposes: every mutable scheduling
    field (``state``, ``inflight``, ``last_beat``, counters) is guarded
    by the owning :class:`ReplicaSet`'s condition — the replica itself
    takes no lock, so there is exactly one lock order through the set.
    """

    __slots__ = ("rid", "entry", "device", "state", "unhealthy_reason",
                 "inflight", "last_beat", "last_routed", "requests",
                 "failures", "prewarms", "breaker", "batcher", "engine",
                 "beat_thread", "last_bringup")

    def __init__(self, rid, entry, config, device=None,
                 decode_model=None, draft_model=None):
        self.rid = rid
        self.entry = entry
        self.device = device
        self.state = STARTING
        self.unhealthy_reason = None
        self.inflight = 0
        self.last_beat = time.monotonic()
        self.last_routed = 0            # routing-fairness tiebreak
        self.requests = 0               # dispatches routed here
        self.failures = 0               # typed execute failures
        self.prewarms = 0               # completed prewarm passes
        self.last_bringup = 0.0         # monotonic of last prewarm try
        # per-REPLICA breaker extending §8's per-version one: same
        # windowed error rate + the consecutive-failures fast trip
        # (a replica failing everything since instant T is dead — do
        # not wait for a 20-outcome window to fill against a corpse)
        self.breaker = CircuitBreaker(
            config.circuit_window, config.circuit_threshold,
            config.circuit_cooldown_ms, model=entry.name,
            version=f"{entry.version}#{rid}",
            consecutive=config.replica_failure_threshold)
        if decode_model is not None:
            self.batcher = None
            from .decode import DecodeEngine
            self.engine = DecodeEngine(
                decode_model, config,
                model_name=f"{entry.name}/{rid}",
                draft=draft_model,
                fault_scope=f"replica.{rid}.decode")
        else:
            self.batcher = DynamicBatcher(config, device=device)
            self.engine = None
        self.beat_thread = None

    def __repr__(self):
        return (f"Replica({self.entry.name}:{self.entry.version}/"
                f"{self.rid}, {self.state}, inflight={self.inflight})")


class ReplicaSet:
    """N replicas of ONE model version, with health-checked
    least-loaded routing, deadline-preserving failover, and
    prewarm-gated rolling recovery (module docstring; docs/serving.md
    §10).

    ``devices`` is an optional list of per-replica device groups
    (``parallel.placement.replica_groups`` output); each replica's
    programs build and run on its group's lead device.  For decoder
    entries, per-replica decode models come from
    ``entry.decode_model_factory`` (``add_decoder(model_factory=...)``)
    or — for :class:`~mxnet_tpu.serving.decode.PagedLMAdapter` models —
    an automatic per-replica adapter clone over the shared LM weights.
    """

    def __init__(self, entry, config, devices=None, autostart=True,
                 n=None):
        self.entry = entry
        self.config = config
        self.name = entry.name
        self._cond = _engine.make_condition("serving.ReplicaSet._cond")
        self._replicas = OrderedDict()          # rid -> Replica
        self._idx = itertools.count()           # rid allocator
        self._ticket = itertools.count(1)       # routing fairness clock
        self._stopping = False
        self._last_sweep = 0.0          # monotonic; rate-limits _sweep
        self._drain_waiters = 0         # gates the per-request notify
        self._stats = {"dispatched": 0, "failovers": 0,
                       "unhealthy_marks": 0, "rejoins": 0,
                       "prewarms": 0, "no_healthy_rejects": 0,
                       "drained": 0}
        n = config.replicas if n is None else int(n)
        if n < 1:
            raise MXNetError("ReplicaSet: need >= 1 replica")
        self._single = n == 1
        self._devices = list(devices) if devices else None
        for _ in range(n):
            self._create_replica()
        _engine.watch_races(self)
        if autostart:
            self.start()

    # ------------------------------------------------------------ creation
    def _device_for(self, idx):
        if not self._devices:
            return None
        group = self._devices[idx % len(self._devices)]
        if isinstance(group, (tuple, list)):
            return group[0] if group else None
        return group

    def _decode_models(self, rid):
        """A fresh (model, draft) pair for one decode replica — every
        replica's engine owns its model's device state (KV pool,
        compiled programs), so N engines can never share one stateful
        model object."""
        entry = self.entry

        def fresh(model, factory, role):
            if factory is not None:
                return factory()
            from .decode import PagedLMAdapter
            if isinstance(model, PagedLMAdapter):
                # clone over the SHARED weights: per-replica pool and
                # program handles, one set of parameters in memory
                return PagedLMAdapter(
                    model.lm, attention_impl=model.attention_impl,
                    eos_id=getattr(model, "eos_id", None))
            if self._single:
                # a 1-replica set is the model's sole consumer — it
                # may own the registered object itself
                return model
            raise MXNetError(
                f"ReplicaSet({entry.name!r}): cannot replicate the "
                f"registered decode {role} ({type(model).__name__}) — "
                f"each replica's engine needs its own instance because "
                f"the model holds engine-local KV state (pages are "
                f"numbered per-engine).  Register with add_decoder("
                f"{role}_factory=...) returning a fresh object per "
                f"replica")

        model = fresh(entry.decode_model, entry.decode_model_factory,
                      "model")
        draft = None
        if entry.draft_model is not None:
            draft = fresh(entry.draft_model, entry.draft_model_factory,
                          "draft")
        return model, draft

    def _create_replica(self):
        idx = next(self._idx)
        rid = f"r{idx}"
        decode_model = draft = None
        if self.entry.decode_model is not None:
            decode_model, draft = self._decode_models(rid)
        rep = Replica(rid, self.entry, self.config,
                      device=self._device_for(idx),
                      decode_model=decode_model, draft_model=draft)
        with self._cond:
            self._replicas[rid] = rep
        self._publish_state(rep)
        return rep

    # ----------------------------------------------------------- lifecycle
    def start(self):
        """Prewarm every STARTING replica (serially — a replica is
        routable the moment ITS prewarm passes, so a slow sibling
        never blocks the set) and start the heartbeat threads."""
        with self._cond:
            self._stopping = False
            reps = list(self._replicas.values())
        for rep in reps:
            if rep.state == STARTING:
                self._bring_up(rep)
        return self

    def _bring_up(self, rep):
        """STARTING/UNHEALTHY -> PREWARMING -> HEALTHY (or back to
        UNHEALTHY on a failed prewarm).  Runs the prewarm OUTSIDE the
        set condition — it compiles/executes.  The beat thread starts
        either way: a replica whose FIRST prewarm failed still needs
        one, because the heartbeat loop is also the retry engine that
        brings it back once the failure clears (_maybe_rejoin)."""
        with self._cond:
            rep.state = PREWARMING
            rep.unhealthy_reason = None
            rep.last_bringup = time.monotonic()
        self._publish_state(rep)
        try:
            self._prewarm_replica(rep)
            ok = True
        except Exception as e:      # noqa: BLE001 — stay unroutable
            _LOG.warning("replica %s/%s: prewarm failed: %s",
                         self.name, rep.rid, e)
            self._mark_unhealthy(rep, f"prewarm failed: {e}")
            ok = False
        if ok:
            with self._cond:
                rep.state = HEALTHY
                rep.last_beat = time.monotonic()
                rep.prewarms += 1
                self._stats["prewarms"] += 1
            self._publish_state(rep)
        if rep.beat_thread is None or not rep.beat_thread.is_alive():
            t = _engine.make_thread(
                self._beat_loop, args=(rep,),
                name=f"mxnet-replica-{self.name}-{rep.rid}",
                owner=f"ReplicaSet({self.name})")
            with self._cond:
                rep.beat_thread = t
            t.start()
        return ok

    def _prewarm_replica(self, rep):
        """Build AND execute every shape bucket of this replica's
        program set — the hot-swap admission gate applied per replica:
        routable means zero compiles left on the request path.  With
        the persistent compile cache on, sibling replicas deserialize
        the first replica's stored executables (disk hits), so N
        replicas cost ONE cold compile per bucket."""
        if rep.engine is not None:
            rep.engine.start()
            # warm every prefill bucket + the decode program through
            # one short generation per bucket (prompt sized to the
            # bucket, one new token); pages are released at eviction so
            # the pool stays clean for traffic
            geo = rep.engine.geometry
            for bucket in rep.engine.prefill_buckets:
                length = min(bucket, geo.max_context - 1)
                if geo.pages_for(length + 1) > geo.usable_pages:
                    break           # pool-bounded: warm what can run
                prompt = np.zeros(length, np.int32)
                rep.engine.generate(prompt, max_new_tokens=1,
                                    eos_id=-1, timeout=60)
            return
        entry = self.entry
        for rows in prewarm_buckets(entry,
                                    self.config.max_batch_size):
            prog = rep.batcher.program_for(entry, rows)
            outs = prog(*synth_inputs(entry, rows))
            _engine.sync_outputs(
                outs if isinstance(outs, (tuple, list)) else (outs,),
                site="serving.replica.prewarm")

    def stop(self, timeout=None):
        """Stop every replica: heartbeats down, engines stopped,
        states STOPPED.  Returns False if an engine's step loop
        outlived the budget (call again to finish, mirroring
        ``ModelServer.stop``)."""
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        with self._cond:
            self._stopping = True
            reps = list(self._replicas.values())
            self._cond.notify_all()
        ok = True
        for rep in reps:
            t = rep.beat_thread
            if t is not None and t is not threading.current_thread():
                t.join(None if deadline is None
                       else max(0.0, deadline - time.monotonic()))
            if rep.engine is not None:
                if not rep.engine.stop(
                        timeout=None if deadline is None
                        else max(0.0, deadline - time.monotonic())):
                    ok = False
                    continue
            with self._cond:
                rep.state = STOPPED
            self._publish_state(rep)
        return ok

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------------ health
    def _publish_state(self, rep):
        if _rm._ENABLED:
            _rm.SERVING_REPLICA_STATE.set(
                _STATE_CODE[rep.state], model=self.name,
                replica=rep.rid)

    def _mark_unhealthy(self, rep, reason):
        """HEALTHY/PREWARMING -> UNHEALTHY; unroutable until it
        rejoins through prewarm (heartbeat recovery) or a breaker
        probe succeeds (transient-failure recovery)."""
        changed = False
        with self._cond:
            if rep.state not in (UNHEALTHY, DRAINING, STOPPED):
                rep.state = UNHEALTHY
                rep.unhealthy_reason = reason
                self._stats["unhealthy_marks"] += 1
                changed = True
        if changed:
            self._publish_state(rep)
            _LOG.warning("replica %s/%s marked UNHEALTHY: %s",
                         self.name, rep.rid, reason)
            _tr.record_incident(
                f"serving.replica_unhealthy: {self.name}/{rep.rid}: "
                f"{reason}", self.debug_state)

    def _beat_loop(self, rep):
        """One replica's heartbeat worker: beat, publish age, sweep
        the whole set for stale siblings, trigger own rejoin when
        beats resume after a stale window.  The fault site
        ``replica.<rid>.heartbeat`` sits BEFORE the beat update, so a
        ``stall`` rule is exactly a wedged worker: the thread sleeps,
        the beat ages, siblings detect it."""
        interval = self.config.replica_heartbeat_ms / 1e3
        while True:
            with self._cond:
                if self._stopping or rep.state == STOPPED:
                    return
            beat_ok = True
            try:
                # stall sleeps HERE (outside any lock); fail skips the
                # beat — both age the heartbeat
                _faults.inject(f"replica.{rep.rid}.heartbeat")
            except Exception:       # noqa: BLE001 — a missed beat
                beat_ok = False
            now = time.monotonic()
            with self._cond:
                if beat_ok and rep.state not in (DRAINING, STOPPED):
                    rep.last_beat = now
            self._sweep(now)
            self._maybe_rejoin(rep)
            with self._cond:
                if self._stopping or rep.state == STOPPED:
                    return
                self._cond.wait(interval)

    def _sweep(self, now=None, force=False):
        """Mark every replica whose heartbeat aged past the window
        UNHEALTHY, and publish heartbeat-age gauges.  Called from
        every beat AND from every routing decision, so detection needs
        neither traffic nor a dedicated monitor — but rate-limited to
        one pass per beat interval (staleness is measured in beat
        windows; re-walking the set on every request of a busy server
        buys nothing but lock traffic and O(replicas) gauge writes)."""
        now = time.monotonic() if now is None else now
        window = self.config.replica_heartbeat_window_ms / 1e3
        min_gap = self.config.replica_heartbeat_ms / 1e3
        stale = []
        with self._cond:
            if not force and now - self._last_sweep < min_gap:
                return
            self._last_sweep = now
            for rep in self._replicas.values():
                if rep.state in (DRAINING, STOPPED):
                    continue
                age = now - rep.last_beat
                if _rm._ENABLED:
                    _rm.SERVING_REPLICA_HEARTBEAT_AGE.set(
                        age, model=self.name, replica=rep.rid)
                # PREWARMING is exempt: a replica mid-bring-up has no
                # beat thread yet, and _bring_up owns its transition
                if rep.state == HEALTHY and age > window:
                    stale.append((rep, age))
        for rep, age in stale:
            self._mark_unhealthy(
                rep, f"heartbeat stale: {age * 1e3:.0f}ms > window "
                f"{self.config.replica_heartbeat_window_ms:.0f}ms")

    def _maybe_rejoin(self, rep):
        """Heartbeat-recovery rejoin: beats resumed on a replica that
        went stale -> it re-passes PREWARM before becoming routable
        again (the rolling-recovery gate — the pause may have been an
        eviction/restart, and a rejoining replica must never serve a
        cold program).  A replica whose last PREWARM itself failed
        retries here too, backed off by ``circuit_cooldown_ms`` — one
        transient prewarm failure must not strand it dark forever.
        Only the replica's own beat thread calls this, so the CAS
        under the condition cannot race another rejoin."""
        window = self.config.replica_heartbeat_window_ms / 1e3
        cooldown = self.config.circuit_cooldown_ms / 1e3
        now = time.monotonic()
        with self._cond:
            reason = rep.unhealthy_reason or ""
            eligible = (rep.state == UNHEALTHY
                        and (now - rep.last_beat) < window
                        and (reason.startswith("heartbeat")
                             or (reason.startswith("prewarm failed")
                                 and now - rep.last_bringup
                                 >= cooldown)))
        if not eligible:
            return
        if self._bring_up(rep):
            with self._cond:
                self._stats["rejoins"] += 1
            _LOG.info("replica %s/%s rejoined after prewarm",
                      self.name, rep.rid)

    # ------------------------------------------------------------- routing
    def _select(self, exclude=()):
        """The least-loaded routable replica (HEALTHY, breaker
        admitting), ties broken least-recently-routed; a
        failure-tripped UNHEALTHY replica whose breaker cooldown
        passed may be returned as its half-open probe.  Raises
        :class:`ServerOverloadedError` when nothing is routable — to a
        caller, a fully-dark replica set IS an overload: back off and
        retry (by then a probe or rejoin may have recovered one)."""
        self._sweep()
        with self._cond:
            if self._stopping:
                raise MXNetError(
                    f"ReplicaSet({self.name!r}) is stopped")
            healthy = sorted(
                (rep for rep in self._replicas.values()
                 if rep.rid not in exclude and rep.state == HEALTHY),
                key=lambda r: (r.inflight, r.last_routed))
            probes = [rep for rep in self._replicas.values()
                      if rep.rid not in exclude
                      and rep.state == UNHEALTHY
                      and rep.unhealthy_reason == "failures"]
            states = {rep.rid: rep.state
                      for rep in self._replicas.values()}
        # probe candidates go FIRST: a failure-tripped replica whose
        # cooldown passed gets exactly ONE request as its half-open
        # probe (the breaker admits a single probe per cooldown; a
        # failed probe fails over like any other failure), because with
        # healthy siblings always winning the sort, a healthy-last
        # order would never probe and the replica would stay dark
        # forever
        for rep in probes + healthy:
            try:
                rep.breaker.admit()
            except ServerOverloadedError:
                # breaker OPEN (windowed trip) on a still-HEALTHY
                # replica: reflect it in the state machine too
                if rep.state == HEALTHY:
                    self._mark_unhealthy(rep, "failures")
                continue
            return rep
        with self._cond:
            self._stats["no_healthy_rejects"] += 1
        raise ServerOverloadedError(
            self.name, self.config.retry_after_ms,
            f"no healthy replicas ({states})")

    def _note_dispatch(self, rep):
        with self._cond:
            rep.inflight += 1
            rep.requests += 1
            rep.last_routed = next(self._ticket)
            self._stats["dispatched"] += 1
        if _rm._ENABLED:
            _rm.SERVING_REPLICA_REQUESTS.inc(model=self.name,
                                             replica=rep.rid)
        _tr.tag("replica", rep.rid)

    def _note_done(self, rep):
        with self._cond:
            rep.inflight -= 1
            # only a drain (remove/restart) waits on inflight; waking
            # every beat thread per completed request would put an
            # O(replicas) sweep on the hot path for nothing
            if self._drain_waiters:
                self._cond.notify_all()

    def _note_failover(self, rep, exc):
        with self._cond:
            self._stats["failovers"] += 1
        if _rm._ENABLED:
            _rm.SERVING_REPLICA_FAILOVERS.inc(model=self.name)
        _tr.tag("failover_from", rep.rid)
        _LOG.warning("replica %s/%s failed (%s); failing over to a "
                     "sibling", self.name, rep.rid, exc)

    def _record_outcome(self, rep, ok):
        """Feed one EXECUTE outcome to the replica's breaker and keep
        the state machine in step with it: a trip marks UNHEALTHY
        ("failures"), a successful probe re-closes AND re-heals the
        state — the breaker half-open machinery IS the recovery path
        for transient-failure unhealth (programs are still warm; the
        prewarm gate applies to restarts and heartbeat rejoins, where
        the replica may have lost its state)."""
        from .resilience import CLOSED, OPEN
        state = rep.breaker.record(ok)
        if not ok:
            with self._cond:
                rep.failures += 1
            if state == OPEN:
                self._mark_unhealthy(rep, "failures")
        elif state == CLOSED:
            healed = False
            with self._cond:
                if rep.state == UNHEALTHY \
                        and rep.unhealthy_reason == "failures":
                    rep.state = HEALTHY
                    rep.unhealthy_reason = None
                    self._stats["rejoins"] += 1
                    healed = True
            if healed:
                self._publish_state(rep)
                _LOG.info("replica %s/%s re-closed after probe",
                          self.name, rep.rid)

    # ------------------------------------------------------------- predict
    def run_batch(self, request_inputs, deadline=None):
        """Dispatch one coalesced batch to the best replica, failing
        over to siblings on retryable failures while the ORIGINAL
        deadline allows.  Each replica is tried at most once per call;
        results are byte-identical across replicas (same program, same
        inputs), so the caller cannot observe which one served."""
        deadline = deadline or Deadline()
        excluded = set()
        while True:
            rep = self._select(exclude=excluded)
            self._note_dispatch(rep)
            try:
                _faults.inject(f"replica.{rep.rid}.execute")
                results = rep.batcher.run_batch(self.entry,
                                                request_inputs,
                                                deadline=deadline)
            except Exception as e:      # noqa: BLE001 — policy below
                self._note_done(rep)
                if isinstance(e, DeadlineExceededError):
                    # a deadline that expired waiting (e.g. on another
                    # thread's bucket build) says nothing about THIS
                    # replica's health — same exclusion the model-level
                    # breaker applies; the budget is burned, so no
                    # sibling can serve it either
                    raise
                self._record_outcome(rep, False)
                # only retryable failures reroute: a deterministic
                # error (malformed request, poisoned input) fails
                # identically everywhere — surfacing it immediately
                # beats running it N times (the worker-level bisection
                # isolates poison)
                if not is_transient(e) or deadline.expired():
                    raise
                excluded.add(rep.rid)
                with self._cond:
                    remaining = any(
                        r.rid not in excluded
                        and r.state in (HEALTHY, UNHEALTHY)
                        for r in self._replicas.values())
                if not remaining:
                    raise
                self._note_failover(rep, e)
                continue
            self._note_done(rep)
            self._record_outcome(rep, True)
            return results

    # ------------------------------------------------------------ generate
    def generate(self, prompt, max_new_tokens=None, eos_id=None,
                 on_token=None, timeout=None, _trace_ctx=_UNSET):
        """Route one generation to the best replica's decode engine;
        if that replica dies mid-generation (its engine quarantines or
        stops the sequence — pages reclaimed leak-free by the §8
        path), re-admit the prompt as a FRESH request on a sibling
        while the retry budget (``config.retry_max``) and the ORIGINAL
        deadline allow.  Greedy decoding is deterministic, so the
        failed-over result is byte-identical to an undisturbed run.
        Note for streaming callers: a failover restarts the token
        stream — ``on_token`` may re-deliver from the first token.
        """
        from .decode import _AMBIENT
        deadline = Deadline.start(timeout)
        excluded = set()
        failovers = 0
        while True:
            rep = self._select(exclude=excluded)
            if rep.engine is None:
                raise MXNetError(
                    f"ReplicaSet({self.name!r}): not a decoder entry")
            self._note_dispatch(rep)
            seq = None
            try:
                seq = rep.engine.submit(
                    prompt, max_new_tokens=max_new_tokens,
                    eos_id=eos_id, on_token=on_token,
                    timeout=deadline.remaining(),
                    _trace_ctx=_AMBIENT if _trace_ctx is _UNSET
                    else _trace_ctx)
                out = rep.engine.result(seq,
                                        timeout=deadline.remaining())
            except ServerOverloadedError as e:
                # engine queue shed: says nothing about health — try a
                # less loaded sibling once, else surface the shed
                self._note_done(rep)
                excluded.add(rep.rid)
                with self._cond:
                    remaining = any(
                        r.rid not in excluded and r.state == HEALTHY
                        for r in self._replicas.values())
                if not remaining or deadline.expired():
                    raise
                self._note_failover(rep, e)
                continue
            except Exception as e:      # noqa: BLE001 — policy below
                self._note_done(rep)
                reason = None if seq is None else seq.finish_reason
                replica_death = reason in ("quarantined", "stopped",
                                           "error")
                if replica_death or is_transient(e):
                    self._record_outcome(rep, False)
                if not (replica_death or is_transient(e)) \
                        or failovers >= self.config.retry_max \
                        or deadline.expired():
                    raise
                excluded.add(rep.rid)
                with self._cond:
                    remaining = any(
                        r.rid not in excluded
                        and r.state in (HEALTHY, UNHEALTHY)
                        for r in self._replicas.values())
                if not remaining:
                    raise
                failovers += 1
                self._note_failover(rep, e)
                continue
            self._note_done(rep)
            self._record_outcome(rep, True)
            return out

    # -------------------------------------------------------- rolling ops
    def add_replica(self):
        """Add one replica UNDER LOAD: created, prewarmed (every
        bucket built + executed), and only then routable — traffic
        keeps flowing to the existing replicas meanwhile.  Returns the
        new replica id."""
        rep = self._create_replica()
        self._bring_up(rep)
        return rep.rid

    def remove_replica(self, rid, timeout=None):
        """Remove one replica UNDER LOAD: DRAINING (unroutable) ->
        wait for its in-flight work to finish -> stop.  In-flight
        requests complete on it; nothing new routes to it."""
        with self._cond:
            rep = self._replicas.get(rid)
            if rep is None:
                raise MXNetError(
                    f"ReplicaSet({self.name!r}): no replica {rid!r} "
                    f"(have {list(self._replicas)})")
            if len(self._replicas) == 1:
                raise MXNetError(
                    f"ReplicaSet({self.name!r}): refusing to remove "
                    f"the last replica — stop() the set instead")
            rep.state = DRAINING
        self._publish_state(rep)
        deadline = Deadline.start(timeout)
        with self._cond:
            self._drain_waiters += 1
            try:
                while rep.inflight > 0:
                    if deadline.expired():
                        raise MXNetError(
                            f"ReplicaSet({self.name!r}): replica "
                            f"{rid} still has {rep.inflight} in-flight "
                            f"request(s) after {timeout}s drain")
                    self._cond.wait(
                        min(0.05, deadline.remaining() or 0.05))
            finally:
                self._drain_waiters -= 1
        if rep.engine is not None:
            rep.engine.stop()
        with self._cond:
            rep.state = STOPPED
            self._replicas.pop(rid, None)
            self._stats["drained"] += 1
        self._publish_state(rep)
        return True

    def restart(self, rid, timeout=None):
        """Replace one replica in place: drain + stop the old
        incarnation, then bring the SAME rid back through the full
        STARTING -> PREWARMING -> HEALTHY ladder (fresh breaker, fresh
        engine/KV state) — the operator-initiated half of rolling
        recovery."""
        with self._cond:
            rep = self._replicas.get(rid)
            if rep is None:
                raise MXNetError(
                    f"ReplicaSet({self.name!r}): no replica {rid!r}")
            rep.state = DRAINING
        self._publish_state(rep)
        deadline = Deadline.start(timeout)
        with self._cond:
            self._drain_waiters += 1
            try:
                while rep.inflight > 0 and not deadline.expired():
                    self._cond.wait(
                        min(0.05, deadline.remaining() or 0.05))
            finally:
                self._drain_waiters -= 1
        if rep.engine is not None:
            rep.engine.stop()
        with self._cond:
            rep.state = STOPPED
        self._publish_state(rep)
        idx = int(rid[1:]) if rid[1:].isdigit() else 0
        decode_model = draft = None
        if self.entry.decode_model is not None:
            decode_model, draft = self._decode_models(rid)
        fresh = Replica(rid, self.entry, self.config,
                        device=self._device_for(idx),
                        decode_model=decode_model, draft_model=draft)
        with self._cond:
            self._replicas[rid] = fresh
        self._publish_state(fresh)
        self._bring_up(fresh)
        return fresh.rid

    # ------------------------------------------------------------- readers
    def replicas(self):
        """{rid: state} snapshot."""
        with self._cond:
            return {rid: rep.state
                    for rid, rep in self._replicas.items()}

    def replica(self, rid):
        with self._cond:
            return self._replicas[rid]

    def decode_stats(self):
        """{rid: engine stats} for every decode replica."""
        with self._cond:
            reps = list(self._replicas.items())
        return {rid: rep.engine.stats() for rid, rep in reps
                if rep.engine is not None}

    def check_leaks(self):
        """Assert every decode replica's page allocator is exact
        (refcount == block-table slots + cache holds) — the
        quarantine-is-leak-free proof surface for chaos tests."""
        with self._cond:
            reps = list(self._replicas.values())
        for rep in reps:
            if rep.engine is not None:
                rep.engine.allocator.check_leaks()

    def stats(self):
        with self._cond:
            out = dict(self._stats)
            out["replicas"] = {
                rid: {"state": rep.state, "inflight": rep.inflight,
                      "requests": rep.requests,
                      "failures": rep.failures,
                      "prewarms": rep.prewarms,
                      "heartbeat_age_s": round(
                          time.monotonic() - rep.last_beat, 6)}
                for rid, rep in self._replicas.items()}
        return out

    def debug_state(self):
        """JSON-serializable snapshot for the flight recorder /
        ``tools/diagnose.py``: per-replica state machine, load,
        heartbeat age, breaker state, and (for decoders) the engine's
        own debug state."""
        now = time.monotonic()
        with self._cond:
            reps = list(self._replicas.items())
            out = {"model": self.name,
                   "version": self.entry.version,
                   "stopping": self._stopping,
                   "stats": dict(self._stats)}
        out["replicas"] = {}
        for rid, rep in reps:
            info = {"state": rep.state,
                    "unhealthy_reason": rep.unhealthy_reason,
                    "inflight": rep.inflight,
                    "requests": rep.requests,
                    "failures": rep.failures,
                    "prewarms": rep.prewarms,
                    "heartbeat_age_s": round(now - rep.last_beat, 6),
                    "breaker": rep.breaker.debug_state()}
            if rep.engine is not None:
                info["engine"] = rep.engine.debug_state()
            else:
                info["programs"] = rep.batcher.programs()
            out["replicas"][rid] = info
        return out

    def __repr__(self):
        return (f"ReplicaSet({self.name}:{self.entry.version}, "
                f"{self.replicas()})")
