"""Traffic plane, part 3: per-tenant tiered admission
(docs/serving.md §11).

The ModelServer's watermark shed (§4) is blind to WHO is asking: when
the queue fills, the request that happens to arrive next is shed,
whether it came from the paying tenant the SLO contract names or from
a free-tier batch job.  This module puts identity ahead of that shed:

- **tiers** (:class:`TierPolicy`): named priority classes
  (``MXNET_SERVING_TENANT_TIERS``, e.g. ``gold=100/50``) with a
  per-tenant token-bucket quota (requests/s + burst) — a tenant over
  its quota is shed with a typed
  :class:`~mxnet_tpu.serving.resilience.ServerOverloadedError` whose
  retry-after says when a token accrues;
- **priority shedding under overload**: the controller tracks a live
  pressure signal in ``[0, 1]`` (the server's queue fraction at every
  admission, max'd with whatever the
  :mod:`~mxnet_tpu.serving.autoscaler` last published from its SLO
  sensors) and sheds LOW tiers first — tier ``k`` of ``K`` (lowest
  priority first) sheds at pressure
  ``shed_start + (1-shed_start)*(k+1)/K``, so the highest tier is
  never pressure-shed here (only the watermark itself stops it);
- wired into ``ModelServer.predict/generate`` admission AHEAD of the
  watermark shed, with per-tenant metrics
  (``serving.tenant.{requests,shed}``) under the PR 8 label-cardinality
  guard and an ``admission.check`` fault site for chaos tests.
"""
from __future__ import annotations

import threading
import time

from .. import faults
from .. import runtime_metrics as _rm
from ..base import MXNetError, get_env
from .resilience import ServerOverloadedError

__all__ = ["TierPolicy", "AdmissionController", "parse_tier_spec"]

DEFAULT_TIER = "default"


class TierPolicy:
    """One admission class: ``priority`` orders shedding (higher
    survives longer), ``quota_rps`` is the per-tenant token refill rate
    (None = unmetered), ``burst`` the bucket capacity (default
    ``max(1, quota_rps)``)."""

    def __init__(self, name, priority, quota_rps=None, burst=None):
        self.name = str(name)
        self.priority = float(priority)
        self.quota_rps = None if quota_rps is None else float(quota_rps)
        if self.quota_rps is not None and self.quota_rps <= 0:
            raise MXNetError(
                f"TierPolicy({name!r}): quota_rps must be > 0 "
                f"(omit it for unmetered)")
        if burst is None:
            burst = None if self.quota_rps is None \
                else max(1.0, self.quota_rps)
        self.burst = None if burst is None else float(burst)
        if self.burst is not None and self.burst < 1:
            raise MXNetError(
                f"TierPolicy({name!r}): burst must be >= 1")

    def __repr__(self):
        return (f"TierPolicy({self.name!r}, priority={self.priority}, "
                f"quota_rps={self.quota_rps}, burst={self.burst})")


def parse_tier_spec(spec):
    """Parse ``MXNET_SERVING_TENANT_TIERS``:
    ``name=priority[/quota_rps[/burst]]`` comma-separated, e.g.
    ``gold=100,silver=10/20,free=1/5/8``.  Returns ``{name:
    TierPolicy}`` in declaration order."""
    tiers = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise MXNetError(
                f"tenant tier spec {part!r}: expected "
                f"name=priority[/quota_rps[/burst]]")
        name, rhs = part.split("=", 1)
        name = name.strip()
        fields = [f.strip() for f in rhs.split("/")]
        if not 1 <= len(fields) <= 3:
            raise MXNetError(
                f"tenant tier spec {part!r}: expected "
                f"priority[/quota_rps[/burst]]")
        try:
            priority = float(fields[0])
            quota = float(fields[1]) if len(fields) > 1 else None
            burst = float(fields[2]) if len(fields) > 2 else None
        except ValueError as e:
            raise MXNetError(
                f"tenant tier spec {part!r}: non-numeric field") from e
        if name in tiers:
            raise MXNetError(f"tenant tier {name!r} declared twice")
        tiers[name] = TierPolicy(name, priority, quota, burst)
    if not tiers:
        raise MXNetError(f"tenant tier spec {spec!r}: no tiers")
    return tiers


class _Bucket:
    """Token bucket, mutated only under the controller's lock."""

    __slots__ = ("tokens", "stamp")

    def __init__(self, tokens, stamp):
        self.tokens = tokens
        self.stamp = stamp


class AdmissionController:
    """Tier-ordered, quota-metered admission gate.

    ``check(tenant, model=...)`` either returns (admitted) or raises
    :class:`ServerOverloadedError` — the same typed contract as every
    other shed, so ``honor_retry_after`` clients back off identically.
    Two shed causes, in evaluation order:

    1. **pressure** (overload): effective pressure = max(the ``load``
       the server passes from its queue fraction, the last
       :meth:`update_pressure` value — published by the autoscaler's
       SLO sensors each tick, decaying after ``pressure_ttl_s`` so a
       dead controller cannot pin the gate shut).  A tier sheds when
       pressure reaches its threshold; thresholds stack low tier first.
    2. **quota**: the tenant's token bucket (rate = its tier's
       ``quota_rps``, capacity ``burst``); an empty bucket sheds with
       retry-after = time until one token accrues.

    A tenant maps to a tier by :meth:`register_tenant`, by a
    ``tenant="name:tier"`` suffix at the call site, or to
    ``default_tier`` (the highest-priority tier unless configured).
    Anonymous requests (``tenant=None``) ride the default tier
    unmetered by quota but still pressure-ordered.
    """

    def __init__(self, tiers, *, default_tier=None, shed_start=None,
                 retry_after_ms=50, pressure_ttl_s=5.0):
        if isinstance(tiers, str):
            tiers = parse_tier_spec(tiers)
        if not tiers:
            raise MXNetError("AdmissionController: no tiers")
        self.tiers = {name: pol for name, pol in tiers.items()}
        if shed_start is None:
            shed_start = get_env("MXNET_SERVING_ADMISSION_SHED_START",
                                 typ=float)
        self.shed_start = float(shed_start)
        if not 0.0 <= self.shed_start <= 1.0:
            raise MXNetError(
                "AdmissionController: shed_start must be in [0, 1]")
        self.retry_after_ms = float(retry_after_ms)
        self.pressure_ttl_s = float(pressure_ttl_s)
        if default_tier is None:
            default_tier = max(self.tiers.values(),
                               key=lambda p: p.priority).name
        if default_tier not in self.tiers:
            raise MXNetError(
                f"AdmissionController: default tier {default_tier!r} "
                f"not in {sorted(self.tiers)}")
        self.default_tier = default_tier
        # pressure threshold per tier: rank tiers by priority
        # ascending; tier k of K sheds at
        # shed_start + (1 - shed_start) * (k + 1) / K, so the lowest
        # tier goes first and the highest only at full pressure
        ranked = sorted(self.tiers.values(), key=lambda p: p.priority)
        k_total = len(ranked)
        self._shed_at = {
            pol.name: self.shed_start
            + (1.0 - self.shed_start) * (k + 1) / k_total
            for k, pol in enumerate(ranked)}
        self._lock = threading.Lock()
        self._tenants = {}              # tenant -> tier name
        self._buckets = {}              # tenant -> _Bucket
        self._pressure = 0.0
        self._pressure_stamp = 0.0
        self._stats = {"admitted": 0, "quota_sheds": 0,
                       "pressure_sheds": 0}
        self._by_tenant = {}            # tenant -> {admitted, shed}

    @classmethod
    def from_config(cls, config):
        """Build from ``ServingConfig`` when its ``tenant_tiers`` spec
        is set; None otherwise (admission off — the pre-PR-17 path)."""
        spec = getattr(config, "tenant_tiers", None)
        if not spec:
            return None
        return cls(spec, retry_after_ms=config.retry_after_ms,
                   shed_start=config.admission_shed_start)

    # ------------------------------------------------------------ identity
    def register_tenant(self, tenant, tier):
        if tier not in self.tiers:
            raise MXNetError(
                f"register_tenant({tenant!r}): unknown tier {tier!r} "
                f"(have {sorted(self.tiers)})")
        with self._lock:
            self._tenants[str(tenant)] = tier

    def resolve(self, tenant):
        """(tenant, tier) for a call-site identity: ``None`` ->
        anonymous on the default tier; ``"name"`` -> registered or
        default tier; ``"name:tier"`` -> explicit tier (validated)."""
        if tenant is None:
            return None, self.default_tier
        tenant = str(tenant)
        if ":" in tenant:
            tenant, tier = tenant.rsplit(":", 1)
            if tier not in self.tiers:
                raise MXNetError(
                    f"tenant {tenant!r}: unknown tier {tier!r} "
                    f"(have {sorted(self.tiers)})")
            return tenant, tier
        with self._lock:
            return tenant, self._tenants.get(tenant, self.default_tier)

    # ------------------------------------------------------------ pressure
    def update_pressure(self, pressure, now=None):
        """Publish an overload signal in [0, 1] (the autoscaler's SLO
        sensors, or any operator).  Stale publishes expire after
        ``pressure_ttl_s``."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._pressure = min(1.0, max(0.0, float(pressure)))
            self._pressure_stamp = now

    def pressure(self, now=None):
        now = time.monotonic() if now is None else now
        with self._lock:
            if now - self._pressure_stamp > self.pressure_ttl_s:
                return 0.0
            return self._pressure

    # ------------------------------------------------------------- check
    def check(self, tenant, *, model="", load=0.0, cost=1.0, now=None):
        """Admit or shed one request.  ``load`` is the caller's
        instantaneous pressure (the server's queue fraction); ``cost``
        the quota tokens this request spends.  Raises
        :class:`ServerOverloadedError` on shed; returns the resolved
        ``(tenant, tier)`` on admit."""
        now = time.monotonic() if now is None else now
        faults.inject("admission.check")
        tenant, tier = self.resolve(tenant)
        policy = self.tiers[tier]
        label = tenant if tenant is not None else "__anon__"
        reason = None
        retry_ms = self.retry_after_ms
        with self._lock:
            pressure = float(load)
            if now - self._pressure_stamp <= self.pressure_ttl_s:
                pressure = max(pressure, self._pressure)
            pressure = min(1.0, max(0.0, pressure))
            if pressure >= self._shed_at[tier]:
                self._stats["pressure_sheds"] += 1
                reason = (f"tier {tier!r} sheds at pressure "
                          f"{pressure:.2f} >= "
                          f"{self._shed_at[tier]:.2f} (priority "
                          f"shedding, low tier first)")
            elif policy.quota_rps is not None and tenant is not None:
                bucket = self._buckets.get(tenant)
                if bucket is None:
                    bucket = _Bucket(policy.burst, now)
                    self._buckets[tenant] = bucket
                bucket.tokens = min(
                    policy.burst,
                    bucket.tokens
                    + (now - bucket.stamp) * policy.quota_rps)
                bucket.stamp = now
                if bucket.tokens < cost:
                    self._stats["quota_sheds"] += 1
                    wait_s = (cost - bucket.tokens) / policy.quota_rps
                    retry_ms = max(retry_ms, 1e3 * wait_s)
                    reason = (f"tenant {tenant!r} over its {tier!r} "
                              f"quota ({policy.quota_rps}/s, burst "
                              f"{policy.burst})")
                else:
                    bucket.tokens -= cost
            per = self._by_tenant.setdefault(
                label, {"tier": tier, "admitted": 0, "shed": 0})
            per["tier"] = tier
            if reason is None:
                self._stats["admitted"] += 1
                per["admitted"] += 1
            else:
                per["shed"] += 1
        if reason is not None:
            if _rm._ENABLED:
                _rm.SERVING_TENANT_SHED.inc(tenant=label, tier=tier)
            raise ServerOverloadedError(model, retry_ms, reason)
        if _rm._ENABLED:
            _rm.SERVING_TENANT_REQUESTS.inc(tenant=label, tier=tier)
        return tenant, tier

    # ------------------------------------------------------------- state
    def shed_thresholds(self):
        """{tier: pressure threshold}, low tier first."""
        return dict(sorted(self._shed_at.items(), key=lambda kv: kv[1]))

    def stats(self):
        with self._lock:
            out = dict(self._stats)
            out["by_tenant"] = {t: dict(v)
                                for t, v in self._by_tenant.items()}
        out["pressure"] = self.pressure()
        return out

    def debug_state(self):
        """JSON-serializable snapshot for the flight recorder /
        tools/diagnose.py."""
        with self._lock:
            buckets = {t: round(b.tokens, 3)
                       for t, b in self._buckets.items()}
            tenants = dict(self._tenants)
        state = self.stats()
        state.update(
            tiers={n: repr(p) for n, p in self.tiers.items()},
            shed_thresholds=self.shed_thresholds(),
            default_tier=self.default_tier,
            tenant_tiers=tenants,
            quota_tokens=buckets)
        return state

    def __repr__(self):
        return (f"AdmissionController(tiers={sorted(self.tiers)}, "
                f"default={self.default_tier!r}, "
                f"shed_start={self.shed_start})")
