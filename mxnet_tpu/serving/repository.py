"""Versioned model store for the serving subsystem (docs/serving.md §2).

Reference analogue: MXNet Model Server's model store — named models,
integer versions, atomic ``swap`` between them while traffic is in
flight.  Three sources register:

- ``load_artifact``: a StableHLO artifact exported by
  ``deploy.export_stablehlo`` (the language-neutral path; the manifest
  is the serving signature);
- ``add_block``: a (hybridized) Gluon block served in-process through
  ``parallel.functional.functionalize`` — weights snapshot at
  registration, so later training does not mutate the served version;
- ``add_function``: a raw python callable (testing / custom runners).

Hot-swap contract: ``swap(name, version)`` atomically repoints the
*current* entry.  Requests resolve their entry once at admission, so an
in-flight batch completes on the version it was admitted under; only
requests admitted after the swap see the new version.
"""
from __future__ import annotations

import itertools
import logging
from collections import OrderedDict

import numpy as np

from .. import engine, faults as _faults
from ..base import MXNetError

__all__ = ["ModelEntry", "ModelRepository", "prewarm_buckets",
           "synth_inputs"]

_LOG = logging.getLogger("mxnet_tpu")

_UID = itertools.count(1)


class ModelEntry:
    """One immutable servable version of a model.

    ``signature`` is manifest-style: ``[{"shape": [...], "dtype": ...}]``
    with ``None`` dimensions free (``dynamic_batch`` additionally frees
    every leading dimension).  ``make_program(bucket_rows)`` returns a
    fresh compiled callable over raw arrays for one padded bucket size —
    the DynamicBatcher caches these per bucket.
    """

    def __init__(self, name, version, kind, signature, dynamic_batch,
                 make_program, fixed_batch=None, decode_model=None,
                 decode_meta=None, quantization=None, draft_model=None,
                 decode_model_factory=None, draft_model_factory=None):
        self.name = name
        self.version = version
        # "stablehlo" | "block" | "function" | "decoder"
        self.kind = kind
        self.signature = signature
        self.dynamic_batch = bool(dynamic_batch)
        self.fixed_batch = fixed_batch      # exported batch when static
        self.make_program = make_program
        # autoregressive entries: the decode-model object generate()
        # drives (serving/decode.py protocol), and/or the manifest's
        # decode-capable metadata block (artifact exports)
        self.decode_model = decode_model
        self.decode_meta = decode_meta
        # speculative-decoding draft attached to this decoder entry
        # (docs/serving.md §9); the entry's engine owns its binding
        self.draft_model = draft_model
        # replica serving (docs/serving.md §10): callables yielding a
        # FRESH decode model / draft per replica — each replica's
        # engine owns its model's device state (KV pool binding), so N
        # replicas cannot share one stateful model object.  None and
        # the replica layer clones PagedLMAdapters itself.
        self.decode_model_factory = decode_model_factory
        self.draft_model_factory = draft_model_factory
        # manifest v4 quantization block for quantized artifacts
        # (mode, per-tensor scales, calibration error) — None for f32
        self.quantization = quantization
        self.uid = next(_UID)               # distinct across re-registrations

    @property
    def manifest(self):
        # admission-time signature: the batch axis is always free here —
        # static entries are padded up to their exported batch by the
        # batcher before PJRT sees them (rows > fixed_batch is rejected
        # separately via max_rows)
        return {"dynamic_batch": True, "inputs": self.signature}

    def max_rows(self, max_batch_size):
        """Row capacity of one dispatched batch for this entry."""
        if self.dynamic_batch:
            return max_batch_size
        return self.fixed_batch if self.fixed_batch else max_batch_size

    def __repr__(self):
        return (f"ModelEntry({self.name}:{self.version}, {self.kind}, "
                f"dynamic_batch={self.dynamic_batch})")


def _as_tuple(out):
    if isinstance(out, tuple):
        return out
    if isinstance(out, list):
        return tuple(out)
    return (out,)


def prewarm_buckets(entry, max_batch_size):
    """The shape buckets a prewarm of ``entry`` must cover — ONE
    definition shared by :meth:`ModelRepository.prewarm` and the
    replica layer's per-replica prewarm (docs/serving.md §10), so a
    replica can never rejoin "warm" against a different bucket set
    than the dispatcher will use."""
    from .batcher import bucket_set
    if entry.dynamic_batch:
        return bucket_set(max_batch_size)
    if entry.fixed_batch is None:
        raise MXNetError(
            f"prewarm({entry.name!r}): static signature without a "
            f"batch dimension cannot be batch-served")
    return [entry.fixed_batch]


def synth_inputs(entry, rows):
    """Zero-filled inputs matching ``entry``'s signature at ``rows``
    batch rows — the prewarm payload that forces an XLA compile (or
    cached-executable load) without real data."""
    from ..deploy import _resolve_dtype
    inputs = []
    for spec in entry.signature:
        shape = [1 if d is None else d for d in spec["shape"]]
        if entry.dynamic_batch and shape:
            shape[0] = rows
        inputs.append(np.zeros(tuple(shape),
                               _resolve_dtype(spec["dtype"])))
    return inputs


def _block_signature(example_inputs, dynamic_batch):
    sig = []
    for x in example_inputs:
        shape = list(x.shape)
        if dynamic_batch:
            shape[0] = None
        sig.append({"shape": shape, "dtype": str(x._data.dtype)
                    if hasattr(x, "_data") else str(x.dtype)})
    return sig


class ModelRepository:
    """Thread-safe name -> versions -> :class:`ModelEntry` store with an
    atomically swappable *current* pointer per name."""

    def __init__(self):
        self._lock = engine.make_lock("serving.ModelRepository._lock")
        # name -> {"current": version, "versions": OrderedDict}
        self._models = {}
        self._unload_listeners = []

    def subscribe_unload(self, callback):
        """Register ``callback(entry)`` to run whenever a version is
        unloaded — ModelServer wires its batcher's program-cache
        eviction here so retired versions do not pin compiled programs.
        """
        with self._lock:
            self._unload_listeners.append(callback)

    def unsubscribe_unload(self, callback):
        """Remove a listener added by :meth:`subscribe_unload` (a
        stopped ModelServer must not stay pinned by the repository)."""
        with self._lock:
            try:
                self._unload_listeners.remove(callback)
            except ValueError:
                pass

    def _notify_unload(self, entries):
        for cb in list(self._unload_listeners):
            for entry in entries:
                try:
                    cb(entry)
                except Exception:   # noqa: BLE001 — eviction best-effort
                    pass

    # ------------------------------------------------------------ register
    def _register(self, entry, activate):
        """Version assignment and registration under ONE lock hold, so
        concurrent auto-versioned registrations cannot collide."""
        with self._lock:
            slot = self._models.setdefault(
                entry.name, {"current": None, "versions": OrderedDict()})
            if entry.version is None:
                ints = [v for v in slot["versions"]
                        if isinstance(v, int)]
                entry.version = max(ints) + 1 if ints else 1
            if entry.version in slot["versions"]:
                raise MXNetError(
                    f"model {entry.name!r} version {entry.version} "
                    f"already registered; unload it or pick a new "
                    f"version")
            slot["versions"][entry.version] = entry
            # activate=False stages even the FIRST version: an operator
            # pre-loading a new model name must be able to validate it
            # before swap() makes it live
            if activate:
                slot["current"] = entry.version
        return entry

    def load_artifact(self, name, path, version=None, activate=True):
        """Register a StableHLO artifact (``deploy.export_stablehlo``
        output).  ``path`` is the ``.shlo`` file or the bare prefix; the
        ``.json`` manifest beside it becomes the serving signature."""
        import jax

        from .. import deploy
        if not path.endswith(".shlo"):
            path = path + ".shlo"
        # chaos site: artifact pull/parse failure during a deploy —
        # must surface as a typed load error on the operator path while
        # traffic keeps serving the currently-active version
        _faults.inject("repository.load_artifact")
        model = deploy.load_stablehlo(path)
        manifest = model.manifest
        if manifest is None:
            raise MXNetError(
                f"load_artifact({name!r}): no manifest next to {path} — "
                f"serving needs the .json signature (re-export with "
                f"deploy.export_stablehlo)")
        dynamic = bool(manifest.get("dynamic_batch"))
        sig = manifest["inputs"]
        fixed = None if dynamic else (sig[0]["shape"][0] if sig else None)
        if version is None:
            version = manifest.get("version")
        exported = model.exported
        quantization = manifest.get("quantization")
        if quantization is not None:
            # serving-admission policy on top of the structural +
            # digest checks validate_manifest already ran: production
            # artifacts must carry the scale digest, and an operator
            # can bound the calibration error a replica will serve
            from ..base import env_truthy, get_env
            if env_truthy("MXNET_SERVING_QUANT_REQUIRE_DIGEST", True) \
                    and not isinstance(quantization.get("digest"), str):
                raise MXNetError(
                    f"load_artifact({name!r}): quantized manifest "
                    f"ships no scale digest — re-export with "
                    f"deploy.export_stablehlo(quantize=...) (or set "
                    f"MXNET_SERVING_QUANT_REQUIRE_DIGEST=0 to admit "
                    f"unprotected scales)")
            max_err = get_env("MXNET_SERVING_QUANT_MAX_REL_ERR",
                              typ=float)
            rel = (quantization.get("calibration") or {}).get(
                "max_rel_err")
            if max_err is not None and rel is not None \
                    and float(rel) > float(max_err):
                raise MXNetError(
                    f"load_artifact({name!r}): quantized artifact's "
                    f"calibration error {float(rel):.4g} exceeds the "
                    f"admission bound MXNET_SERVING_QUANT_MAX_REL_ERR="
                    f"{float(max_err):.4g} — recalibrate/re-export, or "
                    f"raise the bound")

        def make_program(bucket_rows):
            # persistent-cache path first: an AOT executable keyed on
            # (artifact hash, bucket, dtypes, topology) deserializes in
            # milliseconds instead of recompiling — a warm server
            # restart compiles ZERO new XLA programs.  Any failure falls
            # back to the plain jit wrapper (fresh wrapper per bucket:
            # its cache holds exactly one program, so bucket-cache
            # misses == compiled programs either way).
            from .. import compile_cache as _cc
            if _cc.get_default().enabled \
                    or (model.manifest or {}).get("precompiled"):
                try:
                    prog = model.aot_program(rows=bucket_rows)

                    def wrapped(*xs):
                        return _as_tuple(prog(*xs))
                    wrapped._mx_from_disk_cache = getattr(
                        prog, "_mx_from_disk_cache", False)
                    return wrapped
                except Exception as e:      # noqa: BLE001 — degrade
                    _LOG.warning(
                        "serving: compile-cache path failed for "
                        "%s bucket %s (%s); falling back to jit",
                        name, bucket_rows, e)
            return jax.jit(lambda *xs: _as_tuple(exported.call(*xs)))

        entry = ModelEntry(name, version, "stablehlo", sig, dynamic,
                           make_program, fixed_batch=fixed,
                           decode_meta=manifest.get("decode"),
                           quantization=quantization)
        return self._register(entry, activate)

    def add_block(self, name, block, *example_inputs, version=None,
                  activate=True, dynamic_batch=True):
        """Register a (hybridized) block for in-process serving.  The
        inference forward is functionalized and the current parameter
        values are snapshotted, so subsequent training does not mutate
        this served version (export-then-swap to publish new weights)."""
        import jax

        from ..ndarray import NDArray
        from ..parallel.functional import functionalize

        nd_inputs = tuple(x if isinstance(x, NDArray) else NDArray(x)
                          for x in example_inputs)
        apply_fn, params = functionalize(block, *nd_inputs,
                                         train_mode=False)
        params = dict(params)               # snapshot of current values

        def infer(*xs):
            out, _aux = apply_fn(params, *xs)
            return _as_tuple(out)

        def make_program(bucket_rows):
            return jax.jit(infer)

        sig = _block_signature(nd_inputs, dynamic_batch)
        entry = ModelEntry(name, version, "block", sig, dynamic_batch,
                           make_program,
                           fixed_batch=None if dynamic_batch
                           else nd_inputs[0].shape[0])
        return self._register(entry, activate)

    def add_decoder(self, name, model, version=None, activate=True,
                    attention_impl=None, eos_id=None, draft=None,
                    model_factory=None, draft_factory=None):
        """Register an autoregressive decode model served through
        ``ModelServer.generate()`` (docs/serving.md §6).

        ``model`` is either a
        :class:`~mxnet_tpu.models.transformer_blocks.TransformerDecoderLM`
        (wrapped in the compiled paged-KV adapter) or any object already
        implementing the decode-model protocol
        (``prefill``/``decode_step`` — fake/cheap models in tests).
        Decoder entries answer ``generate()`` only; ``predict()``
        rejects them with a pointer here.  Versioning/hot-swap semantics
        match every other entry kind: the decode engine resolves its
        entry at creation, requests admitted after a ``swap`` see the
        new version's engine.

        ``draft`` attaches a speculative-decoding draft model (same
        protocol, typically much smaller) to this entry: with
        ``spec_k`` > 0 the entry's engine has the draft propose k
        tokens per sequence per round and the target verify them in
        one call (docs/serving.md §9).  The draft gets its OWN adapter
        (its pool/programs bind to this entry's engine), loaded and
        compile-cached through the same machinery as the target.

        ``model_factory`` / ``draft_factory`` (callables returning a
        fresh decode-model / draft object) serve multi-replica
        deployments (docs/serving.md §10): each replica's engine needs
        its OWN model instance because the model binds replica-local
        device state (KV pool, compiled programs).  Unneeded for
        ``TransformerDecoderLM`` — the replica layer clones its
        adapter automatically."""
        from .decode import as_decode_model
        adapter = as_decode_model(model, attention_impl=attention_impl,
                                  eos_id=eos_id)
        draft_adapter = None
        if draft is not None:
            draft_adapter = as_decode_model(
                draft, attention_impl=attention_impl)
        sig = [{"shape": [None], "dtype": "int32"}]

        def make_program(bucket_rows):
            raise MXNetError(
                f"model {name!r} is a decoder entry — it serves "
                f"autoregressive generate(), not predict()")

        def wrap_factory(factory):
            if factory is None:
                return None
            return lambda: as_decode_model(
                factory(), attention_impl=attention_impl, eos_id=eos_id)

        entry = ModelEntry(name, version, "decoder", sig, False,
                           make_program, decode_model=adapter,
                           draft_model=draft_adapter,
                           decode_model_factory=wrap_factory(
                               model_factory),
                           draft_model_factory=wrap_factory(
                               draft_factory))
        return self._register(entry, activate)

    def add_function(self, name, fn, signature, version=None,
                     activate=True, dynamic_batch=True):
        """Register a raw callable ``fn(*arrays) -> array|tuple``
        (custom runners, tests).  ``signature`` is manifest-style."""
        from .. import deploy
        # a hand-written signature gets the same validation an exported
        # manifest does — a malformed entry (or a concrete leading dim
        # under dynamic_batch, which would mis-split rows at un-pad)
        # would otherwise surface as an opaque failure mid-request
        deploy.validate_signature(signature,
                                  where=f"add_function({name!r})",
                                  dynamic_batch=dynamic_batch)

        def make_program(bucket_rows):
            return lambda *xs: _as_tuple(fn(*xs))

        fixed = None
        if not dynamic_batch and signature \
                and signature[0].get("shape"):
            fixed = signature[0]["shape"][0]
        entry = ModelEntry(name, version, "function", signature,
                           dynamic_batch, make_program,
                           fixed_batch=fixed)
        return self._register(entry, activate)

    # ------------------------------------------------------------- resolve
    def get(self, name):
        """The current :class:`ModelEntry` for ``name`` (atomic read)."""
        return self._resolve(name)

    def _resolve(self, name, version=None):
        """The entry for (name, version); version=None means current."""
        with self._lock:
            slot = self._models.get(name)
            if slot is None:
                raise MXNetError(
                    f"no model {name!r} in the repository "
                    f"(known: {sorted(self._models)})")
            v = slot["current"] if version is None else version
            if v is None:
                raise MXNetError(
                    f"model {name!r} has no active version (staged: "
                    f"{list(slot['versions'])}) — activate one with "
                    f"swap({name!r}, version), or address it directly "
                    f"with version=")
            if v not in slot["versions"]:
                raise MXNetError(
                    f"model {name!r} has no version {v!r} "
                    f"(have: {list(slot['versions'])})")
            return slot["versions"][v]

    def prewarm(self, name, version=None, *, batcher, max_batch_size=None):
        """Compile/load EVERY shape bucket of (name, version) through
        ``batcher``'s program cache and execute each program once, so an
        atomic hot-swap admits traffic with zero compiles left on the
        request path (docs/serving.md §5).  The deploy loop is::

            repo.load_artifact("m", path, activate=False)   # stage v2
            srv.prewarm("m", version=2)                     # compile all
            repo.swap("m", 2)                               # cutover

        ``version=None`` prewarms the current version (cold-start path:
        prewarm before admitting any traffic).  Programs backed by the
        persistent compile cache deserialize instead of compiling;
        jit-backed programs are forced through their first (compiling)
        call here with zero-filled inputs.  Returns a summary dict
        (buckets warmed, compile/disk-hit counts from the batcher
        delta).
        """
        entry = self._resolve(name, version)
        if max_batch_size is None:
            max_batch_size = batcher.config.max_batch_size
        buckets = prewarm_buckets(entry, max_batch_size)
        compiled = disk_hits = 0
        for rows in buckets:
            # attribute builds to THIS entry (the global batcher
            # counters also move for concurrent traffic on other
            # models/versions — the documented prewarm-under-load flow)
            before = batcher.programs(entry)
            prog = batcher.program_for(entry, rows)
            if batcher.programs(entry) > before:
                if getattr(prog, "_mx_from_disk_cache", False):
                    disk_hits += 1
                else:
                    compiled += 1
            # force the XLA compile (or executable load) NOW: a
            # jit-backed program otherwise compiles lazily on the first
            # real request — exactly the cliff prewarm exists to remove
            inputs = synth_inputs(entry, rows)
            try:
                outs = prog(*inputs)
                engine.sync_outputs(
                    outs if isinstance(outs, (tuple, list)) else (outs,),
                    site="serving.prewarm")
            except Exception as e:
                raise MXNetError(
                    f"prewarm({name!r}:{entry.version}): bucket {rows} "
                    f"failed: {e}") from e
        return {"model": name, "version": entry.version,
                "buckets": buckets,
                "compiled": compiled, "disk_hits": disk_hits}

    def swap(self, name, version):
        """Atomically repoint ``name`` to ``version``; returns the
        previous current version.  In-flight requests finish on the
        entry they were admitted under."""
        with self._lock:
            slot = self._models.get(name)
            if slot is None:
                raise MXNetError(f"no model {name!r} in the repository")
            if version not in slot["versions"]:
                raise MXNetError(
                    f"model {name!r} has no version {version!r} "
                    f"(have: {list(slot['versions'])})")
            prev, slot["current"] = slot["current"], version
            return prev

    def versions(self, name):
        with self._lock:
            slot = self._models.get(name)
            return list(slot["versions"]) if slot else []

    def models(self):
        with self._lock:
            return sorted(self._models)

    def debug_state(self):
        """JSON-serializable snapshot of the version map (one entry per
        model: current version, staged versions, entry kinds) for the
        flight recorder (``ModelServer.debug_state``)."""
        with self._lock:
            return {
                name: {
                    "current": slot["current"],
                    "versions": [
                        {"version": v, "kind": e.kind, "uid": e.uid,
                         "dynamic_batch": e.dynamic_batch}
                        for v, e in slot["versions"].items()],
                }
                for name, slot in self._models.items()}

    def current_version(self, name):
        with self._lock:
            slot = self._models.get(name)
            return slot["current"] if slot else None

    def unload(self, name, version=None):
        """Drop one version (or the whole model when ``version`` is
        None).  Refuses to drop the current version of a multi-version
        model — swap first.  Unload listeners (program-cache eviction)
        run after the lock is released."""
        with self._lock:
            slot = self._models.get(name)
            if slot is None:
                raise MXNetError(f"no model {name!r} in the repository")
            if version is None:
                removed = list(slot["versions"].values())
                del self._models[name]
            else:
                if version not in slot["versions"]:
                    raise MXNetError(
                        f"model {name!r} has no version {version!r}")
                if version == slot["current"] \
                        and len(slot["versions"]) > 1:
                    raise MXNetError(
                        f"model {name!r} version {version!r} is "
                        f"current — swap to another version before "
                        f"unloading it")
                removed = [slot["versions"].pop(version)]
                if not slot["versions"]:
                    del self._models[name]
        self._notify_unload(removed)
