"""Traffic plane, part 1: seed-deterministic multi-tenant workload
traces (docs/serving.md §11).

Every serving bench before this module drove a uniform Poisson open
loop — nothing like the heavy-tailed, bursty, multi-tenant shape that
production serving actually absorbs.  This module is the single source
of truth for synthetic traffic:

- **arrival processes**: :func:`exponential_gap` is THE Poisson
  inter-arrival primitive (``benchmark/bench_serving.py`` imports it —
  one implementation, byte-identical draws), plus heavy-tailed
  lognormal and Pareto processes for :func:`generate_trace`;
- **trace generation** (:func:`generate_trace`): mixed
  predict/generate requests over N tenants and M models with
  hot-tenant/hot-model Zipf skew, lognormal prompt lengths, Pareto
  output lengths, shared-prefix clusters (drives the §9 radix prefix
  cache realistically), a diurnal rate ramp, and a step burst window —
  all from ONE numpy seed, so a trace is reproducible from its header
  alone;
- **record/replay** (:class:`Trace`): a JSONL format that round-trips
  bit-exactly (``save -> load -> save`` is byte-identical), so a
  recorded incident workload is a shippable artifact;
- **closed-loop replay** (:func:`replay_trace`): a client pool that
  paces requests to the trace timeline and HONORS the server's
  retry-after hints with jitter (:func:`resilience.honor_retry_after`)
  — shed storms must not come back as one synchronized wave — and
  proves the zero-hung-requests contract (every request resolves to a
  typed terminal status);
- **SLO scoring** (:func:`summarize`): attainment and goodput against
  declared latency/TTFT targets, per tier — the objective the
  :mod:`~mxnet_tpu.serving.autoscaler` control loop is judged on.
"""
from __future__ import annotations

import json
import random
import threading
import time

import numpy as np

from ..base import MXNetError, get_env
from .. import engine as _engine
from .resilience import Deadline, DeadlineExceededError, \
    ServerOverloadedError, honor_retry_after

__all__ = ["TraceRequest", "TraceConfig", "Trace", "generate_trace",
           "exponential_gap", "predict_payload", "prompt_tokens",
           "replay_trace", "summarize"]

TRACE_VERSION = 1

#: canonical field order of one JSONL request row — fixed so a trace
#: file is byte-stable across writers
_REQUEST_FIELDS = ("t", "tenant", "tier", "model", "op", "rows",
                   "prompt_len", "max_new_tokens", "prefix_group",
                   "seed")


# ---------------------------------------------------------------------------
# arrival processes
# ---------------------------------------------------------------------------
def exponential_gap(rng, rate):
    """One Poisson inter-arrival gap (seconds) at ``rate`` requests/s
    from ``rng`` (a ``numpy.random.RandomState``).  The ONE shared
    Poisson primitive: bench_serving's open-loop tiers and
    :func:`generate_trace` draw through here, so the same seed yields
    the same schedule everywhere."""
    return float(rng.exponential(1.0 / rate))


def _lognormal_gap(rng, rate, sigma):
    """Heavy-tailed inter-arrival with mean ``1/rate``: lognormal with
    ``exp(mu + sigma^2/2) = 1/rate``."""
    mu = -np.log(rate) - 0.5 * sigma * sigma
    return float(rng.lognormal(mu, sigma))


def _pareto_gap(rng, rate, alpha):
    """Pareto (Lomax-shifted) inter-arrival with mean ``1/rate``:
    ``x_m * (1 + Pareto(alpha))`` has mean ``x_m * alpha/(alpha-1)``."""
    xm = (1.0 / rate) * (alpha - 1.0) / alpha
    return xm * (1.0 + float(rng.pareto(alpha)))


_PROCESSES = ("poisson", "lognormal", "pareto")


# ---------------------------------------------------------------------------
# trace records
# ---------------------------------------------------------------------------
class TraceRequest:
    """One replayable request: arrival offset ``t`` (seconds from trace
    start), tenant/tier identity, target model, ``op`` in
    ``predict|generate``, and the deterministic payload recipe —
    ``rows``+``seed`` rebuild a predict input, ``prompt_len``/
    ``max_new_tokens``/``prefix_group``/``seed`` rebuild a prompt
    (:func:`predict_payload`, :func:`prompt_tokens`)."""

    __slots__ = _REQUEST_FIELDS

    def __init__(self, t, tenant, tier, model, op, rows=0,
                 prompt_len=0, max_new_tokens=0, prefix_group=None,
                 seed=0):
        if op not in ("predict", "generate"):
            raise MXNetError(f"TraceRequest: op must be "
                             f"predict|generate, got {op!r}")
        self.t = float(t)
        self.tenant = str(tenant)
        self.tier = str(tier)
        self.model = str(model)
        self.op = op
        self.rows = int(rows)
        self.prompt_len = int(prompt_len)
        self.max_new_tokens = int(max_new_tokens)
        self.prefix_group = None if prefix_group is None \
            else int(prefix_group)
        self.seed = int(seed)

    def to_dict(self):
        return {k: getattr(self, k) for k in _REQUEST_FIELDS}

    @classmethod
    def from_dict(cls, d):
        return cls(**{k: d[k] for k in _REQUEST_FIELDS})

    def __eq__(self, other):
        return isinstance(other, TraceRequest) \
            and self.to_dict() == other.to_dict()

    def __repr__(self):
        return (f"TraceRequest(t={self.t:.6f}, {self.tenant}/"
                f"{self.tier}, {self.model}.{self.op})")


def _canonical(obj):
    """Canonical JSON: sorted keys, no whitespace — the byte-stability
    half of the record/replay round-trip contract (floats go through
    repr, which round-trips doubles exactly)."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


class Trace:
    """An ordered request list plus the header that generated it.

    JSONL on disk: line 1 is the header (``kind=header``, format
    version, generator config), every following line one request
    (``kind=request``).  ``save -> load -> save`` is byte-identical —
    asserted by tests/test_traffic.py — so a recorded workload is a
    stable artifact, diffable and shippable."""

    def __init__(self, header, requests):
        self.header = dict(header)
        self.header.setdefault("kind", "header")
        self.header.setdefault("version", TRACE_VERSION)
        self.requests = list(requests)

    def __len__(self):
        return len(self.requests)

    def __eq__(self, other):
        return isinstance(other, Trace) \
            and self.header == other.header \
            and self.requests == other.requests

    @property
    def duration_s(self):
        return self.requests[-1].t if self.requests else 0.0

    def to_jsonl(self):
        lines = [_canonical(self.header)]
        for req in self.requests:
            row = req.to_dict()
            row["kind"] = "request"
            lines.append(_canonical(row))
        return "\n".join(lines) + "\n"

    def save(self, path):
        with open(path, "w") as f:
            f.write(self.to_jsonl())
        return path

    @classmethod
    def load(cls, path):
        with open(path) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
        if not lines:
            raise MXNetError(f"Trace.load({path!r}): empty file")
        header = json.loads(lines[0])
        if header.get("kind") != "header":
            raise MXNetError(
                f"Trace.load({path!r}): first line is not a trace "
                f"header (kind={header.get('kind')!r})")
        if header.get("version") != TRACE_VERSION:
            raise MXNetError(
                f"Trace.load({path!r}): format version "
                f"{header.get('version')!r}, this reader speaks "
                f"{TRACE_VERSION}")
        requests = []
        for ln in lines[1:]:
            row = json.loads(ln)
            if row.pop("kind", None) != "request":
                raise MXNetError(
                    f"Trace.load({path!r}): non-request row {ln!r}")
            requests.append(TraceRequest.from_dict(row))
        return cls(header, requests)


# ---------------------------------------------------------------------------
# trace generation
# ---------------------------------------------------------------------------
class TraceConfig:
    """Workload-shape knobs for :func:`generate_trace`.  Everything is
    derived from ``seed`` (``MXNET_SERVING_TRACE_SEED``) — two configs
    with equal fields yield byte-identical traces.

    - ``base_rate`` requests/s (``MXNET_SERVING_TRACE_RATE``) modulated
      by a diurnal sine ramp (``diurnal_amplitude``) and one step-burst
      window: rate multiplies by ``burst_x`` for ``burst_duration_s``
      starting at ``burst_at`` (fraction of ``duration_s``);
    - ``process`` in ``poisson|lognormal|pareto`` picks the
      inter-arrival law (the heavy-tailed laws keep mean ``1/rate`` but
      arrive in clumps — the shape shed/autoscale logic must survive);
    - ``tenants`` tenants named ``t0..`` with Zipf(``tenant_skew``)
      traffic shares, assigned round-robin over ``tiers``; ``models``
      weighted by Zipf(``model_skew``) (hot model first);
    - ``generate_fraction`` of requests are decode (``generate``) ops
      with lognormal prompt lengths (median ``prompt_len_median``,
      shape ``prompt_sigma``, cap ``prompt_max``) and Pareto output
      lengths (mean ``output_mean``, cap ``output_max``); the rest are
      ``predict`` ops with 1..``rows_max`` rows;
    - a ``prefix_share`` fraction of generate requests join one of
      ``prefix_clusters`` shared-prefix groups (first ``prefix_len``
      prompt tokens identical within a group — the radix-cache driver).
    """

    def __init__(self, seed=None, duration_s=8.0, base_rate=None,
                 process="lognormal", tenants=4,
                 tiers=("gold", "silver", "free"), tenant_skew=1.2,
                 models=("m",), model_skew=1.5, generate_fraction=0.35,
                 burst_at=0.45, burst_x=1.0, burst_duration_s=1.0,
                 diurnal_amplitude=0.3, arrival_sigma=0.8,
                 arrival_alpha=2.5, prompt_len_median=8.0,
                 prompt_sigma=0.6, prompt_max=24, output_mean=6.0,
                 output_alpha=2.0, output_max=16, prefix_clusters=4,
                 prefix_share=0.5, prefix_len=6, rows_max=3):
        self.seed = int(get_env("MXNET_SERVING_TRACE_SEED", typ=int)
                        if seed is None else seed)
        self.duration_s = float(duration_s)
        self.base_rate = float(
            get_env("MXNET_SERVING_TRACE_RATE", typ=float)
            if base_rate is None else base_rate)
        self.process = str(process)
        self.tenants = int(tenants)
        self.tiers = tuple(str(t) for t in tiers)
        self.tenant_skew = float(tenant_skew)
        self.models = tuple(str(m) for m in models)
        self.model_skew = float(model_skew)
        self.generate_fraction = float(generate_fraction)
        self.burst_at = float(burst_at)
        self.burst_x = float(burst_x)
        self.burst_duration_s = float(burst_duration_s)
        self.diurnal_amplitude = float(diurnal_amplitude)
        self.arrival_sigma = float(arrival_sigma)
        self.arrival_alpha = float(arrival_alpha)
        self.prompt_len_median = float(prompt_len_median)
        self.prompt_sigma = float(prompt_sigma)
        self.prompt_max = int(prompt_max)
        self.output_mean = float(output_mean)
        self.output_alpha = float(output_alpha)
        self.output_max = int(output_max)
        self.prefix_clusters = int(prefix_clusters)
        self.prefix_share = float(prefix_share)
        self.prefix_len = int(prefix_len)
        self.rows_max = int(rows_max)

        if self.process not in _PROCESSES:
            raise MXNetError(
                f"TraceConfig: process must be one of {_PROCESSES}, "
                f"got {self.process!r}")
        if self.duration_s <= 0 or self.base_rate <= 0:
            raise MXNetError(
                "TraceConfig: duration_s and base_rate must be > 0")
        if self.tenants < 1 or not self.tiers or not self.models:
            raise MXNetError(
                "TraceConfig: need >= 1 tenant, tier, and model")
        if not 0.0 <= self.generate_fraction <= 1.0 \
                or not 0.0 <= self.prefix_share <= 1.0:
            raise MXNetError(
                "TraceConfig: generate_fraction and prefix_share must "
                "be in [0, 1]")
        if self.burst_x < 1.0:
            raise MXNetError(
                "TraceConfig: burst_x must be >= 1 (1 = no burst)")
        if not 0.0 <= self.burst_at <= 1.0:
            raise MXNetError(
                "TraceConfig: burst_at is a fraction of duration_s")
        if not 0.0 <= self.diurnal_amplitude < 1.0:
            raise MXNetError(
                "TraceConfig: diurnal_amplitude must be in [0, 1)")
        if self.arrival_alpha <= 1.0 or self.output_alpha <= 1.0:
            raise MXNetError(
                "TraceConfig: Pareto alphas must be > 1 (finite mean)")
        if self.rows_max < 1 or self.prompt_max < 1 \
                or self.output_max < 1 or self.prefix_len < 1:
            raise MXNetError(
                "TraceConfig: rows/prompt/output/prefix caps must "
                "be >= 1")
        if self.prefix_clusters < 1:
            raise MXNetError(
                "TraceConfig: prefix_clusters must be >= 1")

    def header(self):
        """The generator fields, ordered — embedded in every saved
        trace so a file regenerates from its own header."""
        out = {"kind": "header", "version": TRACE_VERSION}
        for k in ("seed", "duration_s", "base_rate", "process",
                  "tenants", "tiers", "tenant_skew", "models",
                  "model_skew", "generate_fraction", "burst_at",
                  "burst_x", "burst_duration_s", "diurnal_amplitude",
                  "arrival_sigma", "arrival_alpha", "prompt_len_median",
                  "prompt_sigma", "prompt_max", "output_mean",
                  "output_alpha", "output_max", "prefix_clusters",
                  "prefix_share", "prefix_len", "rows_max"):
            v = getattr(self, k)
            out[k] = list(v) if isinstance(v, tuple) else v
        return out


def _zipf_weights(n, skew):
    w = np.array([1.0 / (i + 1.0) ** skew for i in range(n)])
    return w / w.sum()


def generate_trace(config=None, **kwargs):
    """Generate a :class:`Trace` from a :class:`TraceConfig` (or its
    kwargs).  Deterministic: one ``RandomState(seed)`` drives every
    draw in arrival order, so equal configs are byte-identical."""
    cfg = config if config is not None else TraceConfig(**kwargs)
    rng = np.random.RandomState(cfg.seed)
    tenant_w = _zipf_weights(cfg.tenants, cfg.tenant_skew)
    model_w = _zipf_weights(len(cfg.models), cfg.model_skew)
    tiers = [cfg.tiers[i % len(cfg.tiers)] for i in range(cfg.tenants)]
    burst_t0 = cfg.burst_at * cfg.duration_s
    burst_t1 = burst_t0 + cfg.burst_duration_s

    requests = []
    t = 0.0
    while True:
        # rate modulation: diurnal sine ramp over the trace duration,
        # times the step burst inside its window
        rate = cfg.base_rate * (
            1.0 + cfg.diurnal_amplitude
            * float(np.sin(2.0 * np.pi * t / cfg.duration_s)))
        if cfg.burst_x > 1.0 and burst_t0 <= t < burst_t1:
            rate *= cfg.burst_x
        if cfg.process == "poisson":
            gap = exponential_gap(rng, rate)
        elif cfg.process == "lognormal":
            gap = _lognormal_gap(rng, rate, cfg.arrival_sigma)
        else:
            gap = _pareto_gap(rng, rate, cfg.arrival_alpha)
        t += gap
        if t >= cfg.duration_s:
            break
        ti = int(rng.choice(cfg.tenants, p=tenant_w))
        mi = int(rng.choice(len(cfg.models), p=model_w))
        op = "generate" \
            if float(rng.random_sample()) < cfg.generate_fraction \
            else "predict"
        rows = prompt_len = max_new = 0
        prefix_group = None
        if op == "predict":
            rows = 1 + int(rng.randint(cfg.rows_max))
        else:
            prompt_len = int(np.clip(int(round(float(rng.lognormal(
                np.log(cfg.prompt_len_median), cfg.prompt_sigma)))),
                1, cfg.prompt_max))
            mean_scale = cfg.output_mean \
                * (cfg.output_alpha - 1.0) / cfg.output_alpha
            max_new = int(np.clip(int(round(
                (1.0 + float(rng.pareto(cfg.output_alpha)))
                * mean_scale)), 1, cfg.output_max))
            if float(rng.random_sample()) < cfg.prefix_share:
                prefix_group = int(rng.randint(cfg.prefix_clusters))
        requests.append(TraceRequest(
            t=t, tenant=f"t{ti}", tier=tiers[ti],
            model=cfg.models[mi], op=op, rows=rows,
            prompt_len=prompt_len, max_new_tokens=max_new,
            prefix_group=prefix_group,
            seed=int(rng.randint(0, 2 ** 31 - 1))))
    return Trace(cfg.header(), requests)


# ---------------------------------------------------------------------------
# deterministic payloads
# ---------------------------------------------------------------------------
def predict_payload(req, features=2, dtype=np.float32):
    """Rebuild the predict input a trace row describes — the same
    ``(rows, features)`` array on every replay (keyed by the row's
    ``seed``), so replays are byte-comparable across runs."""
    rng = np.random.RandomState(req.seed)
    return rng.randn(req.rows, features).astype(dtype)


def prompt_tokens(req, vocab=16, prefix_len=None):
    """Rebuild the prompt a trace row describes.  Rows sharing a
    ``prefix_group`` share their first ``prefix_len`` tokens exactly
    (drawn from the group id, not the request seed) — the shared-prefix
    clusters that make the §9 radix cache earn its keep — while the
    suffix stays per-request unique."""
    if req.prompt_len < 1:
        raise MXNetError(f"prompt_tokens: {req!r} is not a generate "
                         f"row (prompt_len={req.prompt_len})")
    rng = np.random.RandomState(req.seed)
    tokens = rng.randint(1, vocab, size=req.prompt_len)
    if req.prefix_group is not None:
        if prefix_len is None:
            prefix_len = 6
        n_pre = min(int(prefix_len), req.prompt_len - 1)
        if n_pre > 0:
            pre_rng = np.random.RandomState(7919 + req.prefix_group)
            tokens[:n_pre] = pre_rng.randint(1, vocab, size=n_pre)
    return [int(x) for x in tokens]


# ---------------------------------------------------------------------------
# closed-loop replay
# ---------------------------------------------------------------------------
def replay_trace(trace, call, *, clients=8, speed=None, attempts=4,
                 timeout_s=30.0, jitter_seed=0, on_backoff=None):
    """Replay ``trace`` through ``call(req)`` with a closed-loop client
    pool.

    Each of ``clients`` workers owns an interleaved slice of the trace
    and paces it to the recorded timeline (compressed by ``speed``,
    default ``MXNET_SERVING_TRACE_SPEED``); within one client requests
    are serial, so a slow server pushes back on that client's schedule
    — closed-loop, not a fire-and-forget thread storm.  Every call runs
    under its own :class:`Deadline` and inside
    :func:`~mxnet_tpu.serving.resilience.honor_retry_after` with a
    per-client seeded jitter rng: shed requests back off by the
    server's own retry-after hint, never as a synchronized wave.

    ``call(req)`` performs one server round trip and may return a dict
    of extra fields to record (e.g. ``{"ttft_s": ...}`` from an
    ``on_token`` timestamp).  Returns ``(records, wall_s)`` where every
    record carries a terminal ``status`` in
    ``ok|shed|deadline|error`` — a replay that returns PROVES zero hung
    requests (a worker that wedges past every request deadline raises
    instead of returning partial records)."""
    if speed is None:
        speed = get_env("MXNET_SERVING_TRACE_SPEED", typ=float)
    speed = float(speed)
    if speed <= 0:
        raise MXNetError("replay_trace: speed must be > 0")
    reqs = trace.requests
    records = [None] * len(reqs)
    clients = max(1, min(int(clients), max(1, len(reqs))))
    start_evt = threading.Event()
    epoch = []

    def worker(tid):
        rng = random.Random(100003 + jitter_seed * 1009 + tid)
        start_evt.wait(timeout_s)
        t0 = epoch[0]
        for i in range(tid, len(reqs), clients):
            req = reqs[i]
            lag = t0 + req.t / speed - time.monotonic()
            if lag > 0:
                time.sleep(lag)
            deadline = Deadline.start(timeout_s)
            t_start = time.monotonic()
            status, err, info = "ok", None, None
            try:
                info = honor_retry_after(
                    lambda: call(req), attempts=attempts, rng=rng,
                    deadline=deadline, on_backoff=on_backoff)
            except ServerOverloadedError as e:
                status, err = "shed", e
            except DeadlineExceededError as e:
                status, err = "deadline", e
            except MXNetError as e:
                status, err = "error", e
            rec = {"index": i, "t": req.t, "tenant": req.tenant,
                   "tier": req.tier, "model": req.model, "op": req.op,
                   "status": status,
                   "error": type(err).__name__ if err else None,
                   "start_s": t_start - t0,
                   "latency_s": time.monotonic() - t_start}
            if isinstance(info, dict):
                rec.update(info)
            records[i] = rec

    pool = [_engine.make_thread(worker, name=f"mxnet-replay-{tid}",
                                owner="replay_trace", args=(tid,))
            for tid in range(clients)]
    for th in pool:
        th.start()
    epoch.append(time.monotonic())
    start_evt.set()
    wall0 = epoch[0]
    # one total budget: the trace timeline plus every request's own
    # deadline — past it a worker is wedged, which is itself a failure
    budget = trace.duration_s / speed + timeout_s * (attempts + 1) + 30
    join_by = wall0 + budget
    for th in pool:
        th.join(max(0.0, join_by - time.monotonic()))
    wall_s = time.monotonic() - wall0
    hung = [i for i, r in enumerate(records) if r is None]
    if hung:
        raise MXNetError(
            f"replay_trace: {len(hung)} request(s) never resolved "
            f"within {budget:.1f}s (first: {hung[:5]}) — the "
            f"zero-hung-requests contract is broken")
    return records, wall_s


def summarize(records, *, wall_s, latency_slo_s=None, ttft_slo_s=None):
    """Score a replay against declared SLO targets.

    A record counts toward **attainment** when it completed (``ok``)
    AND met every declared target that applies to it: ``latency_slo_s``
    end to end, plus ``ttft_slo_s`` for generate rows that measured a
    ``ttft_s``.  ``attainment`` divides by ALL requests — a shed or
    hung-then-typed request is an SLO miss, not a denominator dodge —
    and ``goodput_rps`` is SLO-meeting completions per wall second.
    Per-tier rollups expose the tiered-admission contract: under
    overload the free tier's shed count rises first."""
    n = len(records)
    by_status = {}
    by_tier = {}
    slo_ok = 0
    lat_ok = []
    ttfts = []
    for r in records:
        by_status[r["status"]] = by_status.get(r["status"], 0) + 1
        tier = by_tier.setdefault(
            r["tier"], {"requests": 0, "ok": 0, "shed": 0, "slo_ok": 0})
        tier["requests"] += 1
        if r["status"] == "shed":
            tier["shed"] += 1
        if r["status"] != "ok":
            continue
        tier["ok"] += 1
        lat_ok.append(r["latency_s"])
        met = latency_slo_s is None or r["latency_s"] <= latency_slo_s
        ttft = r.get("ttft_s")
        if ttft is not None:
            ttfts.append(ttft)
            if ttft_slo_s is not None and ttft > ttft_slo_s:
                met = False
        if met:
            slo_ok += 1
            tier["slo_ok"] += 1

    def pct(xs, q):
        return float(np.percentile(xs, q)) if xs else float("nan")

    return {
        "requests": n,
        "ok": by_status.get("ok", 0),
        "shed": by_status.get("shed", 0),
        "deadline": by_status.get("deadline", 0),
        "error": by_status.get("error", 0),
        "slo_ok": slo_ok,
        "attainment": slo_ok / n if n else float("nan"),
        "goodput_rps": slo_ok / wall_s if wall_s > 0 else float("nan"),
        "latency_p50_s": pct(lat_ok, 50),
        "latency_p99_s": pct(lat_ok, 99),
        "ttft_p50_s": pct(ttfts, 50),
        "ttft_p99_s": pct(ttfts, 99),
        "wall_s": wall_s,
        "by_tier": by_tier,
    }
