"""Autoregressive decode engine: token-level continuous batching over a
paged KV cache (docs/serving.md §6).

``predict()`` serves one-shot programs; *the* heavy-traffic workload is
autoregressive generation, whose unit of work is a token, not a
request.  Request-level batching would hold every sequence of a batch
hostage to its longest member; this engine reschedules at TOKEN
granularity instead — every step it admits waiting sequences into free
decode slots, runs ONE fixed-shape decode step for all running
sequences, and evicts the finished ones (the continuous-batching design
of Orca/vLLM, with the kernel layout of "Ragged Paged Attention",
PAPERS.md).  The host-side step loop only schedules and samples; all
per-token math lives in two compiled program families, so the scheduler
stays off the device critical path (the prefetch discipline of the
tf.data design, PAPERS.md):

- **prefill** — one program per prompt-length bucket (the serving
  batcher's power-of-two ``bucket_set`` machinery reused for the length
  axis), batch 1, writes the prompt's K/V into cache pages and returns
  last-token logits;
- **decode** — ONE program at the fixed ``decode_max_batch``, one token
  per slot, reading/writing K/V through per-sequence block tables
  (``serving.kv_cache``).

Total compiled programs are therefore bounded by
``len(bucket_set(max_context)) + 1`` for ANY traffic mix — the same
O(log N) discipline the predict path gets from ``DynamicBatcher`` —
and with the persistent compile cache configured
(``MXNET_COMPILE_CACHE_DIR``) both families deserialize on a warm
restart instead of compiling (weights enter the programs as inputs, so
the cache key is the architecture, not the checkpoint).

KV memory: sequences own fixed-size pages from a preallocated device
pool via a free-list allocator (:mod:`mxnet_tpu.serving.kv_cache`).
Admission reserves a sequence's worst case
(``ceil((prompt + max_new_tokens) / page_size)``) up front —
all-or-nothing, so a running sequence can never hit pool exhaustion
mid-flight and no preemption machinery is needed; eviction returns the
pages, unblocking the admission queue.  (vLLM-style lazy allocation
with preemption is a policy swap inside ``_admit_locked``.)

Two composable optimizations ride the same paged substrate
(docs/serving.md §9):

- **prefix caching** (``config.prefix_cache``): prompts are looked up
  in a radix tree over page-size token chunks at admission; a hit
  aliases the cached (refcounted, immutable) pages instead of
  re-running prefill — the one page the sequence must append into is
  copy-on-write duplicated — and the last-token logits are recovered
  through a width-1 (full hit) or tail-width (partial hit) call of the
  **verify** program family.  Lookup/verify failures DEGRADE to a
  plain prefill, never to wrong tokens.
- **speculative decoding** (``config.spec_k`` + a draft model): the
  draft proposes up to k tokens per running sequence (batched draft
  decode steps over the SAME block tables, its K/V in a parallel
  draft pool), the target verifies all k+1 positions in ONE call of
  the verify family (the ragged multi-token shape
  ``ragged_paged_verify`` exists for), greedy acceptance is exact
  (the degenerate rejection-sampling case — byte-identical outputs
  speculation on or off), and rejected positions roll back through
  the block-table/context-length bookkeeping alone (their stale K/V
  is never attended and is overwritten in place).

Programs stay bounded: prefill buckets + 1 decode + the verify-width
family (+ the draft's own prefill/decode/verify families when
speculation is on) — asserted via ``_cache_size()`` like everything
else.
"""
from __future__ import annotations

import itertools
import logging
import threading
import time

import numpy as np

from .. import engine as _engine, faults as _faults, \
    runtime_metrics as _rm, tracing as _tr
from ..base import MXNetError, entropy_rng
from .batcher import bucket_set, next_bucket
from .kv_cache import DeviceKVPool, PageAllocator, PageGeometry
from .resilience import Deadline, DeadlineExceededError, retry_call

__all__ = ["DecodeEngine", "GenerateRequest", "PagedLMAdapter",
           "as_decode_model"]

_LOG = logging.getLogger("mxnet_tpu")
_SEQ_IDS = itertools.count(1)
# traced sequences record a decode.step span for their FIRST decode
# step and then every Nth token — per-token spans on a long generation
# would blow the per-trace span budget without adding information
_STEP_SPAN_EVERY = 8
# submit(_trace_ctx=...) sentinel: "no caller decision — inspect the
# ambient context / make the head-sampling call here".  ModelServer
# always passes its root's context instead (None when that root was
# sampled out), so one request NEVER gets two sampling decisions.
_AMBIENT = object()


class GenerateRequest:
    """One ``generate()`` call's lifecycle handle.

    ``tokens`` fills with generated ids (EOS included when hit) as the
    engine steps; ``event`` fires at eviction (finished, failed, or
    cancelled).  ``finish_reason`` is one of ``eos | length |
    cancelled | stopped | error | deadline | quarantined``.
    """

    __slots__ = ("seq_id", "prompt", "max_new_tokens", "eos_id",
                 "on_token", "tokens", "event", "error", "finish_reason",
                 "slot", "context_len", "t_submit", "t_first", "t_prev",
                 "cancelled", "trace", "root_span", "queue_span",
                 "released_pages", "deadline", "prefix_len", "cow",
                 "draft_ctx", "no_cache", "no_spec")

    def __init__(self, prompt, max_new_tokens, eos_id, on_token,
                 deadline=None):
        self.seq_id = next(_SEQ_IDS)
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id
        self.on_token = on_token
        self.tokens = []                  # generated ids (ints)
        self.event = threading.Event()
        self.error = None
        self.finish_reason = None
        self.slot = None                  # decode-batch slot while running
        self.context_len = 0              # tokens whose K/V is written
        self.t_submit = time.monotonic()
        self.t_first = None               # first-token timestamp (TTFT)
        self.t_prev = None                # previous-token timestamp
        self.cancelled = False
        # end-to-end deadline (resilience.Deadline; may be unbounded):
        # checked in the waiting line (expire before consuming a slot
        # or pages) and after every step while running
        self.deadline = deadline or Deadline()
        # tracing: the request's TraceContext (None when untraced), an
        # engine-owned root span when generate() was called without an
        # ambient trace, and the queue-wait span started at submit and
        # ended by the step loop at admission
        self.trace = None
        self.root_span = None
        self.queue_span = _tr._NOOP
        self.released_pages = 0
        # prefix-cache admission plan (set by the step loop): tokens of
        # prompt covered by aliased cached pages, and the (src, dst)
        # copy-on-write pair when the hit covers the whole prompt
        self.prefix_len = 0
        self.cow = None
        # speculative decoding: positions with valid DRAFT K/V (lags
        # context_len by <= 1 after a fully-accepted round)
        self.draft_ctx = 0
        # degrade flags: a failed cached-path prefill requeues with the
        # cache bypassed; a failed draft prefill decodes plainly
        self.no_cache = False
        self.no_spec = False

    def token_at(self, pos):
        """The sequence's token at global position ``pos`` (prompt,
        then generated ids)."""
        if pos < self.prompt.size:
            return int(self.prompt[pos])
        return self.tokens[pos - self.prompt.size]

    @property
    def ttft(self):
        """Seconds from submit to first token, or None."""
        return None if self.t_first is None \
            else self.t_first - self.t_submit


class DecodeEngine:
    """Continuous-batching scheduler over one decode model.

    ``model`` implements the decode-model protocol (duck-typed so
    scheduler tests run on fake numpy models with zero compiles):

    - attrs ``vocab_size``, ``max_context`` (and for pool sizing,
      optional ``num_layers`` / ``num_heads`` / ``head_dim``);
    - ``prefill(tokens (1, L) i32, length () i32, block_table (P,) i32)
      -> last-token logits (V,)``, writing the prompt's K/V;
    - ``decode_step(tokens (B,) i32, positions (B,) i32,
      block_tables (B, P) i32) -> logits (B, V)`` — inactive slots
      carry zeros and their logits are never read;
    - optional ``verify(tokens (1, W) i32, start () i32, length () i32,
      block_table (P,) i32) -> logits (W, V)`` — the multi-token
      window forward prefix caching and speculative decoding need
      (writes the window's K/V, judges every position in one call);
    - optional ``copy_page(src, dst)`` — the copy-on-write page
      duplication behind full prefix-cache hits;
    - optional ``setup(geometry)`` (allocate device pools) and
      ``programs()`` (compiled-program count, for the bound asserts).

    A ``draft`` model (same protocol, smaller) plus ``config.spec_k``
    turns decode rounds speculative; ``config.prefix_cache`` turns on
    copy-on-write prefix sharing (both in docs/serving.md §9).

    The engine owns the HOST side only: waiting queue (bounded by
    ``config.queue_depth`` — submission past it sheds with
    :class:`~mxnet_tpu.serving.server.ServerOverloadedError`, the same
    backpressure contract as the predict path), slot map, page
    allocator, sampling (greedy argmax), callbacks, metrics.  One
    background thread drives :meth:`step`; tests drive it directly with
    ``autostart=False``.
    """

    def __init__(self, model, config=None, model_name="decoder",
                 autostart=False, draft=None, fault_scope="decode"):
        from .config import ServingConfig
        from .kv_cache import PrefixCache
        self.model = model
        self.config = config or ServingConfig()
        self.model_name = model_name
        # fault-injection site prefix: "decode" for a plain engine
        # (sites decode.prefill / decode.step / ...), scoped to
        # "replica.<rid>.decode" for a replica-owned engine so a chaos
        # plan can kill ONE replica's step loop deterministically
        # (docs/serving.md §10)
        self.fault_scope = str(fault_scope)
        max_context = int(model.max_context)
        self.geometry = PageGeometry(
            page_size=self.config.decode_page_size,
            pool_pages=self.config.decode_pool_pages,
            max_context=max_context,
            num_layers=getattr(model, "num_layers", 1),
            num_heads=getattr(model, "num_heads", 1),
            head_dim=getattr(model, "head_dim", 1))
        self.allocator = PageAllocator(self.geometry)
        self.max_batch = self.config.decode_max_batch
        # prompt-length buckets: the SAME power-of-two policy the
        # predict path uses for batch rows, applied to the length axis —
        # at most len(bucket_set(max_context)) prefill programs
        self.prefill_buckets = bucket_set(max_context)
        # --- speculative decoding (docs/serving.md §9) ---------------
        # a draft model + spec_k > 0 turns decode rounds into propose-k
        # -> verify-(k+1)-in-one-call; both models need the protocol
        # halves they play (the draft proposes via prefill/decode_step,
        # the target judges via verify)
        self.draft = draft
        self.spec_k = int(self.config.spec_k or 0)
        if self.spec_k and draft is None:
            _LOG.warning(
                "decode engine %s: spec_k=%d but no draft model — "
                "speculative decoding disabled (register the draft via "
                "add_decoder(draft=...) or MXNET_SERVING_SPEC_DRAFT)",
                model_name, self.spec_k)
            self.spec_k = 0
        if self.spec_k and getattr(model, "verify", None) is None:
            raise MXNetError(
                f"decode engine {model_name!r}: speculative decoding "
                f"needs the target model to implement verify() "
                f"(multi-token window forward)")
        if self.spec_k and self.spec_k + 1 > max_context:
            raise MXNetError(
                f"decode engine {model_name!r}: spec_k={self.spec_k} "
                f"+ 1 exceeds max_context {max_context}")
        self.draft_geometry = None
        if self.spec_k:
            # the draft's K/V lives in a PARALLEL pool with the same
            # page layout, indexed by the SAME block tables — one
            # allocator serves both models, and a cached prefix page
            # carries both models' K/V for its chunk
            self.draft_geometry = PageGeometry(
                page_size=self.geometry.page_size,
                pool_pages=self.geometry.pool_pages,
                max_context=max_context,
                num_layers=getattr(draft, "num_layers", 1),
                num_heads=getattr(draft, "num_heads", 1),
                head_dim=getattr(draft, "head_dim", 1))
        # --- prefix cache (docs/serving.md §9) -----------------------
        self.prefix_cache = None
        if self.config.prefix_cache:
            missing = [m for m in ("verify", "copy_page")
                       if getattr(model, m, None) is None]
            if missing:
                _LOG.warning(
                    "decode engine %s: prefix cache requested but the "
                    "model lacks %s — disabled (plain prefill serves "
                    "every prompt)", model_name, "/".join(missing))
            else:
                self.prefix_cache = PrefixCache(
                    self.allocator,
                    max_pages=self.config.prefix_cache_pages)
        # program accounting: prefill buckets + 1 decode per model,
        # + the verify-width family (shared by prefix-hit tails and
        # speculation windows, <= the same bucket set) + 1 COW copy
        # program when the prefix cache is on
        bound = len(self.prefill_buckets) + 1
        if self.prefix_cache is not None or self.spec_k:
            bound += len(self.prefill_buckets)      # verify family
        if self.prefix_cache is not None:
            bound += 1                              # COW copy program
        if self.spec_k:
            bound += 1                  # ONE batched verify program
            # draft: prefill buckets + 1 decode + its verify family
            # (prefix-hit tail writes draft K/V through verify too)
            bound += 2 * len(self.prefill_buckets) + 1
            if self.prefix_cache is not None:
                bound += 1                          # draft COW program
        self.program_bound = bound
        setup = getattr(model, "setup", None)
        if setup is not None:
            setup(self.geometry)
        self._model_bound = setup is not None
        self._draft_bound = False
        if self.spec_k:
            draft_setup = getattr(draft, "setup", None)
            if draft_setup is not None:
                draft_setup(self.draft_geometry)
                self._draft_bound = True
        self._cond = _engine.make_condition("serving.DecodeEngine._cond")
        self._waiting = []                # FIFO of GenerateRequest
        self._running = {}                # slot -> GenerateRequest
        self._free_slots = list(range(self.max_batch - 1, -1, -1))
        self._started = False
        self._stopping = False
        self._thread = None
        self._stats = {"steps": 0, "admitted": 0, "evicted": 0,
                       "generated_tokens": 0, "peak_running": 0,
                       "shed": 0, "retries": 0, "quarantined": 0,
                       "deadline_exceeded": 0, "prefix_hits": 0,
                       "prefix_misses": 0, "prefix_tokens_saved": 0,
                       "prefix_degraded": 0, "spec_rounds": 0,
                       "spec_proposed": 0, "spec_accepted": 0,
                       "spec_fallbacks": 0}
        # jitter source for transient-retry backoff — instance-owned so
        # tests can inject a seeded one; entropy-seeded by default so
        # replicas do not retry in lockstep against a shared backend
        # deliberate jitter for retry backoff — the one sanctioned
        # ambient-entropy source (determinism-soundness exempts it)
        self._retry_rng = entropy_rng()
        _engine.watch_races(self)
        if autostart:
            self.start()

    # ----------------------------------------------------------- lifecycle
    def start(self):
        setup = getattr(self.model, "setup", None)
        draft_setup = getattr(self.draft, "setup", None) \
            if self.spec_k else None
        with self._cond:
            if self._started:
                return self
            # restart after a stop(): the stop tore the adapter's
            # device pool down — bind it again before serving
            if setup is not None and not self._model_bound:
                setup(self.geometry)
                self._model_bound = True
            if draft_setup is not None and not self._draft_bound:
                draft_setup(self.draft_geometry)
                self._draft_bound = True
            self._started = True
            self._stopping = False
            self._thread = _engine.make_thread(
                self._loop, name=f"mxnet-decode-{self.model_name}",
                owner=f"DecodeEngine({self.model_name})")
        self._thread.start()
        return self

    def stop(self, timeout=None):
        """Stop the step loop and fail every outstanding request with
        ``finish_reason="stopped"``.  Returns True once the loop thread
        is down."""
        with self._cond:
            started, thread = self._started, self._thread
            self._stopping = True
            self._cond.notify_all()
        if started and thread is not None:
            thread.join(timeout)
            if thread.is_alive():
                return False
        with self._cond:
            outstanding = self._waiting + list(self._running.values())
            self._waiting = []
        for seq in outstanding:
            self._evict(seq, reason="stopped",
                        error=MXNetError(
                            "DecodeEngine stopped before this request "
                            "finished"))
        with self._cond:
            self._started = False
            self._thread = None
        # unbind the model adapter (drops its device KV pool) so a
        # later engine — this one restarted, or a fresh server — can
        # bind; only reached once the step loop is provably down.  The
        # prefix cache's page references go with it: a stopped engine
        # must not pin pool pages (check_leaks stays exact at teardown)
        teardown = getattr(self.model, "teardown", None)
        draft_teardown = getattr(self.draft, "teardown", None) \
            if self.spec_k else None
        with self._cond:
            if self.prefix_cache is not None:
                self.prefix_cache.clear()
            if teardown is not None and self._model_bound:
                teardown()
                self._model_bound = False
            if draft_teardown is not None and self._draft_bound:
                draft_teardown()
                self._draft_bound = False
        return True

    @property
    def started(self):
        return self._started

    # -------------------------------------------------------------- submit
    def submit(self, prompt, max_new_tokens=None, eos_id=None,
               on_token=None, timeout=None, _trace_ctx=_AMBIENT):
        """Queue one prompt for generation; returns the
        :class:`GenerateRequest` handle (``result()`` blocks on it).
        ``on_token(token_id)`` streams each generated id from the engine
        thread as it is sampled.

        ``timeout`` becomes the sequence's END-TO-END deadline: an
        expired waiting sequence is failed with
        :class:`~mxnet_tpu.serving.resilience.DeadlineExceededError`
        before it consumes a decode slot or KV pages, and an expired
        running sequence is evicted (pages reclaimed) on the step that
        observes the expiry.

        ``_trace_ctx`` (internal): the caller's already-decided trace
        context — a :class:`~mxnet_tpu.tracing.TraceContext`, or None
        for "the request was sampled out, stay on the no-op path".
        Left at the sentinel, the engine inspects the ambient context
        and roots its own trace (the directly-driven case)."""
        prompt = np.asarray(prompt).astype(np.int32).reshape(-1)
        if prompt.size < 1:
            raise MXNetError("generate: prompt must hold >= 1 token")
        if max_new_tokens is None:
            max_new_tokens = self.config.decode_max_new_tokens
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 1:
            raise MXNetError("generate: max_new_tokens must be >= 1")
        total = prompt.size + max_new_tokens
        if total > self.geometry.max_context:
            raise MXNetError(
                f"generate: prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) = {total} exceeds the model's "
                f"max_context ({self.geometry.max_context})")
        worst = self.geometry.pages_for(total)
        if worst > self.geometry.usable_pages:
            raise MXNetError(
                f"generate: request needs {worst} KV pages but the pool "
                f"only has {self.geometry.usable_pages} usable pages — "
                f"raise MXNET_SERVING_DECODE_POOL_PAGES or shorten the "
                f"request")
        if eos_id is None:
            eos_id = getattr(self.model, "eos_id", None)
        seq = GenerateRequest(prompt, max_new_tokens, eos_id, on_token,
                              deadline=Deadline.start(timeout))
        # trace identity: an explicit caller decision wins (the
        # ModelServer passes its root's context — None when that root
        # was sampled out, so the head-sampling call is made ONCE per
        # request); otherwise join the ambient trace, else root one
        # here so a directly-driven engine still records full
        # timelines.  The engine-owned root is ended at eviction, in
        # the step loop.
        if _tr._ENABLED:
            if _trace_ctx is not _AMBIENT:
                seq.trace = _trace_ctx
            else:
                ctx = _tr.current_context()
                if ctx is None:
                    root = _tr.trace("decode.request",
                                     model=self.model_name)
                    if root.sampled:
                        seq.root_span = root
                        ctx = root.context
                seq.trace = ctx
        admission = _tr.span("decode.admission", parent=seq.trace,
                             prompt_tokens=int(prompt.size),
                             max_new_tokens=max_new_tokens,
                             pages_reserved=worst)
        try:
            with self._cond:
                if not self._started or self._stopping:
                    raise MXNetError(
                        "DecodeEngine is not accepting requests (not "
                        "started, or stopping)")
                # the serving tier's backpressure contract applies to
                # the decode path too: a bounded waiting line and a
                # cheap reject with a retry hint, never an unbounded
                # queue
                if len(self._waiting) >= self.config.queue_depth:
                    from .server import ServerOverloadedError
                    self._stats["shed"] += 1
                    if _rm._ENABLED:
                        _rm.SERVING_SHED.inc(model=self.model_name)
                    admission.set_tag("shed", True)
                    raise ServerOverloadedError(
                        self.model_name, self.config.retry_after_ms,
                        f"decode waiting queue {len(self._waiting)} >= "
                        f"queue_depth {self.config.queue_depth}")
                self._waiting.append(seq)
                seq.queue_span = _tr.span(
                    "decode.queue_wait", parent=seq.trace,
                    waiting=len(self._waiting))
                self._cond.notify_all()
        except MXNetError as e:
            # flight recorder on overload; the not-accepting reject is
            # not an incident.  Runs after _cond is released.
            from .server import ServerOverloadedError
            if isinstance(e, ServerOverloadedError):
                _tr.record_incident("decode.shed", self.debug_state)
            # order matters on an engine-rooted trace: the admission
            # span (carrying the shed tag) must land BEFORE the root
            # ends and completes the trace — a straggler would be
            # dropped (the finally's end() is then an idempotent no-op)
            admission.end()
            if seq.root_span is not None:
                seq.root_span.end(error=type(e).__name__)
            raise
        finally:
            admission.end()
        return seq

    def result(self, seq, timeout=None):
        """Block until ``seq`` finishes; returns the generated ids as an
        int32 array.  On timeout — the tighter of this call's
        ``timeout`` and the sequence's submit-time deadline — the
        request is cancelled (its slot and pages are reclaimed on the
        next step) and
        :class:`~mxnet_tpu.serving.resilience.DeadlineExceededError`
        raises."""
        wait = Deadline.start(timeout)
        if seq.deadline.t is not None \
                and (wait.t is None or seq.deadline.t < wait.t):
            wait = seq.deadline
        if not seq.event.wait(wait.remaining()):
            with self._cond:
                seq.cancelled = True
                self._stats["deadline_exceeded"] += 1
                self._cond.notify_all()
            if _rm._ENABLED:
                _rm.SERVING_DEADLINE_EXCEEDED.inc(model=self.model_name)
            raise DeadlineExceededError(
                "generate", wait.timeout,
                f"{len(seq.tokens)} token(s) generated so far; the "
                f"sequence is cancelled and its pages reclaimed")
        if seq.error is not None:
            raise seq.error
        return np.asarray(seq.tokens, np.int32)

    def generate(self, prompt, max_new_tokens=None, eos_id=None,
                 on_token=None, timeout=None):
        """``submit`` + ``result`` in one call; ``timeout`` is the
        end-to-end deadline (see :meth:`submit`)."""
        return self.result(
            self.submit(prompt, max_new_tokens=max_new_tokens,
                        eos_id=eos_id, on_token=on_token,
                        timeout=timeout),
            timeout=timeout)

    # ---------------------------------------------------------- scheduling
    def _loop(self):
        while True:
            with self._cond:
                while not self._stopping and not self._waiting \
                        and not self._running:
                    # mxlint: disable=deadline-soundness (contract:
                    # idle park — no sequence is admitted, so there is
                    # no deadline to consume; every submit/stop
                    # notifies)
                    self._cond.wait()
                if self._stopping:
                    return
            try:
                self.step()
            except Exception as e:      # noqa: BLE001 — fail the batch
                # a model/compile failure must surface on the callers,
                # not kill the loop silently
                _LOG.warning("decode engine %s: step failed: %s",
                             self.model_name, e)
                with self._cond:
                    victims = self._waiting \
                        + list(self._running.values())
                    self._waiting = []
                for seq in victims:
                    self._evict(seq, reason="error", error=e)
                # an eviction storm (every in-flight sequence failed
                # at once) is exactly what the flight recorder is for
                _tr.record_incident(
                    f"decode.step_failure: {e}", self.debug_state)

    def step(self):
        """ONE scheduler iteration: admit -> prefill admitted -> one
        decode step for every running sequence -> evict finished.
        Returns the number of tokens generated this step.  The step
        loop is the only mutator of the slot map and the allocator;
        ``submit``/``stats`` only touch the waiting queue and read
        counters under the condition."""
        admitted = self._admit()
        produced = 0
        for seq in admitted:
            produced += self._prefill_one(seq)
        produced += self._decode_step()
        with self._cond:
            self._stats["steps"] += 1
            self._stats["generated_tokens"] += produced
            occupancy = self.allocator.occupancy
            shared = self.allocator.shared_pages
        if _rm._ENABLED:
            _rm.SERVING_DECODE_STEPS.inc(model=self.model_name)
            _rm.SERVING_DECODE_KV_OCCUPANCY.set(
                occupancy, engine=self.model_name)
            _rm.KV_SHARED_PAGES.set(shared, engine=self.model_name)
        return produced

    def _prefix_plan(self, seq):
        """Admission-time prefix-cache lookup — called OUTSIDE the
        engine condition (the fault site may sleep, and the radix walk
        is single-writer step-loop state anyway).  Returns
        ``(shared_pages, cow_src, hit_tokens, attempted)``; ANY lookup
        failure — including an injected ``decode.prefix_lookup``
        corruption — degrades to a miss, so the cache can cost a
        prefill but never produce wrong tokens."""
        cache = self.prefix_cache
        L = int(seq.prompt.size)
        ps = self.geometry.page_size
        if cache is None or seq.no_cache or L < ps:
            return [], None, 0, False
        try:
            _faults.inject(self.fault_scope + ".prefix_lookup")
            pages = cache.lookup(seq.prompt)
        except Exception as e:      # noqa: BLE001 — degrade to a miss
            _LOG.warning(
                "decode engine %s: prefix lookup failed for seq %d "
                "(%s); degrading to plain prefill", self.model_name,
                seq.seq_id, e)
            with self._cond:
                self._stats["prefix_degraded"] += 1
            return [], None, 0, True
        if not pages:
            return [], None, 0, True
        hit = len(pages) * ps
        if hit == L:
            # full hit: the sequence must append into the last matched
            # page (position L-1 is re-run to recover its logits) —
            # copy-on-write that one, alias the rest read-only
            return pages[:-1], pages[-1], hit, True
        return pages, None, hit, True

    def _admit(self):
        """Move waiting sequences into free decode slots while both a
        slot AND the sequence's worst-case page reservation fit
        (all-or-nothing, FIFO — a too-big head blocks the line rather
        than starving: pages freed by the next eviction admit it).
        With the prefix cache on, a cached prefix shrinks the fresh
        reservation to the unmatched pages (the shared ones are
        aliased), and cache-only pages are LRU-evicted on demand when
        the free list cannot cover an admission."""
        admitted, dropped, expired = [], [], []
        with self._cond:
            # prune cancelled AND deadline-expired entries ANYWHERE in
            # the line first — a timed-out caller must not keep
            # occupying bounded queue space just because the decode
            # batch happens to be full, and a dead request must never
            # consume a slot or KV pages
            live = []
            now = time.monotonic()
            for seq in self._waiting:
                if seq.cancelled:
                    dropped.append(seq)
                elif seq.deadline.expired(now):
                    expired.append(seq)
                else:
                    live.append(seq)
            self._waiting = live
            if expired:
                self._stats["deadline_exceeded"] += len(expired)
        while True:
            with self._cond:
                if not self._waiting or not self._free_slots:
                    break
                seq = self._waiting[0]
            # the lookup runs between the lock holds: the step loop is
            # the only consumer of the line, so the head is stable
            shared, cow_src, hit, attempted = self._prefix_plan(seq)
            with self._cond:
                if not self._waiting or self._waiting[0] is not seq \
                        or not self._free_slots:
                    break
                total = self.geometry.pages_for(
                    seq.prompt.size + seq.max_new_tokens)
                fresh = total - len(shared)
                if not self.allocator.can_allocate(fresh) \
                        and self.prefix_cache is not None:
                    # refcount-aware LRU: only pages the cache alone
                    # holds can free — and never the pages THIS
                    # admission planned to alias or COW-copy from
                    # (freeing them would strand a half-shared
                    # sequence and fail the whole step)
                    planned = set(shared)
                    if cow_src is not None:
                        planned.add(cow_src)
                    self.prefix_cache.evict(
                        fresh - self.allocator.free_pages,
                        protect_pages=planned)
                if not self.allocator.admit(seq.seq_id, shared, fresh):
                    if shared or cow_src is not None:
                        # the HIT plan is unservable under pool
                        # pressure (the protected planned pages may be
                        # the only evictable ones left): degrade to a
                        # miss — now everything cache-only may evict —
                        # rather than blocking the line on a plan the
                        # pool cannot afford
                        shared, cow_src, hit = [], None, 0
                        fresh = total
                        if not self.allocator.can_allocate(fresh):
                            self.prefix_cache.evict(
                                fresh - self.allocator.free_pages)
                        if not self.allocator.admit(seq.seq_id, [],
                                                    fresh):
                            break
                    else:
                        break
                seq.prefix_len = hit
                if cow_src is not None:
                    seq.cow = (cow_src, self.allocator.pages_of(
                        seq.seq_id)[len(shared)])
                # misses are counted here; a HIT is counted only once
                # the cached prefill actually serves (_prefill_cached)
                # — a demoted hit ran the full prefill and must not
                # inflate the hit ratio or the tokens-saved counter
                if attempted and not hit:
                    self._stats["prefix_misses"] += 1
                    if _rm._ENABLED:
                        _rm.SERVING_PREFIX_MISSES.inc(
                            model=self.model_name)
                self._waiting.pop(0)
                seq.slot = self._free_slots.pop()
                self._running[seq.slot] = seq
                self._stats["admitted"] += 1
                self._stats["peak_running"] = max(
                    self._stats["peak_running"], len(self._running))
                admitted.append(seq)
        for seq in admitted:
            # queue wait ends at slot assignment (cross-thread end:
            # the span was started in the submitter's thread)
            seq.queue_span.end(
                slot=seq.slot,
                kv_pages=len(self.allocator.pages_of(seq.seq_id)),
                kv_free_pages=self.allocator.free_pages)
        for seq in dropped:
            seq.queue_span.end(error="cancelled")
            self._finish(seq, "cancelled",
                         MXNetError("generate: request cancelled "
                                    "before admission"))
        for seq in expired:
            if _rm._ENABLED:
                _rm.SERVING_DEADLINE_EXCEEDED.inc(model=self.model_name)
            seq.queue_span.end(error="deadline")
            self._finish(seq, "deadline",
                         DeadlineExceededError(
                             "generate", seq.deadline.timeout,
                             "deadline expired while waiting — "
                             "cancelled before admission"))
        return admitted

    def _note_retry(self, attempt, exc):
        with self._cond:
            self._stats["retries"] += 1
        if _rm._ENABLED:
            _rm.SERVING_RETRIES.inc(model=self.model_name)
        _LOG.warning("decode engine %s: transient failure (retry "
                     "%d/%d): %s", self.model_name, attempt,
                     self.config.retry_max, exc)

    def _quarantine(self, seq, error, where):
        """Evict ONE poisoned sequence after its model call failed
        (post-retry, post-bisection): pages reclaimed through the
        release path the leak guards watch, batchmates keep decoding.
        """
        _LOG.warning("decode engine %s: quarantining seq %d after %s "
                     "failure: %s", self.model_name, seq.seq_id, where,
                     error)
        with self._cond:
            self._stats["quarantined"] += 1
        if _rm._ENABLED:
            _rm.SERVING_DECODE_QUARANTINED.inc(model=self.model_name)
        self._release(seq)
        self._finish(seq, "quarantined", error)
        _tr.record_incident(
            f"decode.quarantine: {where} failed for seq {seq.seq_id}: "
            f"{error}", self.debug_state)

    def _prefill_one(self, seq):
        """Run the (length-bucketed) prefill program for one admitted
        sequence and sample its first token — or, on a prefix-cache
        hit, skip the matched work via :meth:`_prefill_cached`.
        Transient failures retry with backoff; a persistent failure
        quarantines THIS sequence only (prefill is per-sequence, so no
        bisection is needed)."""
        if seq.prefix_len:
            return self._prefill_cached(seq)
        L = seq.prompt.size
        bucket = next_bucket(L, self.geometry.max_context)
        with _tr.span("decode.prefill", parent=seq.trace,
                      prompt_tokens=int(L), bucket=bucket,
                      kv_pages=len(self.allocator.pages_of(seq.seq_id))):
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :L] = seq.prompt

            def call():
                _faults.inject(self.fault_scope + ".prefill")
                return np.asarray(self.model.prefill(
                    tokens, np.int32(L),
                    self.allocator.block_table(seq.seq_id)))

            try:
                logits = retry_call(
                    call, retries=self.config.retry_max,
                    backoff_ms=self.config.retry_backoff_ms,
                    deadline=seq.deadline, rng=self._retry_rng,
                    on_retry=self._note_retry)
            except Exception as e:      # noqa: BLE001 — isolate it
                self._quarantine(seq, e, where="prefill")
                return 0
            seq.context_len = L
            seq.draft_ctx = L
            self._draft_prefill(seq, tokens, L)
            self._cache_insert(seq)
            self._emit(seq, int(np.argmax(logits)))
        self._maybe_evict(seq)
        return 1

    def _prefill_cached(self, seq):
        """Prefix-hit admission: copy-on-write the one page the
        sequence appends into, then recover the last-token logits
        through the VERIFY family — width 1 for a full hit (only the
        last prompt token is re-run), the tail bucket for a partial hit
        (unmatched tokens prefill while attending over the aliased
        cached pages).  Any failure here demotes the sequence to a
        plain prefill on the next step: the cache may cost time, never
        correctness."""
        L = int(seq.prompt.size)
        m = seq.prefix_len
        start = L - 1 if m == L else m
        tail = seq.prompt[start:]
        length = int(tail.size)
        bucket = next_bucket(length, self.geometry.max_context)
        with _tr.span("decode.prefill", parent=seq.trace,
                      prompt_tokens=int(L), bucket=bucket,
                      prefix_hit_tokens=int(m),
                      cow=seq.cow is not None,
                      kv_pages=len(self.allocator.pages_of(seq.seq_id))):
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :length] = tail
            block_table = self.allocator.block_table(seq.seq_id)

            def call():
                if seq.cow is not None:
                    # src is immutable, so re-copying on a retry is
                    # harmless — clear the plan only after both copies
                    # landed
                    src, dst = seq.cow
                    self.model.copy_page(src, dst)
                    if self.spec_k and not seq.no_spec:
                        self.draft.copy_page(src, dst)
                    seq.cow = None
                _faults.inject(self.fault_scope + ".prefill")
                return np.asarray(self.model.verify(
                    tokens, np.int32(start), np.int32(length),
                    block_table))

            try:
                logits = retry_call(
                    call, retries=self.config.retry_max,
                    backoff_ms=self.config.retry_backoff_ms,
                    deadline=seq.deadline, rng=self._retry_rng,
                    on_retry=self._note_retry)
            except Exception as e:      # noqa: BLE001 — degrade
                self._demote_to_plain(seq, e)
                return 0
            # the hit is real only now — the cached path SERVED.  A
            # full hit still re-ran its last token, so it saves m-1
            saved = m - 1 if m == L else m
            with self._cond:
                self._stats["prefix_hits"] += 1
                self._stats["prefix_tokens_saved"] += saved
            if _rm._ENABLED:
                _rm.SERVING_PREFIX_HITS.inc(model=self.model_name)
                _rm.SERVING_PREFIX_TOKENS_SAVED.inc(
                    saved, model=self.model_name)
            seq.context_len = L
            seq.draft_ctx = L
            if self.spec_k and not seq.no_spec:
                # the draft's K/V for the tail rides the same verify
                # shape (its logits are discarded); cached pages
                # already hold the draft K/V their writer produced
                try:
                    self.draft.verify(tokens, np.int32(start),
                                      np.int32(length), block_table)
                except Exception as e:  # noqa: BLE001 — optimization
                    self._spec_fallback(seq, e, where="draft tail")
            self._cache_insert(seq)
            self._emit(seq, int(np.argmax(logits[length - 1])))
        self._maybe_evict(seq)
        return 1

    def _draft_prefill(self, seq, tokens, L):
        """Write the prompt's DRAFT K/V (speculation needs the draft to
        know the prefix).  A draft failure never fails the request —
        the sequence just decodes plainly."""
        if not self.spec_k or seq.no_spec:
            return
        try:
            self.draft.prefill(tokens, np.int32(L),
                               self.allocator.block_table(seq.seq_id))
        except Exception as e:          # noqa: BLE001 — optimization
            self._spec_fallback(seq, e, where="draft prefill")

    def _spec_fallback(self, seq, error, where):
        _LOG.warning(
            "decode engine %s: %s failed for seq %d (%s); the "
            "sequence decodes without speculation", self.model_name,
            where, seq.seq_id, error)
        seq.no_spec = True
        with self._cond:
            self._stats["spec_fallbacks"] += 1

    def _cache_insert(self, seq):
        """Admit the prompt's full-page chunks into the prefix cache,
        backed by this sequence's (now fully written) pages.  Chunks
        that were aliased at admission are already cached and skip."""
        if self.prefix_cache is None or seq.no_cache:
            return
        with self._cond:
            self.prefix_cache.insert(
                seq.prompt, self.allocator.pages_of(seq.seq_id))

    def _demote_to_plain(self, seq, error):
        """Cached-path prefill failed: release everything the sequence
        holds (aliased refs and fresh pages alike) and put it back at
        the HEAD of the waiting line with the cache bypassed — the
        next step admits it down the plain-prefill path.  Degradation,
        not quarantine: the failure sits on the optimization path, so
        the model itself is not implicated."""
        _LOG.warning(
            "decode engine %s: cached prefill failed for seq %d (%s); "
            "demoting to plain prefill", self.model_name, seq.seq_id,
            error)
        with self._cond:
            self._stats["prefix_degraded"] += 1
            # undo the admission bookkeeping (it re-admits next step:
            # counting it twice would break admitted-evicted==running)
            self._stats["admitted"] -= 1
            if seq.slot is not None:
                self._running.pop(seq.slot, None)
                self._free_slots.append(seq.slot)
                seq.slot = None
            self.allocator.release(seq.seq_id)
            seq.prefix_len = 0
            seq.cow = None
            seq.no_cache = True
            self._waiting.insert(0, seq)
            self._cond.notify_all()

    def _decode_call(self, active):
        """One fixed-shape decode-step model call for the ``active``
        subset (inactive slots zeroed, exactly the padding contract the
        programs already honor).  Transient failures retry with
        backoff; a persistent failure BISECTS the subset so the
        poisoned sequence is quarantined alone and the rest of the
        batch keeps decoding.  Returns ``(seq, logits_row, t0, t1,
        batch_n)`` tuples for the sequences that got a token."""
        B, P = self.max_batch, self.geometry.pages_per_seq
        tokens = np.zeros((B,), np.int32)
        positions = np.zeros((B,), np.int32)
        block_tables = np.zeros((B, P), np.int32)
        for seq in active:
            # the slot's current token is the LAST sampled one — its
            # K/V is written at `positions` (== context so far) by the
            # decode program, which then attends over the full context
            tokens[seq.slot] = seq.tokens[-1]
            positions[seq.slot] = seq.context_len
            block_tables[seq.slot] = self.allocator.block_table(
                seq.seq_id)

        def call():
            _faults.inject(self.fault_scope + ".step")
            return np.asarray(self.model.decode_step(
                tokens, positions, block_tables))

        # retry backoff must not sleep past the TIGHTEST member
        # deadline: the single engine thread is every sequence's clock,
        # so one sleep drains every running budget at once
        times = [s.deadline.t for s in active if s.deadline.t is not None]
        group_deadline = Deadline(min(times)) if times else Deadline()
        t0 = time.perf_counter()
        try:
            logits = retry_call(
                call, retries=self.config.retry_max,
                backoff_ms=self.config.retry_backoff_ms,
                deadline=group_deadline,
                rng=self._retry_rng, on_retry=self._note_retry)
        except Exception as e:          # noqa: BLE001 — isolate it
            if len(active) == 1:
                self._quarantine(active[0], e, where="decode step")
                return []
            _LOG.warning("decode engine %s: step failed for %d "
                         "sequence(s) (%s); bisecting to quarantine "
                         "the poisoned sequence", self.model_name,
                         len(active), e)
            mid = len(active) // 2
            # re-running a subset re-writes the SAME K/V positions
            # (idempotent) — a failed call never advanced context_len
            return self._decode_call(active[:mid]) \
                + self._decode_call(active[mid:])
        t1 = time.perf_counter()
        return [(seq, logits[seq.slot], t0, t1, len(active))
                for seq in active]

    def _decode_step(self):
        """One decode round over every running sequence: speculative
        sequences (draft available, >= 2 tokens of budget left) go
        through :meth:`_spec_round`; everything else gets the plain
        bisection-aware batched decode step.  The two groups share the
        fixed-shape programs — each zeroes the other's slots."""
        with self._cond:
            running = [s for s in self._running.values()
                       if not s.cancelled]
            cancelled = [s for s in self._running.values()
                         if s.cancelled]
        for seq in cancelled:
            self._release(seq)
            self._finish(seq, "cancelled",
                         MXNetError("generate: request cancelled"))
        if not running:
            return 0
        # deterministic bisection order: slot order, not dict order
        running.sort(key=lambda s: s.slot)
        if not self.spec_k:
            return self._plain_decode(running)
        spec, plain = [], []
        for s in running:
            # a sequence one token from its cap gains nothing from a
            # proposal round (the verify bonus token finishes it), and
            # a draft-fallback sequence decodes plainly for good
            if not s.no_spec and s.max_new_tokens - len(s.tokens) >= 2:
                spec.append(s)
            else:
                plain.append(s)
        produced = 0
        if plain:
            produced += self._plain_decode(plain)
        if spec:
            produced += self._spec_round(spec)
        return produced

    def _plain_decode(self, running):
        """One non-speculative decode step for ``running`` (the
        original bisection-aware path)."""
        produced = 0
        for seq, row, t0, t1, batch_n in self._decode_call(running):
            # per-sequence decode-step spans (first step, then every
            # Nth): ONE device call serves the whole batch, so each due
            # sequence gets the shared interval with its own tags
            if seq.trace is not None:
                n_prior = len(seq.tokens)
                if n_prior == 1 or n_prior % _STEP_SPAN_EVERY == 0:
                    _tr.record_span(
                        "decode.step", seq.trace, t0, t1,
                        {"step": n_prior, "slot": seq.slot,
                         "context_len": seq.context_len,
                         "batch": batch_n,
                         "kv_pages": len(self.allocator.pages_of(
                             seq.seq_id))})
            seq.context_len += 1
            self._emit(seq, int(np.argmax(row)))
            produced += 1
            self._maybe_evict(seq)
        return produced

    def _spec_round(self, seqs):
        """One speculative round (docs/serving.md §9): the draft
        proposes up to ``spec_k`` tokens per sequence via batched draft
        decode steps over the SHARED block tables (writing its own
        pool), then the target judges each sequence's whole window —
        last sampled token + proposals — in ONE verify call, the
        ragged multi-token shape ``ragged_paged_verify`` exists for
        (one ``verify_batch`` program when the model has it, else one
        width-bucketed call per window).  Greedy acceptance is exact
        (the
        zero-temperature limit of rejection sampling): proposal i
        survives iff it equals the target argmax after position i, the
        first mismatch is replaced by the target's own token, and a
        fully accepted window earns the bonus token — so outputs are
        byte-identical with speculation on or off.  Rejected positions
        roll back through bookkeeping alone: their K/V sits beyond
        ``context_len``, is never attended, and is overwritten in
        place by later writes.

        Failure containment: a draft failure degrades the ROUND to one
        plain decode step (the draft is an optimization); a verify
        failure is a target-model failure and quarantines that
        sequence alone, like the prefill/decode paths (§8)."""
        k = self.spec_k
        B, P = self.max_batch, self.geometry.pages_per_seq
        tables = {s.seq_id: self.allocator.block_table(s.seq_id)
                  for s in seqs}
        plan = []
        for s in seqs:
            ctx = s.context_len
            # known tokens the draft consumes before free-running: the
            # catch-up gap (a fully-accepted previous round leaves the
            # last accepted proposal's draft K/V unwritten) + the last
            # sampled token
            feed = [s.token_at(p) for p in range(s.draft_ctx, ctx + 1)]
            m = min(k, s.max_new_tokens - len(s.tokens) - 1)
            plan.append({"seq": s, "feed": feed, "cur": feed.pop(0),
                         "pos": s.draft_ctx, "proposals": [],
                         "steps": m + len(feed)})
        max_steps = max(p["steps"] for p in plan)
        for st in range(max_steps):
            tokens = np.zeros((B,), np.int32)
            positions = np.zeros((B,), np.int32)
            block_tables = np.zeros((B, P), np.int32)
            active = [p for p in plan if st < p["steps"]]
            for p in active:
                slot = p["seq"].slot
                tokens[slot] = p["cur"]
                positions[slot] = p["pos"]
                block_tables[slot] = tables[p["seq"].seq_id]
            try:
                logits = np.asarray(self.draft.decode_step(
                    tokens, positions, block_tables))
            except Exception as e:  # noqa: BLE001 — draft died
                # proposals so far are unusable mid-round state; the
                # round degrades to ONE plain target step (correct by
                # construction) and the draft gets another chance next
                # round — partially written draft K/V beyond draft_ctx
                # is rolled back by never advancing the counter
                _LOG.warning(
                    "decode engine %s: draft step failed mid-round "
                    "(%s); running this round without speculation",
                    self.model_name, e)
                with self._cond:
                    self._stats["spec_fallbacks"] += len(seqs)
                return self._plain_decode(seqs)
            for p in active:
                out = int(np.argmax(logits[p["seq"].slot]))
                p["pos"] += 1
                if p["feed"]:
                    p["cur"] = p["feed"].pop(0)     # catch-up: discard
                else:
                    p["proposals"].append(out)
                    p["cur"] = out
        W = next_bucket(k + 1, self.geometry.max_context)
        if getattr(self.model, "verify_batch", None) is not None:
            judged = self._verify_batched(plan, tables, W)
        else:
            judged = self._verify_each(plan, tables, W)
        produced = 0
        for p, logits, t0, t1 in judged:
            seq = p["seq"]
            proposals = p["proposals"]
            ctx = seq.context_len
            # greedy-exact acceptance: row i of logits is the target's
            # next-token distribution after consuming window[i]
            accept = 0
            while accept < len(proposals) \
                    and proposals[accept] == int(np.argmax(logits[accept])):
                accept += 1
            emits = proposals[:accept] + [int(np.argmax(logits[accept]))]
            with self._cond:
                self._stats["spec_rounds"] += 1
                self._stats["spec_proposed"] += len(proposals)
                self._stats["spec_accepted"] += accept
            if _rm._ENABLED:
                _rm.SERVING_SPEC_PROPOSED.inc(len(proposals),
                                              model=self.model_name)
                _rm.SERVING_SPEC_ACCEPTED.inc(accept,
                                              model=self.model_name)
            # KV rollback of rejected positions = counter bookkeeping:
            # target context covers the accepted prefix + the emitted
            # correction/bonus token's predecessor; the draft rolls
            # back to the target's context when it speculated past it
            seq.context_len = ctx + accept + 1
            seq.draft_ctx = min(p["pos"], seq.context_len)
            if seq.trace is not None:
                n_prior = len(seq.tokens)
                if n_prior == 1 or n_prior % _STEP_SPAN_EVERY == 0:
                    _tr.record_span(
                        "decode.verify", seq.trace, t0, t1,
                        {"proposed": len(proposals),
                         "accepted": accept, "slot": seq.slot,
                         "context_len": seq.context_len})
            for t in emits:
                self._emit(seq, int(t))
                produced += 1
                if self._maybe_evict(seq):
                    break
        return produced

    def _verify_batched(self, entries, tables, W):
        """ONE fixed-shape verify call judging every entry's window at
        once (inactive slots zeroed — the padding contract of
        ``paged_verify_batch``).  Transient failures retry with
        backoff; a persistent failure BISECTS so the poisoned sequence
        is quarantined alone while its batchmates' windows are
        re-judged — the §8 containment applied to the verify family.
        Re-running a subset re-writes the SAME K/V positions
        (idempotent: a failed call never advanced context_len).
        Returns ``(entry, logits (W, V), t0, t1)`` tuples."""
        B, P = self.max_batch, self.geometry.pages_per_seq
        tokens = np.zeros((B, W), np.int32)
        starts = np.zeros((B,), np.int32)
        lengths = np.zeros((B,), np.int32)
        block_tables = np.zeros((B, P), np.int32)
        for p in entries:
            seq = p["seq"]
            window = [seq.tokens[-1]] + p["proposals"]
            tokens[seq.slot, :len(window)] = window
            starts[seq.slot] = seq.context_len
            lengths[seq.slot] = len(window)
            block_tables[seq.slot] = tables[seq.seq_id]

        def call():
            _faults.inject(self.fault_scope + ".verify")
            return np.asarray(self.model.verify_batch(
                tokens, starts, lengths, block_tables))

        times = [p["seq"].deadline.t for p in entries
                 if p["seq"].deadline.t is not None]
        group_deadline = Deadline(min(times)) if times else Deadline()
        t0 = time.perf_counter()
        try:
            logits = retry_call(
                call, retries=self.config.retry_max,
                backoff_ms=self.config.retry_backoff_ms,
                deadline=group_deadline, rng=self._retry_rng,
                on_retry=self._note_retry)
        except Exception as e:          # noqa: BLE001 — isolate it
            if len(entries) == 1:
                self._quarantine(entries[0]["seq"], e, where="verify")
                return []
            _LOG.warning(
                "decode engine %s: verify failed for %d window(s) "
                "(%s); bisecting to quarantine the poisoned sequence",
                self.model_name, len(entries), e)
            mid = len(entries) // 2
            return self._verify_batched(entries[:mid], tables, W) \
                + self._verify_batched(entries[mid:], tables, W)
        t1 = time.perf_counter()
        return [(p, logits[p["seq"].slot], t0, t1) for p in entries]

    def _verify_each(self, entries, tables, W):
        """Per-sequence verify fallback for models without
        ``verify_batch`` (fakes, external adapters): same judging, one
        width-W call per window; a persistent failure quarantines that
        sequence alone (already single, no bisection needed)."""
        out = []
        for p in entries:
            seq = p["seq"]
            window = [seq.tokens[-1]] + p["proposals"]
            tokens = np.zeros((1, W), np.int32)
            tokens[0, :len(window)] = window
            block_table = tables[seq.seq_id]
            length = len(window)

            def call():
                _faults.inject(self.fault_scope + ".verify")
                return np.asarray(self.model.verify(
                    tokens, np.int32(seq.context_len),
                    np.int32(length), block_table))

            t0 = time.perf_counter()
            try:
                logits = retry_call(
                    call, retries=self.config.retry_max,
                    backoff_ms=self.config.retry_backoff_ms,
                    deadline=seq.deadline, rng=self._retry_rng,
                    on_retry=self._note_retry)
            except Exception as e:      # noqa: BLE001 — isolate it
                self._quarantine(seq, e, where="verify")
                continue
            out.append((p, logits, t0, time.perf_counter()))
        return out

    # ----------------------------------------------------- token plumbing
    def _emit(self, seq, token):
        now = time.monotonic()
        if seq.t_first is None:
            seq.t_first = now
            if _rm._ENABLED:
                _rm.SERVING_DECODE_TTFT_SECONDS.observe(
                    now - seq.t_submit, model=self.model_name,
                    exemplar=None if seq.trace is None
                    else seq.trace.trace_id)
        elif _rm._ENABLED:
            _rm.SERVING_DECODE_TOKEN_SECONDS.observe(
                now - seq.t_prev, model=self.model_name)
        seq.t_prev = now
        seq.tokens.append(token)
        if _rm._ENABLED:
            _rm.SERVING_DECODE_TOKENS.inc(model=self.model_name)
        if seq.on_token is not None:
            try:
                seq.on_token(token)
            except Exception as e:      # noqa: BLE001 — caller's bug
                _LOG.warning("decode engine %s: on_token callback "
                             "failed: %s", self.model_name, e)

    def _maybe_evict(self, seq):
        """Finish checks after a sampled token; evicts when done.  A
        running sequence past its deadline evicts here (pages
        reclaimed) — a request never outlives its timeout inside the
        decode batch."""
        reason = error = None
        if seq.eos_id is not None and seq.tokens[-1] == seq.eos_id:
            reason = "eos"
        elif len(seq.tokens) >= seq.max_new_tokens:
            reason = "length"
        elif seq.cancelled:
            reason = "cancelled"
            error = MXNetError("generate: request cancelled")
        elif seq.deadline.expired():
            reason = "deadline"
            error = DeadlineExceededError(
                "generate", seq.deadline.timeout,
                f"deadline expired mid-generation after "
                f"{len(seq.tokens)} token(s); sequence evicted and "
                f"pages reclaimed")
            with self._cond:
                self._stats["deadline_exceeded"] += 1
            if _rm._ENABLED:
                _rm.SERVING_DEADLINE_EXCEEDED.inc(model=self.model_name)
        if reason is None:
            return False
        self._release(seq)
        self._finish(seq, reason, error)
        return True

    def _release(self, seq):
        """Return a running sequence's slot + pages.  The evictions
        counter moves here, not in ``_finish``: a request cancelled
        while still WAITING never held a slot or pages, so counting it
        would break ``admitted - evicted == running``."""
        with self._cond:
            if seq.slot is not None:
                self._running.pop(seq.slot, None)
                self._free_slots.append(seq.slot)
                seq.slot = None
                seq.released_pages = self.allocator.release(seq.seq_id)
                self._stats["evicted"] += 1
                if _rm._ENABLED:
                    _rm.SERVING_DECODE_EVICTIONS.inc(
                        model=self.model_name)
                self._cond.notify_all()

    def _finish(self, seq, reason, error=None):
        seq.finish_reason = reason
        if error is not None:
            seq.error = error
        if seq.trace is not None:
            now = time.perf_counter()
            _tr.record_span(
                "decode.evict", seq.trace, now, now,
                {"reason": reason,
                 "pages_released": seq.released_pages,
                 "generated_tokens": len(seq.tokens)})
            if seq.root_span is not None:
                # engine-rooted trace: the request span closes at
                # eviction (server-rooted ones close in the caller)
                seq.root_span.end(finish_reason=reason)
        seq.event.set()

    def _evict(self, seq, reason, error):
        """Out-of-band eviction (stop/step-failure): release whatever
        the sequence holds and fail it."""
        self._release(seq)
        seq.queue_span.end(error=reason)     # idempotent if admitted
        self._finish(seq, reason, error)

    # ---------------------------------------------------------------- info
    def stats(self):
        with self._cond:
            out = dict(self._stats)
            out["running"] = len(self._running)
            out["waiting"] = len(self._waiting)
            out.update(self.allocator.stats())
            if self.prefix_cache is not None:
                out.update(self.prefix_cache.stats())
        out["program_bound"] = self.program_bound
        out["spec_k"] = self.spec_k
        if out.get("spec_proposed"):
            out["spec_acceptance"] = (out["spec_accepted"]
                                      / out["spec_proposed"])
        programs = getattr(self.model, "programs", None)
        if programs is not None:
            total = programs()
            draft_programs = getattr(self.draft, "programs", None) \
                if self.spec_k else None
            if draft_programs is not None:
                total += draft_programs()
            out["programs"] = total
        return out

    def debug_state(self):
        """JSON-serializable scheduler snapshot for the flight
        recorder: per-sequence slot map with block-table occupancy,
        the waiting line, free slots/pages, and the counters
        (``ModelServer.debug_state`` aggregates one per engine)."""
        now = time.monotonic()
        with self._cond:
            running = [
                {"seq_id": s.seq_id, "slot": s.slot,
                 "context_len": s.context_len,
                 "generated_tokens": len(s.tokens),
                 "max_new_tokens": s.max_new_tokens,
                 "cancelled": s.cancelled,
                 "age_s": round(now - s.t_submit, 6),
                 "kv_pages": len(self.allocator.pages_of(s.seq_id)),
                 "trace_id": None if s.trace is None
                 else s.trace.trace_id}
                for s in self._running.values()]
            waiting = [
                {"seq_id": s.seq_id, "prompt_tokens": int(s.prompt.size),
                 "cancelled": s.cancelled,
                 "age_s": round(now - s.t_submit, 6)}
                for s in self._waiting]
            state = {
                "model": self.model_name,
                "started": self._started,
                "stopping": self._stopping,
                "max_batch": self.max_batch,
                "free_slots": len(self._free_slots),
                "running": running,
                "waiting": waiting,
                "allocator": self.allocator.stats(),
                "stats": dict(self._stats),
            }
            if self.prefix_cache is not None:
                state["prefix_cache"] = self.prefix_cache.stats()
        state["program_bound"] = self.program_bound
        state["spec_k"] = self.spec_k
        programs = getattr(self.model, "programs", None)
        if programs is not None:
            state["programs"] = programs()
            draft_programs = getattr(self.draft, "programs", None) \
                if self.spec_k else None
            if draft_programs is not None:
                state["draft_programs"] = draft_programs()
        return state


# ---------------------------------------------------------------------------
# model adapters
# ---------------------------------------------------------------------------
class PagedLMAdapter:
    """Decode-model protocol over a
    :class:`~mxnet_tpu.models.transformer_blocks.TransformerDecoderLM`.

    Owns the device KV pools and compiles the bounded program families
    from the LM's pure-jax decode-mode forwards (``paged_prefill`` /
    ``paged_decode_step`` / ``paged_verify``, plus the one COW
    page-copy program):

    - with the persistent compile cache configured, programs go through
      ``compile_cache.aot_program`` keyed on the ARCHITECTURE (weights
      are program inputs), so a warm restart deserializes instead of
      compiling;
    - otherwise one fresh ``jax.jit`` wrapper per family — the prefill
      wrapper's ``_cache_size()`` counts exactly the length buckets
      compiled, which is what the program-bound tests assert.

    Attention inside the decode step is the ragged-paged-attention
    Pallas kernel on TPU and its pure-jax reference elsewhere
    (``attention_impl`` overrides).
    """

    def __init__(self, lm, attention_impl=None, eos_id=None):
        import jax

        from ..models.transformer_blocks import paged_lm_params
        self.lm = lm
        self.vocab_size = lm.vocab_size
        self.max_context = lm.max_context
        self.num_layers = lm.num_layers
        self.num_heads = lm.num_heads
        self.head_dim = lm.head_dim
        if eos_id is not None:
            self.eos_id = int(eos_id)
        if attention_impl is None:
            attention_impl = ("pallas" if jax.default_backend() == "tpu"
                              else "jax")
        if attention_impl not in ("pallas", "jax"):
            raise MXNetError(
                f"PagedLMAdapter: attention_impl must be 'pallas' or "
                f"'jax', got {attention_impl!r}")
        self.attention_impl = attention_impl
        self.params = paged_lm_params(lm)
        self.pool = None
        self.compiled = 0               # programs built by XLA this process
        self.disk_hits = 0              # deserialized from the compile cache
        self._aot = {}                  # ("prefill", L) | ("decode",) -> prog

    def refresh(self):
        """Re-snapshot the LM's parameters (publish new weights).
        Compiled programs survive — weights are program inputs."""
        from ..models.transformer_blocks import paged_lm_params
        self.params = paged_lm_params(self.lm)

    def teardown(self):
        """Unbind from a stopped engine: drop the device pool (a
        retired engine must not pin KV HBM) so a later engine can
        bind.  Compiled-program caches survive for the rebind."""
        self.pool = None

    # ------------------------------------------------------------- programs
    def setup(self, geometry):
        import functools

        import jax

        from ..models.transformer_blocks import (paged_decode_step,
                                                 paged_prefill,
                                                 paged_verify,
                                                 paged_verify_batch)
        from .kv_cache import copy_page_arrays
        # one LIVE engine per adapter: the pool and program wrappers are
        # this adapter's state, and a second engine calling setup()
        # would zero the pool under the first one's feet (two servers
        # sharing one repository entry, or a construction race).  The
        # engine's stop() calls teardown(), so restart/hot-swap cycles
        # rebind cleanly.
        if self.pool is not None:
            raise MXNetError(
                "PagedLMAdapter: already bound to a live decode engine "
                "— one decoder entry serves ONE engine at a time; "
                "register a separate add_decoder entry per server")
        rebind = (getattr(self, "geometry", None) is not None
                  and self.geometry.page_size == geometry.page_size)
        self.geometry = geometry
        self.pool = DeviceKVPool(geometry)
        if rebind:
            # teardown() -> setup() cycle with the same page size: the
            # program wrappers' traced statics are unchanged, so the
            # compiled caches survive the rebind (zero recompiles on a
            # server restart within one process)
            return
        kw = dict(num_heads=self.num_heads, page_size=geometry.page_size,
                  activation=self.lm._activation,
                  layer_norm_eps=self.lm._eps)
        # donation lets XLA update the KV pools in place; the CPU
        # backend cannot honor it and would warn on every program
        cpu = jax.default_backend() == "cpu"
        donate = (4, 5) if not cpu else ()
        self._prefill_jit = jax.jit(
            functools.partial(paged_prefill, **kw),
            donate_argnums=donate)
        self._decode_jit = jax.jit(
            functools.partial(paged_decode_step,
                              attention_impl=self.attention_impl, **kw),
            donate_argnums=donate)
        # verify family (prefix-hit tails + speculative windows): the
        # pools sit at argument positions 5/6; the COW page copy is one
        # more (traced-scalar src/dst, so ONE program for every copy)
        self._verify_jit = jax.jit(
            functools.partial(paged_verify,
                              attention_impl=self.attention_impl, **kw),
            donate_argnums=(5, 6) if not cpu else ())
        self._verify_batch_jit = jax.jit(
            functools.partial(paged_verify_batch,
                              attention_impl=self.attention_impl, **kw),
            donate_argnums=(5, 6) if not cpu else ())
        self._copy_jit = jax.jit(
            copy_page_arrays,
            donate_argnums=(0, 1) if not cpu else ())

    def _cache(self):
        from .. import compile_cache as _cc
        cache = _cc.get_default()
        return cache if cache.enabled else None

    def _fingerprint(self, kind, rows):
        """Architecture-level program identity for the compile-cache
        key.  Weights are program INPUTS, so two checkpoints of one
        architecture share executables."""
        import hashlib

        import jax
        g = self.geometry
        desc = "\x1f".join([
            "mxnet_tpu.paged_lm/v1", kind, f"rows={rows}",
            f"layers={self.num_layers}", f"heads={self.num_heads}",
            f"units={self.lm.units}", f"vocab={self.vocab_size}",
            f"hidden={int(self.params['cells'][0]['f1_w'].shape[0])}",
            f"act={self.lm._activation}", f"eps={self.lm._eps!r}",
            f"max_pos={self.max_context}",
            f"page={g.page_size}", f"pool={g.pool_pages}",
            f"pps={g.pages_per_seq}", f"batch={rows}",
            f"impl={self.attention_impl}", jax.__version__,
        ])
        return hashlib.sha256(desc.encode()).hexdigest()

    def _avals(self, arrays):
        import jax
        return jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(np.shape(a),
                                           np.asarray(a).dtype
                                           if not hasattr(a, "dtype")
                                           else a.dtype), arrays)

    def _aot_for(self, kind, rows, fn, example_args):
        """Cache-through AOT program for one (kind, shape) — built once
        per process, deserialized from the persistent cache when it can
        be."""
        from .. import compile_cache as _cc
        key_id = (kind, rows)
        prog = self._aot.get(key_id)
        if prog is None:
            key = _cc.cache_key(self._fingerprint(kind, rows), rows,
                                ["float32", "int32"])
            prog, source = _cc.aot_program(fn, self._avals(example_args),
                                           key)
            if source == "disk":
                self.disk_hits += 1
            else:
                self.compiled += 1
            self._aot[key_id] = prog
        return prog

    def programs(self):
        """Compiled-program count across all families — prefill,
        decode, verify, and the COW page copy (the decode engine's
        ``programs <= program_bound`` acceptance check, via the jit
        ``_cache_size()`` helper)."""
        if self._aot:
            return len(self._aot)
        return (self._prefill_jit._cache_size()
                + self._decode_jit._cache_size()
                + self._verify_jit._cache_size()
                + self._verify_batch_jit._cache_size()
                + self._copy_jit._cache_size())

    # ------------------------------------------------------------ protocol
    def prefill(self, tokens, length, block_table):
        pool = self.pool
        args = (self.params, tokens, length, block_table,
                pool.k_pages, pool.v_pages)
        if self._cache() is not None:
            prog = self._aot_for("prefill", tokens.shape[1],
                                 self._prefill_jit, args)
        else:
            prog = self._prefill_jit
        # device-call child of the engine's decode.prefill span (no-op
        # without an ambient span): separates program dispatch from the
        # scheduler's host-side framing
        with _tr.span("paged_lm.prefill", bucket=int(tokens.shape[1])):
            logits, k_pages, v_pages = prog(*args)
        pool.swap(k_pages, v_pages)
        return logits

    def decode_step(self, tokens, positions, block_tables):
        pool = self.pool
        args = (self.params, tokens, positions, block_tables,
                pool.k_pages, pool.v_pages)
        if self._cache() is not None:
            prog = self._aot_for("decode", tokens.shape[0],
                                 self._decode_jit, args)
        else:
            prog = self._decode_jit
        # no adapter-level span here: the step loop calls this with no
        # ambient span (ONE device call serves many traces) and records
        # the timed interval per due sequence as decode.step instead
        logits, k_pages, v_pages = prog(*args)
        pool.swap(k_pages, v_pages)
        return logits

    def verify(self, tokens, start, length, block_table):
        """Multi-token window forward (speculation verify / prefix-hit
        tail): writes the window's K/V through the block table and
        returns per-row logits (rows past ``length`` are garbage the
        engine never reads).  One program per width bucket."""
        pool = self.pool
        args = (self.params, tokens, start, length, block_table,
                pool.k_pages, pool.v_pages)
        if self._cache() is not None:
            prog = self._aot_for("verify", tokens.shape[1],
                                 self._verify_jit, args)
        else:
            prog = self._verify_jit
        with _tr.span("paged_lm.verify", bucket=int(tokens.shape[1])):
            logits, k_pages, v_pages = prog(*args)
        pool.swap(k_pages, v_pages)
        return logits

    def verify_batch(self, tokens, starts, lengths, block_tables):
        """Batched verify: every running sequence's speculation window
        judged in ONE fixed-shape device call (B and W are both
        static, so this is ONE program)."""
        pool = self.pool
        args = (self.params, tokens, starts, lengths, block_tables,
                pool.k_pages, pool.v_pages)
        if self._cache() is not None:
            prog = self._aot_for(f"verify_batch_w{tokens.shape[1]}",
                                 tokens.shape[0],
                                 self._verify_batch_jit, args)
        else:
            prog = self._verify_batch_jit
        logits, k_pages, v_pages = prog(*args)
        pool.swap(k_pages, v_pages)
        return logits

    def copy_page(self, src, dst):
        """Copy-on-write page duplication across all layers of both
        pools — ONE compiled program (``src``/``dst`` are traced
        scalars)."""
        pool = self.pool
        args = (pool.k_pages, pool.v_pages, np.int32(src),
                np.int32(dst))
        if self._cache() is not None:
            prog = self._aot_for("cow", 1, self._copy_jit, args)
        else:
            prog = self._copy_jit
        pool.swap(*prog(*args))


def as_decode_model(obj, attention_impl=None, eos_id=None):
    """Normalize what ``ModelRepository.add_decoder`` accepted into the
    decode-model protocol: objects already implementing
    ``prefill``/``decode_step`` pass through (fake/cheap test models);
    a :class:`TransformerDecoderLM` is wrapped in
    :class:`PagedLMAdapter`."""
    if hasattr(obj, "prefill") and hasattr(obj, "decode_step"):
        return obj
    from ..models.transformer_blocks import TransformerDecoderLM
    if isinstance(obj, TransformerDecoderLM):
        return PagedLMAdapter(obj, attention_impl=attention_impl,
                              eos_id=eos_id)
    raise MXNetError(
        f"as_decode_model: {type(obj).__name__} neither implements the "
        f"decode-model protocol (prefill/decode_step) nor is a "
        f"TransformerDecoderLM")
