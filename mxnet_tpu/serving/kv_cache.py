"""Paged KV cache for the decode engine (docs/serving.md §6, §9).

The KV cache of an autoregressive batch is ragged — every sequence has
a different length, and lengths grow every step.  A contiguous
per-sequence (max_len) slab wastes HBM on short sequences and
fragments on long ones; the paged layout ("Ragged Paged Attention",
PAPERS.md / vLLM's PagedAttention) instead preallocates ONE device
pool of fixed-size pages and gives each sequence a *block table* of
page indices, so long and short sequences share the pool with zero
fragmentation and page granularity waste only.

Four pieces, split by where the state lives:

- :class:`PageGeometry` — the shared layout constants (page size, pool
  pages, per-sequence table width, model dims).  Everything that must
  agree between the allocator, the device pool, and the compiled
  programs derives from here, so it cannot drift.
- :class:`PageAllocator` — HOST-side free-list bookkeeping: page
  alloc/free per sequence, block-table materialization, occupancy.
  Page 0 is reserved as the *null page*: block-table entries past a
  sequence's allocation point at it, and padded/inactive batch slots
  write their garbage K/V into it — so compiled programs never need a
  "valid" mask on the write path.  Pages are REFCOUNTED: the null-page
  aliasing trick generalized — a full, immutable prefix page can back
  many block tables at once (prefix caching, docs/serving.md §9), and
  a page returns to the free list only when its last reference drops.
- :class:`PrefixCache` — a radix tree over page-size token-id chunks
  mapping cached prompt prefixes to the (refcounted, immutable) pages
  that hold their K/V, with refcount-aware LRU eviction.  A request
  whose prefix is cached aliases those pages instead of re-running
  prefill.
- :class:`DeviceKVPool` — the preallocated DEVICE arrays, one K and one
  V pool of shape (layers, pool_pages, page_size, heads, head_dim).
  Compiled decode programs take the pools as (donated) inputs and
  return the updated arrays; :meth:`DeviceKVPool.swap` rebinds them.
  :meth:`DeviceKVPool.copy_page` is the copy-on-write primitive: the
  one shared page a new sequence must append into is duplicated into a
  private page (ONE compiled program for all copies).

The allocator is deliberately strict: freeing a page twice, freeing a
page that is not allocated, or releasing an unknown sequence raises
``MXNetError`` — the decode scheduler's invariants (admit/evict every
step) are enforced here rather than trusted.
"""
from __future__ import annotations

import itertools

from .. import engine
from .. import faults as _faults
from ..base import MXNetError

__all__ = ["PageGeometry", "PageAllocator", "PrefixCache",
           "DeviceKVPool"]


class PageGeometry:
    """Layout constants shared by the allocator, the device pool, and
    the compiled decode programs.

    - ``page_size``: tokens per KV page.
    - ``pool_pages``: TOTAL pages in the device pool, including the
      reserved null page 0 (``usable_pages`` = pool_pages - 1).
    - ``max_context``: longest context a sequence may reach (prompt +
      generated); ``pages_per_seq`` block-table slots cover it.
    - ``num_layers`` / ``num_heads`` / ``head_dim``: the model dims the
      pool arrays are shaped with.
    """

    def __init__(self, page_size, pool_pages, max_context, num_layers,
                 num_heads, head_dim):
        if page_size < 1:
            raise MXNetError("PageGeometry: page_size must be >= 1")
        if pool_pages < 2:
            raise MXNetError(
                "PageGeometry: pool_pages must be >= 2 (page 0 is the "
                "reserved null page)")
        if max_context < 1:
            raise MXNetError("PageGeometry: max_context must be >= 1")
        self.page_size = int(page_size)
        self.pool_pages = int(pool_pages)
        self.max_context = int(max_context)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.pages_per_seq = -(-self.max_context // self.page_size)

    @property
    def usable_pages(self):
        return self.pool_pages - 1

    def pages_for(self, tokens):
        """Pages needed to hold ``tokens`` tokens of context."""
        if tokens < 0:
            raise MXNetError(f"pages_for: negative token count {tokens}")
        return -(-tokens // self.page_size)

    def kv_bytes(self, dtype_size=4):
        """Device bytes of ONE pool array (K or V)."""
        return (self.num_layers * self.pool_pages * self.page_size
                * self.num_heads * self.head_dim * dtype_size)

    def __repr__(self):
        return (f"PageGeometry(page_size={self.page_size}, "
                f"pool_pages={self.pool_pages}, "
                f"max_context={self.max_context}, "
                f"pages_per_seq={self.pages_per_seq}, "
                f"layers={self.num_layers}, heads={self.num_heads}, "
                f"head_dim={self.head_dim})")


class PageAllocator:
    """Refcounted free-list page allocator with per-sequence block
    tables.

    Thread-safe: every mutator and :meth:`stats` holds the internal
    ``_lock``, so a server thread releasing a cancelled sequence cannot
    tear the free list under the decode loop's admission.  The lock
    nests INSIDE the decode engine's condition (``_cond`` ->
    ``PageAllocator._lock``, never the reverse), and it is
    non-reentrant — nested work goes through ``_locked``-suffixed
    helpers.  All-or-nothing
    semantics: an allocation that cannot be fully satisfied changes
    nothing and returns False, so a half-admitted sequence can never
    strand pages.

    Every in-use page carries a reference count: 1 for a privately
    owned page, +1 per additional sequence aliasing it (:meth:`share` /
    :meth:`admit`), +1 when the :class:`PrefixCache` holds it
    (:meth:`retain_cached`).  :meth:`release` decrements; a page
    returns to the free list only at refcount zero, so a cached prefix
    page survives its writer's eviction and a shared page survives all
    but its last reader.
    """

    def __init__(self, geometry):
        self.geometry = geometry
        # LIFO free list: a just-freed page is reused first, which keeps
        # the working set of hot pages small and makes block-table reuse
        # after eviction directly observable (tests assert it)
        self._free = list(range(geometry.pool_pages - 1, 0, -1))
        self._pages = {}                # seq_id -> [page, ...]
        self._refs = {}                 # page -> reference count (>= 1)
        self._cached = {}               # page -> PrefixCache-held refs
        self.peak_used = 0
        # guards every mutator (and stats()); acquired AFTER the decode
        # engine's condition when both are held.  engine.make_lock is a
        # plain non-reentrant Lock, hence the _locked helper split.
        self._lock = engine.make_lock("serving.PageAllocator._lock")
        engine.watch_races(self)

    # ------------------------------------------------------------ queries
    @property
    def free_pages(self):
        return len(self._free)

    @property
    def used_pages(self):
        return self.geometry.usable_pages - len(self._free)

    @property
    def occupancy(self):
        """Used fraction of the usable pool (0.0 - 1.0)."""
        return self.used_pages / max(1, self.geometry.usable_pages)

    @property
    def shared_pages(self):
        """Pages referenced more than once (actively shared between
        sequences, or between a sequence and the prefix cache)."""
        return sum(1 for n in self._refs.values() if n > 1)

    @property
    def cached_pages(self):
        """Pages the prefix cache holds a reference on."""
        return len(self._cached)

    def refcount(self, page):
        return self._refs.get(page, 0)

    def cache_only(self, page):
        """True when the prefix cache holds the ONLY references to
        ``page`` — the refcount-aware LRU eviction predicate."""
        return self._refs.get(page, 0) == self._cached.get(page, 0) > 0

    def pages_of(self, seq_id):
        return list(self._pages.get(seq_id, ()))

    def can_allocate(self, n_pages):
        return n_pages <= len(self._free)

    # ---------------------------------------------------------- mutation
    def allocate(self, seq_id, n_pages):
        """Grow ``seq_id``'s allocation by ``n_pages`` pages (first call
        creates it).  Returns True, or False (state unchanged) when the
        free list cannot cover the request."""
        if n_pages < 0:
            raise MXNetError(f"allocate({seq_id!r}): negative page "
                             f"count {n_pages}")
        # chaos site: injected pool exhaustion — reported the way real
        # exhaustion is (refusal, state unchanged), so the admission/
        # deadline path downstream is what gets proven
        if n_pages and _faults.check("kv_cache.allocate"):
            return False
        with self._lock:
            owned = self._pages.setdefault(seq_id, [])
            if len(owned) + n_pages > self.geometry.pages_per_seq:
                raise MXNetError(
                    f"allocate({seq_id!r}): {len(owned)} + {n_pages} "
                    f"pages exceed the block table "
                    f"({self.geometry.pages_per_seq} slots = "
                    f"max_context {self.geometry.max_context} / "
                    f"page_size {self.geometry.page_size})")
            if n_pages > len(self._free):
                if not owned:
                    del self._pages[seq_id]
                return False
            for _ in range(n_pages):
                page = self._free.pop()
                owned.append(page)
                self._refs[page] = 1
            self.peak_used = max(self.peak_used, self.used_pages)
            return True

    def share(self, seq_id, pages):
        """Alias already-referenced ``pages`` into ``seq_id``'s block
        table (in logical order, BEFORE any privately allocated pages).
        The sequence must not re-alias a page it already references.
        Raises on an unreferenced or out-of-range page — sharing hands
        out read-only views, never resurrects a freed page."""
        with self._lock:
            return self._share_locked(seq_id, pages)

    def _share_locked(self, seq_id, pages):
        # mxlint: disable=lock-discipline (contract: callers hold
        # self._lock — share() and admit() both acquire it; the lock
        # is non-reentrant, hence this unlocked helper)
        owned = self._pages.setdefault(seq_id, [])
        if len(owned) + len(pages) > self.geometry.pages_per_seq:
            raise MXNetError(
                f"share({seq_id!r}): {len(owned)} + {len(pages)} pages "
                f"exceed the block table "
                f"({self.geometry.pages_per_seq} slots)")
        for p in pages:
            if self._refs.get(p, 0) < 1 \
                    or not 1 <= p < self.geometry.pool_pages:
                raise MXNetError(
                    f"share({seq_id!r}): page {p} is free or out of "
                    f"range — only live pages can be aliased")
            if p in owned:
                raise MXNetError(
                    f"share({seq_id!r}): page {p} already in this "
                    f"sequence's block table")
            owned.append(p)
            # mxlint: disable=lock-discipline (caller holds self._lock)
            self._refs[p] += 1
        return True

    def admit(self, seq_id, shared_pages, fresh_pages):
        """All-or-nothing admission of one sequence: alias
        ``shared_pages`` (prefix-cache hit) then allocate
        ``fresh_pages`` private pages behind them.  Returns True, or
        False (state unchanged) when the free list cannot cover the
        private part — the same refusal contract as :meth:`allocate`,
        so the scheduler's FIFO head-blocking logic needs no new case.
        """
        with self._lock:
            if seq_id in self._pages:
                raise MXNetError(
                    f"admit({seq_id!r}): sequence already admitted")
            # mirror allocate()'s chaos site BEFORE any mutation so an
            # injected exhaustion is indistinguishable from a real one
            # (faults.check never raises or blocks, so holding _lock
            # across it is safe)
            if fresh_pages and _faults.check("kv_cache.allocate"):
                return False
            if fresh_pages > len(self._free):
                return False
            if len(shared_pages) + fresh_pages \
                    > self.geometry.pages_per_seq:
                raise MXNetError(
                    f"admit({seq_id!r}): {len(shared_pages)} shared + "
                    f"{fresh_pages} fresh pages exceed the block table "
                    f"({self.geometry.pages_per_seq} slots)")
            if shared_pages:
                self._share_locked(seq_id, shared_pages)
            owned = self._pages.setdefault(seq_id, [])
            for _ in range(fresh_pages):
                page = self._free.pop()
                owned.append(page)
                self._refs[page] = 1
            self.peak_used = max(self.peak_used, self.used_pages)
            return True

    def retain_cached(self, page):
        """The prefix cache takes one reference on a live page (the
        page outlives the sequence that wrote it)."""
        with self._lock:
            if self._refs.get(page, 0) < 1 \
                    or not 1 <= page < self.geometry.pool_pages:
                raise MXNetError(
                    f"retain_cached: page {page} is free or out of "
                    f"range — only live pages can be cached")
            self._refs[page] += 1
            self._cached[page] = self._cached.get(page, 0) + 1

    def release_cached(self, page):
        """The prefix cache drops its reference on ``page`` (eviction);
        the page returns to the free list when nothing else holds it."""
        with self._lock:
            if self._cached.get(page, 0) < 1:
                raise MXNetError(
                    f"release_cached: page {page} is not cache-held — "
                    f"double eviction, or never retained")
            self._cached[page] -= 1
            if not self._cached[page]:
                del self._cached[page]
            self._decref(page, f"release_cached({page})")

    def _decref(self, page, where):
        # caller holds self._lock (non-reentrant, so no lock here):
        # release(), release_cached() both acquire it lexically
        refs = self._refs.get(page, 0)
        if refs < 1 or not 1 <= page < self.geometry.pool_pages:
            raise MXNetError(
                f"{where}: page {page} is already free or out of "
                f"range — allocator state corrupted")
        if refs == 1:
            # mxlint: disable=lock-discipline (caller holds self._lock)
            del self._refs[page]
            # mxlint: disable=lock-discipline (caller holds self._lock)
            self._free.append(page)
        else:
            # mxlint: disable=lock-discipline (caller holds self._lock)
            self._refs[page] = refs - 1

    def release(self, seq_id):
        """Drop every reference ``seq_id`` holds; a page returns to the
        free list when its LAST reference drops.  Raises on an unknown
        sequence or a corrupted (double-freed / duplicated) page — the
        leak/double-free guard the scheduler tests lean on."""
        with self._lock:
            pages = self._pages.pop(seq_id, None)
            if pages is None:
                raise MXNetError(
                    f"release({seq_id!r}): unknown sequence (double "
                    f"release, or never admitted)")
            free = set(self._free)
            for p in pages:
                if p in free:
                    raise MXNetError(
                        f"release({seq_id!r}): page {p} is already "
                        f"free — allocator state corrupted")
                self._decref(p, f"release({seq_id!r})")
            return len(pages)

    def block_table(self, seq_id):
        """The (pages_per_seq,) int32 block table of ``seq_id`` —
        allocated pages first, null page 0 in every unused slot (what
        the compiled programs and the attention kernel consume)."""
        import numpy as np
        table = np.zeros((self.geometry.pages_per_seq,), np.int32)
        pages = self._pages.get(seq_id, ())
        table[:len(pages)] = pages
        return table

    def check_leaks(self):
        """Assert the pool is fully accounted for — EXACT under shared
        pages: every usable page is either in the free list or carries
        a refcount equal to the number of block-table slots plus
        cache-held references that point at it, with the free list and
        the referenced set disjoint.  Cheap enough to run every test
        step; returns the live (distinct referenced) page count."""
        owners = {}                     # page -> reference count seen
        for pages in self._pages.values():
            for p in pages:
                owners[p] = owners.get(p, 0) + 1
        for p, n in self._cached.items():
            owners[p] = owners.get(p, 0) + n
        free = set(self._free)
        if len(free) != len(self._free):
            raise MXNetError("free list holds duplicate pages")
        overlap = free.intersection(owners)
        if overlap:
            raise MXNetError(
                f"pages {sorted(overlap)} are both free and referenced")
        if owners != self._refs:
            drift = {p: (owners.get(p), self._refs.get(p))
                     for p in set(owners) | set(self._refs)
                     if owners.get(p) != self._refs.get(p)}
            raise MXNetError(
                f"refcount drift (page: owners vs refs): {drift}")
        total = len(free) + len(owners)
        if total != self.geometry.usable_pages:
            raise MXNetError(
                f"page leak: {len(owners)} referenced + {len(free)} "
                f"free != {self.geometry.usable_pages} usable pages")
        return len(owners)

    def stats(self):
        with self._lock:        # one consistent snapshot
            return {"used_pages": self.used_pages,
                    "free_pages": self.free_pages,
                    "peak_used_pages": self.peak_used,
                    "occupancy": self.occupancy,
                    "shared_pages": self.shared_pages,
                    "cached_pages": self.cached_pages,
                    "sequences": len(self._pages)}


class _PrefixNode:
    """One full-page chunk of a cached prefix: the radix-tree edge is
    the chunk's token-id content (exact content hash — the raw bytes of
    the page's token ids key the child map), the node owns one
    cache-held reference on the physical page holding that chunk's
    K/V."""

    __slots__ = ("key", "page", "children", "parent", "tick")

    def __init__(self, key, page, parent):
        self.key = key                  # bytes of the chunk's token ids
        self.page = page                # physical page id
        self.children = {}              # chunk bytes -> _PrefixNode
        self.parent = parent            # _PrefixNode or the root dict
        self.tick = 0                   # LRU clock at last touch


class PrefixCache:
    """Radix tree over page-size token-id chunks -> immutable KV pages
    (docs/serving.md §9).

    Sharing granularity is one FULL page: a prompt's full-page chunks
    are content-addressed (the chunk's token ids, byte-exact) down the
    tree, and a hit hands back the pages whose K/V a previous sequence
    already wrote — the admitting request aliases them (refcounted in
    the :class:`PageAllocator`) instead of re-running prefill.  Cached
    pages are IMMUTABLE by construction: a full prompt page is never
    rewritten after prefill (generated tokens land in later pages), and
    the one page a full-length hit must append into is copy-on-write
    duplicated first (:meth:`DeviceKVPool.copy_page`).

    Eviction is refcount-aware LRU over LEAF nodes only (an inner
    node's page is part of every descendant's prefix): a leaf whose
    page has live sequence references is skipped, everything else frees
    in least-recently-touched order.  ``max_pages`` caps cache-held
    pages; the decode engine additionally evicts on demand when the
    free list cannot cover an admission.

    Single-writer like the allocator: only the engine's step loop
    mutates it.
    """

    def __init__(self, allocator, max_pages=None):
        self.allocator = allocator
        self.page_size = allocator.geometry.page_size
        self.max_pages = int(max_pages) if max_pages else None
        self._root = {}                 # chunk bytes -> _PrefixNode
        self._ticks = itertools.count(1)
        self._nodes = 0
        self.evicted_pages = 0

    # ------------------------------------------------------------ queries
    @property
    def pages(self):
        return self._nodes              # one page per node, by invariant

    def _chunks(self, prompt):
        """The full page-size chunks of ``prompt`` as content keys."""
        import numpy as np
        ids = np.asarray(prompt, np.int32).reshape(-1)
        ps = self.page_size
        return [ids[i * ps:(i + 1) * ps].tobytes()
                for i in range(ids.size // ps)]

    def lookup(self, prompt):
        """Longest cached prefix of ``prompt``: the physical pages of
        every matched full-page chunk, in logical order (empty = miss).
        Touches the matched path's LRU clocks."""
        pages, children = [], self._root
        tick = next(self._ticks)
        for key in self._chunks(prompt):
            node = children.get(key)
            if node is None:
                break
            node.tick = tick
            pages.append(node.page)
            children = node.children
        return pages

    def insert(self, prompt, seq_pages):
        """Admit ``prompt``'s full-page chunks, backed by the admitting
        sequence's pages (``seq_pages`` in logical order — the cache
        takes one reference per newly inserted page).  Chunks already
        cached are skipped (the sequence aliased those very pages at
        admission, or wrote a duplicate it keeps privately).  Returns
        the number of pages newly cached."""
        added, children, parent = 0, self._root, None
        tick = next(self._ticks)
        for i, key in enumerate(self._chunks(prompt)):
            node = children.get(key)
            if node is None:
                if self.max_pages is not None \
                        and self._nodes >= self.max_pages \
                        and not self.evict(1, protect=parent):
                    break               # full of live pages — stop here
                page = seq_pages[i]
                self.allocator.retain_cached(page)
                node = _PrefixNode(key, page, parent)
                children[key] = node
                self._nodes += 1
                added += 1
            node.tick = tick
            children, parent = node.children, node
        return added

    def _leaves(self):
        out, stack = [], list(self._root.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            else:
                out.append(node)
        return out

    def evict(self, n_pages, protect=None, protect_pages=None):
        """Free at least ``n_pages`` cache-held pages (refcount-aware
        LRU, leaves first — evicting a leaf may expose its parent as
        the next candidate).  Nodes on the path ending at ``protect``
        are exempt (an in-progress insert must not evict its own
        ancestors), as are nodes holding any page in ``protect_pages``
        (a pending admission must not have the very pages it planned
        to alias freed under it).  Returns the number of pages
        actually freed."""
        keep = set()
        node = protect
        while isinstance(node, _PrefixNode):
            keep.add(id(node))
            node = node.parent
        pinned = set(protect_pages or ())
        freed = 0
        while freed < n_pages:
            candidates = [
                leaf for leaf in self._leaves()
                if id(leaf) not in keep
                and leaf.page not in pinned
                and self.allocator.cache_only(leaf.page)]
            if not candidates:
                break
            leaf = min(candidates, key=lambda n: n.tick)
            siblings = leaf.parent.children \
                if isinstance(leaf.parent, _PrefixNode) else self._root
            del siblings[leaf.key]
            self.allocator.release_cached(leaf.page)
            self._nodes -= 1
            freed += 1
            self.evicted_pages += 1
        return freed

    def clear(self):
        """Drop every cached page (engine stop: the cache must not pin
        pool pages past its engine's life)."""
        stack = list(self._root.values())
        self._root = {}
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            self.allocator.release_cached(node.page)
            self._nodes -= 1

    def stats(self):
        # hit/miss/tokens-saved counters live with the decode engine
        # (its step loop is the only lookup caller); these are the
        # tree-structure numbers only
        return {"prefix_nodes": self._nodes,
                "prefix_pages": self.pages,
                "prefix_evicted_pages": self.evicted_pages}


class DeviceKVPool:
    """The preallocated device-side page pools.

    One K and one V array of shape
    ``(num_layers, pool_pages, page_size, num_heads, head_dim)``,
    allocated ONCE at engine start.  Compiled prefill/decode programs
    take both as inputs (donated, so XLA updates them in place) and
    return the new arrays; :meth:`swap` rebinds after each step.  Page 0
    is the null page — writes routed there (padded prefill tail,
    inactive decode slots) land in memory nothing ever attends to.
    """

    def __init__(self, geometry, dtype=None):
        import jax
        import jax.numpy as jnp
        self.geometry = geometry
        self.dtype = dtype or jnp.float32
        g = geometry
        shape = (g.num_layers, g.pool_pages, g.page_size, g.num_heads,
                 g.head_dim)
        # device_put COMMITS the arrays: compiled steps return committed
        # outputs, and a jit cache keys on placement — an uncommitted
        # initial pool would make the very first call of each program
        # family compile twice (once for each placement)
        dev = jax.devices()[0]
        self.k_pages = jax.device_put(jnp.zeros(shape, self.dtype), dev)
        self.v_pages = jax.device_put(jnp.zeros(shape, self.dtype), dev)

    def swap(self, k_pages, v_pages):
        """Adopt the pool arrays a compiled step returned (the donated
        buffers' successors)."""
        self.k_pages = k_pages
        self.v_pages = v_pages

    def copy_page(self, src, dst, prog=None):
        """Copy-on-write: duplicate page ``src`` into ``dst`` across
        all layers of both pools (the one shared prefix page a new
        sequence must append into becomes private).  ``prog`` is the
        caller's compiled :func:`copy_page_arrays` (the adapter routes
        it through its program cache so COW is ONE program); without
        one the copy runs eagerly (tests)."""
        import numpy as np
        fn = prog if prog is not None else copy_page_arrays
        self.k_pages, self.v_pages = fn(
            self.k_pages, self.v_pages,
            np.int32(src), np.int32(dst))

    @property
    def nbytes(self):
        return int(self.k_pages.nbytes) + int(self.v_pages.nbytes)


def copy_page_arrays(k_pages, v_pages, src, dst):
    """Pure-jnp page duplication (jit-safe; ``src``/``dst`` are traced
    scalars, so ONE compiled program serves every copy-on-write)."""
    return (k_pages.at[:, dst].set(k_pages[:, src]),
            v_pages.at[:, dst].set(v_pages[:, src]))
