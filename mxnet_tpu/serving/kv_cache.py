"""Paged KV cache for the decode engine (docs/serving.md §6).

The KV cache of an autoregressive batch is ragged — every sequence has
a different length, and lengths grow every step.  A contiguous
per-sequence (max_len) slab wastes HBM on short sequences and
fragments on long ones; the paged layout ("Ragged Paged Attention",
PAPERS.md / vLLM's PagedAttention) instead preallocates ONE device
pool of fixed-size pages and gives each sequence a *block table* of
page indices, so long and short sequences share the pool with zero
fragmentation and page granularity waste only.

Three pieces, split by where the state lives:

- :class:`PageGeometry` — the shared layout constants (page size, pool
  pages, per-sequence table width, model dims).  Everything that must
  agree between the allocator, the device pool, and the compiled
  programs derives from here, so it cannot drift.
- :class:`PageAllocator` — HOST-side free-list bookkeeping: page
  alloc/free per sequence, block-table materialization, occupancy.
  Page 0 is reserved as the *null page*: block-table entries past a
  sequence's allocation point at it, and padded/inactive batch slots
  write their garbage K/V into it — so compiled programs never need a
  "valid" mask on the write path.
- :class:`DeviceKVPool` — the preallocated DEVICE arrays, one K and one
  V pool of shape (layers, pool_pages, page_size, heads, head_dim).
  Compiled decode programs take the pools as (donated) inputs and
  return the updated arrays; :meth:`DeviceKVPool.swap` rebinds them.

The allocator is deliberately strict: freeing a page twice, freeing a
page that is not allocated, or releasing an unknown sequence raises
``MXNetError`` — the decode scheduler's invariants (admit/evict every
step) are enforced here rather than trusted.
"""
from __future__ import annotations

from .. import faults as _faults
from ..base import MXNetError

__all__ = ["PageGeometry", "PageAllocator", "DeviceKVPool"]


class PageGeometry:
    """Layout constants shared by the allocator, the device pool, and
    the compiled decode programs.

    - ``page_size``: tokens per KV page.
    - ``pool_pages``: TOTAL pages in the device pool, including the
      reserved null page 0 (``usable_pages`` = pool_pages - 1).
    - ``max_context``: longest context a sequence may reach (prompt +
      generated); ``pages_per_seq`` block-table slots cover it.
    - ``num_layers`` / ``num_heads`` / ``head_dim``: the model dims the
      pool arrays are shaped with.
    """

    def __init__(self, page_size, pool_pages, max_context, num_layers,
                 num_heads, head_dim):
        if page_size < 1:
            raise MXNetError("PageGeometry: page_size must be >= 1")
        if pool_pages < 2:
            raise MXNetError(
                "PageGeometry: pool_pages must be >= 2 (page 0 is the "
                "reserved null page)")
        if max_context < 1:
            raise MXNetError("PageGeometry: max_context must be >= 1")
        self.page_size = int(page_size)
        self.pool_pages = int(pool_pages)
        self.max_context = int(max_context)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.pages_per_seq = -(-self.max_context // self.page_size)

    @property
    def usable_pages(self):
        return self.pool_pages - 1

    def pages_for(self, tokens):
        """Pages needed to hold ``tokens`` tokens of context."""
        if tokens < 0:
            raise MXNetError(f"pages_for: negative token count {tokens}")
        return -(-tokens // self.page_size)

    def kv_bytes(self, dtype_size=4):
        """Device bytes of ONE pool array (K or V)."""
        return (self.num_layers * self.pool_pages * self.page_size
                * self.num_heads * self.head_dim * dtype_size)

    def __repr__(self):
        return (f"PageGeometry(page_size={self.page_size}, "
                f"pool_pages={self.pool_pages}, "
                f"max_context={self.max_context}, "
                f"pages_per_seq={self.pages_per_seq}, "
                f"layers={self.num_layers}, heads={self.num_heads}, "
                f"head_dim={self.head_dim})")


class PageAllocator:
    """Free-list page allocator with per-sequence block tables.

    NOT thread-safe by itself — the decode engine mutates it only from
    its step loop (one writer); readers go through :meth:`stats`, which
    callers take under the engine's condition.  All-or-nothing
    semantics: an allocation that cannot be fully satisfied changes
    nothing and returns False, so a half-admitted sequence can never
    strand pages.
    """

    def __init__(self, geometry):
        self.geometry = geometry
        # LIFO free list: a just-freed page is reused first, which keeps
        # the working set of hot pages small and makes block-table reuse
        # after eviction directly observable (tests assert it)
        self._free = list(range(geometry.pool_pages - 1, 0, -1))
        self._pages = {}                # seq_id -> [page, ...]
        self.peak_used = 0

    # ------------------------------------------------------------ queries
    @property
    def free_pages(self):
        return len(self._free)

    @property
    def used_pages(self):
        return self.geometry.usable_pages - len(self._free)

    @property
    def occupancy(self):
        """Used fraction of the usable pool (0.0 - 1.0)."""
        return self.used_pages / max(1, self.geometry.usable_pages)

    def pages_of(self, seq_id):
        return list(self._pages.get(seq_id, ()))

    def can_allocate(self, n_pages):
        return n_pages <= len(self._free)

    # ---------------------------------------------------------- mutation
    def allocate(self, seq_id, n_pages):
        """Grow ``seq_id``'s allocation by ``n_pages`` pages (first call
        creates it).  Returns True, or False (state unchanged) when the
        free list cannot cover the request."""
        if n_pages < 0:
            raise MXNetError(f"allocate({seq_id!r}): negative page "
                             f"count {n_pages}")
        # chaos site: injected pool exhaustion — reported the way real
        # exhaustion is (refusal, state unchanged), so the admission/
        # deadline path downstream is what gets proven
        if n_pages and _faults.check("kv_cache.allocate"):
            return False
        owned = self._pages.setdefault(seq_id, [])
        if len(owned) + n_pages > self.geometry.pages_per_seq:
            raise MXNetError(
                f"allocate({seq_id!r}): {len(owned)} + {n_pages} pages "
                f"exceed the block table "
                f"({self.geometry.pages_per_seq} slots = max_context "
                f"{self.geometry.max_context} / page_size "
                f"{self.geometry.page_size})")
        if n_pages > len(self._free):
            if not owned:
                del self._pages[seq_id]
            return False
        for _ in range(n_pages):
            owned.append(self._free.pop())
        self.peak_used = max(self.peak_used, self.used_pages)
        return True

    def release(self, seq_id):
        """Return every page of ``seq_id`` to the free list.  Raises on
        an unknown sequence or a corrupted (double-freed / duplicated)
        page — the leak/double-free guard the scheduler tests lean on."""
        pages = self._pages.pop(seq_id, None)
        if pages is None:
            raise MXNetError(
                f"release({seq_id!r}): unknown sequence (double "
                f"release, or never admitted)")
        free = set(self._free)
        for p in pages:
            if p in free or not 1 <= p < self.geometry.pool_pages:
                raise MXNetError(
                    f"release({seq_id!r}): page {p} is already free or "
                    f"out of range — allocator state corrupted")
            free.add(p)
            self._free.append(p)
        return len(pages)

    def block_table(self, seq_id):
        """The (pages_per_seq,) int32 block table of ``seq_id`` —
        allocated pages first, null page 0 in every unused slot (what
        the compiled programs and the attention kernel consume)."""
        import numpy as np
        table = np.zeros((self.geometry.pages_per_seq,), np.int32)
        pages = self._pages.get(seq_id, ())
        table[:len(pages)] = pages
        return table

    def check_leaks(self):
        """Assert the pool is fully accounted for: every usable page is
        exactly once in the free list or in exactly one block table.
        Cheap enough to run every test step; returns the live page
        count."""
        seen = {}
        for sid, pages in self._pages.items():
            for p in pages:
                if p in seen:
                    raise MXNetError(
                        f"page {p} owned by both {seen[p]!r} and "
                        f"{sid!r}")
                seen[p] = sid
        free = set(self._free)
        if len(free) != len(self._free):
            raise MXNetError("free list holds duplicate pages")
        overlap = free.intersection(seen)
        if overlap:
            raise MXNetError(
                f"pages {sorted(overlap)} are both free and allocated")
        total = len(free) + len(seen)
        if total != self.geometry.usable_pages:
            raise MXNetError(
                f"page leak: {len(seen)} allocated + {len(free)} free "
                f"!= {self.geometry.usable_pages} usable pages")
        return len(seen)

    def stats(self):
        return {"used_pages": self.used_pages,
                "free_pages": self.free_pages,
                "peak_used_pages": self.peak_used,
                "occupancy": self.occupancy,
                "sequences": len(self._pages)}


class DeviceKVPool:
    """The preallocated device-side page pools.

    One K and one V array of shape
    ``(num_layers, pool_pages, page_size, num_heads, head_dim)``,
    allocated ONCE at engine start.  Compiled prefill/decode programs
    take both as inputs (donated, so XLA updates them in place) and
    return the new arrays; :meth:`swap` rebinds after each step.  Page 0
    is the null page — writes routed there (padded prefill tail,
    inactive decode slots) land in memory nothing ever attends to.
    """

    def __init__(self, geometry, dtype=None):
        import jax
        import jax.numpy as jnp
        self.geometry = geometry
        self.dtype = dtype or jnp.float32
        g = geometry
        shape = (g.num_layers, g.pool_pages, g.page_size, g.num_heads,
                 g.head_dim)
        # device_put COMMITS the arrays: compiled steps return committed
        # outputs, and a jit cache keys on placement — an uncommitted
        # initial pool would make the very first call of each program
        # family compile twice (once for each placement)
        dev = jax.devices()[0]
        self.k_pages = jax.device_put(jnp.zeros(shape, self.dtype), dev)
        self.v_pages = jax.device_put(jnp.zeros(shape, self.dtype), dev)

    def swap(self, k_pages, v_pages):
        """Adopt the pool arrays a compiled step returned (the donated
        buffers' successors)."""
        self.k_pages = k_pages
        self.v_pages = v_pages

    @property
    def nbytes(self):
        return int(self.k_pages.nbytes) + int(self.v_pages.nbytes)
