"""Resilience primitives for the serving tier (docs/serving.md §8):
deadlines, bounded retries, and per-model-version circuit breakers.

The serving stack's failure philosophy: a caller sees **bounded latency
or a typed, fast failure — never a hang**.  Three pieces enforce it:

- :class:`Deadline` — a request's ``timeout`` becomes an absolute
  monotonic deadline carried through admission -> queue -> batch
  assembly -> execute, so every layer can answer "is this request
  already dead?" without re-deriving budgets.  An expired request is
  cancelled *before* it consumes a batch slot and fails with
  :class:`DeadlineExceededError` instead of hanging.
- :func:`retry_call` — bounded retries with jittered exponential
  backoff for TRANSIENT failures only (``exc.transient`` truthy — the
  marker :class:`~mxnet_tpu.faults.InjectedFault` and real device
  blips carry).  Deterministic errors (shape mismatch, poisoned input)
  fail immediately; retrying them would just triple the latency of a
  guaranteed failure.
- :class:`CircuitBreaker` — per model version, a sliding window of the
  last N request outcomes.  When the window is full and its error rate
  reaches the threshold the circuit OPENs: admissions shed instantly
  with a retry-after hint (no queueing behind a known-bad version).
  After a cooldown one HALF_OPEN probe is admitted; success re-CLOSEs,
  failure re-OPENs.  The state machine is the standard
  closed/open/half-open design production serving meshes use to stop
  retry storms against a dead backend.

:class:`ServerOverloadedError` lives here (re-exported by
``serving.server`` for compatibility) so :class:`CircuitOpenError` can
subclass it without an import cycle — to a caller, an open circuit IS
an overload: back off and retry later.
"""
from __future__ import annotations

import time
from collections import deque

from .. import engine, runtime_metrics as _rm, tracing as _tr
from ..base import MXNetError, entropy_rng

__all__ = ["Deadline", "DeadlineExceededError", "ServerOverloadedError",
           "CircuitOpenError", "CircuitBreaker", "is_transient",
           "retry_call", "honor_retry_after"]


class ServerOverloadedError(MXNetError):
    """Request shed by the backpressure bounds.  ``retry_after_ms`` is
    the server's backoff hint (an HTTP frontend maps this to 429 +
    Retry-After); the message names which bound actually tripped so
    operators tune the right knob."""

    def __init__(self, model, retry_after_ms, reason):
        self.model = model
        self.retry_after_ms = retry_after_ms
        super().__init__(
            f"server overloaded: {reason} for model {model!r}; "
            f"retry after {retry_after_ms}ms")


class DeadlineExceededError(MXNetError):
    """The request's end-to-end deadline expired — in the queue, inside
    a coalesced batch, or mid-generation.  Replaces the silent hang: a
    caller that set ``timeout`` gets this error within ~one scheduling
    quantum of the deadline, and the server stops spending device time
    on the corpse."""

    def __init__(self, where, timeout, detail=""):
        self.timeout = timeout
        suffix = f" ({detail})" if detail else ""
        super().__init__(
            f"{where}: no result within {timeout}s deadline{suffix}")


class CircuitOpenError(ServerOverloadedError):
    """Admission refused because the model version's circuit is OPEN
    (error rate over the sliding window tripped the breaker).  Carries
    the standard overload retry-after contract: back off, then retry —
    by then the breaker is probing or closed again."""

    def __init__(self, model, retry_after_ms, reason):
        super().__init__(model, retry_after_ms, reason)


class Deadline:
    """Absolute monotonic deadline (or no deadline at all).

    ``Deadline.start(timeout)`` converts a caller-relative ``timeout``
    into the absolute point every later layer compares against —
    computed ONCE at admission so queue wait, batch formation, retries,
    and execute all drain the same budget.
    """

    __slots__ = ("t", "timeout")

    def __init__(self, t=None, timeout=None):
        self.t = t                      # monotonic instant, or None
        self.timeout = timeout          # original relative budget (s)

    @classmethod
    def start(cls, timeout):
        if timeout is None:
            return cls()
        timeout = float(timeout)
        return cls(time.monotonic() + timeout, timeout)

    @property
    def unset(self):
        return self.t is None

    def expired(self, now=None):
        return self.t is not None \
            and (time.monotonic() if now is None else now) >= self.t

    def remaining(self, now=None):
        """Seconds left (never negative), or None when unbounded —
        shaped for ``Event.wait(remaining)``."""
        if self.t is None:
            return None
        return max(0.0,
                   self.t - (time.monotonic() if now is None else now))


# ---------------------------------------------------------------------------
# retries
# ---------------------------------------------------------------------------
def is_transient(exc):
    """Whether the retry policy may re-execute after ``exc``.  The
    contract is an explicit opt-in marker (``exc.transient`` truthy —
    :class:`~mxnet_tpu.faults.InjectedFault` sets it): retrying an
    arbitrary exception re-runs a failure that will deterministically
    recur and doubles down on a poisoned request."""
    return bool(getattr(exc, "transient", False))


def retry_call(fn, *, retries, backoff_ms, deadline=None, rng=None,
               on_retry=None):
    """Run ``fn()`` with up to ``retries`` re-executions of TRANSIENT
    failures, sleeping a jittered exponential backoff between attempts
    (``backoff_ms * 2^attempt * U[0.5, 1.0)``).  A deadline that cannot
    cover the next backoff stops retrying — better to surface the real
    error than burn the caller's remaining budget sleeping."""
    # deliberate nondeterminism, via the one sanctioned source: the
    # jitter must differ across processes or the retry waves sync up
    # (mxlint determinism-soundness exempts entropy_rng)
    rng = rng or entropy_rng()
    attempt = 0
    while True:
        try:
            return fn()
        except Exception as e:      # noqa: BLE001 — policy filter below
            if attempt >= retries or not is_transient(e):
                raise
            delay = (backoff_ms / 1e3) * (2 ** attempt) \
                * (0.5 + rng.random() / 2.0)
            if deadline is not None and deadline.t is not None \
                    and deadline.remaining() <= delay:
                raise
            attempt += 1
            if on_retry is not None:
                on_retry(attempt, e)
            if delay > 0:
                time.sleep(delay)


def honor_retry_after(fn, *, attempts=4, deadline=None, rng=None,
                      on_backoff=None):
    """Client-side twin of the server's ``retry_after_ms`` hint: run
    ``fn()``, and on :class:`ServerOverloadedError` (including
    :class:`CircuitOpenError`) sleep the server's hint **scaled by a
    jitter factor of U[1.0, 1.5)** before retrying, up to ``attempts``
    re-executions.

    The jitter is the point.  A shed storm hits every closed-loop
    client at once; clients that all sleep exactly ``retry_after_ms``
    come back as one synchronized wave and shed again — the hint alone
    *causes* the retry storm it exists to prevent.  Multiplicative
    jitter spreads the wave, and honoring the server's hint (instead of
    a client-invented backoff) keeps the retry rate matched to what the
    server said it can absorb.

    ``deadline`` (a :class:`Deadline`) bounds the whole loop: a sleep
    that cannot fit in the remaining budget re-raises the overload
    error instead of burning the budget asleep.  ``on_backoff(attempt,
    delay_s, exc)`` observes each sleep (bench/client metrics).  Errors
    other than the overload family propagate immediately — this helper
    honors backpressure; it is not a general retry policy
    (:func:`retry_call` is).
    """
    rng = rng or entropy_rng()   # sanctioned jitter source — see retry_call
    attempt = 0
    while True:
        try:
            return fn()
        except ServerOverloadedError as e:
            if attempt >= attempts:
                raise
            delay = (max(0, e.retry_after_ms) / 1e3) \
                * (1.0 + rng.random() / 2.0)
            if deadline is not None and deadline.t is not None \
                    and deadline.remaining() <= delay:
                raise
            attempt += 1
            if on_backoff is not None:
                on_backoff(attempt, delay, e)
            if delay > 0:
                time.sleep(delay)


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------
CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
_STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """Per-model-version error-rate breaker.

    - CLOSED: admit everything; record outcomes into a sliding window
      of the last ``window`` requests.  Once the window is FULL and
      ``errors / window >= threshold``, trip to OPEN (the full-window
      requirement doubles as the min-samples guard — a single early
      failure cannot trip a cold breaker).
    - OPEN: shed instantly with :class:`CircuitOpenError` carrying the
      remaining cooldown as ``retry_after_ms``; after ``cooldown_ms``
      the next admission becomes the HALF_OPEN probe.
    - HALF_OPEN: exactly one probe request is in flight; concurrent
      admissions shed.  Probe success -> CLOSED (window cleared),
      probe failure -> OPEN for another cooldown.

    ``consecutive`` (0 = off) adds a second, faster trip rule on top of
    the windowed error rate: N consecutive failures open the circuit
    even before the window fills.  The replica layer (docs/serving.md
    §10) uses it as its dead-replica detector — a replica that fails
    every request since some instant is *down*, and waiting for a
    20-outcome window to fill against a corpse just queues more
    casualties.  A single success resets the run.

    ``window <= 0`` disables the windowed error-rate rule; the breaker
    as a whole (admit/record no-ops) is off only when ``consecutive``
    is ALSO 0 — a replica layer running with the windowed breaker
    disabled still needs its dead-replica fast trip.  Outcome
    recording is the caller's job and should count EXECUTE outcomes
    only — sheds, deadline expiries, and validation rejects say
    nothing about the model version's health.
    """

    def __init__(self, window, threshold, cooldown_ms, model="?",
                 version=None, consecutive=0):
        self.window = int(window)
        self.threshold = float(threshold)
        self.cooldown_ms = float(cooldown_ms)
        self.consecutive = int(consecutive or 0)
        self.model = model
        self.version = version
        self._lock = engine.make_lock("serving.CircuitBreaker._lock")
        self._outcomes = deque(maxlen=max(1, self.window))
        self._consec_failures = 0       # current run of failures
        self._state = CLOSED
        self._opened_at = None          # monotonic of last trip
        self._probing = False
        self._probe_started = None      # monotonic of probe admission
        self._stats = {"opened": 0, "closed": 0, "rejected": 0,
                       "probes": 0}

    # ------------------------------------------------------------- gauges
    def _publish(self):
        # mxlint: disable=lock-discipline (contract: callers hold
        # self._lock; the metric has its own lock)
        if _rm._ENABLED:
            _rm.SERVING_CIRCUIT_STATE.set(
                _STATE_CODE[self._state], model=self.model,
                version=str(self.version))

    @property
    def state(self):
        with self._lock:
            return self._state

    @property
    def _disabled(self):
        # mxlint: disable=lock-discipline (reads two immutable ints)
        return self.window <= 0 and self.consecutive <= 0

    # ---------------------------------------------------------- admission
    def admit(self):
        """Gate one admission.  Raises :class:`CircuitOpenError` when
        OPEN (or while the half-open probe is outstanding); returns
        True when this admission IS the probe (the caller must report
        its outcome via :meth:`record` or the breaker stays stuck in
        HALF_OPEN — record() is called for every execute outcome, so
        the existing bookkeeping covers it)."""
        if self._disabled:
            return False
        with self._lock:
            if self._state == CLOSED:
                return False
            now = time.monotonic()
            if self._state == OPEN:
                elapsed_ms = (now - self._opened_at) * 1e3
                if elapsed_ms < self.cooldown_ms:
                    self._stats["rejected"] += 1
                    retry_ms = max(1, int(self.cooldown_ms - elapsed_ms))
                    raise CircuitOpenError(
                        self.model, retry_ms,
                        f"circuit open ({self._state_reason()})")
                # cooldown over: this admission becomes the probe
                self._state = HALF_OPEN
                self._probing = True
                self._probe_started = now
                self._stats["probes"] += 1
                self._publish()
                return True
            # HALF_OPEN: one probe only — but a probe whose outcome
            # never came back (shed by the queue watermark, expired
            # before execute) must not wedge the breaker forever; after
            # one cooldown it is considered abandoned and the next
            # admission takes over as the probe
            if self._probing and (now - self._probe_started) * 1e3 \
                    < max(1.0, self.cooldown_ms):
                self._stats["rejected"] += 1
                raise CircuitOpenError(
                    self.model, max(1, int(self.cooldown_ms)),
                    "circuit half-open (probe in flight)")
            self._probing = True
            self._probe_started = now
            self._stats["probes"] += 1
            return True

    def _state_reason(self):
        # mxlint: disable=lock-discipline (contract: callers hold
        # self._lock)
        errs = sum(1 for ok in self._outcomes if not ok)
        return (f"{errs}/{len(self._outcomes)} recent requests failed "
                f">= threshold {self.threshold:.0%} for model "
                f"{self.model!r}:{self.version}")

    def record(self, ok):
        """Record one EXECUTE outcome.  Returns the state after the
        update so callers can fire incident dumps on a trip without
        re-locking."""
        if self._disabled:
            return CLOSED
        tripped = False
        with self._lock:
            if self._state == HALF_OPEN and self._probing:
                self._probing = False
                if ok:
                    self._state = CLOSED
                    self._outcomes.clear()
                    self._consec_failures = 0
                    self._stats["closed"] += 1
                else:
                    self._state = OPEN
                    self._opened_at = time.monotonic()
                    self._stats["opened"] += 1
                    tripped = True
                self._publish()
                state = self._state
            elif self._state == CLOSED:
                self._outcomes.append(bool(ok))
                self._consec_failures = 0 if ok \
                    else self._consec_failures + 1
                trip = False
                if len(self._outcomes) == self.window:
                    errs = sum(1 for o in self._outcomes if not o)
                    trip = errs / self.window >= self.threshold
                # the fast dead-backend rule: N-in-a-row failures open
                # the circuit without waiting for the window to fill
                if self.consecutive \
                        and self._consec_failures >= self.consecutive:
                    trip = True
                if trip:
                    self._state = OPEN
                    self._opened_at = time.monotonic()
                    self._stats["opened"] += 1
                    tripped = True
                    self._publish()
                state = self._state
            else:
                # OPEN: a straggler from before the trip — ignore
                state = self._state
        if tripped:
            # flight recorder outside the lock: a breaker trip is an
            # incident worth a dump (debounced inside record_incident)
            _tr.record_incident(
                f"serving.circuit_open: {self.model}:{self.version}",
                self.debug_state)
        return state

    # ------------------------------------------------------------ readers
    def debug_state(self):
        with self._lock:
            return {"model": self.model, "version": self.version,
                    "state": self._state, "window": self.window,
                    "threshold": self.threshold,
                    "cooldown_ms": self.cooldown_ms,
                    "consecutive": self.consecutive,
                    "consec_failures": self._consec_failures,
                    "recent_errors": sum(
                        1 for ok in self._outcomes if not ok),
                    "recent": len(self._outcomes),
                    "probing": self._probing,
                    "stats": dict(self._stats)}

    def __repr__(self):
        return (f"CircuitBreaker({self.model}:{self.version}, "
                f"state={self.state}, window={self.window}, "
                f"threshold={self.threshold})")
