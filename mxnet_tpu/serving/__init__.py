"""Inference serving subsystem (docs/serving.md).

The reference line served non-Python consumers through the predict-only
C ABI and MXNet Model Server; here the deployment boundary is the
StableHLO artifact (``deploy.export_stablehlo``) and this package is
the missing serving tier over it:

- :class:`ModelRepository` — versioned artifacts/blocks, atomic
  hot-swap;
- :class:`DynamicBatcher` — shape-bucketed batch coalescing with a
  per-bucket compiled-program cache (O(log N) programs for N request
  shapes);
- :class:`ModelServer` — bounded queues, worker pool, load shedding
  (:class:`ServerOverloadedError` + retry-after), graceful drain, and
  ``prewarm()`` (compile/load every bucket BEFORE a hot-swap admits
  traffic — with the persistent compile cache
  (``mxnet_tpu.compile_cache``, ``MXNET_COMPILE_CACHE_DIR``) a warm
  restart compiles zero new XLA programs);
- first-class ``runtime_metrics`` instrumentation (queue depth, batch
  occupancy, per-model latency, shed counter, bucket-cache
  mem/disk/miss counter — ``docs/observability.md``);
- :class:`DecodeEngine` — autoregressive ``generate()`` with
  token-level continuous batching over a paged KV cache
  (:mod:`~mxnet_tpu.serving.kv_cache`): admit/evict sequences every
  STEP, prompt-length-bucketed prefill + one fixed-shape decode
  program (ragged paged attention, ``ops/pallas_kernels.py``), and
  streaming token callbacks (docs/serving.md §6) — plus the two
  composable decode optimizations of docs/serving.md §9:
  copy-on-write prefix caching (:class:`PrefixCache` radix tree over
  refcounted KV pages; a cached prompt prefix skips its prefill) and
  speculative decoding (a draft model proposes k tokens, the target
  verifies all k+1 in ONE ``ragged_paged_verify`` call, greedy
  acceptance exact);
- :class:`ReplicaSet` — multi-replica serving on the device mesh
  (docs/serving.md §10): N data-parallel replicas of one model version
  on disjoint device groups, each with its own program cache / decode
  engine / KV pool; heartbeat + consecutive-failure health checks,
  least-loaded routing among HEALTHY replicas only, failover under the
  request's original deadline (byte-identical results), and
  prewarm-gated rolling add/remove/rejoin — active whenever
  ``ServingConfig(replicas=N > 1)`` (``MXNET_SERVING_REPLICAS``);
- the traffic plane (docs/serving.md §11): seed-deterministic
  multi-tenant workload traces with bit-exact JSONL record/replay
  (:mod:`~mxnet_tpu.serving.traffic` — heavy-tailed bursty arrivals,
  shared-prefix clusters, closed-loop retry-after-honoring clients),
  SLO-driven autoscaling (:class:`Autoscaler` — a control loop over
  the runtime-metrics signals driving ``ReplicaSet``
  add/remove_replica with hysteresis, cooldowns, and prewarm-aware
  lead), and tiered admission (:class:`AdmissionController` — per-
  tenant quota token buckets plus priority shedding, lowest tier
  first, active whenever ``MXNET_SERVING_TENANT_TIERS`` is set);
- the resilience layer (docs/serving.md §8): end-to-end request
  deadlines (:class:`DeadlineExceededError` instead of silent hangs),
  bounded jittered retries for transient execute failures,
  failed-batch bisection (one poisoned request fails alone), decode
  step-failure quarantine, and per-model-version circuit breakers
  (:class:`CircuitBreaker`, :class:`CircuitOpenError`) — all provable
  under the deterministic fault-injection plans of
  :mod:`mxnet_tpu.faults` (``MXNET_FAULTS``).

>>> from mxnet_tpu import serving
>>> repo = serving.ModelRepository()
>>> repo.load_artifact("net", "model.shlo")
>>> with serving.ModelServer(repo) as srv:
...     y = srv.predict("net", x)          # coalesced + shape-bucketed
"""
from .admission import AdmissionController, TierPolicy, \
    parse_tier_spec
from .autoscaler import Autoscaler, AutoscalerConfig, \
    RuntimeMetricsSource, SLOTargets
from .batcher import DynamicBatcher, next_bucket, pad_batch, \
    unpad_outputs
from .config import ServingConfig
from .decode import DecodeEngine, GenerateRequest, PagedLMAdapter
from .kv_cache import DeviceKVPool, PageAllocator, PageGeometry, \
    PrefixCache
from .replica import Replica, ReplicaSet
from .repository import ModelEntry, ModelRepository
from .resilience import (CircuitBreaker, CircuitOpenError, Deadline,
                         DeadlineExceededError, honor_retry_after)
from .server import ModelServer, ServerOverloadedError
from .traffic import Trace, TraceConfig, TraceRequest, \
    generate_trace, replay_trace, summarize

__all__ = ["ModelRepository", "ModelEntry", "ModelServer",
           "DynamicBatcher", "ServingConfig", "ServerOverloadedError",
           "next_bucket", "pad_batch", "unpad_outputs",
           "DecodeEngine", "GenerateRequest", "PagedLMAdapter",
           "PageGeometry", "PageAllocator", "PrefixCache",
           "DeviceKVPool",
           "Deadline", "DeadlineExceededError", "CircuitBreaker",
           "CircuitOpenError", "honor_retry_after",
           "Replica", "ReplicaSet",
           "AdmissionController", "TierPolicy", "parse_tier_spec",
           "Autoscaler", "AutoscalerConfig", "RuntimeMetricsSource",
           "SLOTargets",
           "Trace", "TraceConfig", "TraceRequest", "generate_trace",
           "replay_trace", "summarize"]
