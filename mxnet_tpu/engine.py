"""Execution-engine semantics over XLA/PJRT async dispatch.

Reference: ``src/engine/`` (``ThreadedEnginePerDevice``, ``NaiveEngine``,
``ThreadedVar`` version counting, async error propagation — SURVEY.md 2.1,
5.5).  TPU-native redesign: PJRT already executes asynchronously and JAX
arrays are futures, so the heavy dependency scheduler is *not* rebuilt.
What survives is the reference's **semantic contract**:

- every NDArray owns a version-counted variable (write bumps the version —
  used by autograd staleness checks and the profiler);
- ``wait_to_read`` / ``waitall`` sync points;
- async errors are captured and re-raised at the next sync point on the
  dependent array (reference: exception stored on ThreadedVar, rethrown at
  ``WaitToRead`` — src/engine/threaded_engine.cc semantics);
- ``MXNET_ENGINE_TYPE=NaiveEngine`` forces synchronous execution after every
  op for debugging/bisection, exactly like the reference env knob.
"""
from __future__ import annotations

import threading
import time
import weakref

from .base import get_env
from . import runtime_metrics as _rm

__all__ = ["Engine", "engine", "waitall", "is_naive", "set_bulk_size",
           "bulk", "Var", "sync_outputs"]


class Var:
    """Version-counted engine variable attached to each NDArray.

    Reference: ``ThreadedVar`` in src/engine/threaded_engine.h — there it
    carries pending reader/writer queues; here XLA orders execution, so the
    var carries the *version* (for autograd/cache invalidation) and any
    deferred exception (for async error propagation).
    """

    __slots__ = ("version", "exc", "__weakref__")

    _counter_lock = threading.Lock()

    def __init__(self):
        self.version = 0
        self.exc = None

    def bump(self):
        self.version += 1

    def set_exception(self, exc: BaseException):
        self.exc = exc

    def check(self):
        if self.exc is not None:
            exc, self.exc = self.exc, None
            raise exc


class Engine:
    """Process-wide engine singleton (reference: Engine::Get())."""

    _instance = None

    def __init__(self):
        # id-keyed so NDArray.__eq__ (an elementwise op, reference
        # semantics) is never invoked by container bookkeeping
        self._live = weakref.WeakValueDictionary()
        # bulk-exec on by default like the reference
        # (MXNET_EXEC_BULK_EXEC_TRAIN=1, segment cap 15); =0 disables —
        # autograd's bulk backward replay consults bulk_size > 1
        if str(get_env("MXNET_EXEC_BULK_EXEC_TRAIN", "1")) == "0":
            self._bulk_size = 1
        else:
            self._bulk_size = int(
                get_env("MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN", 15))
        self._lock = threading.Lock()

    @classmethod
    def get(cls) -> "Engine":
        if cls._instance is None:
            cls._instance = Engine()
        return cls._instance

    # -- tracking ----------------------------------------------------------
    def track(self, arr):
        """Register a live NDArray so waitall() can block on it."""
        with self._lock:
            self._live[id(arr)] = arr
            if _rm._ENABLED:
                n = len(self._live)
                _rm.ENGINE_TRACKED.set(n)
                _rm.ENGINE_TRACKED_PEAK.set_max(n)

    def wait_for_all(self):
        """Block until all tracked arrays are ready (reference:
        Engine::WaitForAll / mx.nd.waitall)."""
        import jax
        for arr in list(self._live.values()):
            # dense arrays only: sparse NDArrays' _data is a densifying
            # property the sweep must not trigger
            if not hasattr(arr, "_components"):
                d = arr._data
                if not isinstance(d, jax.Array):
                    if arr._lazy_cb is None:
                        # husk of a failed fused step: its error was
                        # already raised synchronously at step(); direct
                        # reads still raise via the var's stored exception
                        continue
                    # else: pending deferred forward — materialize below
                elif getattr(d, "is_deleted", None) and d.is_deleted():
                    # donated away (stale alias of an updated buffer):
                    # no pending compute to wait on
                    continue
            try:
                arr.wait_to_read()
            except Exception:
                # waitall re-raises the *first* pending error, like the
                # reference rethrow-at-sync-point contract.
                raise

    def wait_for_var(self, arr):
        arr.wait_to_read()

    # -- modes -------------------------------------------------------------
    @property
    def is_naive(self) -> bool:
        return get_env("MXNET_ENGINE_TYPE") == "NaiveEngine"

    def set_bulk_size(self, size: int) -> int:
        """Reference: mx.engine.set_bulk_size. Here it caps how many eager
        ops the bulking context may fuse into one jit segment."""
        old, self._bulk_size = self._bulk_size, int(size)
        return old

    @property
    def bulk_size(self) -> int:
        return self._bulk_size


def engine() -> Engine:
    return Engine.get()


def waitall():
    # a deferred hybrid backward counts as outstanding async work
    from . import autograd
    if autograd._STATE.pending is not None:
        autograd.flush_pending()
    if not _rm._ENABLED:
        Engine.get().wait_for_all()
        return
    t0 = time.perf_counter()
    try:
        Engine.get().wait_for_all()
    finally:
        # waitall is the framework's full-pipeline stall point: count it
        # and record how long the host sat blocked
        _rm.ENGINE_WAITALL.inc()
        _rm.ENGINE_WAITALL_SECONDS.observe(time.perf_counter() - t0)


def sync_outputs(arrays, site="serving"):
    """Bounded sync point: block until the given raw jax arrays are
    ready, re-raising any async execution error here (the engine
    rethrow-at-sync-point contract applied to ONE dispatched batch
    instead of the whole pipeline — waitall's surgical sibling, used by
    the serving worker pool around each batch dispatch)."""
    import jax
    if not _rm._ENABLED:
        jax.block_until_ready(arrays)
        return arrays
    t0 = time.perf_counter()
    try:
        jax.block_until_ready(arrays)
    finally:
        _rm.ENGINE_SYNC_SECONDS.observe(time.perf_counter() - t0,
                                        site=site)
    return arrays


def is_naive() -> bool:
    return Engine.get().is_naive


def set_bulk_size(size: int) -> int:
    return Engine.get().set_bulk_size(size)


def _refresh_tracked_gauge():
    """Scrape-time refresh: the tracked-arrays gauge is written on
    track(), so after a burst of arrays is garbage-collected it would
    read stale-high until the next allocation — exporters re-sample the
    WeakValueDictionary instead.  Never instantiates the engine."""
    eng = Engine._instance
    if eng is not None and _rm._ENABLED:
        with eng._lock:
            _rm.ENGINE_TRACKED.set(len(eng._live))


_rm.register_collect_hook(_refresh_tracked_gauge)


class bulk:
    """Context manager hinting that ops inside may be fused (reference:
    mx.engine.bulk / engine bulk-exec mode).  Execution remains correct
    without fusion; this is a performance hint consumed by the imperative
    dispatcher."""

    def __init__(self, size: int):
        self.size = size
        self._old = None

    def __enter__(self):
        self._old = Engine.get().set_bulk_size(self.size)
        return self

    def __exit__(self, *exc):
        Engine.get().set_bulk_size(self._old)
        return False
