"""Execution-engine semantics over XLA/PJRT async dispatch.

Reference: ``src/engine/`` (``ThreadedEnginePerDevice``, ``NaiveEngine``,
``ThreadedVar`` version counting, async error propagation — SURVEY.md 2.1,
5.5).  TPU-native redesign: PJRT already executes asynchronously and JAX
arrays are futures, so the heavy dependency scheduler is *not* rebuilt.
What survives is the reference's **semantic contract**:

- every NDArray owns a version-counted variable (write bumps the version —
  used by autograd staleness checks and the profiler);
- ``wait_to_read`` / ``waitall`` sync points;
- async errors are captured and re-raised at the next sync point on the
  dependent array (reference: exception stored on ThreadedVar, rethrown at
  ``WaitToRead`` — src/engine/threaded_engine.cc semantics);
- ``MXNET_ENGINE_TYPE=NaiveEngine`` forces synchronous execution after every
  op for debugging/bisection, exactly like the reference env knob.

**Concurrency sanitizer** (``MXNET_ENGINE_SANITIZE=1``): engine and
serving locks are created through :func:`make_lock` /
:func:`make_condition`; with the knob on they record per-thread
lock-acquisition order into a process-wide graph and raise
``MXNetError`` the moment two locks are ever taken in both orders (a
potential deadlock — caught on the *second* order, before it can
actually interleave into one), and in-place NDArray writes assert the
array is engine-tracked (an untracked write is invisible to
``waitall``/async error propagation).  Off (the default) the factories
return plain ``threading`` primitives, so the production path pays
nothing.  The existing serving/engine tests double as race tests when
re-run under the knob — CI's ``sanity_lint`` job does exactly that
(docs/static_analysis.md §sanitizer).

**Thread-lifecycle sanitizer** (same knob): framework threads are
created through :func:`make_thread`, which registers each thread with
its owner and creation site; :func:`check_thread_leaks` raises on any
registered thread that survives its owner's stop (asserted at test
teardown by tests/conftest.py under the knob).  The static twin is
mxlint's thread-lifecycle pass.
"""
from __future__ import annotations

import threading
import time
import weakref

from .base import MXNetError, env_truthy, get_env
from . import runtime_metrics as _rm

__all__ = ["Engine", "engine", "waitall", "is_naive", "set_bulk_size",
           "bulk", "Var", "sync_outputs", "make_lock", "make_condition",
           "make_thread", "check_thread_leaks", "forget_thread",
           "thread_registry", "sanitizer_active", "watch_races"]

# ---------------------------------------------------------------------------
# Concurrency sanitizer (MXNET_ENGINE_SANITIZE=1)
# ---------------------------------------------------------------------------

_SANITIZE = env_truthy("MXNET_ENGINE_SANITIZE", False)


def sanitizer_active() -> bool:
    """Whether lock-order recording + tracked-array assertions are on
    for locks created from now on (tools/diagnose.py reports this)."""
    return _SANITIZE


class _LockOrders:
    """Process-wide lock-acquisition-order graph.

    Locks are identified by the *name* given to :func:`make_lock`, so
    every instance of a class shares one ordering contract (the static
    counterpart is mxlint's lock-discipline pass).  ``check(name)``
    runs BEFORE blocking on the lock: an inversion raises instead of
    deadlocking."""

    def __init__(self):
        self._mu = threading.Lock()
        self._edges = {}                # (held, acquiring) -> thread name
        self._held = threading.local()  # per-thread acquisition stack

    def _stack(self):
        st = getattr(self._held, "stack", None)
        if st is None:
            st = self._held.stack = []
        return st

    def check_and_record(self, name: str):
        """Run BEFORE blocking on a *blocking* acquire: record the
        prospective held->name edges, then probe for the reverse order.
        Recording before the block matters — two threads entering a
        first-time ABBA simultaneously must see each other's edge and
        raise instead of deadlocking inside the real acquire.  (A
        timed-out blocking acquire leaves its edge behind: the ordering
        intent was real and can deadlock for the timeout's duration, so
        the conservative record is correct for a sanitizer.)  Trylocks
        never call this: a non-blocking attempt cannot deadlock and
        must not constrain blocking acquirers."""
        st = self._stack()
        me = threading.current_thread().name
        for held in st:
            if held == name:
                continue
            with self._mu:
                self._edges.setdefault((held, name), me)
                rev = self._edges.get((name, held))
            if rev is not None:
                raise MXNetError(
                    f"MXNET_ENGINE_SANITIZE: lock-order inversion — "
                    f"thread {me!r} acquires {name!r} while holding "
                    f"{held!r}, but thread {rev!r} acquired them in the "
                    f"reverse order; two such threads interleaving "
                    f"deadlock.  Pick one global order "
                    f"(docs/static_analysis.md)")

    def push(self, name: str):
        self._stack().append(name)

    def pop(self, name: str):
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] == name:
                del st[i]
                return

    def reset(self):
        """Forget every recorded edge (test isolation helper)."""
        with self._mu:
            self._edges.clear()


_LOCK_ORDERS = _LockOrders()


class _SanLock:
    """``threading.Lock`` wrapper with acquisition-order recording."""

    __slots__ = ("name", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()

    def acquire(self, blocking=True, timeout=-1):
        if blocking:
            _LOCK_ORDERS.check_and_record(self.name)
        got = self._lock.acquire(blocking, timeout)
        if got:
            _LOCK_ORDERS.push(self.name)
        return got

    def release(self):
        _LOCK_ORDERS.pop(self.name)
        self._lock.release()

    def locked(self):
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class _SanCondition:
    """``threading.Condition`` wrapper: order-records acquire/release;
    ``wait`` pops the held record while the underlying lock is released
    and re-pushes on wakeup (no false edge against locks taken by the
    thread that woke us)."""

    __slots__ = ("name", "_cond")

    def __init__(self, name: str):
        self.name = name
        self._cond = threading.Condition()

    def acquire(self, *args):
        blocking = args[0] if args else True
        if blocking:
            _LOCK_ORDERS.check_and_record(self.name)
        got = self._cond.acquire(*args)
        if got:
            _LOCK_ORDERS.push(self.name)
        return got

    def release(self):
        _LOCK_ORDERS.pop(self.name)
        self._cond.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def wait(self, timeout=None):
        _LOCK_ORDERS.pop(self.name)
        try:
            return self._cond.wait(timeout)
        finally:
            _LOCK_ORDERS.push(self.name)

    def wait_for(self, predicate, timeout=None):
        _LOCK_ORDERS.pop(self.name)
        try:
            return self._cond.wait_for(predicate, timeout)
        finally:
            _LOCK_ORDERS.push(self.name)

    def notify(self, n=1):
        # mxlint: disable=condition-discipline (contract: pure
        # delegation — the caller entered `with cond:` on THIS wrapper,
        # which acquired the wrapped lock; notifying unlocked raises
        # RuntimeError in the wrapped Condition itself)
        self._cond.notify(n)

    def notify_all(self):
        # mxlint: disable=condition-discipline (contract: pure
        # delegation, see notify())
        self._cond.notify_all()


def make_lock(name: str):
    """A mutex for engine/serving shared state: plain ``threading.Lock``
    normally, order-recording :class:`_SanLock` under
    ``MXNET_ENGINE_SANITIZE=1``.  ``name`` is the lock's identity in the
    order graph — use ``Class.attr`` so all instances share one
    contract."""
    return _SanLock(name) if _SANITIZE else threading.Lock()


def make_condition(name: str):
    """Condition-variable sibling of :func:`make_lock`."""
    return _SanCondition(name) if _SANITIZE else threading.Condition()


# ---------------------------------------------------------------------------
# Thread-lifecycle sanitizer (the runtime twin of mxlint's
# thread-lifecycle pass, docs/static_analysis.md §15)
# ---------------------------------------------------------------------------

class _ThreadRegistry:
    """Process-wide table of framework threads created via
    :func:`make_thread` while ``MXNET_ENGINE_SANITIZE=1``: who owns
    each thread, where it was created, whether it was deliberately
    abandoned.  ``check_leaks`` is the teardown assertion; ``rows`` is
    what tools/diagnose.py prints."""

    def __init__(self):
        self._mu = threading.Lock()
        # Thread -> {owner, site, daemon, created, abandoned}
        self._threads = {}

    def register(self, t, owner, site):
        with self._mu:
            self._threads[t] = {
                "owner": owner or "<unowned>",
                "site": site,
                "daemon": bool(t.daemon),
                "created": time.monotonic(),
                "abandoned": None,
            }

    def forget(self, t, reason):
        with self._mu:
            info = self._threads.get(t)
            if info is not None:
                info["abandoned"] = reason or "abandoned"

    def _prune(self):
        # contract: every caller already holds self._mu (rows /
        # check_leaks take it before calling)
        for t in [t for t in self._threads if not t.is_alive()]:
            # mxlint: disable=lock-discipline
            del self._threads[t]

    def rows(self):
        now = time.monotonic()
        with self._mu:
            self._prune()
            return [
                {"name": t.name, "owner": info["owner"],
                 "site": info["site"], "daemon": info["daemon"],
                 "age_s": now - info["created"],
                 "abandoned": info["abandoned"]}
                for t, info in sorted(self._threads.items(),
                                      key=lambda kv: kv[1]["created"])]

    def check_leaks(self, grace_s=1.0):
        """Raise ``MXNetError`` if any registered, non-abandoned thread
        is still alive after ``grace_s`` (split across the survivors —
        a stopping thread gets a moment to observe its stop signal, a
        genuinely leaked one cannot hide behind the grace)."""
        with self._mu:
            self._prune()
            live = [(t, info) for t, info in self._threads.items()
                    if info["abandoned"] is None]
        if not live:
            return
        deadline = time.monotonic() + max(0.0, grace_s)
        for t, _ in live:
            t.join(max(0.0, deadline - time.monotonic()))
        now = time.monotonic()
        leaked = [(t, info) for t, info in live if t.is_alive()]
        if not leaked:
            with self._mu:
                self._prune()
            return
        lines = [
            f"  {t.name!r} owner={info['owner']} "
            f"created at {info['site']} "
            f"daemon={info['daemon']} age={now - info['created']:.1f}s"
            for t, info in leaked]
        raise MXNetError(
            "MXNET_ENGINE_SANITIZE: thread leak — "
            f"{len(leaked)} framework thread(s) survived their owner's "
            "stop:\n" + "\n".join(lines) + "\n"
            "Every make_thread thread must exit on its owner's "
            "stop()/close() path (or be explicitly forgotten via "
            "forget_thread with a documented reason).  Static twin: "
            "mxlint thread-lifecycle (docs/static_analysis.md)")

    def reset(self):
        """Drop every record (test isolation helper)."""
        with self._mu:
            self._threads.clear()


_THREADS = _ThreadRegistry()


def _caller_site(depth=2):
    import sys
    import os as _os
    f = sys._getframe(depth)
    path = f.f_code.co_filename
    try:
        rel = _os.path.relpath(path, _os.path.dirname(
            _os.path.dirname(_os.path.abspath(__file__))))
        if not rel.startswith(".."):
            path = rel
    except ValueError:
        pass
    return f"{path}:{f.f_lineno}"


def make_thread(target, *, name, owner=None, args=(), kwargs=None,
                daemon=True):
    """Factory for every framework-owned thread (mirrors
    :func:`make_lock`): a plain ``threading.Thread`` normally; under
    ``MXNET_ENGINE_SANITIZE=1`` the thread is additionally registered
    with its ``owner`` (``Class.attr``-style identity) and creation
    site so :func:`check_thread_leaks` can name any thread that
    survives its owner's stop.  The returned object is always a real
    ``threading.Thread`` — zero behavioral difference either way."""
    t = threading.Thread(target=target, name=name, args=args,
                         kwargs=kwargs or {}, daemon=daemon)
    if _SANITIZE:
        _THREADS.register(t, owner, _caller_site())
    return t


def forget_thread(t, reason):
    """Exempt ``t`` from :func:`check_thread_leaks`: the caller is
    deliberately abandoning it (e.g. ``run_with_deadline``'s watchdog
    worker wedged past its deadline — daemonized by construction, and
    joining it would just relocate the hang).  ``reason`` is recorded
    and shown by tools/diagnose.py."""
    if _SANITIZE:
        _THREADS.forget(t, reason)


def check_thread_leaks(grace_s=1.0):
    """Teardown assertion (no-op when the sanitizer is off): every
    registered framework thread must have exited — a survivor raises
    ``MXNetError`` naming its owner and creation site.  The serving /
    replica / autoscaler / supervisor suites call this at teardown
    under ``MXNET_ENGINE_SANITIZE=1`` (tests/conftest.py)."""
    if _SANITIZE:
        _THREADS.check_leaks(grace_s)


def thread_registry():
    """Live registered-thread rows (owner, site, daemon, age) for
    tools/diagnose.py; empty when the sanitizer is off."""
    return _THREADS.rows()


# ---------------------------------------------------------------------------
# Eraser-style lockset race sanitizer (the runtime twin of mxlint's
# shared-state-race / atomicity passes, docs/static_analysis.md §20-21)
# ---------------------------------------------------------------------------

# classes whose __setattr__ has been wrapped by watch_races (wrap once
# per class; per-instance tracking state lives in the instance dict).
# _RACE_MU serializes the wrap: two threads constructing the first two
# instances of one class concurrently must not double-wrap __setattr__
_RACE_MU = threading.Lock()
_RACE_WATCHED_CLASSES = set()


def _race_stack(frame, limit=4):
    import traceback
    return "".join(traceback.format_stack(frame, limit=limit)).rstrip()


def _note_race_write(obj, fields, name):
    """The Eraser lockset state machine, write-only: the first writer
    owns the field (exclusive); the moment a SECOND thread writes, the
    field's candidate lockset becomes the intersection of the two
    writers' held locks, and every later write intersects again.  An
    empty intersection is the proof: two threads wrote this field with
    no lock in common, so an interleaving that tears a read-modify-
    write exists — raise naming both writes instead of silently losing
    an update on some future schedule."""
    import sys
    me = threading.current_thread().name
    locks = frozenset(_LOCK_ORDERS._stack())
    frame = sys._getframe(2)            # the assignment site
    st = fields.get(name)
    if st is None:                      # first write: exclusive owner
        fields[name] = {
            "thread": me, "locks": locks, "shared": False,
            "stack": _race_stack(frame)}
        return
    if not st["shared"] and st["thread"] == me:
        # still exclusive: refresh to the freshest write so the
        # eventual second-thread intersection uses real evidence
        st["locks"] = locks
        st["stack"] = _race_stack(frame)
        return
    candidate = st["locks"] & locks
    if candidate:
        st.update(shared=True, thread=me, locks=candidate,
                  stack=_race_stack(frame))
        return
    prev_thread, prev_stack = st["thread"], st["stack"]
    prev_locks = sorted(st["locks"]) or ["<none>"]
    # re-arm before raising so a caught error does not cascade into a
    # storm of reports for every later write to the same field
    fields[name] = {"thread": me, "locks": locks, "shared": False,
                    "stack": _race_stack(frame)}
    raise MXNetError(
        f"MXNET_ENGINE_SANITIZE: data race on "
        f"{type(obj).__name__}.{name} — no common lock across "
        f"writers.\n"
        f"  thread {me!r} writes holding "
        f"{sorted(locks) or ['<none>']}:\n{_race_stack(frame)}\n"
        f"  thread {prev_thread!r} wrote holding {prev_locks}:\n"
        f"{prev_stack}\n"
        f"Guard both writes with one engine.make_lock lock or confine "
        f"the field to a single thread.  Static twin: mxlint "
        f"shared-state-race (docs/static_analysis.md)")


def _install_race_hook(cls):
    with _RACE_MU:
        if cls in _RACE_WATCHED_CLASSES:
            return
        orig = cls.__setattr__

        def __setattr__(self, name, value, _orig=orig):
            fields = self.__dict__.get("_mx_race_fields_")
            if fields is not None \
                    and name not in self.__dict__["_mx_race_exempt_"]:
                _note_race_write(self, fields, name)
            _orig(self, name, value)

        cls.__setattr__ = __setattr__
        _RACE_WATCHED_CLASSES.add(cls)


def watch_races(obj, exempt=()):
    """Arm Eraser-style per-field lockset tracking on ``obj`` (no-op
    unless ``MXNET_ENGINE_SANITIZE=1``): every attribute write records
    the writing thread and the locks held (by ``make_lock`` name, via
    the same per-thread stack the lock-order sanitizer keeps); once two
    threads have written a field, the field's candidate lockset is the
    running intersection of the writers' locksets, and an empty
    intersection raises ``MXNetError`` naming the field, both threads,
    and both write stacks.  Call at the END of ``__init__`` —
    construction is single-threaded by contract and stays untracked.

    ``exempt`` names fields deliberately handed between threads by
    some other protocol (e.g. a field only ever plain-assigned once,
    published via the GIL's store atomicity).

    The thread-shared serving classes (ModelServer, DecodeEngine,
    ReplicaSet, Autoscaler, PageAllocator) arm themselves; use this
    directly when testing new multi-threaded state."""
    if not _SANITIZE:
        return obj
    _install_race_hook(type(obj))
    # plain dict stores (not setattr) so arming never trips the hook
    obj.__dict__["_mx_race_exempt_"] = frozenset(exempt)
    obj.__dict__["_mx_race_fields_"] = {}
    return obj


class Var:
    """Version-counted engine variable attached to each NDArray.

    Reference: ``ThreadedVar`` in src/engine/threaded_engine.h — there it
    carries pending reader/writer queues; here XLA orders execution, so the
    var carries the *version* (for autograd/cache invalidation) and any
    deferred exception (for async error propagation).
    """

    __slots__ = ("version", "exc", "__weakref__")

    _counter_lock = threading.Lock()

    def __init__(self):
        self.version = 0
        self.exc = None

    def bump(self):
        self.version += 1

    def set_exception(self, exc: BaseException):
        self.exc = exc

    def check(self):
        if self.exc is not None:
            exc, self.exc = self.exc, None
            raise exc


# Engine.get() double-checked locking: plain primitive (make_lock reads
# module state this lock may guard the first initialization of).
_INSTANCE_LOCK = threading.Lock()


class Engine:
    """Process-wide engine singleton (reference: Engine::Get())."""

    _instance = None

    def __init__(self):
        # id-keyed so NDArray.__eq__ (an elementwise op, reference
        # semantics) is never invoked by container bookkeeping
        self._live = weakref.WeakValueDictionary()
        # bulk-exec on by default like the reference
        # (MXNET_EXEC_BULK_EXEC_TRAIN=1, segment cap 15); =0 disables —
        # autograd's bulk backward replay consults bulk_size > 1
        if str(get_env("MXNET_EXEC_BULK_EXEC_TRAIN", "1")) == "0":
            self._bulk_size = 1
        else:
            self._bulk_size = int(
                get_env("MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN", 15))
        self._lock = make_lock("engine.Engine._lock")

    @classmethod
    def get(cls) -> "Engine":
        if cls._instance is None:
            with _INSTANCE_LOCK:
                if cls._instance is None:
                    cls._instance = Engine()
        return cls._instance

    # -- tracking ----------------------------------------------------------
    def track(self, arr):
        """Register a live NDArray so waitall() can block on it."""
        with self._lock:
            self._live[id(arr)] = arr
            if _rm._ENABLED:
                n = len(self._live)
                _rm.ENGINE_TRACKED.set(n)
                _rm.ENGINE_TRACKED_PEAK.set_max(n)

    def wait_for_all(self):
        """Block until all tracked arrays are ready (reference:
        Engine::WaitForAll / mx.nd.waitall)."""
        import jax
        for arr in list(self._live.values()):
            # dense arrays only: sparse NDArrays' _data is a densifying
            # property the sweep must not trigger
            if not hasattr(arr, "_components"):
                d = arr._data
                if not isinstance(d, jax.Array):
                    if arr._lazy_cb is None:
                        # husk of a failed fused step: its error was
                        # already raised synchronously at step(); direct
                        # reads still raise via the var's stored exception
                        continue
                    # else: pending deferred forward — materialize below
                elif getattr(d, "is_deleted", None) and d.is_deleted():
                    # donated away (stale alias of an updated buffer):
                    # no pending compute to wait on
                    continue
            try:
                arr.wait_to_read()
            except Exception:
                # waitall re-raises the *first* pending error, like the
                # reference rethrow-at-sync-point contract.
                raise

    def wait_for_var(self, arr):
        arr.wait_to_read()

    def _sanitize_check_registered(self, arr):
        """MXNET_ENGINE_SANITIZE assertion: an in-place write to an
        array the engine is not tracking is invisible to waitall() and
        async error propagation (NDArray._set_data calls this before
        bumping the var)."""
        with self._lock:
            ok = id(arr) in self._live
        if not ok:
            raise MXNetError(
                "MXNET_ENGINE_SANITIZE: in-place write to an NDArray "
                "the engine is not tracking — waitall()/async error "
                "propagation cannot see this mutation; arrays must be "
                "registered via engine().track() (every normal NDArray "
                "construction path does this)")

    # -- modes -------------------------------------------------------------
    @property
    def is_naive(self) -> bool:
        return get_env("MXNET_ENGINE_TYPE") == "NaiveEngine"

    def set_bulk_size(self, size: int) -> int:
        """Reference: mx.engine.set_bulk_size. Here it caps how many eager
        ops the bulking context may fuse into one jit segment."""
        with self._lock:
            old, self._bulk_size = self._bulk_size, int(size)
        return old

    @property
    def bulk_size(self) -> int:
        return self._bulk_size


def engine() -> Engine:
    return Engine.get()


def waitall():
    # a deferred hybrid backward counts as outstanding async work
    from . import autograd
    if autograd._STATE.pending is not None:
        autograd.flush_pending()
    if not _rm._ENABLED:
        Engine.get().wait_for_all()
        return
    t0 = time.perf_counter()
    try:
        Engine.get().wait_for_all()
    finally:
        # waitall is the framework's full-pipeline stall point: count it
        # and record how long the host sat blocked
        _rm.ENGINE_WAITALL.inc()
        _rm.ENGINE_WAITALL_SECONDS.observe(time.perf_counter() - t0)


def sync_outputs(arrays, site="serving"):
    """Bounded sync point: block until the given raw jax arrays are
    ready, re-raising any async execution error here (the engine
    rethrow-at-sync-point contract applied to ONE dispatched batch
    instead of the whole pipeline — waitall's surgical sibling, used by
    the serving worker pool around each batch dispatch)."""
    import jax
    if not _rm._ENABLED:
        jax.block_until_ready(arrays)
        return arrays
    t0 = time.perf_counter()
    try:
        jax.block_until_ready(arrays)
    finally:
        _rm.ENGINE_SYNC_SECONDS.observe(time.perf_counter() - t0,
                                        site=site)
    return arrays


def is_naive() -> bool:
    return Engine.get().is_naive


def set_bulk_size(size: int) -> int:
    return Engine.get().set_bulk_size(size)


def _refresh_tracked_gauge():
    """Scrape-time refresh: the tracked-arrays gauge is written on
    track(), so after a burst of arrays is garbage-collected it would
    read stale-high until the next allocation — exporters re-sample the
    WeakValueDictionary instead.  Never instantiates the engine."""
    eng = Engine._instance
    if eng is not None and _rm._ENABLED:
        with eng._lock:
            _rm.ENGINE_TRACKED.set(len(eng._live))


_rm.register_collect_hook(_refresh_tracked_gauge)


class bulk:
    """Context manager hinting that ops inside may be fused (reference:
    mx.engine.bulk / engine bulk-exec mode).  Execution remains correct
    without fusion; this is a performance hint consumed by the imperative
    dispatcher."""

    def __init__(self, size: int):
        self.size = size
        self._old = None

    def __enter__(self):
        self._old = Engine.get().set_bulk_size(self.size)
        return self

    def __exit__(self, *exc):
        Engine.get().set_bulk_size(self._old)
        return False
