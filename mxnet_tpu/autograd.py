"""Autograd: tape-based reverse-mode differentiation for the eager path.

Reference surface: ``python/mxnet/autograd.py`` (record/pause scopes,
``backward``, ``grad``, custom ``Function``) backed by ``src/imperative/``
(``Imperative::RecordOp`` builds an nnvm tape; ``Imperative::Backward``
builds + runs the gradient graph in ONE call — SURVEY.md 3.2).

TPU-native redesign: each recorded tape node holds the pure JAX function of
the op it recorded.  ``backward()`` walks the tape once in reverse
topological order, obtaining per-node cotangents with ``jax.vjp`` — so the
backward of a node is itself XLA-compiled, and the whole backward remains a
single Python-level pass (no per-op ABI crossings, matching the reference's
one-call design).  The hybridized path does not use this tape at all: it
differentiates the traced program with ``jax.grad`` (see gluon/block.py).
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, List, Optional, Sequence

import jax
import numpy as _np

from .base import MXNetError

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "set_recording", "set_training", "backward",
           "grad", "get_symbol", "Function", "mark_variables",
           "flush_pending"]


class _AGState(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False
        # True while a retain_graph=True backward replays: cached-program
        # backward (CachedOp) must then keep residual buffers (no donation)
        self.retain = False
        # deferred single-CachedOp backward awaiting Trainer.step fusion
        # (see backward() / flush_pending)
        self.pending = None


_STATE = _AGState()


def is_recording() -> bool:
    return _STATE.recording


def is_training() -> bool:
    return _STATE.training


def set_recording(flag: bool) -> bool:
    old, _STATE.recording = _STATE.recording, flag
    return old


def set_training(flag: bool) -> bool:
    old, _STATE.training = _STATE.training, flag
    return old


class _RecordScope:
    def __init__(self, recording: Optional[bool], training: Optional[bool]):
        self._rec = recording
        self._train = training
        self._old = None

    def __enter__(self):
        self._old = (_STATE.recording, _STATE.training)
        if self._rec is not None:
            _STATE.recording = self._rec
        if self._train is not None:
            _STATE.training = self._train
        return self

    def __exit__(self, *exc):
        _STATE.recording, _STATE.training = self._old
        return False


def record(train_mode: bool = True) -> _RecordScope:
    """``with autograd.record():`` — turn on tape recording."""
    return _RecordScope(True, train_mode)


def pause(train_mode: bool = False) -> _RecordScope:
    """``with autograd.pause():`` — suspend recording inside record()."""
    return _RecordScope(False, train_mode)


def train_mode() -> _RecordScope:
    return _RecordScope(None, True)


def predict_mode() -> _RecordScope:
    return _RecordScope(None, False)


# ---------------------------------------------------------------------------
# Tape
# ---------------------------------------------------------------------------

class TapeNode:
    """One recorded op invocation (reference: nnvm node + AGInfo).

    Holds the op's pure JAX callable and its *raw* input values so that
    ``jax.vjp`` can re-linearize at backward time.  ``input_entries`` are
    (TapeNode|None, output_index, NDArray) triples linking to producers.
    """

    __slots__ = ("fn", "input_entries", "n_outputs", "out_grads", "name",
                 "_pending", "custom_backward", "key", "fused_info",
                 "out_avals")

    def __init__(self, fn: Callable, input_entries, n_outputs: int,
                 name: str = "", custom_backward: Optional[Callable] = None,
                 key=None):
        self.fn = fn
        self.input_entries = input_entries
        self.n_outputs = n_outputs
        self.out_grads: List = [None] * n_outputs
        self.name = name
        self.custom_backward = custom_backward
        # (op_name, kwargs_signature) when fn's computation is fully
        # determined by it — lets the bulk backward cache compiled replay
        # programs across tapes (engine bulk-exec).  None = not bulkable.
        self.key = key
        self.out_avals = None
        self._pending = 0
        # set by CachedOp on recorded dispatch: exposes (bwd_impl, res)
        # so Trainer.step can fuse backward+optimizer into one program
        self.fused_info = None


def _accumulate(slot_list, idx, value):
    if slot_list[idx] is None:
        slot_list[idx] = value
    else:
        slot_list[idx] = slot_list[idx] + value


def _topo_order(root_nodes) -> List[TapeNode]:
    """Reverse-topological order over the tape reachable from root nodes."""
    order: List[TapeNode] = []
    visited = set()

    def visit(node):
        stack = [(node, False)]
        while stack:
            n, processed = stack.pop()
            if processed:
                order.append(n)
                continue
            if id(n) in visited:
                continue
            visited.add(id(n))
            stack.append((n, True))
            for prod, _, _ in n.input_entries:
                if prod is not None and id(prod) not in visited:
                    stack.append((prod, False))

    for n in root_nodes:
        visit(n)
    return order[::-1]  # producers last -> reverse gives consumers first


def in_retain_backward() -> bool:
    """True while a retain_graph=True backward pass is replaying
    (thread-local; nested backwards restore the outer value)."""
    return _STATE.retain


def flush_pending():
    """Execute a deferred backward (see backward()'s deferral below).

    Called from every grad-reading surface (.grad property,
    Parameter.grad/list_grad, waitall, the next backward) so deferral is
    invisible to user code — grads materialize before anyone can observe
    their absence."""
    p = _STATE.pending
    if p is None:
        return
    _STATE.pending = None
    leaf_acc = {}

    def _leaf_contribute(arr, g):
        key = id(arr)
        if key in leaf_acc:
            leaf_acc[key] = (arr, leaf_acc[key][1] + g)
        else:
            leaf_acc[key] = (arr, g)

    prev_retain = _STATE.retain
    _STATE.retain = False
    try:
        with pause(train_mode=p["train_mode"]):
            _replay([p["node"]], leaf_acc, _leaf_contribute)
    finally:
        _STATE.retain = prev_retain
    for arr, g in leaf_acc.values():
        _write_grad(arr, g)
    for h in p["heads"]:
        h._autograd_node = None


def peek_pending():
    """The deferred-backward record, or None (Trainer.step fusion hook)."""
    return _STATE.pending


def flush_if_pending_grad(arr):
    """Flush the deferred backward iff ``arr`` IS one of its grad
    destination buffers.  Covers code that hoisted grad-array aliases
    out of the loop (``grads = [p.grad() for p in params]``) and then
    reads or consumes them between ``loss.backward()`` and
    ``trainer.step()`` — without this they'd silently observe the
    previous step's gradients (the eager path wrote in place)."""
    p = _STATE.pending
    if p is not None and id(arr) in p["grad_ids"]:
        flush_pending()


def clear_pending():
    """Drop the deferred backward WITHOUT executing it (the caller fused
    it into its own program).  Clears head tape links like a normal
    backward."""
    p = _STATE.pending
    _STATE.pending = None
    if p is not None:
        for h in p["heads"]:
            h._autograd_node = None


def backward(heads, head_grads=None, retain_graph: bool = False,
             train_mode: bool = True):
    """Compute gradients of ``heads`` w.r.t. all arrays that were
    ``attach_grad()``-ed (reference: MXAutogradBackwardEx ->
    Imperative::Backward).  Grad arrays are written into ``arr.grad``
    respecting each array's ``grad_req`` ('write' or 'add').

    Deferral: when the tape is a single CachedOp node (the hybridized
    three-call recipe), the replay is DEFERRED — ``Trainer.step`` then
    compiles backward+optimizer into ONE donated XLA program (engine
    bulk-exec pushed to its limit; reference: the async engine made
    ``backward()`` return before compute finished too, so laziness here
    is the same contract).  Any grad read in between flushes first.
    Disable with ``MXNET_FUSED_HYBRID_STEP=0``."""
    from .ndarray import NDArray, array as _mkarray

    flush_pending()                     # at most one deferred tape
    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]

    # Per-pass leaf accumulation: an array used by several ops (or twice by
    # one op) must SUM its partials within this backward; grad_req 'write'
    # vs 'add' only governs behavior across separate backward() calls.
    leaf_acc = {}

    def _leaf_contribute(arr, g):
        key = id(arr)
        if key in leaf_acc:
            leaf_acc[key] = (arr, leaf_acc[key][1] + g)
        else:
            leaf_acc[key] = (arr, g)

    root_nodes = []
    for h, hg in zip(heads, head_grads):
        info = h._autograd_node
        if info is None:
            if h._grad_req == "null":
                raise MXNetError(
                    "cannot differentiate a head that was not computed "
                    "inside autograd.record()")
            # head IS a leaf variable: d head / d head = ones
            g = jax.numpy.ones_like(h._data) if hg is None else hg._data
            _leaf_contribute(h, g)
            continue
        node, out_idx = info
        g = jax.numpy.ones_like(h._data) if hg is None else hg._data
        _accumulate(node.out_grads, out_idx, g)
        root_nodes.append(node)

    # Deferral eligibility: one CachedOp root carrying fusion info, no
    # leaf heads, all grad-receiving leaves use grad_req='write', eager
    # (non-naive) engine, and the knob is on.
    from .base import get_env
    from .engine import is_naive
    if (not retain_graph and len(root_nodes) == 1 and not leaf_acc
            and root_nodes[0].fused_info is not None
            and not is_naive()
            and get_env("MXNET_FUSED_HYBRID_STEP", "1") != "0"
            and all(arr._grad is None or arr._grad_req == "write"
                    for _p, _o, arr in root_nodes[0].input_entries)):
        _STATE.pending = {"node": root_nodes[0], "heads": list(heads),
                          "train_mode": train_mode,
                          # id()s of the grad buffers this deferral will
                          # write: a read of any of them (held alias from
                          # an earlier p.grad()) must flush first or it
                          # sees the PREVIOUS step's gradients
                          "grad_ids": {
                              id(arr._grad) for _p, _o, arr
                              in root_nodes[0].input_entries
                              if arr._grad is not None}}
        return

    prev_retain = _STATE.retain
    _STATE.retain = bool(retain_graph)
    try:
        # replay under the requested mode: mode-dependent ops (Dropout,
        # BatchNorm) recorded in train mode must re-linearize their
        # training branch, not the identity/predict branch (reference:
        # MXAutogradBackwardEx train_mode argument)
        with pause(train_mode=train_mode):
            done = False
            try:
                done = _try_bulk_replay(root_nodes, _leaf_contribute)
            except Exception:       # noqa: BLE001 — any trace/compile
                done = False        # failure falls back to per-node replay
            if not done:
                _replay(root_nodes, leaf_acc, _leaf_contribute)
    finally:
        _STATE.retain = prev_retain

    for arr, g in leaf_acc.values():
        _write_grad(arr, g)

    # Drop tape references on heads so memory frees (reference clears AGInfo)
    if not retain_graph:
        for h in heads:
            h._autograd_node = None


# Compiled whole-tape backward programs keyed by tape signature
# (engine bulk-exec mode; see _try_bulk_replay).  Bounded FIFO: variable
# shapes (ragged batches, bucketed lengths) would otherwise pin one
# compiled program per distinct signature forever.  A signature whose
# program failed to compile/run maps to None (negative cache) so the
# expensive failure isn't retried every backward.
_BULK_BWD_CACHE = OrderedDict()
_BULK_BWD_CACHE_CAP = 64


def _try_bulk_replay(root_nodes, _leaf_contribute):
    """Replay the WHOLE tape backward as one cached XLA program
    (reference: engine bulk-exec mode, MXNET_EXEC_BULK_EXEC_TRAIN —
    there it batches engine ops into segments; here the entire eager
    backward becomes a single dispatch instead of 2+ per op).

    Only fn-based nodes whose computation is determined by their
    ``key`` participate; custom-backward nodes (Function, CachedOp) and
    RNG/const-closure ops fall back to per-node replay.  The compiled
    program is cached on the tape's structural signature (op keys,
    topology, shapes), so steady-state training loops hit the cache.
    Returns True when the tape was handled.
    """
    from .engine import engine as _eng
    if _eng().bulk_size <= 1:
        return False
    nodes = _topo_order(root_nodes)
    if len(nodes) < 2:
        return False
    for n in nodes:
        if n.custom_backward is not None or n.key is None:
            return False
    # RNG ops participate with their per-step key as a program input
    # (never baked into the cached program)
    rng_keys = [getattr(n.fn, "_rng_key", None) for n in nodes]
    node_pos = {id(n): i for i, n in enumerate(nodes)}
    arrs, arr_pos = [], {}
    sig_nodes = []
    for n in nodes:
        ents = []
        for prod, oidx, arr in n.input_entries:
            k = id(arr)
            if k not in arr_pos:
                arr_pos[k] = len(arrs)
                arrs.append(arr)
            ents.append((node_pos[id(prod)] if prod is not None else -1,
                         oidx, arr_pos[k],
                         arr._grad_req != "null" and arr._grad is not None))
        sig_nodes.append((n.key, n.n_outputs, tuple(ents),
                          tuple(g is not None for g in n.out_grads)))
    # is_training() is baked into the traced program (Dropout/BatchNorm
    # branch on it at trace time), so the effective mode is part of the key
    sig = (tuple(sig_nodes),
           tuple((tuple(a.shape), str(a._data.dtype)) for a in arrs),
           is_training())
    init = [g for n in nodes for g in n.out_grads if g is not None]

    if sig in _BULK_BWD_CACHE and _BULK_BWD_CACHE[sig] is None:
        return False                     # negative-cached failing program
    cached = _BULK_BWD_CACHE.get(sig)
    if cached is None:
        from .random import trace_key_scope
        fns = []
        for n in nodes:
            base = getattr(n.fn, "_rng_base", None)
            if base is None:
                fns.append(n.fn)
            else:
                def fn_k(k, *a, _f=base):
                    with trace_key_scope(k):
                        return _f(*a)
                fns.append(fn_k)
        avals = [_node_out_avals(n) for n in nodes]
        has_rng = [rk is not None for rk in rng_keys]
        leaf_positions = sorted({e[2] for s in sig_nodes
                                 for e in s[2] if e[3]})

        def prog_fn(arr_datas, init_gs, keys):
            store = [[None] * s[1] for s in sig_nodes]
            it = iter(init_gs)
            for i, s in enumerate(sig_nodes):
                for j, has in enumerate(s[3]):
                    if has:
                        store[i][j] = next(it)
            kit = iter(keys)
            node_keys = [next(kit) if h else None for h in has_rng]
            leaf_g = {}
            for i, (key, n_out, ents, _m) in enumerate(sig_nodes):
                if all(g is None for g in store[i]):
                    continue
                cots = [g if g is not None
                        else jax.numpy.zeros(av.shape, av.dtype)
                        for g, av in zip(store[i], avals[i])]
                primals = [arr_datas[e[2]] for e in ents]
                if has_rng[i]:
                    primals = [node_keys[i]] + primals
                _, vjp_fn = jax.vjp(fns[i], *primals)
                in_grads = vjp_fn(tuple(cots) if n_out > 1 else cots[0])
                if has_rng[i]:
                    in_grads = in_grads[1:]       # drop key cotangent
                for (p, oidx, apos, is_leaf), g in zip(ents, in_grads):
                    if g is None or \
                            getattr(g, "dtype", None) == jax.dtypes.float0:
                        continue
                    if p >= 0:
                        _accumulate(store[p], oidx, g)
                    if is_leaf:
                        if apos in leaf_g:
                            leaf_g[apos] = leaf_g[apos] + g
                        else:
                            leaf_g[apos] = g
            return [leaf_g.get(p) for p in leaf_positions]

        cached = (jax.jit(prog_fn), leaf_positions)
        _BULK_BWD_CACHE[sig] = cached
        while len(_BULK_BWD_CACHE) > _BULK_BWD_CACHE_CAP:
            _BULK_BWD_CACHE.popitem(last=False)

    jitted, leaf_positions = cached
    try:
        outs = jitted([a._data for a in arrs], init,
                      [rk for rk in rng_keys if rk is not None])
    except Exception:
        # trace/compile/run failure: blacklist this signature so every
        # later backward doesn't re-pay the failing compile, and warn once
        _BULK_BWD_CACHE[sig] = None
        import logging
        logging.getLogger(__name__).warning(
            "bulk backward program failed for a %d-node tape; falling "
            "back to per-node replay for this tape shape", len(nodes))
        return False
    for pos, g in zip(leaf_positions, outs):
        if g is not None:
            _leaf_contribute(arrs[pos], g)
    for n in nodes:
        n.out_grads = [None] * n.n_outputs
    return True


def _replay(root_nodes, leaf_acc, _leaf_contribute):
    for node in _topo_order(root_nodes):
        if all(g is None for g in node.out_grads):
            continue
        out_grads = [
            g if g is not None else jax.numpy.zeros(av.shape, av.dtype)
            for g, av in zip(node.out_grads, _node_out_avals(node))
        ]
        in_primals = [e[2]._data for e in node.input_entries]
        if node.custom_backward is not None:
            in_grads = node.custom_backward(out_grads, in_primals)
        else:
            _, vjp_fn = jax.vjp(node.fn, *in_primals)
            cot = tuple(out_grads) if node.n_outputs > 1 else out_grads[0]
            in_grads = vjp_fn(cot)
        for (prod, oidx, arr), g in zip(node.input_entries, in_grads):
            if g is None:
                continue
            if prod is not None:
                _accumulate(prod.out_grads, oidx, g)
            if arr._grad_req != "null" and arr._grad is not None:
                _leaf_contribute(arr, g)
        # out_grads are per-PASS accumulators: always reset after replay.
        # retain_graph keeps the tape (nodes + saved tensors) alive for a
        # second backward — retaining stale cotangents would instead make
        # every later pass re-add this pass's contributions (~3x grads).
        node.out_grads = [None] * node.n_outputs


def _node_out_avals(node: TapeNode):
    """Output abstract values: stashed at record time for custom nodes
    (a per-step eval_shape costs ~10ms of host time on the fused-step
    hot path), else recovered by abstract eval of the node fn."""
    if node.out_avals is not None:
        return node.out_avals
    in_avals = [jax.ShapeDtypeStruct(e[2].shape, e[2]._data.dtype)
                for e in node.input_entries]
    outs = jax.eval_shape(node.fn, *in_avals)
    if node.n_outputs == 1 and not isinstance(outs, (tuple, list)):
        outs = [outs]
    node.out_avals = list(outs)
    return node.out_avals


def _write_grad(arr, g):
    import jax.numpy as jnp
    if arr._grad is None:
        return
    if arr._grad_req == "add":
        arr._grad._set_data(arr._grad._data + g.astype(arr._grad._data.dtype))
    else:
        arr._grad._set_data(jnp.asarray(g, dtype=arr._grad._data.dtype))


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Return gradients of heads w.r.t. variables without touching ``.grad``
    buffers (reference: autograd.grad).

    ``create_graph=True`` returns gradients that are themselves recorded
    on the tape, so they can be differentiated again (higher-order
    gradients / gradient penalties).  The tape reachable from ``heads``
    is functionalized into one pure JAX function and the whole gradient
    computation becomes a single fn-based tape node — differentiable to
    arbitrary order by construction (each extra order adds one more
    ``jax.vjp`` composition)."""
    from .base import MXNetError
    from .ndarray import NDArray
    if create_graph:
        return _grad_create_graph(heads, variables, head_grads,
                                  train_mode=train_mode)
    if isinstance(variables, NDArray):
        variables = [variables]
        single = True
    else:
        single = False
    saved = [(v._grad, v._grad_req) for v in variables]
    for v in variables:
        v.attach_grad()
    try:
        backward(heads, head_grads, retain_graph=bool(retain_graph),
                 train_mode=train_mode)
        outs = [v.grad.copy() for v in variables]
    finally:
        for v, (g, req) in zip(variables, saved):
            v._grad, v._grad_req = g, req
    return outs[0] if single else outs


def _grad_create_graph(heads, variables, head_grads=None, train_mode=True):
    """Differentiable gradients: functionalize the tape and record the
    gradient computation as one new fn-based tape node.

    Reference: ``autograd.grad(create_graph=True)`` (upstream supports
    second-order for a subset of ops via FGradient-of-FGradient; here the
    replayed function is pure JAX, so any order works).  The tape is left
    intact (as with ``retain_graph=True``), letting the returned grads
    compose with the original graph — e.g. WGAN-GP style penalties.

    Limitations (raise loudly): ``variables`` must be leaf arrays, and the
    reachable tape may not contain host-side custom-backward nodes
    (autograd.Function, CustomOp, recorded CachedOp dispatch) — those are
    opaque to re-linearization.
    """
    from .ndarray import NDArray

    single = isinstance(variables, NDArray)
    if single:
        variables = [variables]
    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]

    for v in variables:
        if v._autograd_node is not None:
            raise MXNetError(
                "grad(create_graph=True): variables must be leaf arrays "
                "(computed inside record() -> differentiate w.r.t. its "
                "leaf inputs instead)")
    for hg in head_grads:
        if hg is not None and hg._autograd_node is not None:
            raise MXNetError(
                "grad(create_graph=True): head_grads recorded on the tape "
                "are treated as constants of the gradient node, which "
                "would silently drop their own gradient paths — pass "
                "detached head_grads (e.g. hg.copy() outside record())")

    head_entries = []
    root_nodes = []
    for h in heads:
        info = h._autograd_node
        if info is None:
            # head IS a leaf (d head / d head = ones): replay reads its
            # value straight from the input slot
            head_entries.append((None, h))
            continue
        head_entries.append(info)
        root_nodes.append(info[0])

    # producers-first order for forward replay
    nodes = _topo_order(root_nodes)[::-1]
    for n in nodes:
        if n.custom_backward is not None:
            raise MXNetError(
                f"grad(create_graph=True): tape contains a host-side "
                f"custom-backward node ({n.name or 'Function'}) that "
                f"cannot be re-linearized for higher-order gradients")

    # Inputs of the functionalized tape: the (deduplicated) variables
    # first, then every other distinct leaf array (entries with no
    # producer node, plus any head that is itself a leaf).  Duplicate
    # variables must collapse to ONE input slot — the replay reads values
    # by array identity, and a stale duplicate slot would never be read,
    # zeroing its cotangent.
    uniq_vars, var_slot, _seen = [], [], {}
    for v in variables:
        if id(v) not in _seen:
            _seen[id(v)] = len(uniq_vars)
            uniq_vars.append(v)
        var_slot.append(_seen[id(v)])
    all_inputs = list(uniq_vars)
    pos = {id(a): i for i, a in enumerate(all_inputs)}
    for n in nodes:
        for prod, _oidx, arr in n.input_entries:
            if prod is None and id(arr) not in pos:
                pos[id(arr)] = len(all_inputs)
                all_inputs.append(arr)
    for ent in head_entries:
        if ent[0] is None and id(ent[1]) not in pos:
            pos[id(ent[1])] = len(all_inputs)
            all_inputs.append(ent[1])

    node_idx = {id(n): i for i, n in enumerate(nodes)}
    # cotangents for the heads (constants of the gradient node)
    cots = [jax.numpy.ones_like(h._data) if hg is None else hg._data
            for h, hg in zip(heads, head_grads)]

    def _replay_forward(datas):
        store = [None] * len(nodes)
        for i, n in enumerate(nodes):
            ins = []
            for prod, oidx, arr in n.input_entries:
                if prod is None:
                    ins.append(datas[pos[id(arr)]])
                else:
                    ins.append(store[node_idx[id(prod)]][oidx])
            o = n.fn(*ins)
            store[i] = tuple(o) if isinstance(o, (tuple, list)) else (o,)
        return tuple(datas[pos[id(ent[1])]] if ent[0] is None
                     else store[node_idx[id(ent[0])]][ent[1]]
                     for ent in head_entries)

    n_vars = len(uniq_vars)

    def grad_fn(*datas):
        # Bake the recorded effective mode into the function: mode-
        # dependent ops (Dropout, BatchNorm) read the thread-local at
        # trace time, and this fn is re-traced whenever the grad node is
        # differentiated again — possibly under a different ambient mode.
        with _RecordScope(False, train_mode):
            _, vjp_fn = jax.vjp(lambda *ds: _replay_forward(ds), *datas)
            in_grads = vjp_fn(tuple(cots))
        out = tuple(
            g if getattr(g, "dtype", None) != jax.dtypes.float0
            else jax.numpy.zeros_like(d)
            for g, d in zip(in_grads[:n_vars], datas[:n_vars]))
        # tape convention: single-output node fns return a bare array
        return out[0] if n_vars == 1 else out

    # Structural signature of the functionalized tape.  When every inner
    # node is itself key-determined (no RNG closures) and the head
    # cotangents are the default ones, grad_fn's computation is fully
    # determined by this signature — so (a) the eager call below can run
    # a cached jitted program instead of re-tracing the nested vjp every
    # call, and (b) the recorded node gets a bulk key, letting a later
    # backward over it compile the WHOLE outer tape as one program
    # (engine bulk-exec; see _try_bulk_replay).  Steady-state loops that
    # re-build the same-shaped tape each step (e.g. WGAN-GP) then pay
    # zero retrace cost.
    gkey = None
    if all(n.key is not None and
           getattr(n.fn, "_rng_base", None) is None for n in nodes) \
            and all(hg is None for hg in head_grads):
        sig_nodes = tuple(
            (n.key, n.n_outputs,
             tuple((node_idx[id(prod)] if prod is not None else -1,
                    oidx, pos[id(arr)] if prod is None else -1)
                   for prod, oidx, arr in n.input_entries))
            for n in nodes)
        sig_heads = tuple((-1, pos[id(ent[1])]) if ent[0] is None
                          else (node_idx[id(ent[0])], ent[1])
                          for ent in head_entries)
        sig_shapes = tuple((tuple(a.shape), str(a._data.dtype))
                           for a in all_inputs)
        gkey = ("__grad__", sig_nodes, sig_heads, sig_shapes, n_vars,
                bool(train_mode))

    with pause():
        runner = grad_fn
        if gkey is not None:
            cached = _GRAD_FN_CACHE.get(gkey)
            if cached is None:
                # AOT-compile so the cache holds ONLY the executable —
                # caching jit(grad_fn) itself would pin every tape
                # intermediate through the closure for the cache's
                # lifetime (gigabytes on large-model loops)
                avals = [jax.ShapeDtypeStruct(a.shape, a._data.dtype)
                         for a in all_inputs]
                cached = jax.jit(grad_fn).lower(*avals).compile()
                _GRAD_FN_CACHE[gkey] = cached
                while len(_GRAD_FN_CACHE) > _GRAD_FN_CACHE_CAP:
                    _GRAD_FN_CACHE.popitem(last=False)
            runner = cached
        raw_grads = runner(*[a._data for a in all_inputs])
    if n_vars == 1:
        raw_grads = (raw_grads,)
    outs = [NDArray(g) for g in raw_grads]

    # record the gradient computation itself so the grads differentiate
    entries = [(None, 0, a) for a in all_inputs]
    gnode = TapeNode(fn=grad_fn, input_entries=entries,
                     n_outputs=len(outs), name="grad", key=gkey)
    for i, o in enumerate(outs):
        o._autograd_node = (gnode, i)
    results = [outs[s] for s in var_slot]
    return results[0] if single else results


# Compiled grad_fn programs for create_graph tapes, keyed by structural
# signature (bounded FIFO, same rationale as _BULK_BWD_CACHE).
_GRAD_FN_CACHE = OrderedDict()
_GRAD_FN_CACHE_CAP = 64


def mark_variables(variables, gradients, grad_reqs="write"):
    """Reference: autograd.mark_variables — attach explicit grad buffers."""
    from .ndarray import NDArray
    if isinstance(variables, NDArray):
        variables, gradients = [variables], [gradients]
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad = g
        v._grad_req = req


def get_symbol(x):
    """Reference: autograd.get_symbol — recover the symbolic graph of a
    recorded computation.  Returns a Symbol replaying the tape."""
    raise MXNetError("get_symbol: use HybridBlock tracing / mx.sym instead "
                     "(tape-to-symbol export is not supported)")


class Function:
    """Custom differentiable function (reference: mx.autograd.Function).

    Subclass and implement ``forward(self, *inputs)`` and
    ``backward(self, *output_grads)``; both operate on NDArrays.
    """

    def __init__(self):
        self._saved = ()

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray import NDArray, array as _mkarray
        from . import ndarray as nd
        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single else list(outputs)
        if is_recording():
            func = self

            def custom_backward(out_grads, in_primals):
                ograds = [NDArray(g) for g in out_grads]
                with pause():
                    igrads = func.backward(*ograds)
                if not isinstance(igrads, (list, tuple)):
                    igrads = [igrads]
                return [g._data if g is not None else None for g in igrads]

            record_custom_node(inputs, outs, custom_backward,
                               name=type(self).__name__)
        return outs[0] if single else outs


def record_custom_node(inputs, outputs, custom_backward, name=""):
    """Link a TapeNode with a caller-supplied backward onto the tape
    (shared by autograd.Function and CachedOp's recorded dispatch).

    ``custom_backward(out_grads, in_primals) -> per-input grads`` replaces
    vjp replay; output avals are stashed so backward can synthesize zero
    cotangents for unconsumed outputs without eval_shape-ing a real fn.
    """
    entries = []
    for a in inputs:
        prod = a._autograd_node
        entries.append((None, 0, a) if prod is None
                       else (prod[0], prod[1], a))
    node = TapeNode(fn=None, input_entries=entries,
                    n_outputs=len(outputs), name=name,
                    custom_backward=custom_backward)
    avals = [jax.ShapeDtypeStruct(o.shape, o._data.dtype) for o in outputs]
    node.out_avals = list(avals)
    node.fn = lambda *xs: tuple(
        jax.numpy.zeros(a.shape, a.dtype) for a in avals)
    for i, o in enumerate(outputs):
        o._autograd_node = (node, i)
    return node
