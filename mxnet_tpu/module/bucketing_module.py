"""BucketingModule: bounded compile-cache policy for variable-length input.

Reference: ``python/mxnet/module/bucketing_module.py`` (SURVEY.md 2.2) and
the §2.4 P8 mandate — on TPU every distinct shape is a fresh XLA
compilation, so the reference's bucketing idea (bin variable-length
sequences into a small fixed set of shapes, one executor per bucket,
parameters shared) is *more* load-bearing here than on GPU.  The bucket
registry is explicit: ``num_compiles``/``active_buckets`` expose exactly how
many programs exist, and ``bucket_keys`` fixed at construction caps them.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    """reference: mx.mod.BucketingModule(sym_gen, default_bucket_key).

    sym_gen(bucket_key) -> (symbol, data_names, label_names)
    """

    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, bucket_keys=None):
        super().__init__(logger=logger)
        if default_bucket_key is None:
            raise MXNetError("BucketingModule: default_bucket_key required")
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._fixed_param_names = fixed_param_names
        # explicit compile-cache policy: when bucket_keys is given, only
        # those keys may ever be bound (a hard cap on XLA programs)
        self._allowed_keys = set(bucket_keys) | {default_bucket_key} \
            if bucket_keys is not None else None
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None

    # ---------------------------------------------------------------- state
    @property
    def default_bucket_key(self):
        return self._default_bucket_key

    @property
    def active_buckets(self):
        return sorted(self._buckets)

    @property
    def num_compiles(self):
        """Total XLA programs traced across all bucket executors."""
        return sum(m.num_compiles for m in self._buckets.values())

    @property
    def symbol(self):
        return self._curr_module.symbol if self._curr_module else None

    # ----------------------------------------------------------------- bind
    def _gen_module(self, bucket_key):
        if self._allowed_keys is not None and \
                bucket_key not in self._allowed_keys:
            raise MXNetError(
                f"bucket key {bucket_key!r} not in the registered bucket "
                f"set {sorted(self._allowed_keys)}; refusing an unbounded "
                f"compile (P8 policy)")
        symbol, data_names, label_names = self._sym_gen(bucket_key)
        return Module(symbol, data_names, label_names, logger=self.logger,
                      context=self._context,
                      fixed_param_names=self._fixed_param_names)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        # a rebind invalidates every bucket executor: stale modules would
        # keep sharing storage with the *old* default module.  Trained
        # values survive via the same preserve/restore Module.bind does.
        preserved = None
        if self.binded and self.params_initialized:
            preserved = self.get_params()
        self._buckets = {}
        self.params_initialized = False
        self._bind_args = dict(for_training=for_training,
                               inputs_need_grad=inputs_need_grad,
                               grad_req=grad_req)
        module = self._gen_module(self._default_bucket_key)
        module.bind(data_shapes, label_shapes, **self._bind_args)
        self._buckets[self._default_bucket_key] = module
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self.binded = True
        if preserved is not None:
            # same warn-and-reinit contract as Module.bind(force_rebind)
            module._restore_preserved(preserved)
            self.params_initialized = True

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """Bind (or reuse) the executor for bucket_key, sharing parameters
        with the default bucket's module (reference: switch_bucket)."""
        if not self.binded:
            raise MXNetError("switch_bucket: call bind first")
        if bucket_key not in self._buckets:
            module = self._gen_module(bucket_key)
            module.bind(data_shapes, label_shapes,
                        shared_module=self._buckets[self._default_bucket_key],
                        **self._bind_args)
            if self.optimizer_initialized:
                module._optimizer = self._curr_module._optimizer
                module._updater = self._curr_module._updater
                module.optimizer_initialized = True
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    # ------------------------------------------------------------- delegate
    def init_params(self, *args, **kwargs):
        self._curr_module.init_params(*args, **kwargs)
        self.params_initialized = True
        for m in self._buckets.values():
            m.params_initialized = True

    def init_optimizer(self, *args, **kwargs):
        self._curr_module.init_optimizer(*args, **kwargs)
        self.optimizer_initialized = True
        # one optimizer/updater instance drives every bucket: shared params
        # must see one consistent state/update-count stream
        for m in self._buckets.values():
            m._optimizer = self._curr_module._optimizer
            m._updater = self._curr_module._updater
            m.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        key = getattr(data_batch, "bucket_key", None)
        if key is None:
            key = self._curr_bucket_key
        if key != self._curr_bucket_key:
            self.switch_bucket(key, data_batch.provide_data,
                               data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads=out_grads)

    def update(self):
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._curr_module.update_metric(eval_metric, labels)

    def get_params(self):
        return self._buckets[self._default_bucket_key].get_params()

    def set_params(self, arg_params, aux_params, **kwargs):
        self._buckets[self._default_bucket_key].set_params(
            arg_params, aux_params, **kwargs)
        self.params_initialized = True

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        self._buckets[self._default_bucket_key].save_checkpoint(
            prefix, epoch, save_optimizer_states)
