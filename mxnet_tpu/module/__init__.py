"""Legacy Module API (reference: ``python/mxnet/module/`` — SURVEY.md 2.2).

The imperative Gluon API (``mxnet_tpu.gluon``) is the modern path; this
package re-creates the symbolic training surface — ``Module.fit`` over a
bound Executor, and ``BucketingModule``'s explicit compile-cache policy for
variable-length inputs (SURVEY.md 2.4 P8).
"""
from .base_module import BaseModule
from .module import Module, save_checkpoint, load_checkpoint
from .bucketing_module import BucketingModule

__all__ = ["BaseModule", "Module", "BucketingModule", "save_checkpoint",
           "load_checkpoint"]
