"""BaseModule: the fit/score/predict training loop contract.

Reference: ``python/mxnet/module/base_module.py`` (SURVEY.md 2.2, 3.5).
The high-level loop (epochs -> batches -> forward_backward/update ->
update_metric -> callbacks) is API-identical; the per-batch work lowers to
one compiled XLA program via Executor instead of per-op engine pushes.
"""
from __future__ import annotations

import logging
import time

from ..base import MXNetError
from .. import metric as metric_mod
from .. import ndarray as nd
from .. import runtime_metrics as _rm
from .. import tracing as _tr
from ..util import as_list as _as_list

__all__ = ["BaseModule"]


def _as_metric(m):
    if isinstance(m, metric_mod.EvalMetric):
        return m
    return metric_mod.create(m)


class BaseModule:
    """Abstract module: subclasses implement bind/init_params/forward/
    backward/update/get_outputs/update_metric."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    # -- abstract ----------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        raise NotImplementedError

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    # -- shared loop -------------------------------------------------------
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None):
        """The reference training loop (reference: BaseModule.fit)."""
        if num_epoch is None:
            raise MXNetError("fit: num_epoch must be given")
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        init_kwargs = dict(arg_params=arg_params, aux_params=aux_params,
                           allow_missing=allow_missing,
                           force_init=force_init)
        if initializer is not None:
            # None = "use the module's default initializer"; an explicit
            # init_params(initializer=None) means keep-current, which is
            # not what fit's optional argument expresses
            init_kwargs["initializer"] = initializer
        self.init_params(**init_kwargs)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        eval_metric = _as_metric(eval_metric)
        validation_metric = (_as_metric(validation_metric)
                             if validation_metric else eval_metric)
        if monitor is not None:
            monitor.install(self)

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            for nbatch, data_batch in enumerate(train_data):
                if monitor is not None:
                    monitor.tic()
                t_step = time.perf_counter() if _rm._ENABLED else None
                self.forward_backward(data_batch)
                self.update()
                if t_step is not None:
                    ctx = _tr.current_context()
                    _rm.TRAINER_STEP_SECONDS.observe(
                        time.perf_counter() - t_step,
                        exemplar=ctx.trace_id if ctx is not None
                        else None)
                if monitor is not None:
                    monitor.toc_print()
                self.update_metric(eval_metric, data_batch.label)
                if batch_end_callback is not None:
                    param = _BatchEndParam(epoch=epoch, nbatch=nbatch,
                                           eval_metric=eval_metric,
                                           locals=locals())
                    for cb in _as_list(batch_end_callback):
                        cb(param)
            names, vals = eval_metric.get()
            for name, val in zip(_as_list(names), _as_list(vals)):
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)
            if epoch_end_callback is not None:
                arg_params, aux_params = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_params, aux_params)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f",
                                     epoch, name, val)
            train_data.reset()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None,
              reset=True, epoch=0):
        """reference: BaseModule.score."""
        if not (self.binded and self.params_initialized):
            raise MXNetError("score: module must be binded and initialized")
        eval_metric = _as_metric(eval_metric)
        eval_metric.reset()
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                param = _BatchEndParam(epoch=epoch, nbatch=nbatch,
                                       eval_metric=eval_metric,
                                       locals=locals())
                for cb in _as_list(batch_end_callback):
                    cb(param)
        if score_end_callback is not None:
            param = _BatchEndParam(epoch=epoch, nbatch=nbatch,
                                   eval_metric=eval_metric, locals=locals())
            for cb in _as_list(score_end_callback):
                cb(param)
        names, vals = eval_metric.get()
        return list(zip(_as_list(names), _as_list(vals)))

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True):
        """reference: BaseModule.predict — concatenated outputs."""
        if not (self.binded and self.params_initialized):
            raise MXNetError("predict: module must be binded and initialized")
        if reset:
            eval_data.reset()
        out_batches = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            outs = self.get_outputs()
            if eval_batch.pad:
                keep = eval_batch.data[0].shape[0] - eval_batch.pad
                outs = [o[0:keep] for o in outs]
            out_batches.append(outs)
        if not merge_batches:
            return out_batches
        num_outputs = len(out_batches[0]) if out_batches else 0
        merged = [nd.concat(*[b[i] for b in out_batches], dim=0)
                  for i in range(num_outputs)]
        return merged[0] if num_outputs == 1 else merged

    @property
    def symbol(self):
        return self._symbol


class _BatchEndParam:
    __slots__ = ("epoch", "nbatch", "eval_metric", "locals")

    def __init__(self, epoch, nbatch, eval_metric, locals):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals


