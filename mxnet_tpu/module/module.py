"""Module: intermediate-level symbolic training on a bound Executor.

Reference: ``python/mxnet/module/module.py`` (SURVEY.md 2.2, 3.5 call stack
Module.fit -> forward_backward -> executor group -> engine).  Here the
"executor group" is a single Executor whose whole graph is one XLA program;
data parallelism over devices is the kvstore/Trainer tier's job
(``mxnet_tpu.kvstore``, ``mxnet_tpu.parallel``), matching the TPU design
where SPMD sharding — not per-device executor replicas — scales the step.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .. import context as ctx_mod
from .. import initializer as init_mod
from .. import ndarray as nd
from .. import optimizer as opt_mod
from .. import runtime_metrics as _rm
from ..initializer import InitDesc
from ..optimizer.optimizer import get_updater
from .base_module import BaseModule

__all__ = ["Module", "save_checkpoint", "load_checkpoint"]


class Module(BaseModule):
    """reference: mx.mod.Module(symbol, data_names, label_names, context)."""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None):
        super().__init__(logger=logger)
        self._symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._context = context if context is not None \
            else ctx_mod.current_context()
        if isinstance(self._context, (list, tuple)):
            # multi-device replicas are served by the SPMD tier; a Module
            # executes on one (possibly sharded) context
            self._context = self._context[0]
        self._fixed_param_names = set(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        unknown_data = set(self._data_names) - set(arg_names)
        if unknown_data:
            raise MXNetError(
                f"Module: data names {sorted(unknown_data)} not found in "
                f"symbol arguments {arg_names}")
        # labels absent from the graph are tolerated (inference-only
        # symbols; reference _check_input_names uses throw=False here)
        missing_labels = set(self._label_names) - set(arg_names)
        if missing_labels:
            self.logger.warning(
                "Module: label names %s not used by the symbol; ignoring",
                sorted(missing_labels))
            self._label_names = [n for n in self._label_names
                                 if n in arg_names]
        input_names = set(self._data_names) | set(self._label_names)
        self._param_names = [n for n in arg_names if n not in input_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._exec = None
        self._optimizer = None
        self._updater = None
        self._data_shapes = None
        self._label_shapes = None
        self._preloaded = None          # set by Module.load
        self._preloaded_states = None

    # ------------------------------------------------------------------ bind
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        # rebinding must not lose trained values (reference: Module.bind
        # re-copies arg_params into the new executor group)
        preserved = None
        if self.binded and self.params_initialized:
            preserved = self.get_params()
        self._data_shapes = _norm_shapes(data_shapes, self._data_names)
        self._label_shapes = _norm_shapes(label_shapes, self._label_names) \
            if label_shapes else []
        self._for_training = for_training
        self._inputs_need_grad = inputs_need_grad

        shapes = {n: s for n, s in self._data_shapes + self._label_shapes}
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**shapes)
        arg_names = self._symbol.list_arguments()

        args, reqs = {}, {}
        shared = shared_module._exec if shared_module is not None else None
        for name, shape in zip(arg_names, arg_shapes):
            if shared is not None and name in shared.arg_dict and \
                    name in self._param_names:
                args[name] = shared.arg_dict[name]      # shared storage
            else:
                args[name] = nd.zeros(shape, ctx=self._context)
            if not for_training:
                reqs[name] = "null"
            elif name in self._fixed_param_names:
                reqs[name] = "null"
            elif name in self._param_names:
                reqs[name] = grad_req
            else:  # data/label inputs
                reqs[name] = grad_req if (inputs_need_grad and
                                          name in self._data_names) \
                    else "null"
        aux = {}
        for name, shape in zip(self._aux_names, aux_shapes):
            if shared is not None and name in shared.aux_dict:
                aux[name] = shared.aux_dict[name]
            else:
                aux[name] = nd.zeros(shape, ctx=self._context)

        from ..executor import Executor
        self._exec = Executor(self._symbol, self._context, args,
                              args_grad=None, grad_req=reqs, aux_states=aux)
        self.binded = True
        if preserved is not None:
            self._restore_preserved(preserved)
        elif shared_module is not None and shared_module.params_initialized:
            self.params_initialized = True
        elif self._preloaded is not None:
            # Module.load: restore checkpointed params into the fresh bind
            arg_params, aux_params = self._preloaded
            self.init_params(arg_params=arg_params, aux_params=aux_params,
                             allow_extra=True)

    def _restore_preserved(self, preserved):
        """Restore trained values after a force_rebind.  A rebind that
        changes a *parameter* shape cannot reuse the trained value: those
        params are freshly re-initialized (module default initializer)
        with a warning."""
        arg_params, aux_params = preserved
        mismatched = []

        def _compat(params, bound):
            out = {}
            for n, v in params.items():
                if n in bound and tuple(bound[n].shape) == tuple(v.shape):
                    out[n] = v
                elif n in bound:
                    mismatched.append(n)
            return out

        self.init_params(
            initializer=None,
            arg_params=_compat(arg_params, self._exec.arg_dict),
            aux_params=_compat(aux_params, self._exec.aux_dict),
            allow_missing=True, force_init=True, allow_extra=True)
        if mismatched:
            self.logger.warning(
                "bind(force_rebind): parameters %s changed shape; "
                "re-initialized with the default initializer", mismatched)
            # Initializer.__call__ name-dispatch sends aux names
            # (moving_mean/moving_var/gamma/beta) to zeros/ones, so this
            # is safe for aux statistics too
            default_init = init_mod.Uniform(0.01)
            for n in mismatched:
                arr = self._exec.arg_dict[n] if n in self._exec.arg_dict \
                    else self._exec.aux_dict[n]
                default_init(InitDesc(n), arr)

    # ---------------------------------------------------------------- params
    _DEFAULT_INIT = object()  # distinguish "not given" from explicit None

    def init_params(self, initializer=_DEFAULT_INIT, arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        if not self.binded:
            raise MXNetError("init_params: call bind first")
        if initializer is Module._DEFAULT_INIT:
            initializer = init_mod.Uniform(0.01)
        def _copy_in(name, arr, src, kind):
            if tuple(src.shape) != tuple(arr.shape):
                raise MXNetError(
                    f"init_params: shape mismatch for {kind} {name!r}: "
                    f"provided {tuple(src.shape)}, bound {tuple(arr.shape)}")
            arr._set_data(nd.array(src.asnumpy())._data)

        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params is not None and name in arg_params:
                _copy_in(name, arr, arg_params[name], "arg")
            elif arg_params is not None and not allow_missing:
                raise MXNetError(f"init_params: missing arg {name!r}")
            elif initializer is not None:
                initializer(InitDesc(name), arr)
            # initializer=None + missing: keep the current value
            # (reference set_params semantics for partial fine-tune loads)
        for name in self._aux_names:
            arr = self._exec.aux_dict[name]
            if aux_params is not None and name in aux_params:
                _copy_in(name, arr, aux_params[name], "aux")
            elif aux_params is not None and not allow_missing:
                raise MXNetError(f"init_params: missing aux {name!r}")
            elif initializer is not None:
                initializer(InitDesc(name), arr)
        if arg_params is not None and not allow_extra:
            extra = set(arg_params) - set(self._param_names)
            if extra:
                raise MXNetError(
                    f"init_params: extra parameters {sorted(extra)} "
                    f"(pass allow_extra=True to ignore)")
        self.params_initialized = True

    def get_params(self):
        if not self.binded:
            raise MXNetError("get_params: module not bound")
        args = {n: self._exec.arg_dict[n].copy() for n in self._param_names}
        aux = {n: self._exec.aux_dict[n].copy() for n in self._aux_names}
        return args, aux

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        # initializer=None: params absent from the dicts keep their
        # current values (partial fine-tune load), never re-randomized
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    # ------------------------------------------------------------- optimizer
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, opt_mod.Optimizer):
            self._optimizer = optimizer
        else:
            batch_size = self._data_shapes[0][1][0]
            params = dict(optimizer_params)
            params.setdefault("rescale_grad", 1.0 / batch_size)
            self._optimizer = opt_mod.create(optimizer, **params)
        self._updater = get_updater(self._optimizer)
        if self._preloaded_states is not None:
            self._updater.set_states(self._preloaded_states)
            self._preloaded_states = None
        self.optimizer_initialized = True

    # ----------------------------------------------------------- step pieces
    def forward(self, data_batch, is_train=None):
        if not self.binded:
            raise MXNetError("forward: module not bound")
        if is_train is None:
            is_train = self._for_training
        feeds = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feeds[name] = arr
        if self._label_names and data_batch.label is not None:
            for name, arr in zip(self._label_names, data_batch.label):
                feeds[name] = arr
        self._exec.forward(is_train=is_train, **feeds)

    def backward(self, out_grads=None):
        self._exec.backward(out_grads=out_grads)

    def update(self):
        if not self.optimizer_initialized:
            raise MXNetError("update: call init_optimizer first")
        # keyed by parameter *name*: updater state stays correct when the
        # updater is shared across bucket modules whose positional order
        # may differ (reference kvstore keys are strings for the same reason)
        for name in self._param_names:
            if self._exec._grad_req.get(name, "null") == "null":
                continue
            self._updater(name, self._exec.grad_dict[name],
                          self._exec.arg_dict[name])
        if _rm._ENABLED and _rm.grad_norm_enabled():
            _rm.publish_grad_norm(
                self._exec.grad_dict[n] for n in self._param_names
                if self._exec._grad_req.get(n, "null") != "null"
                and n in self._exec.grad_dict)

    def get_outputs(self, merge_multi_context=True):
        return list(self._exec.outputs)

    def get_input_grads(self, merge_multi_context=True):
        if not self._inputs_need_grad:
            raise MXNetError("bind with inputs_need_grad=True first")
        return [self._exec.grad_dict[n] for n in self._data_names]

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels, self.get_outputs())

    # ------------------------------------------------------------ checkpoint
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        save_checkpoint(prefix, epoch, self._symbol, *self.get_params())
        if save_optimizer_states and self._updater is not None:
            with open(f"{prefix}-{epoch:04d}.states", "wb") as f:
                f.write(self._updater.get_states(dump_optimizer=False))

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        sym, arg_params, aux_params = load_checkpoint(prefix, epoch)
        mod = Module(sym, **kwargs)
        mod._preloaded = (arg_params, aux_params)  # applied at bind()
        if load_optimizer_states:
            with open(f"{prefix}-{epoch:04d}.states", "rb") as f:
                mod._preloaded_states = f.read()  # applied at init_optimizer
        return mod

    @property
    def num_compiles(self):
        return self._exec.num_compiles if self._exec is not None else 0


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """reference: mx.model.save_checkpoint — symbol JSON + params file."""
    symbol.save(f"{prefix}-symbol.json")
    payload = {f"arg:{k}": v for k, v in arg_params.items()}
    payload.update({f"aux:{k}": v for k, v in aux_params.items()})
    nd.save(f"{prefix}-{epoch:04d}.params", payload)


def load_checkpoint(prefix, epoch):
    """reference: mx.model.load_checkpoint."""
    from .. import symbol as sym_mod
    symbol = sym_mod.load(f"{prefix}-symbol.json")
    payload = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in payload.items():
        kind, name = k.split(":", 1)
        (arg_params if kind == "arg" else aux_params)[name] = v
    return symbol, arg_params, aux_params


def _norm_shapes(shapes, names):
    """Accept [(name, shape)...] / [DataDesc...]; return [(name, shape)...]"""
    out = []
    for entry in shapes or []:
        if hasattr(entry, "name"):       # DataDesc namedtuple
            out.append((entry.name, tuple(entry.shape)))
        else:
            name, shape = entry[0], entry[1]
            out.append((name, tuple(shape)))
    return out
