"""Version compatibility shims for the JAX APIs the parallel layer uses.

JAX moves these symbols between releases (``shard_map`` left
``jax.experimental`` in 0.8; ``lax.pvary`` was replaced by
``lax.pcast(..., to='varying')`` in 0.9).  Every module that needs them
imports from here, so the next JAX bump touches ONE file instead of the
whole ``parallel/`` package (VERDICT r2 weak #8).
"""
from __future__ import annotations

from jax import lax

try:                                      # jax >= 0.8 public location
    from jax import shard_map
except ImportError:                       # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

__all__ = ["shard_map", "shard_map_unchecked", "to_varying"]


def shard_map_unchecked(f, mesh, in_specs, out_specs):
    """``shard_map`` with the static replication check disabled.

    The quantized-collective bodies (``mxnet_tpu.quantize``) produce
    replicated outputs via a symmetric ``all_gather`` + local reduce —
    semantically replicated, but not provably so to shard_map's static
    checker (only psum-family results are).  The kwarg spelling moved
    across JAX versions (``check_rep`` -> ``check_vma``), hence the
    shim.
    """
    try:
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)
    except TypeError:                     # pragma: no cover - jax >= 0.8
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)


def to_varying(x, axis_names):
    """Mark ``x`` as varying over ``axis_names`` under shard_map's
    varying-manual-axes typing.

    Constants start axis-unvarying; carries of ``lax.scan``/``fori_loop``
    that become varying must START varying, so initial carries get cast
    through this.  No-op on JAX versions without vma tracking.
    """
    if isinstance(axis_names, str):
        axis_names = (axis_names,)
    pcast = getattr(lax, "pcast", None)
    if pcast is not None:                 # jax >= 0.9
        return pcast(x, tuple(axis_names), to="varying")
    pvary = getattr(lax, "pvary", None)
    if pvary is not None:                 # 0.8.x
        return pvary(x, tuple(axis_names))
    return x
