"""Language-neutral deployment artifacts (docs/frontends.md §2).

The reference serves non-Python consumers through the flat C ABI
(`cpp-package`, Scala, …, SURVEY.md §2.3) and `amalgamation/` for
predict-only mobile builds.  Here the deployment boundary is the
compiled program, not the API: a hybridized block exports to a
**StableHLO artifact** (serialized `jax.export` module with the weights
baked in) that any PJRT-bearing runtime executes WITHOUT importing this
framework — the test suite proves it by running one in a subprocess
that imports only ``jax``.

The ``path.json`` manifest is the artifact's *serving signature*:
input shapes/dtypes (``null`` marks a dimension left symbolic at export
time), output shapes/dtypes, and whether the batch dimension is
dynamic.  ``mxnet_tpu.serving`` consumes it to pick shape buckets and
to validate requests before they reach PJRT, and ``load_stablehlo``
validates calls against it so a shape/dtype mistake raises a clear
``MXNetError`` instead of an opaque PJRT failure.
"""
from __future__ import annotations

import hashlib
import json
import os

import numpy as np

from . import faults as _faults, tracing as _tr
from .base import MXNetError

__all__ = ["export_stablehlo", "load_stablehlo", "load_manifest",
           "validate_manifest", "validate_signature", "validate_inputs",
           "StableHLOModel"]


def _manifest_path(path):
    """``model.shlo`` / ``model`` -> ``model.json``."""
    base = path[:-len(".shlo")] if path.endswith(".shlo") else path
    return base + ".json"


def _sig_entry(shape, dtype):
    return {"shape": [d if isinstance(d, int) else None for d in shape],
            "dtype": str(dtype)}


def _aot_buckets(precompile, dynamic_batch, fixed_batch):
    """Normalize ``export_stablehlo(precompile=...)`` into a bucket
    list.  ``True`` means the serving default bucket set (powers of two
    up to ``MXNET_SERVING_MAX_BATCH``) for dynamic exports, or the one
    exported shape for static ones."""
    from .base import get_env
    from .serving.batcher import bucket_set
    if not dynamic_batch and fixed_batch is None:
        raise MXNetError(
            "export_stablehlo: precompile needs a leading batch "
            "dimension (or dynamic_batch=True)")
    if precompile is True:
        if not dynamic_batch:
            return [fixed_batch]
        return bucket_set(int(get_env("MXNET_SERVING_MAX_BATCH")))
    buckets = sorted({int(b) for b in precompile})
    if any(b < 1 for b in buckets):
        raise MXNetError("export_stablehlo: precompile buckets must be "
                         ">= 1")
    if not dynamic_batch and buckets != [fixed_batch]:
        raise MXNetError(
            f"export_stablehlo: a static export can only precompile its "
            f"exported batch ({fixed_batch}), got buckets {buckets} — "
            f"export with dynamic_batch=True for a bucket set")
    return buckets


def _quantization_digest(qblock) -> str:
    """Content address of a manifest ``quantization`` block (minus the
    digest field itself): canonical-JSON sha256.  Load-time validation
    recomputes it, so a hand-edited (or bit-rotted) scale is rejected
    at ``validate_manifest`` instead of silently mis-describing the
    baked weights."""
    body = {k: v for k, v in qblock.items() if k != "digest"}
    payload = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(payload.encode("utf-8")).hexdigest()


def _quantize_params(block_name, params, quantize):
    """Weight-only post-training quantization of a functionalized
    parameter dict: per-tensor symmetric scales over every >=2d float
    tensor (matmul/conv kernels — biases and norm vectors stay f32;
    they are byte-trivial and precision-critical).

    Returns ``(param_fn, quant_block)``: ``param_fn()`` materializes
    the dequantizing parameter dict (int8/fp8 constants widened in f32,
    one narrowing cast — the accumulate-wide contract of
    ``mxnet_tpu.quantize``), and ``quant_block`` is the manifest v4
    ``quantization`` entry (mode, per-tensor scales, digest).
    """
    import jax.numpy as jnp

    from . import quantize as qz
    if quantize not in ("int8", "fp8"):
        raise MXNetError(
            f"export_stablehlo: quantize must be 'int8' or 'fp8', "
            f"got {quantize!r}")
    spec = qz.CompressionSpec(kind=quantize)
    wire_dtype = "int8" if quantize == "int8" else "float8_e4m3fn"
    packed, weights_meta = {}, []
    for n, w in params.items():
        if w.ndim < 2 or not jnp.issubdtype(w.dtype, jnp.floating):
            continue
        scale = qz.tensor_scale(w, spec)
        packed[n] = (qz.quantize_tensor(w, scale, spec), scale,
                     w.dtype)
        weights_meta.append({"name": n, "scale": float(scale),
                             "dtype": wire_dtype,
                             "elems": int(w.size)})
    if not weights_meta:
        raise MXNetError(
            f"export_stablehlo(quantize={quantize!r}): "
            f"{block_name} has no >=2d float weight tensors to "
            f"quantize")

    def param_fn():
        from . import quantize as qz
        return {n: (qz.dequantize_tensor(*packed[n])
                    if n in packed else w)
                for n, w in params.items()}

    quant_block = {"mode": quantize, "weights": weights_meta}
    return param_fn, quant_block


def export_stablehlo(block, *example_inputs, path, emit_text=False,
                     dynamic_batch=False, version=None, precompile=(),
                     decode=None, quantize=None):
    """Export ``block``'s inference forward as a StableHLO artifact.

    Writes ``path.shlo`` (serialized module, weights embedded as
    constants) and ``path.json`` (input/output signature manifest).
    With ``emit_text=True`` also writes ``path.stablehlo.txt`` (the MLIR
    module, for inspection / non-JAX StableHLO consumers).

    ``precompile`` ships ahead-of-time compiled executables next to the
    manifest (manifest v3 ``precompiled`` field): pass an iterable of
    shape buckets (dynamic exports) or ``True`` (the serving default
    bucket set; for static exports, the one exported shape).  Each
    bucket's executable is serialized into ``path.aot/<key>.bin`` keyed
    exactly as the serving compile cache
    (``mxnet_tpu.compile_cache.cache_key``), so a server loading the
    artifact on the SAME device topology and jax version starts with
    zero XLA compiles.  A replica on a different topology silently
    falls back to compiling (the key will not match) — precompiled
    blobs are an optimization, never a compatibility constraint.

    ``dynamic_batch=True`` exports the leading dimension of every input
    as ONE shared symbolic size, so the same artifact serves any batch
    size — the shape-bucketed serving path (``mxnet_tpu.serving``)
    requires this to coalesce ragged request batches into O(log N)
    compiled programs.  The manifest records the dynamic dimension as
    ``null``.  ``version`` tags the manifest for
    ``serving.ModelRepository`` hot-swap bookkeeping.

    ``decode`` ships decode-capable metadata in the manifest (v3
    ``decode`` field): a dict of the dimensions an autoregressive
    runtime needs to size a paged KV cache and drive the step loop —
    ``vocab_size``, ``num_layers``, ``num_heads``, ``head_dim``,
    ``max_context``, optional ``eos_id``, optional speculative-decoding
    deployment metadata — a ``draft`` dims block (same field rules,
    vocab must match the target's) and the tuned proposal depth
    ``spec_k`` (``TransformerDecoderLM.decode_meta(draft=..,
    spec_k=..)`` produces it; docs/serving.md §9).  The exported
    program itself stays the one-shot forward; the metadata is the
    contract for external decode runtimes and for
    ``serving.ModelRepository`` (which surfaces it as
    ``entry.decode_meta``; in-process generation registers the block
    via ``add_decoder``).

    ``quantize='int8'|'fp8'`` exports the QUANTIZED serving shape
    (manifest v4): every >=2d float weight tensor is packed to
    int8/float8 with a per-tensor symmetric scale and the program
    dequantizes at entry (XLA folds the widen-multiply into the
    consuming ops), so the artifact holds 1-byte weight constants —
    ~4x smaller, ~4x fewer bytes per replica pull.  The example inputs
    double as the **calibration batch**: the f32 and quantized forwards
    both run at export time and the observed output error lands in the
    manifest's ``quantization.calibration`` entry, so serving admission
    can bound accepted quality loss (``MXNET_SERVING_QUANT_*``).  The
    per-tensor scales are digest-protected — a tampered/corrupted
    manifest scale is rejected at load, not served.

    The artifact is self-contained: load it with
    ``jax.export.deserialize(open(...).read()).call(*arrays)`` — no
    ``mxnet_tpu`` import needed at serving time (the deployment-boundary
    equivalent of the reference's amalgamation predict-only build).
    """
    import jax
    from jax import export as jexport

    from .parallel.functional import functionalize

    apply_fn, params = functionalize(block, *example_inputs,
                                     train_mode=False)

    quant_block = None
    if quantize:
        param_fn, quant_block = _quantize_params(
            type(block).__name__, params, quantize)

        def infer(*xs):
            out, _aux = apply_fn(param_fn(), *xs)
            return out
    else:
        def infer(*xs):
            out, _aux = apply_fn(params, *xs)
            return out

    if dynamic_batch:
        if any(len(x.shape) < 1 for x in example_inputs):
            raise MXNetError(
                "export_stablehlo(dynamic_batch=True): every input needs "
                "a leading batch dimension")
        (b,) = jexport.symbolic_shape("b")
        args = tuple(
            jax.ShapeDtypeStruct((b,) + tuple(x.shape[1:]), x._data.dtype)
            for x in example_inputs)
    else:
        args = tuple(
            jax.ShapeDtypeStruct(tuple(x.shape), x._data.dtype)
            for x in example_inputs)
    try:
        exported = jexport.export(jax.jit(infer))(*args)
    except Exception as e:
        raise MXNetError(f"export_stablehlo: lowering failed: {e}") from e
    if quant_block is not None:
        # calibration: run the f32 reference AND the quantized forward
        # on the example inputs, record the observed output error so
        # load/admission can bound accepted quality loss
        def _outs(fn):
            out = fn(*(x._data if hasattr(x, "_data") else x
                       for x in example_inputs))
            return out if isinstance(out, (tuple, list)) else (out,)
        refs = _outs(lambda *xs: apply_fn(params, *xs)[0])
        qouts = _outs(infer)
        max_abs = max_rel = 0.0
        for r, q in zip(refs, qouts):
            r = np.asarray(r, np.float32)
            q = np.asarray(q, np.float32)
            abs_err = float(np.max(np.abs(q - r))) if r.size else 0.0
            ref_mag = float(np.max(np.abs(r))) if r.size else 0.0
            max_abs = max(max_abs, abs_err)
            max_rel = max(max_rel, abs_err / (ref_mag + 1e-12))
        quant_block["calibration"] = {
            "examples": int(example_inputs[0].shape[0])
            if example_inputs and example_inputs[0].shape else 0,
            "max_abs_err": max_abs,
            "max_rel_err": max_rel,
        }
        quant_block["digest"] = _quantization_digest(quant_block)
    blob = bytes(exported.serialize())
    manifest = {
        "format": "jax.export/stablehlo",
        "manifest_version": 4 if quant_block is not None else 3,
        # null when the caller did not pick one, so the serving
        # repository's auto-increment stays in charge (a hard-coded 1
        # would collide on the second default export of a model)
        "version": version,
        "dynamic_batch": bool(dynamic_batch),
        "inputs": [_sig_entry(a.shape, a.dtype) for a in args],
        "outputs": [_sig_entry(a.shape, a.dtype)
                    for a in exported.out_avals],
        "block": type(block).__name__,
    }
    if decode is not None:
        manifest["decode"] = dict(decode)
    if quant_block is not None:
        manifest["quantization"] = quant_block
    aot_blobs = []
    if precompile:
        from . import compile_cache as _cc
        fixed = None if dynamic_batch else \
            (args[0].shape[0] if args and args[0].shape else None)
        buckets = _aot_buckets(precompile, dynamic_batch, fixed)
        program_hash = hashlib.sha256(blob).hexdigest()
        dtypes = [str(a.dtype) for a in args]
        aot_dirname = os.path.basename(path) + ".aot"
        entries = []
        for b in buckets:
            if dynamic_batch:
                avals = tuple(
                    jax.ShapeDtypeStruct((b,) + tuple(a.shape[1:]),
                                         a.dtype) for a in args)
            else:
                avals = args
            try:
                compiled = jax.jit(
                    lambda *xs: exported.call(*xs)).lower(*avals).compile()
                body = _cc._serialize_compiled(compiled)
            except Exception as e:
                raise MXNetError(
                    f"export_stablehlo: precompile of bucket {b} "
                    f"failed: {e}") from e
            key = _cc.cache_key(program_hash, b, dtypes)
            aot_blobs.append((key, body))
            entries.append({"bucket": int(b),
                            "file": f"{aot_dirname}/{key}.bin",
                            "key": key})
        manifest["precompiled"] = entries
    # validate BEFORE anything touches disk: a rejected export must not
    # leave an orphan .shlo that a later load_stablehlo would serve
    # manifest-less (and therefore unchecked) — precompiled executables
    # are likewise built in memory above so a failed bucket compile
    # leaves no partial artifact behind
    validate_manifest(manifest, where=f"export_stablehlo({path!r})")
    with open(path + ".shlo", "wb") as f:
        f.write(blob)
    # sweep executables from a PREVIOUS export to this path: new weights
    # mean new keys, and stale unreferenced blobs would otherwise ride
    # along with the artifact forever (one full executable per bucket
    # per re-export)
    aot_dir = path + ".aot"
    keep = {key + ".bin" for key, _body in aot_blobs}
    if os.path.isdir(aot_dir):
        for name in os.listdir(aot_dir):
            if name.endswith(".bin") and name not in keep:
                try:
                    os.unlink(os.path.join(aot_dir, name))
                except OSError:
                    pass
    if aot_blobs:
        from . import compile_cache as _cc
        os.makedirs(aot_dir, exist_ok=True)
        for key, body in aot_blobs:
            _cc.write_payload_file(os.path.join(aot_dir, key + ".bin"),
                                   body)
    with open(path + ".json", "w") as f:
        json.dump(manifest, f, indent=1)
    if emit_text:
        with open(path + ".stablehlo.txt", "w") as f:
            f.write(exported.mlir_module())
    return path + ".shlo"


def load_manifest(path):
    """Read the ``.json`` signature manifest next to an artifact (pass
    either the ``.shlo`` path or the bare prefix).  Returns None when
    the artifact ships without one (pre-manifest exports stay loadable).
    """
    mpath = _manifest_path(path)
    if not os.path.exists(mpath):
        return None
    with open(mpath) as f:
        manifest = json.load(f)
    if not isinstance(manifest.get("inputs"), list):
        raise MXNetError(f"malformed artifact manifest {mpath}: "
                         f"missing 'inputs' signature")
    validate_manifest(manifest, where=mpath)
    return manifest


def _check_sig_entries(entries, kind, where):
    for i, spec in enumerate(entries):
        if not isinstance(spec, dict) \
                or not isinstance(spec.get("shape"), list) \
                or "dtype" not in spec:
            raise MXNetError(
                f"{where}: manifest {kind} {i} is not a "
                f"{{shape, dtype}} signature entry")
        for d in spec["shape"]:
            if d is not None and (not isinstance(d, int) or d < 0):
                raise MXNetError(
                    f"{where}: manifest {kind} {i} has dimension {d!r} — "
                    f"dims are nonnegative ints or null (symbolic)")
        if not _known_dtype(spec["dtype"]):
            raise MXNetError(
                f"{where}: manifest {kind} {i} declares unknown dtype "
                f"{spec['dtype']!r}")


def _known_dtype(d) -> bool:
    """Whether ``d`` names a resolvable dtype.  ``np.dtype`` rejects
    extension-dtype *names* ('bfloat16') with TypeError even though the
    dtype objects themselves canonicalize, so those resolve through
    ml_dtypes (always present — jax depends on it)."""
    try:
        np.dtype(d)
        return True
    except TypeError:
        pass
    except Exception:
        return False
    try:
        import ml_dtypes
        np.dtype(getattr(ml_dtypes, str(d)))
        return True
    except Exception:
        return False


def validate_signature(entries, where="signature", dynamic_batch=False):
    """Structural check of a bare manifest-style signature list (what
    ``serving.ModelRepository.add_function`` accepts): each entry is
    ``{"shape": [int|null, ...], "dtype": name}``.  With
    ``dynamic_batch`` the same batch-major rule a manifest gets applies:
    every entry's leading dim must be symbolic (``None``)."""
    if not isinstance(entries, (list, tuple)):
        raise MXNetError(
            f"{where}: signature must be a list of {{shape, dtype}} "
            f"entries, got {type(entries).__name__}")
    _check_sig_entries(list(entries), "input", where)
    if dynamic_batch:
        for i, spec in enumerate(entries):
            if not spec["shape"] or spec["shape"][0] is not None:
                raise MXNetError(
                    f"{where}: dynamic_batch signature input {i} has a "
                    f"concrete leading dimension "
                    f"({spec['shape'] or 'scalar'}) — every input must "
                    f"share the symbolic (null) batch dim, or register "
                    f"with dynamic_batch=False")
    return entries


def validate_manifest(manifest, where="manifest"):
    """Soundness-check a (v2) artifact manifest against what the serving
    stack infers from it — the static half of ``validate_inputs``.

    Beyond per-entry structure (dims are nonnegative ints or ``null``,
    dtypes canonicalize), the load-bearing inference check: with
    ``dynamic_batch`` every *output* must be batch-major with a symbolic
    leading dimension.  The exported program was traced with one shared
    symbolic batch size, so an output whose leading dim came out
    concrete means the block collapsed the batch axis (a global reduce,
    a transpose) — ``serving`` would mis-split that batch at un-pad
    time, and the right moment to hear about it is export/load, not
    mid-request.  Raises :class:`MXNetError`; returns the manifest.
    """
    if not isinstance(manifest.get("inputs"), list):
        raise MXNetError(f"{where}: manifest missing 'inputs' signature")
    outputs = manifest.get("outputs")
    _check_sig_entries(manifest["inputs"], "input", where)
    if isinstance(outputs, list):
        _check_sig_entries(outputs, "output", where)
    version = manifest.get("version")
    if version is not None and not isinstance(version, int):
        raise MXNetError(
            f"{where}: manifest version must be an int or null, got "
            f"{version!r}")
    mver = manifest.get("manifest_version")
    if mver is not None and (not isinstance(mver, int)
                             or not 2 <= mver <= 4):
        raise MXNetError(
            f"{where}: unsupported manifest_version {mver!r} "
            f"(this loader understands 2..4)")
    pre = manifest.get("precompiled")
    if pre is not None:
        # v3: shipped AOT executables; entries must be loadable without
        # trusting the manifest (relative file under the artifact dir,
        # hex key matching the compile-cache addressing)
        if not isinstance(pre, list):
            raise MXNetError(
                f"{where}: manifest 'precompiled' must be a list")
        for i, e in enumerate(pre):
            if not isinstance(e, dict) \
                    or not isinstance(e.get("bucket"), int) \
                    or e["bucket"] < 1 \
                    or not isinstance(e.get("file"), str) \
                    or not isinstance(e.get("key"), str):
                raise MXNetError(
                    f"{where}: precompiled entry {i} is not a "
                    f"{{bucket>=1, file, key}} record")
            f = e["file"]
            if os.path.isabs(f) or ".." in f.split("/"):
                raise MXNetError(
                    f"{where}: precompiled entry {i} file {f!r} must "
                    f"be a relative path inside the artifact directory")
    qb = manifest.get("quantization")
    if qb is not None:
        # v4: quantized-artifact metadata.  The scales here describe
        # the int8/fp8 constants baked into the .shlo — a wrong scale
        # means the manifest lies about the program, so the block is
        # both structurally checked and digest-verified.
        from .ops.shape_rules import QUANT_DTYPES
        if mver is None or mver < 4:
            raise MXNetError(
                f"{where}: 'quantization' needs manifest_version >= 4 "
                f"(got {mver!r}) — re-export with "
                f"deploy.export_stablehlo(quantize=...)")
        if not isinstance(qb, dict) \
                or qb.get("mode") not in ("int8", "fp8") \
                or not isinstance(qb.get("weights"), list) \
                or not qb["weights"]:
            raise MXNetError(
                f"{where}: manifest 'quantization' must be a dict with "
                f"mode in ('int8', 'fp8') and a non-empty 'weights' "
                f"list")
        for i, w in enumerate(qb["weights"]):
            ok = isinstance(w, dict) \
                and isinstance(w.get("name"), str) \
                and isinstance(w.get("scale"), (int, float)) \
                and not isinstance(w.get("scale"), bool) \
                and isinstance(w.get("dtype"), str) \
                and isinstance(w.get("elems"), int) and w["elems"] >= 1
            if not ok:
                raise MXNetError(
                    f"{where}: quantization weight entry {i} is not a "
                    f"{{name, scale, dtype, elems>=1}} record")
            scale = float(w["scale"])
            if not (scale > 0.0) or not np.isfinite(scale):
                raise MXNetError(
                    f"{where}: quantization scale for {w['name']!r} "
                    f"must be a positive finite float, got {w['scale']!r}"
                    f" — the manifest is corrupted or hand-edited; "
                    f"re-export the artifact")
            if w["dtype"] not in QUANT_DTYPES:
                raise MXNetError(
                    f"{where}: quantization dtype {w['dtype']!r} for "
                    f"{w['name']!r} not in {sorted(QUANT_DTYPES)}")
            if (qb["mode"] == "int8") != (w["dtype"] == "int8"):
                raise MXNetError(
                    f"{where}: quantization weight {w['name']!r} dtype "
                    f"{w['dtype']!r} disagrees with mode "
                    f"{qb['mode']!r}")
        calib = qb.get("calibration")
        if calib is not None:
            if not isinstance(calib, dict):
                raise MXNetError(
                    f"{where}: quantization 'calibration' must be a "
                    f"dict")
            for field in ("max_abs_err", "max_rel_err"):
                v = calib.get(field)
                if v is not None and (
                        not isinstance(v, (int, float))
                        or isinstance(v, bool)
                        or not np.isfinite(float(v)) or float(v) < 0):
                    raise MXNetError(
                        f"{where}: calibration {field} must be a "
                        f"finite nonnegative number, got {v!r}")
        if "digest" in qb:
            # a PRESENT digest key must verify — including a null/
            # non-string value, else nulling the digest would bypass
            # both this check and the serving REQUIRE_DIGEST gate
            digest = qb["digest"]
            if not isinstance(digest, str) \
                    or digest != _quantization_digest(qb):
                raise MXNetError(
                    f"{where}: quantization digest mismatch — the "
                    f"per-tensor scales were modified after export "
                    f"(tampered or corrupted manifest); the baked "
                    f"weights no longer match their description, "
                    f"refusing to serve.  Re-export the artifact.")
    dec = manifest.get("decode")
    if dec is not None:
        # v3: decode-capable metadata — the paged-KV sizing contract for
        # autoregressive runtimes; a malformed block must fail at
        # export/load, not when a runtime divides by head_dim
        if not isinstance(dec, dict):
            raise MXNetError(f"{where}: manifest 'decode' must be a "
                             f"dict of model dimensions")
        for field in ("vocab_size", "num_layers", "num_heads",
                      "head_dim", "max_context"):
            v = dec.get(field)
            if not isinstance(v, int) or v < 1:
                raise MXNetError(
                    f"{where}: decode metadata field {field!r} must be "
                    f"a positive int, got {v!r}")
        eos = dec.get("eos_id")
        if eos is not None and (not isinstance(eos, int) or eos < 0
                                or eos >= dec["vocab_size"]):
            raise MXNetError(
                f"{where}: decode metadata eos_id {eos!r} outside "
                f"[0, vocab_size={dec['vocab_size']})")
        # speculative-decoding deployment metadata (docs/serving.md
        # §9): the draft model's cache-sizing dims next to the
        # target's, and the proposal depth the verify programs were
        # tuned for — same field rules as the target block
        draft = dec.get("draft")
        if draft is not None:
            if not isinstance(draft, dict):
                raise MXNetError(
                    f"{where}: decode metadata 'draft' must be a dict "
                    f"of draft-model dimensions")
            for field in ("vocab_size", "num_layers", "num_heads",
                          "head_dim", "max_context"):
                v = draft.get(field)
                if not isinstance(v, int) or v < 1:
                    raise MXNetError(
                        f"{where}: decode draft metadata field "
                        f"{field!r} must be a positive int, got {v!r}")
            if draft["vocab_size"] != dec["vocab_size"]:
                raise MXNetError(
                    f"{where}: decode draft vocab_size "
                    f"{draft['vocab_size']} != target vocab_size "
                    f"{dec['vocab_size']} — draft proposals must be "
                    f"target token ids")
        spec_k = dec.get("spec_k")
        if spec_k is not None:
            if not isinstance(spec_k, int) or spec_k < 1:
                raise MXNetError(
                    f"{where}: decode metadata spec_k must be a "
                    f"positive int, got {spec_k!r}")
            if spec_k + 1 > dec["max_context"]:
                raise MXNetError(
                    f"{where}: decode metadata spec_k {spec_k} + 1 "
                    f"exceeds max_context {dec['max_context']}")
    if bool(manifest.get("dynamic_batch")):
        for i, spec in enumerate(manifest["inputs"]):
            if not spec["shape"] or spec["shape"][0] is not None:
                raise MXNetError(
                    f"{where}: dynamic_batch manifest input {i} has a "
                    f"concrete leading dimension "
                    f"({spec['shape'] or 'scalar'}) — every input must "
                    f"share the symbolic batch dim")
        for i, spec in enumerate(outputs or ()):
            if not spec["shape"] or spec["shape"][0] is not None:
                raise MXNetError(
                    f"{where}: dynamic_batch manifest output {i} is not "
                    f"batch-major ({spec['shape'] or 'scalar'}): the "
                    f"block collapses the batch axis, so serving could "
                    f"not un-pad per-request rows — export with "
                    f"dynamic_batch=False or keep axis 0 the batch")
    return manifest


def _canon_dtype(d):
    """Canonical dtype NAME for comparison.  Works for extension dtypes
    (bfloat16 lives in ml_dtypes: ``np.dtype('bfloat16')`` raises
    TypeError, but an actual bfloat16 dtype object canonicalizes fine)."""
    try:
        return np.dtype(d).name
    except TypeError:
        return str(d)


def _resolve_dtype(name):
    """Manifest dtype NAME -> numpy dtype object (extension dtypes via
    ml_dtypes) — the inverse of ``_sig_entry`` for building concrete
    avals out of a signature."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, str(name)))


def _shape_dtype(x):
    """(shape, dtype name) of an NDArray / numpy / jax array without
    copying."""
    if hasattr(x, "_data"):            # NDArray
        x = x._data
    a = x if hasattr(x, "shape") and hasattr(x, "dtype") else np.asarray(x)
    return tuple(a.shape), _canon_dtype(a.dtype)


def validate_inputs(manifest, arrays, where="load_stablehlo"):
    """Check caller arrays against a manifest's input signature.

    Raises a descriptive ``MXNetError`` on arity, dtype, rank, or
    dimension mismatch — the serving-time guard that turns what would be
    an opaque PJRT shape error into an actionable message.  ``null``
    dimensions in the manifest (symbolic at export time) accept any
    size; with ``dynamic_batch`` all leading dimensions must also agree
    with each other (they were exported as one symbolic size).
    """
    sig = manifest["inputs"]
    if len(arrays) != len(sig):
        raise MXNetError(
            f"{where}: expected {len(sig)} input(s) per the artifact "
            f"manifest, got {len(arrays)}")
    dynamic = bool(manifest.get("dynamic_batch"))
    lead = None
    for i, (spec, arr) in enumerate(zip(sig, arrays)):
        shape, dtype = _shape_dtype(arr)
        want_shape = list(spec["shape"])
        if dynamic and want_shape:
            want_shape[0] = None
        want_dtype = _canon_dtype(spec["dtype"])
        want_str = "x".join("?" if d is None else str(d)
                            for d in want_shape)
        got_str = "x".join(str(d) for d in shape)
        if dtype != want_dtype:
            raise MXNetError(
                f"{where}: input {i} dtype mismatch — manifest declares "
                f"{want_dtype}[{want_str}], got {dtype}[{got_str}]")
        if len(shape) != len(want_shape):
            raise MXNetError(
                f"{where}: input {i} rank mismatch — manifest declares "
                f"{want_dtype}[{want_str}] ({len(want_shape)}d), got "
                f"{got_str} ({len(shape)}d)")
        for ax, (got, want) in enumerate(zip(shape, want_shape)):
            if want is not None and got != want:
                raise MXNetError(
                    f"{where}: input {i} shape mismatch at axis {ax} — "
                    f"manifest declares {want_dtype}[{want_str}], got "
                    f"{got_str}")
        if dynamic and shape:
            if lead is None:
                lead = shape[0]
            elif shape[0] != lead:
                raise MXNetError(
                    f"{where}: dynamic-batch inputs disagree on the "
                    f"batch dimension ({lead} vs {shape[0]} at input "
                    f"{i}) — it was exported as one shared size")


class StableHLOModel:
    """A reloaded artifact plus its serving signature.

    ``call(*arrays)`` validates against the manifest (when the artifact
    shipped one) and delegates to the deserialized ``jax.export``
    module; attribute access falls through to it, so existing callers of
    ``load_stablehlo(...)`` keep working unchanged.
    """

    def __init__(self, exported, manifest, path, content_hash=None):
        self.exported = exported
        self.manifest = manifest
        self.path = path
        # content address of the serialized module — the program-identity
        # half of every compile-cache key
        self.content_hash = content_hash

    @property
    def dynamic_batch(self):
        return bool(self.manifest and self.manifest.get("dynamic_batch"))

    @property
    def quantization(self):
        """The manifest v4 ``quantization`` block (mode, per-tensor
        scales, calibration error) or None for f32 artifacts."""
        return (self.manifest or {}).get("quantization")

    def _shipped_payload(self, key):
        """Path of a precompiled executable shipped next to the manifest
        (``export_stablehlo(precompile=...)``), or None."""
        if self.manifest is None:
            return None
        for e in self.manifest.get("precompiled") or ():
            if e.get("key") == key:
                path = os.path.join(os.path.dirname(os.path.abspath(
                    self.path)), e["file"])
                if os.path.exists(path):
                    return path
        return None

    def aot_program(self, rows=None, cache=None):
        """Bucket-concrete compiled callable, checked against the
        persistent compile cache BEFORE compiling (docs/serving.md §5).

        Resolution order: compile-cache entry (deserialize, zero XLA
        compiles) -> executable shipped inside the artifact by
        ``export_stablehlo(precompile=...)`` (ingested into the cache
        when one is configured) -> fresh AOT compile (stored back into
        the cache).  ``rows`` is the concrete leading dimension for
        dynamic-batch artifacts (the serving shape bucket); static
        artifacts compile their exported shapes.  The returned callable
        carries ``_mx_from_disk_cache`` so the serving batcher can
        label disk hits vs compiles.
        """
        import jax

        from . import compile_cache as _cc
        if self.manifest is None:
            raise MXNetError(
                f"aot_program({self.path}): the artifact has no "
                f"manifest — re-export with deploy.export_stablehlo")
        sig = self.manifest["inputs"]
        dynamic = self.dynamic_batch
        if dynamic and rows is None:
            raise MXNetError(
                f"aot_program({self.path}): a dynamic-batch artifact "
                f"needs concrete rows= to compile")
        avals, dtypes = [], []
        for i, spec in enumerate(sig):
            shape = list(spec["shape"])
            if dynamic and shape:
                shape[0] = int(rows)
            if any(d is None for d in shape):
                raise MXNetError(
                    f"aot_program({self.path}): input {i} has a "
                    f"symbolic non-batch dimension {spec['shape']} — "
                    f"cannot pick a concrete compile shape")
            avals.append(jax.ShapeDtypeStruct(tuple(shape),
                                              _resolve_dtype(spec["dtype"])))
            dtypes.append(spec["dtype"])
        bucket = int(rows) if dynamic else \
            (sig[0]["shape"][0] if sig and sig[0]["shape"] else 0)
        if self.content_hash is None:
            raise MXNetError(
                f"aot_program({self.path}): no content hash (load the "
                f"artifact via deploy.load_stablehlo)")
        cache = _cc.get_default() if cache is None else cache
        key = _cc.cache_key(self.content_hash, bucket, dtypes)
        shipped = self._shipped_payload(key)
        if shipped is not None and cache.enabled:
            cache.ingest(key, shipped)          # then served as a hit
        prog, _source = _cc.aot_program(
            lambda *xs: self.exported.call(*xs), avals, key, cache,
            shipped_path=shipped)
        return prog

    def validate(self, arrays):
        if self.manifest is not None:
            validate_inputs(self.manifest, arrays,
                            where=os.path.basename(
                                _manifest_path(self.path)))

    def call(self, *arrays):
        self.validate(arrays)
        raw = tuple(a._data if hasattr(a, "_data") else a for a in arrays)
        # execute span under whatever request span the caller entered
        # (no ambient trace -> no-op); the artifact path identifies
        # WHICH program version a slow request actually ran
        with _tr.span("stablehlo.execute", path=self.path):
            # chaos site: artifact-execute fail/delay/stall (the
            # direct-call twin of the batcher's serving.execute site)
            _faults.inject("deploy.execute")
            return self.exported.call(*raw)

    __call__ = call

    def __getattr__(self, name):
        return getattr(self.exported, name)


def load_stablehlo(path):
    """Reload an exported artifact for in-process serving (the exporting
    side of the round trip; serving-side consumers only need jax).

    Returns a :class:`StableHLOModel`: ``.call`` validates inputs
    against the ``.json`` manifest (shape/dtype mismatches raise a
    clear ``MXNetError`` instead of an opaque PJRT failure) and the
    manifest doubles as the serving signature for
    ``mxnet_tpu.serving.ModelRepository``.
    """
    from jax import export as jexport
    if not os.path.exists(path):
        raise MXNetError(f"no artifact at {path}")
    with open(path, "rb") as f:
        raw = f.read()
    exported = jexport.deserialize(bytearray(raw))
    return StableHLOModel(exported, load_manifest(path), path,
                          content_hash=hashlib.sha256(raw).hexdigest())
