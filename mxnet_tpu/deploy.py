"""Language-neutral deployment artifacts (docs/frontends.md §2).

The reference serves non-Python consumers through the flat C ABI
(`cpp-package`, Scala, …, SURVEY.md §2.3) and `amalgamation/` for
predict-only mobile builds.  Here the deployment boundary is the
compiled program, not the API: a hybridized block exports to a
**StableHLO artifact** (serialized `jax.export` module with the weights
baked in) that any PJRT-bearing runtime executes WITHOUT importing this
framework — the test suite proves it by running one in a subprocess
that imports only ``jax``.
"""
from __future__ import annotations

import json
import os

from .base import MXNetError

__all__ = ["export_stablehlo", "load_stablehlo"]


def export_stablehlo(block, *example_inputs, path, emit_text=False):
    """Export ``block``'s inference forward as a StableHLO artifact.

    Writes ``path.shlo`` (serialized module, weights embedded as
    constants) and ``path.json`` (input/output signature manifest).
    With ``emit_text=True`` also writes ``path.stablehlo.txt`` (the MLIR
    module, for inspection / non-JAX StableHLO consumers).

    The artifact is self-contained: load it with
    ``jax.export.deserialize(open(...).read()).call(*arrays)`` — no
    ``mxnet_tpu`` import needed at serving time (the deployment-boundary
    equivalent of the reference's amalgamation predict-only build).
    """
    import jax
    from jax import export as jexport

    from .parallel.functional import functionalize

    apply_fn, params = functionalize(block, *example_inputs,
                                     train_mode=False)

    def infer(*xs):
        out, _aux = apply_fn(params, *xs)
        return out

    args = tuple(
        jax.ShapeDtypeStruct(tuple(x.shape), x._data.dtype)
        for x in example_inputs)
    try:
        exported = jexport.export(jax.jit(infer))(*args)
    except Exception as e:
        raise MXNetError(f"export_stablehlo: lowering failed: {e}") from e
    blob = exported.serialize()
    with open(path + ".shlo", "wb") as f:
        f.write(bytes(blob))
    manifest = {
        "format": "jax.export/stablehlo",
        "inputs": [{"shape": list(x.shape), "dtype": str(x._data.dtype)}
                   for x in example_inputs],
        "block": type(block).__name__,
    }
    with open(path + ".json", "w") as f:
        json.dump(manifest, f, indent=1)
    if emit_text:
        with open(path + ".stablehlo.txt", "w") as f:
            f.write(exported.mlir_module())
    return path + ".shlo"


def load_stablehlo(path):
    """Reload an exported artifact for in-process serving (the exporting
    side of the round trip; serving-side consumers only need jax)."""
    from jax import export as jexport
    if not os.path.exists(path):
        raise MXNetError(f"no artifact at {path}")
    with open(path, "rb") as f:
        return jexport.deserialize(bytearray(f.read()))
