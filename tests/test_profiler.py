"""Profiler tests (reference strategy: tests/python/unittest/test_profiler.py:
start/stop, dump, parse the chrome trace, find named events)."""
import json

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, profiler


@pytest.fixture(autouse=True)
def _clean_profiler():
    yield
    profiler.stop()


class TestProfiler:
    def test_op_events_in_chrome_trace(self, tmp_path):
        fname = str(tmp_path / "profile.json")
        profiler.set_config(filename=fname, aggregate_stats=True)
        profiler.start()
        a = nd.array(np.random.rand(32, 32).astype(np.float32))
        b = nd.array(np.random.rand(32, 32).astype(np.float32))
        c = nd.dot(a, b)
        c = nd.relu(c)
        c.wait_to_read()
        profiler.stop()
        path = profiler.dump()
        with open(path) as f:
            trace = json.load(f)
        events = trace["traceEvents"]
        names = {e["name"] for e in events}
        assert "dot" in names
        assert "relu" in names
        ops = [e for e in events if e["name"] == "dot"]
        assert all(e["ph"] == "X" and e["dur"] >= 0 and "ts" in e
                   for e in ops)
        # aggregate summary written alongside
        with open(path + ".summary.txt") as f:
            summary = f.read()
        assert "dot" in summary and "Calls" in summary

    def test_user_scope_and_step_events(self, tmp_path):
        profiler.set_config(filename=str(tmp_path / "p.json"))
        profiler.start()
        with profiler.scope("train_step"):
            x = nd.ones((8, 8))
            (x * 2).wait_to_read()
        profiler.stop()
        trace = json.loads(profiler.dumps())
        names = [e["name"] for e in trace["traceEvents"]]
        assert "train_step" in names

    def test_pause_resume(self, tmp_path):
        profiler.set_config(filename=str(tmp_path / "p.json"))
        profiler.start()
        profiler.pause()
        nd.ones((4,)).wait_to_read()
        profiler.resume()
        x = nd.zeros((4,))
        nd.exp(x).wait_to_read()
        profiler.stop()
        trace = json.loads(profiler.dumps())
        names = [e["name"] for e in trace["traceEvents"]]
        assert "exp" in names
        assert "_ones" not in names   # recorded nothing while paused

    def test_counter_and_marker(self, tmp_path):
        profiler.set_config(filename=str(tmp_path / "p.json"))
        profiler.start()
        c = profiler.Counter(name="samples")
        c.set_value(10)
        c.increment(5)
        m = profiler.Marker(name="epoch_end")
        m.mark()
        profiler.stop()
        trace = json.loads(profiler.dumps())
        counters = [e for e in trace["traceEvents"] if e["ph"] == "C"]
        assert counters and counters[-1]["args"]["samples"] == 15
        assert any(e["ph"] == "i" and e["name"] == "epoch_end"
                   for e in trace["traceEvents"])

    def test_set_state_and_errors(self):
        profiler.set_state("run")
        with pytest.raises(mx.MXNetError):
            # recording options are locked while running (filename /
            # continuous_dump are the only mid-run reconfigurables)
            profiler.set_config(profile_memory=True)
        profiler.set_state("stop")
        with pytest.raises(mx.MXNetError):
            profiler.set_state("bogus")
        with pytest.raises(mx.MXNetError):
            profiler.set_config(not_an_option=1)

    def test_profile_memory_emits_live_bytes_counter(self, tmp_path):
        """profile_memory=True must be real on every backend: at least
        one live-bytes ph:'C' event lands in the trace (device
        memory_stats when available, host RSS fallback on CPU)."""
        profiler.set_config(filename=str(tmp_path / "m.json"),
                            profile_memory=True)
        profiler.start()
        nd.ones((16, 16)).wait_to_read()
        profiler.stop()
        trace = json.loads(profiler.dumps())
        mem = [e for e in trace["traceEvents"]
               if e["ph"] == "C" and e["name"] == "memory.live_bytes"]
        assert mem
        assert all(v >= 0 for e in mem for v in e["args"].values())
        profiler.set_config(profile_memory=False)

    def test_continuous_dump_writes_on_stop(self, tmp_path):
        path_a = str(tmp_path / "auto.json")
        profiler.set_config(filename=path_a, continuous_dump=True)
        profiler.start()
        nd.exp(nd.zeros((4,))).wait_to_read()
        profiler.stop()                 # auto-dumps without explicit dump()
        with open(path_a) as f:
            trace = json.load(f)
        assert any(e["name"] == "exp" for e in trace["traceEvents"])
        profiler.set_config(continuous_dump=False)

    def test_filename_set_after_start_is_honored(self, tmp_path):
        path_b = str(tmp_path / "late.json")
        profiler.set_config(filename=str(tmp_path / "early.json"))
        profiler.start()
        nd.ones((4,)).wait_to_read()
        # filename (and continuous_dump) may change mid-run
        profiler.set_config(filename=path_b)
        ret = profiler.dump()           # finished=True: stops, then writes
        assert ret == path_b
        assert not (tmp_path / "early.json").exists()
        with open(path_b) as f:
            json.load(f)

    def test_concurrent_record_vs_dump_reset(self):
        """Satellite regression: event appends racing dumps(reset=True)
        must neither crash nor corrupt the trace structure.

        The writers are BOUNDED (ISSUE-15 tier-1 relief): the original
        free-running version raced unbounded appends against a fixed
        200-round dump loop — whenever 4 spinning producers out-ran one
        json-encoding consumer (any loaded CI box), the backlog grew
        every round and the encode diverged into a multi-minute hang
        that truncated the whole tier-1 tail.  A fixed per-writer event
        budget keeps the interleaving (appends land mid-swap on every
        run) while capping total work at well under a second."""
        import threading
        profiler.set_config(filename="/tmp/_race.json")
        profiler.start()
        errs = []

        def writer():
            c = profiler.Counter(name="race")
            try:
                for i in range(4000):
                    c.set_value(i)
                    profiler._record("spin", "user", profiler._now_us(),
                                     1.0)
            except Exception as e:      # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=writer) for _ in range(4)]
        for t in threads:
            t.start()
        # dump-reset continuously while the writers drain their budgets
        while any(t.is_alive() for t in threads):
            json.loads(profiler.dumps(reset=True))
        for t in threads:
            t.join()
        json.loads(profiler.dumps(reset=True))      # the racing tail
        profiler.stop()
        assert not errs

    def test_executor_spans(self, tmp_path):
        from mxnet_tpu import sym
        x = sym.var("x")
        y = sym.exp(x) * 2.0
        ex = y.simple_bind(mx.cpu(), x=(4, 4))
        profiler.set_config(filename=str(tmp_path / "p.json"))
        profiler.start()
        ex.forward()
        profiler.stop()
        trace = json.loads(profiler.dumps())
        assert any(e["name"] == "Executor::forward"
                   for e in trace["traceEvents"])
