"""Profiler tests (reference strategy: tests/python/unittest/test_profiler.py:
start/stop, dump, parse the chrome trace, find named events)."""
import json

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, profiler


@pytest.fixture(autouse=True)
def _clean_profiler():
    yield
    profiler.stop()


class TestProfiler:
    def test_op_events_in_chrome_trace(self, tmp_path):
        fname = str(tmp_path / "profile.json")
        profiler.set_config(filename=fname, aggregate_stats=True)
        profiler.start()
        a = nd.array(np.random.rand(32, 32).astype(np.float32))
        b = nd.array(np.random.rand(32, 32).astype(np.float32))
        c = nd.dot(a, b)
        c = nd.relu(c)
        c.wait_to_read()
        profiler.stop()
        path = profiler.dump()
        with open(path) as f:
            trace = json.load(f)
        events = trace["traceEvents"]
        names = {e["name"] for e in events}
        assert "dot" in names
        assert "relu" in names
        ops = [e for e in events if e["name"] == "dot"]
        assert all(e["ph"] == "X" and e["dur"] >= 0 and "ts" in e
                   for e in ops)
        # aggregate summary written alongside
        with open(path + ".summary.txt") as f:
            summary = f.read()
        assert "dot" in summary and "Calls" in summary

    def test_user_scope_and_step_events(self, tmp_path):
        profiler.set_config(filename=str(tmp_path / "p.json"))
        profiler.start()
        with profiler.scope("train_step"):
            x = nd.ones((8, 8))
            (x * 2).wait_to_read()
        profiler.stop()
        trace = json.loads(profiler.dumps())
        names = [e["name"] for e in trace["traceEvents"]]
        assert "train_step" in names

    def test_pause_resume(self, tmp_path):
        profiler.set_config(filename=str(tmp_path / "p.json"))
        profiler.start()
        profiler.pause()
        nd.ones((4,)).wait_to_read()
        profiler.resume()
        x = nd.zeros((4,))
        nd.exp(x).wait_to_read()
        profiler.stop()
        trace = json.loads(profiler.dumps())
        names = [e["name"] for e in trace["traceEvents"]]
        assert "exp" in names
        assert "_ones" not in names   # recorded nothing while paused

    def test_counter_and_marker(self, tmp_path):
        profiler.set_config(filename=str(tmp_path / "p.json"))
        profiler.start()
        c = profiler.Counter(name="samples")
        c.set_value(10)
        c.increment(5)
        m = profiler.Marker(name="epoch_end")
        m.mark()
        profiler.stop()
        trace = json.loads(profiler.dumps())
        counters = [e for e in trace["traceEvents"] if e["ph"] == "C"]
        assert counters and counters[-1]["args"]["samples"] == 15
        assert any(e["ph"] == "i" and e["name"] == "epoch_end"
                   for e in trace["traceEvents"])

    def test_set_state_and_errors(self):
        profiler.set_state("run")
        with pytest.raises(mx.MXNetError):
            profiler.set_config(filename="x.json")  # while running
        profiler.set_state("stop")
        with pytest.raises(mx.MXNetError):
            profiler.set_state("bogus")
        with pytest.raises(mx.MXNetError):
            profiler.set_config(not_an_option=1)

    def test_executor_spans(self, tmp_path):
        from mxnet_tpu import sym
        x = sym.var("x")
        y = sym.exp(x) * 2.0
        ex = y.simple_bind(mx.cpu(), x=(4, 4))
        profiler.set_config(filename=str(tmp_path / "p.json"))
        profiler.start()
        ex.forward()
        profiler.stop()
        trace = json.loads(profiler.dumps())
        assert any(e["name"] == "Executor::forward"
                   for e in trace["traceEvents"])
