"""Decode engine: token-level continuous batching + paged KV cache
(docs/serving.md §6).

Scheduler invariants run on fake numpy models — ZERO XLA compiles — so
admit/evict, page alloc/free, and block-table reuse are tested at step
granularity.  The end-to-end class at the bottom drives a tiny real
``TransformerDecoderLM`` through ``ModelServer.generate()`` (a handful
of tiny compiles) and asserts the program-count bound via the jit
cache-size helper.
"""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, runtime_metrics as rm, serving
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serving.decode import DecodeEngine
from mxnet_tpu.serving.kv_cache import PageAllocator, PageGeometry


@pytest.fixture(autouse=True)
def _metrics_on():
    rm.reset()
    rm.enable()
    yield
    rm.disable()
    rm.reset()


def _cfg(**kw):
    kw.setdefault("decode_page_size", 4)
    kw.setdefault("decode_pool_pages", 9)      # 8 usable
    kw.setdefault("decode_max_batch", 2)
    kw.setdefault("decode_max_new_tokens", 4)
    return serving.ServingConfig(**kw)


class FakeModel:
    """Decode-model protocol in plain numpy: next token = (last + 1)
    mod vocab; prefill proposes the prompt's last token.  Asserts the
    engine's inactive-slot contract on every step."""

    vocab_size = 16
    max_context = 32

    def __init__(self, eos_id=None):
        self.prefills = 0
        self.steps = 0
        self.step_batches = []          # active slot count per step
        if eos_id is not None:
            self.eos_id = eos_id

    def prefill(self, tokens, length, block_table):
        self.prefills += 1
        assert tokens.ndim == 2 and tokens.shape[0] == 1
        assert tokens.shape[1] >= int(length)
        logits = np.zeros((self.vocab_size,), np.float32)
        logits[int(tokens[0, int(length) - 1]) % self.vocab_size] = 1.0
        return logits

    def decode_step(self, tokens, positions, block_tables):
        self.steps += 1
        active = positions > 0
        # inactive slots carry zeros and an all-null block table
        assert np.all(tokens[~active] == 0)
        assert np.all(block_tables[~active] == 0)
        self.step_batches.append(int(active.sum()))
        logits = np.zeros((tokens.shape[0], self.vocab_size), np.float32)
        logits[np.arange(tokens.shape[0]),
               (tokens + 1) % self.vocab_size] = 1.0
        return logits


class ChainModel:
    """Self-consistent decode-model protocol in plain numpy: next
    token = (last + 1) mod vocab EVERYWHERE — prefill, decode_step,
    and the multi-token verify window agree, so the prefix-cache and
    speculative paths must reproduce the plain path byte-for-byte."""

    vocab_size = 32
    max_context = 64

    def __init__(self):
        self.prefills = 0
        self.steps = 0
        self.verifies = 0
        self.copies = []

    def _row(self, t):
        row = np.zeros((self.vocab_size,), np.float32)
        row[(int(t) + 1) % self.vocab_size] = 1.0
        return row

    def prefill(self, tokens, length, block_table):
        self.prefills += 1
        return self._row(tokens[0, int(length) - 1])

    def decode_step(self, tokens, positions, block_tables):
        self.steps += 1
        return np.stack([self._row(t) for t in tokens])

    def verify(self, tokens, start, length, block_table):
        self.verifies += 1
        return np.stack([self._row(t) for t in tokens[0]])

    def copy_page(self, src, dst):
        self.copies.append((int(src), int(dst)))


class SkewDraft(ChainModel):
    """Draft that proposes (t + skew) — skew=1 agrees with ChainModel
    (full acceptance), skew=2 never agrees (zero acceptance)."""

    def __init__(self, skew=1):
        super().__init__()
        self.skew = skew

    def _row(self, t):
        row = np.zeros((self.vocab_size,), np.float32)
        row[(int(t) + self.skew) % self.vocab_size] = 1.0
        return row


def _drive(eng, seqs, limit=64):
    """Step until every sequence finished (bounded)."""
    n = 0
    while not all(s.event.is_set() for s in seqs):
        eng.step()
        n += 1
        assert n < limit, "scheduler did not converge"
    return n


def _engine(model=None, draft=None, **cfg_kw):
    eng = DecodeEngine(model or FakeModel(), _cfg(**cfg_kw),
                       model_name="fake", draft=draft)
    eng._started = True                 # manual stepping, no loop thread
    return eng


# --------------------------------------------------------------- allocator
class TestPageAllocator:
    def _geom(self, **kw):
        kw.setdefault("page_size", 4)
        kw.setdefault("pool_pages", 9)
        kw.setdefault("max_context", 32)
        return PageGeometry(num_layers=1, num_heads=1, head_dim=1, **kw)

    def test_alloc_release_roundtrip(self):
        a = PageAllocator(self._geom())
        assert a.allocate("s1", 3)
        assert a.used_pages == 3 and a.free_pages == 5
        assert 0 not in a.pages_of("s1")            # null page reserved
        assert a.release("s1") == 3
        assert a.used_pages == 0
        a.check_leaks()

    def test_all_or_nothing(self):
        a = PageAllocator(self._geom(max_context=64))   # 16-slot tables
        assert not a.allocate("big", 9)             # > 8 usable
        assert a.used_pages == 0                    # nothing stranded
        assert a.allocate("s1", 8)
        assert not a.allocate("s2", 1)
        a.check_leaks()

    def test_double_release_raises(self):
        a = PageAllocator(self._geom())
        a.allocate("s1", 2)
        a.release("s1")
        with pytest.raises(MXNetError, match="unknown sequence"):
            a.release("s1")

    def test_corrupted_state_detected(self):
        a = PageAllocator(self._geom())
        a.allocate("s1", 2)
        a._free.append(a.pages_of("s1")[0])         # simulate corruption
        with pytest.raises(MXNetError, match="already free"):
            a.release("s1")

    def test_block_table_width_enforced(self):
        g = self._geom(max_context=8)               # 2 slots of 4
        a = PageAllocator(PageGeometry(4, 9, 8, 1, 1, 1))
        assert g.pages_per_seq == 2
        with pytest.raises(MXNetError, match="exceed the block table"):
            a.allocate("s1", 3)

    def test_block_table_null_fill_and_reuse_after_eviction(self):
        a = PageAllocator(self._geom())
        a.allocate("s1", 2)
        first = a.pages_of("s1")
        table = a.block_table("s1")
        assert list(table[:2]) == first and np.all(table[2:] == 0)
        a.release("s1")
        a.allocate("s2", 2)
        # LIFO free list: the evicted pages back the new sequence
        assert set(a.pages_of("s2")) == set(first)
        a.check_leaks()

    def test_random_arrival_finish_orders_never_leak(self):
        rng = np.random.RandomState(0)
        a = PageAllocator(self._geom(pool_pages=17, max_context=64))
        live, next_id = {}, 0
        for _ in range(300):
            if live and rng.rand() < 0.45:
                sid = rng.choice(sorted(live))
                a.release(sid)
                del live[sid]
            else:
                n = int(rng.randint(1, 5))
                sid = next_id = next_id + 1
                if a.allocate(sid, n):
                    live[sid] = n
            a.check_leaks()
            assert a.used_pages == sum(live.values())
        for sid in sorted(live):
            a.release(sid)
        a.check_leaks()
        assert a.free_pages == a.geometry.usable_pages

    def test_geometry_validation(self):
        with pytest.raises(MXNetError, match="null page"):
            PageGeometry(4, 1, 32, 1, 1, 1)
        with pytest.raises(MXNetError, match="page_size"):
            PageGeometry(0, 8, 32, 1, 1, 1)
        g = self._geom()
        assert g.pages_for(0) == 0
        assert g.pages_for(1) == 1
        assert g.pages_for(4) == 1
        assert g.pages_for(5) == 2


# ----------------------------------------------------- refcounted sharing
class TestRefcountedAllocator:
    def _geom(self, **kw):
        kw.setdefault("page_size", 4)
        kw.setdefault("pool_pages", 17)
        kw.setdefault("max_context", 64)
        return PageGeometry(num_layers=1, num_heads=1, head_dim=1, **kw)

    def test_share_refcounts_and_release_order(self):
        a = PageAllocator(self._geom())
        a.allocate("s1", 3)
        pages = a.pages_of("s1")
        a.share("s2", pages[:2])
        assert a.refcount(pages[0]) == 2 and a.refcount(pages[2]) == 1
        assert a.shared_pages == 2
        a.check_leaks()
        a.release("s1")                 # shared pages survive
        assert a.refcount(pages[0]) == 1
        assert a.used_pages == 2        # page[2] freed
        a.check_leaks()
        a.release("s2")
        assert a.used_pages == 0
        a.check_leaks()

    def test_share_guards(self):
        a = PageAllocator(self._geom())
        a.allocate("s1", 1)
        page = a.pages_of("s1")[0]
        with pytest.raises(MXNetError, match="free or out of range"):
            a.share("s2", [page + 1])       # never allocated
        with pytest.raises(MXNetError, match="already in this"):
            a.share("s1", [page])           # self re-alias
        a.release("s1")
        with pytest.raises(MXNetError, match="free or out of range"):
            a.share("s2", [page])           # freed page cannot alias
        a.check_leaks()

    def test_cache_retain_outlives_writer_and_double_free_guards(self):
        a = PageAllocator(self._geom())
        a.allocate("s1", 2)
        p0, p1 = a.pages_of("s1")
        a.retain_cached(p0)
        a.release("s1")
        assert a.cached_pages == 1 and a.used_pages == 1
        assert a.cache_only(p0)
        a.check_leaks()
        with pytest.raises(MXNetError, match="not cache-held"):
            a.release_cached(p1)
        a.release_cached(p0)
        assert a.used_pages == 0
        with pytest.raises(MXNetError, match="not cache-held"):
            a.release_cached(p0)            # double eviction
        a.check_leaks()

    def test_admit_all_or_nothing_with_shared(self):
        a = PageAllocator(self._geom(pool_pages=5))     # 4 usable
        a.allocate("w", 3)
        shared = a.pages_of("w")[:2]
        for p in shared:
            a.retain_cached(p)
        a.release("w")                  # 2 cached + 1 freed -> 2 free
        assert not a.admit("s", shared, 3)      # fresh 3 > 2 free
        assert a.pages_of("s") == []            # nothing stranded
        a.check_leaks()
        assert a.admit("s", shared, 2)
        assert a.pages_of("s")[:2] == shared
        assert a.refcount(shared[0]) == 2
        a.release("s")
        a.check_leaks()

    def test_random_shared_orders_never_leak(self):
        """The ISSUE-12 satellite: check_leaks stays EXACT across ~300
        random admit/finish/cancel/quarantine orders with shared pages
        and cache retains in the mix."""
        rng = np.random.RandomState(7)
        a = PageAllocator(self._geom(pool_pages=33))
        live, cached, next_id = {}, [], 0
        for _ in range(300):
            r = rng.rand()
            if live and r < 0.30:       # finish/cancel/quarantine:
                sid = rng.choice(sorted(live))      # all are release()
                a.release(sid)
                del live[sid]
            elif cached and r < 0.40:   # cache eviction
                idx = rng.randint(len(cached))
                a.release_cached(cached.pop(idx))
            elif live and cached and r < 0.55:      # shared admission
                sid = next_id = next_id + 1
                share = [p for p in cached if a.refcount(p)][:2]
                share = [p for p in share
                         if all(p not in a.pages_of(s) or s == sid
                                for s in live)]
                fresh = int(rng.randint(0, 3))
                if a.admit(sid, share, fresh):
                    live[sid] = len(share) + fresh
            else:                       # plain admission (+ retain)
                sid = next_id = next_id + 1
                n = int(rng.randint(1, 5))
                if a.allocate(sid, n):
                    live[sid] = n
                    if rng.rand() < 0.5:
                        page = a.pages_of(sid)[0]
                        if page not in cached:
                            a.retain_cached(page)
                            cached.append(page)
            a.check_leaks()
        for sid in sorted(live):
            a.release(sid)
        for page in cached:
            a.release_cached(page)
        a.check_leaks()
        assert a.used_pages == 0


# ------------------------------------------------------------- radix tree
class TestPrefixCacheTree:
    def _cache(self, pool_pages=33, max_pages=None, page_size=4):
        geom = PageGeometry(page_size, pool_pages, 64, 1, 1, 1)
        alloc = PageAllocator(geom)
        from mxnet_tpu.serving.kv_cache import PrefixCache
        return alloc, PrefixCache(alloc, max_pages=max_pages)

    def _seed(self, alloc, cache, sid, prompt):
        """Simulate one admission+prefill+insert for ``prompt``."""
        n = alloc.geometry.pages_for(len(prompt))
        assert alloc.allocate(sid, n)
        cache.insert(np.asarray(prompt, np.int32), alloc.pages_of(sid))
        return alloc.pages_of(sid)

    def test_insert_lookup_roundtrip_and_partial(self):
        alloc, cache = self._cache()
        pages = self._seed(alloc, cache, "s1", list(range(1, 13)))
        # 12 tokens = 3 full pages cached
        assert cache.pages == 3
        hit = cache.lookup(np.arange(1, 13, dtype=np.int32))
        assert hit == pages[:3]
        # longest-prefix semantics: shared 2 pages, then divergence
        hit = cache.lookup(np.asarray(list(range(1, 9)) + [99] * 4,
                                      np.int32))
        assert hit == pages[:2]
        # sub-page prompts and mismatches miss
        assert cache.lookup(np.asarray([1, 2], np.int32)) == []
        assert cache.lookup(np.asarray([9, 9, 9, 9], np.int32)) == []
        alloc.check_leaks()

    def test_branching_prefixes_share_the_trunk(self):
        alloc, cache = self._cache()
        a = self._seed(alloc, cache, "a", [1, 2, 3, 4, 5, 6, 7, 8])
        b_pages = [1, 2, 3, 4, 9, 9, 9, 9]
        n = alloc.geometry.pages_for(len(b_pages))
        alloc.allocate("b", n)
        cache.insert(np.asarray(b_pages, np.int32), alloc.pages_of("b"))
        # trunk chunk [1,2,3,4] cached ONCE (first writer wins)
        assert cache.pages == 3
        assert cache.lookup(np.asarray(b_pages, np.int32)) \
            == [a[0], alloc.pages_of("b")[1]]
        alloc.check_leaks()

    def test_refcount_aware_lru_eviction(self):
        alloc, cache = self._cache()
        live = self._seed(alloc, cache, "live", [1, 2, 3, 4])
        dead = self._seed(alloc, cache, "dead", [5, 6, 7, 8])
        alloc.release("dead")           # its page is now cache-only
        cache.lookup(np.asarray([5, 6, 7, 8], np.int32))  # touch: MRU
        # the LRU candidate [1,2,3,4] is pinned by the live sequence,
        # so eviction must take the MRU-but-evictable page instead
        assert cache.evict(1) == 1
        assert cache.lookup(np.asarray([5, 6, 7, 8], np.int32)) == []
        assert cache.lookup(np.asarray([1, 2, 3, 4], np.int32)) == live
        alloc.check_leaks()
        alloc.release("live")
        assert cache.evict(1) == 1      # now free to go
        assert alloc.used_pages == 0
        alloc.check_leaks()

    def test_leaf_first_eviction_keeps_inner_prefixes_sound(self):
        alloc, cache = self._cache()
        self._seed(alloc, cache, "s", list(range(1, 13)))
        alloc.release("s")
        assert cache.pages == 3
        # evicting one page must take the DEEPEST chunk: the remaining
        # tree still answers its prefix correctly
        assert cache.evict(1) == 1
        assert len(cache.lookup(np.arange(1, 13, dtype=np.int32))) == 2
        cache.clear()
        assert alloc.used_pages == 0
        alloc.check_leaks()

    def test_max_pages_cap(self):
        alloc, cache = self._cache(max_pages=2)
        self._seed(alloc, cache, "a", [1, 2, 3, 4, 5, 6, 7, 8])
        assert cache.pages == 2
        alloc.release("a")
        self._seed(alloc, cache, "b", [9, 9, 9, 9])
        # cap held: inserting b evicted an LRU page first
        assert cache.pages == 2
        alloc.check_leaks()

    def test_random_tree_ops_property(self):
        """Radix property test: lookups always equal the longest
        cached chunk-prefix, never stale pages, never leaks."""
        rng = np.random.RandomState(3)
        alloc, cache = self._cache(pool_pages=65)
        model = {}                      # tuple(chunks) path -> page
        sid = 0
        for _ in range(120):
            prompt = list(rng.randint(0, 3, size=rng.randint(4, 17)))
            chunks = [tuple(prompt[i * 4:(i + 1) * 4])
                      for i in range(len(prompt) // 4)]
            expect = []
            for i in range(len(chunks)):
                page = model.get(tuple(chunks[:i + 1]))
                if page is None:
                    break
                expect.append(page)
            got = cache.lookup(np.asarray(prompt, np.int32))
            assert got == expect, (prompt, got, expect)
            if rng.rand() < 0.6:
                sid += 1
                n = alloc.geometry.pages_for(len(prompt))
                if alloc.allocate(sid, n):
                    pages = alloc.pages_of(sid)
                    cache.insert(np.asarray(prompt, np.int32), pages)
                    for i in range(len(chunks)):
                        model.setdefault(tuple(chunks[:i + 1]),
                                         pages[i])
                    alloc.release(sid)
            alloc.check_leaks()
        cache.clear()
        alloc.check_leaks()
        assert alloc.used_pages == 0


# --------------------------------------------------------------- scheduler
class TestSchedulerInvariants:
    def test_greedy_chain_and_prefill_token(self):
        eng = _engine()
        s = eng.submit([1, 2, 3], max_new_tokens=3)
        _drive(eng, [s])
        # prefill proposes last prompt token, then +1 per decode step
        assert s.tokens == [3, 4, 5]
        assert s.finish_reason == "length"
        eng.allocator.check_leaks()

    def test_admit_and_evict_every_step(self):
        """Slot freed by an eviction is refilled on the NEXT step, not
        after the whole batch drains (token-level, not request-level).
        A step is admit -> prefill -> one decode step, so a 2-token
        request finishes WITHIN its admission step."""
        eng = _engine()                 # 2 slots
        long = eng.submit([1], max_new_tokens=6)
        short = eng.submit([2], max_new_tokens=2)
        third = eng.submit([3], max_new_tokens=4)
        eng.step()                      # admits long+short; third waits
        # short got prefill token + one decode token = done this step
        assert short.event.is_set()
        st = eng.stats()
        assert st["running"] == 1 and st["waiting"] == 1
        eng.step()                      # third admitted into freed slot
        st = eng.stats()
        assert st["running"] == 2 and st["waiting"] == 0
        assert not long.event.is_set()
        _drive(eng, [long, short, third])
        eng.allocator.check_leaks()
        assert eng.allocator.used_pages == 0

    def test_short_request_admitted_mid_flight_finishes_first(self):
        """The ISSUE-7 interleave criterion at engine level."""
        eng = _engine()
        long = eng.submit([1], max_new_tokens=8,
                          on_token=lambda t: None)
        eng.step()                      # long is mid-flight
        short = eng.submit([2], max_new_tokens=2)
        _drive(eng, [short])
        assert short.event.is_set() and not long.event.is_set()
        _drive(eng, [long])
        eng.allocator.check_leaks()

    def test_admission_gates_on_page_reservation(self):
        # 8 usable pages; each request needs ceil((1+15)/4) = 4 pages
        model = FakeModel()
        model.max_context = 16
        eng = _engine(model, decode_max_batch=4, decode_pool_pages=9)
        a = eng.submit([1], max_new_tokens=15)
        b = eng.submit([2], max_new_tokens=15)
        c = eng.submit([3], max_new_tokens=15)
        eng.step()
        st = eng.stats()
        # only two reservations fit even though a slot is free
        assert st["running"] == 2 and st["waiting"] == 1
        assert st["free_pages"] == 0
        _drive(eng, [a, b, c], limit=64)
        eng.allocator.check_leaks()
        assert eng.allocator.free_pages == 8

    def test_eos_evicts(self):
        eng = _engine()
        # chain 5 -> 6 -> 7(eos)
        s = eng.submit([5], max_new_tokens=8, eos_id=7)
        _drive(eng, [s])
        assert s.tokens[-1] == 7 and s.finish_reason == "eos"
        assert len(s.tokens) == 3
        eng.allocator.check_leaks()

    def test_streaming_callbacks_in_order(self):
        eng = _engine()
        got = []
        s = eng.submit([1, 2], max_new_tokens=3, on_token=got.append)
        _drive(eng, [s])
        assert got == s.tokens == [2, 3, 4]

    def test_callback_exception_does_not_kill_sequence(self):
        eng = _engine()

        def boom(tok):
            raise RuntimeError("client went away")

        s = eng.submit([1], max_new_tokens=2, on_token=boom)
        _drive(eng, [s])
        assert s.error is None and len(s.tokens) == 2

    def test_submit_validation(self):
        eng = _engine()
        with pytest.raises(MXNetError, match=">= 1 token"):
            eng.submit([], max_new_tokens=2)
        with pytest.raises(MXNetError, match="max_context"):
            eng.submit([1] * 30, max_new_tokens=10)
        with pytest.raises(MXNetError, match="max_new_tokens"):
            eng.submit([1], max_new_tokens=0)

    def test_waiting_queue_sheds_past_queue_depth(self):
        eng = _engine(queue_depth=2, shed_watermark=2)
        eng.submit([1], max_new_tokens=4)
        eng.submit([2], max_new_tokens=4)
        with pytest.raises(serving.ServerOverloadedError,
                           match="queue_depth"):
            eng.submit([3], max_new_tokens=4)
        assert eng.stats()["shed"] == 1

    def test_cancelled_waiting_pruned_even_with_full_batch(self):
        """A timed-out waiting request is dropped on the next step even
        when no slot frees — it must not occupy bounded queue space."""
        eng = _engine(decode_max_batch=1)
        running = eng.submit([1], max_new_tokens=8)
        eng.step()                      # occupies the only slot
        waiting = eng.submit([2], max_new_tokens=8)
        with pytest.raises(MXNetError):
            eng.result(waiting, timeout=0.01)   # cancels it
        before = rm.SERVING_DECODE_EVICTIONS.value(model="fake")
        eng.step()                      # batch still full, yet pruned
        assert waiting.event.is_set()
        assert waiting.finish_reason == "cancelled"
        assert eng.stats()["waiting"] == 0
        # never admitted -> not an eviction (pages were never held)
        assert rm.SERVING_DECODE_EVICTIONS.value(model="fake") == before
        _drive(eng, [running])
        eng.allocator.check_leaks()

    def test_result_timeout_cancels_and_reclaims(self):
        eng = _engine()
        s = eng.submit([1], max_new_tokens=8)
        eng.step()
        assert eng.allocator.used_pages > 0
        with pytest.raises(MXNetError, match="cancelled"):
            eng.result(s, timeout=0.01)
        eng.step()                      # eviction happens on the step
        assert s.finish_reason == "cancelled"
        eng.allocator.check_leaks()
        assert eng.allocator.used_pages == 0

    def test_metrics_published(self):
        eng = _engine()
        s = eng.submit([1, 2], max_new_tokens=3)
        _drive(eng, [s])
        assert rm.SERVING_DECODE_TOKENS.value(model="fake") == 3
        assert rm.SERVING_DECODE_EVICTIONS.value(model="fake") == 1
        assert rm.SERVING_DECODE_TTFT_SECONDS.count(model="fake") == 1
        assert rm.SERVING_DECODE_TOKEN_SECONDS.count(model="fake") == 2
        assert "serving_decode_steps" in rm.dump_prometheus()

    def test_threaded_engine_lifecycle(self):
        """autostart path: background loop, concurrent submitters,
        clean stop failing a straggler."""
        model = FakeModel()
        eng = DecodeEngine(model, _cfg(decode_max_batch=2),
                           model_name="fake", autostart=True)
        try:
            outs = {}

            def gen(i):
                outs[i] = eng.generate([i + 1], max_new_tokens=2,
                                       timeout=60)

            ts = [threading.Thread(target=gen, args=(i,))
                  for i in range(5)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(120)
            assert len(outs) == 5
            for i, toks in outs.items():
                assert toks.tolist() == [(i + 1) % 16, (i + 2) % 16]
            eng.allocator.check_leaks()
            assert eng.allocator.used_pages == 0
        finally:
            assert eng.stop(timeout=30)
        with pytest.raises(MXNetError, match="not accepting"):
            eng.submit([1])

    def test_stop_fails_outstanding(self):
        eng = DecodeEngine(FakeModel(), _cfg(), model_name="fake",
                           autostart=True)
        # saturate so one request stays waiting, then stop immediately
        seqs = [eng.submit([1], max_new_tokens=4) for _ in range(3)]
        assert eng.stop(timeout=30)
        for s in seqs:
            assert s.event.is_set()
            # each either finished legitimately or was failed by stop
            assert s.finish_reason in ("length", "stopped")
        eng.allocator.check_leaks()


# ----------------------------------------------------- prefix-cache engine
class TestPrefixCacheEngine:
    def _chain(self, prompt, n):
        out, t = [], prompt[-1]
        for _ in range(n):
            t = (t + 1) % ChainModel.vocab_size
            out.append(t)
        return out

    def test_full_hit_skips_prefill_and_cow_copies(self):
        model = ChainModel()
        eng = _engine(model, prefix_cache=True, decode_pool_pages=33)
        prompt = list(range(1, 9))              # 2 full pages
        a = eng.submit(prompt, max_new_tokens=3)
        _drive(eng, [a])
        assert a.tokens == self._chain(prompt, 3)
        assert eng.stats()["prefix_misses"] == 1
        prefills = model.prefills
        b = eng.submit(prompt, max_new_tokens=3)
        _drive(eng, [b])
        assert b.tokens == a.tokens             # byte-identical
        assert model.prefills == prefills       # prefill SKIPPED
        assert model.copies, "full hit must COW its append page"
        st = eng.stats()
        assert st["prefix_hits"] == 1
        assert st["prefix_tokens_saved"] == 7   # 8 matched - 1 re-run
        eng.allocator.check_leaks()

    def test_partial_hit_prefills_only_the_tail(self):
        model = ChainModel()
        eng = _engine(model, prefix_cache=True, decode_pool_pages=33)
        a = eng.submit(list(range(1, 9)), max_new_tokens=2)
        _drive(eng, [a])
        prefills = model.prefills
        prompt = list(range(1, 9)) + [20, 21]   # shared trunk + tail
        b = eng.submit(prompt, max_new_tokens=2)
        _drive(eng, [b])
        assert b.tokens == self._chain(prompt, 2)
        assert model.prefills == prefills       # tail via verify family
        st = eng.stats()
        assert st["prefix_hits"] == 1 and st["prefix_tokens_saved"] == 8
        eng.allocator.check_leaks()

    def test_shared_pages_counted_and_freed_exactly(self):
        model = ChainModel()
        eng = _engine(model, prefix_cache=True, decode_max_batch=2,
                      decode_pool_pages=33)
        prompt = list(range(1, 9))
        a = eng.submit(prompt, max_new_tokens=8)
        eng.step()                              # a running, 2 pages cached
        b = eng.submit(prompt, max_new_tokens=8)
        eng.step()                              # b aliases the trunk
        assert eng.allocator.shared_pages >= 1
        eng.allocator.check_leaks()
        _drive(eng, [a, b])
        eng.allocator.check_leaks()
        # all sequence pages returned; only cache-held pages remain
        st = eng.stats()
        assert st["sequences"] == 0
        assert st["used_pages"] == st["cached_pages"] > 0

    def test_cache_eviction_unblocks_admission(self):
        """A pool full of cache-only pages must yield to admissions
        (refcount-aware LRU eviction on demand)."""
        model = ChainModel()
        eng = _engine(model, prefix_cache=True, decode_pool_pages=9)
        # fill the cache: two distinct 2-page prompts = 4 cached pages
        for base in (0, 8):
            s = eng.submit([base + i for i in range(8)],
                           max_new_tokens=1)
            _drive(eng, [s])
        assert eng.stats()["cached_pages"] == 4
        eng.allocator.check_leaks()
        # 8 usable pages, 4 cache-held: this request needs 5 fresh
        s = eng.submit([20 + i for i in range(16)], max_new_tokens=3)
        _drive(eng, [s])
        assert s.finish_reason == "length"
        assert eng.stats()["prefix_evicted_pages"] >= 1
        eng.allocator.check_leaks()

    def test_eviction_never_frees_the_planned_hit_pages(self):
        """On-demand eviction under a pending HIT must take OTHER
        cache-only pages, never the ones the admission planned to
        alias/COW — freeing those would strand a half-shared sequence
        and storm-fail the step."""
        model = ChainModel()
        eng = _engine(model, prefix_cache=True, decode_pool_pages=9)
        a = eng.submit(list(range(1, 9)), max_new_tokens=1)   # 2 pages
        _drive(eng, [a])
        b = eng.submit([40, 41, 42, 43], max_new_tokens=1)    # 1 page
        _drive(eng, [b])
        assert eng.stats()["cached_pages"] == 3
        # full hit on A needing fresh=6 of 5 free: eviction must take
        # B's page (unprotected), keep A's two, and serve the hit
        s = eng.submit(list(range(1, 9)), max_new_tokens=20)
        _drive(eng, [s], limit=128)
        assert s.finish_reason == "length" and s.error is None
        assert list(s.tokens)[:3] == [9, 10, 11]
        st = eng.stats()
        assert st["prefix_hits"] == 1, st
        assert st["prefix_evicted_pages"] >= 1
        # B evicted, A still cached
        assert eng.prefix_cache.lookup(
            np.asarray([40, 41, 42, 43], np.int32)) == []
        assert len(eng.prefix_cache.lookup(
            np.asarray(list(range(1, 9)), np.int32))) == 2
        eng.allocator.check_leaks()

    def test_unservable_hit_plan_degrades_to_miss(self):
        """When the ONLY evictable pages are the planned hit's own,
        the plan is dropped (degrade to a miss, evict freely, plain
        prefill) instead of blocking the line forever."""
        model = ChainModel()
        eng = _engine(model, prefix_cache=True, decode_pool_pages=9)
        a = eng.submit(list(range(1, 9)), max_new_tokens=1)
        _drive(eng, [a])
        assert eng.stats()["cached_pages"] == 2
        # full hit would alias/COW both cached pages, but the request
        # needs all 8 usable pages fresh-or-shared: total=8, fresh=7 >
        # 6 free with both candidates protected -> degrade
        s = eng.submit(list(range(1, 9)), max_new_tokens=24)
        _drive(eng, [s], limit=128)
        assert s.finish_reason == "length" and s.error is None
        assert list(s.tokens)[:3] == [9, 10, 11]
        st = eng.stats()
        assert st["prefix_hits"] == 0 and st["prefix_misses"] == 2, st
        # the planned pages WERE freed for the degrade (the plain
        # prefill then legitimately re-seeded the cache with its own)
        assert st["prefix_evicted_pages"] == 2, st
        eng.allocator.check_leaks()

    def test_corrupt_lookup_degrades_to_plain_prefill(self):
        """The §9 degrade contract: a failed/corrupted radix lookup is
        a MISS — same tokens, prefill paid, nothing poisoned."""
        from mxnet_tpu import faults
        model = ChainModel()
        eng = _engine(model, prefix_cache=True, decode_pool_pages=33)
        prompt = list(range(1, 9))
        a = eng.submit(prompt, max_new_tokens=3)
        _drive(eng, [a])
        prefills = model.prefills
        with faults.plan("decode.prefix_lookup=corrupt,times=1"):
            b = eng.submit(prompt, max_new_tokens=3)
            _drive(eng, [b])
        assert b.tokens == a.tokens             # never wrong tokens
        assert model.prefills == prefills + 1   # degraded = plain path
        st = eng.stats()
        assert st["prefix_degraded"] == 1
        eng.allocator.check_leaks()

    def test_cached_path_failure_demotes_to_plain(self):
        """A failing verify program on the cached-prefill path releases
        the aliased pages and re-queues the request down the plain
        path — degradation, not quarantine, and leak-free."""
        from mxnet_tpu import faults
        model = ChainModel()
        eng = _engine(model, prefix_cache=True, decode_pool_pages=33,
                      retry_max=0)
        prompt = list(range(1, 9))
        a = eng.submit(prompt, max_new_tokens=3)
        _drive(eng, [a])
        # the next decode.prefill injection fires inside the CACHED
        # prefill (verify family) — after=0 hits the hit-path call
        with faults.plan("decode.prefill=fail,times=1"):
            b = eng.submit(prompt, max_new_tokens=3)
            _drive(eng, [b])
        assert b.tokens == a.tokens
        assert b.finish_reason == "length"
        st = eng.stats()
        assert st["prefix_degraded"] == 1
        assert st["quarantined"] == 0           # degrade, not quarantine
        assert st["admitted"] == st["evicted"] == 2
        # a demoted hit served NO cached work: it must not count as a
        # hit nor keep phantom tokens_saved (hit ratio stays honest)
        assert st["prefix_hits"] == 0
        assert st["prefix_tokens_saved"] == 0
        eng.allocator.check_leaks()

    def test_random_cached_orders_never_leak(self):
        """Engine-level half of the ISSUE-12 satellite: ~300 random
        submit/step/cancel orders over a small shared-prompt pool with
        the cache on — check_leaks() exact at every step."""
        rng = np.random.RandomState(11)
        model = ChainModel()
        eng = _engine(model, prefix_cache=True, decode_max_batch=4,
                      decode_pool_pages=33, queue_depth=256)
        prompts = [list(range(1, 9)), list(range(1, 13)),
                   list(range(1, 9)) + [9, 9], [5, 6, 7, 8]]
        live = []
        for _ in range(300):
            r = rng.rand()
            if r < 0.45:
                s = eng.submit(prompts[rng.randint(len(prompts))],
                               max_new_tokens=int(rng.randint(1, 5)))
                live.append(s)
            elif live and r < 0.6:
                live[rng.randint(len(live))].cancelled = True
            else:
                eng.step()
            eng.allocator.check_leaks()
            live = [s for s in live if not s.event.is_set()]
        _drive(eng, live, limit=256)
        eng.allocator.check_leaks()
        st = eng.stats()
        assert st["sequences"] == 0
        assert st["used_pages"] == st["cached_pages"]


# ----------------------------------------------------- speculative engine
class TestSpeculativeEngine:
    def test_full_acceptance_compresses_steps(self):
        """An agreeing draft emits k+1 tokens per round: 8 tokens land
        in ~2 engine steps instead of 8."""
        model = ChainModel()
        eng = _engine(model, draft=SkewDraft(1), spec_k=3,
                      decode_max_new_tokens=8, decode_pool_pages=33)
        s = eng.submit([5], max_new_tokens=8)
        n = _drive(eng, [s])
        assert s.tokens == [(5 + i) % 32 for i in range(1, 9)]
        assert n <= 4, n
        st = eng.stats()
        assert st["spec_accepted"] == st["spec_proposed"] > 0
        assert st["spec_acceptance"] == 1.0
        eng.allocator.check_leaks()

    def test_zero_acceptance_is_byte_identical_to_plain(self):
        """Rejection sampling in greedy mode is exact: even a draft
        that never agrees yields the plain path's exact tokens."""
        plain = _engine(ChainModel(), decode_max_new_tokens=8)
        want = plain.submit([5], max_new_tokens=8)
        _drive(plain, [want])
        model = ChainModel()
        eng = _engine(model, draft=SkewDraft(2), spec_k=3,
                      decode_max_new_tokens=8, decode_pool_pages=33)
        s = eng.submit([5], max_new_tokens=8)
        _drive(eng, [s])
        assert s.tokens == want.tokens
        st = eng.stats()
        assert st["spec_proposed"] > 0 and st["spec_accepted"] == 0
        eng.allocator.check_leaks()

    def test_eos_mid_window_stops_exactly(self):
        model = ChainModel()
        eng = _engine(model, draft=SkewDraft(1), spec_k=3,
                      decode_max_new_tokens=16, decode_pool_pages=33)
        # chain 5 -> 6 -> 7(eos): eos lands inside the first window
        s = eng.submit([5], max_new_tokens=16, eos_id=7)
        _drive(eng, [s])
        assert s.tokens == [6, 7] and s.finish_reason == "eos"
        eng.allocator.check_leaks()

    def test_length_cap_never_overshoots(self):
        model = ChainModel()
        eng = _engine(model, draft=SkewDraft(1), spec_k=3,
                      decode_max_new_tokens=16, decode_pool_pages=33)
        for n in (1, 2, 3, 4, 5):
            s = eng.submit([1], max_new_tokens=n)
            _drive(eng, [s])
            assert len(s.tokens) == n and s.finish_reason == "length"
            eng.allocator.check_leaks()

    def test_draft_failure_degrades_round_to_plain(self):
        class FlakyDraft(SkewDraft):
            def decode_step(self, tokens, positions, block_tables):
                self.steps += 1
                if self.steps == 1:
                    raise ValueError("draft died")
                return super().decode_step(tokens, positions,
                                           block_tables)

        model = ChainModel()
        eng = _engine(model, draft=FlakyDraft(1), spec_k=2,
                      decode_max_new_tokens=6, decode_pool_pages=33)
        s = eng.submit([5], max_new_tokens=6)
        _drive(eng, [s])
        assert s.tokens == [(5 + i) % 32 for i in range(1, 7)]
        assert eng.stats()["spec_fallbacks"] >= 1
        eng.allocator.check_leaks()

    def test_verify_failure_quarantines_leak_free(self):
        """A persistent verify failure is a TARGET failure: the §8
        quarantine path fires for that sequence alone; batchmates keep
        decoding and every page comes back."""
        from mxnet_tpu import faults
        model = ChainModel()
        eng = _engine(model, draft=SkewDraft(1), spec_k=2,
                      decode_max_batch=2, decode_max_new_tokens=6,
                      decode_pool_pages=33, retry_max=0)
        a = eng.submit([5], max_new_tokens=6)
        b = eng.submit([9], max_new_tokens=6)
        with faults.plan("decode.verify=fail,times=1"):
            _drive(eng, [a, b])
        done = {s.finish_reason for s in (a, b)}
        assert done == {"quarantined", "length"}, done
        ok = a if a.finish_reason == "length" else b
        assert ok.tokens == [(ok.prompt[0] + i) % 32
                             for i in range(1, 7)]
        assert eng.stats()["quarantined"] == 1
        eng.allocator.check_leaks()
        assert eng.stats()["used_pages"] == 0

    def test_spec_composes_with_prefix_cache(self):
        model = ChainModel()
        eng = _engine(model, draft=SkewDraft(1), spec_k=3,
                      prefix_cache=True, decode_max_new_tokens=8,
                      decode_pool_pages=33)
        prompt = list(range(1, 9))
        a = eng.submit(prompt, max_new_tokens=8)
        _drive(eng, [a])
        prefills = model.prefills
        b = eng.submit(prompt, max_new_tokens=8)
        _drive(eng, [b])
        assert b.tokens == a.tokens
        assert model.prefills == prefills       # hit skipped prefill
        st = eng.stats()
        assert st["prefix_hits"] == 1
        assert st["spec_accepted"] == st["spec_proposed"] > 0
        eng.allocator.check_leaks()

    def test_spec_without_draft_disabled_not_fatal(self):
        eng = _engine(ChainModel(), spec_k=3)
        assert eng.spec_k == 0
        s = eng.submit([5], max_new_tokens=2)
        _drive(eng, [s])
        assert s.tokens == [6, 7]


# ------------------------------------------------------------- end to end
@pytest.fixture(scope="module")
def tiny_lm_server():
    mx.random.seed(7)
    from mxnet_tpu.models.transformer_blocks import TransformerDecoderLM
    lm = TransformerDecoderLM(13, units=8, hidden_size=16, num_layers=1,
                              num_heads=2, max_length=16)
    lm.initialize(mx.init.Xavier())
    repo = serving.ModelRepository()
    repo.add_decoder("lm", lm)
    cfg = serving.ServingConfig(decode_page_size=4, decode_pool_pages=17,
                                decode_max_batch=2,
                                decode_max_new_tokens=4)
    srv = serving.ModelServer(repo, cfg)
    yield srv, lm
    srv.stop()


class TestGenerateEndToEnd:
    def _ref_generate(self, lm, prompt, n):
        toks = list(prompt)
        for _ in range(n):
            lg = lm(nd.NDArray(np.asarray([toks], np.int32))).asnumpy()
            toks.append(int(np.argmax(lg[0, -1])))
        return toks[len(prompt):]

    def test_generate_matches_full_forward(self, tiny_lm_server):
        srv, lm = tiny_lm_server
        for prompt, n in (([1, 2, 3], 3), ([5], 2), ([2, 4], 3)):
            got = srv.generate("lm", prompt, max_new_tokens=n,
                               timeout=300).tolist()
            assert got == self._ref_generate(lm, prompt, n)

    def test_concurrent_mixed_lengths_bound_programs(self, tiny_lm_server):
        """Program-count bound under a mixed-length run, via the jit
        cache-size helper (delta around the run — the pjit cache is per
        underlying function, and this adapter owns a fresh one)."""
        srv, lm = tiny_lm_server
        outs = {}

        def gen(i):
            prompt = list(range(1, 2 + i % 4))
            outs[i] = (prompt,
                       srv.generate("lm", prompt,
                                    max_new_tokens=2 + i % 3,
                                    timeout=300))

        ts = [threading.Thread(target=gen, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(300)
        assert len(outs) == 8
        for i, (prompt, toks) in outs.items():
            assert toks.tolist() == self._ref_generate(
                lm, prompt, 2 + i % 3)
        st = srv.decode_stats("lm")
        # <= prefill buckets + 1 decode program, from the pjit caches
        assert st["programs"] <= st["program_bound"], st
        from mxnet_tpu.serving.batcher import bucket_set
        adapter = list(srv._decoders.values())[0].model
        assert adapter._decode_jit._cache_size() == 1
        assert adapter._prefill_jit._cache_size() \
            <= len(bucket_set(adapter.max_context))

    def test_predict_and_generate_reject_wrong_kind(self, tiny_lm_server):
        srv, _lm = tiny_lm_server
        with pytest.raises(MXNetError, match="generate"):
            srv.predict("lm", np.zeros((1, 4), np.int32))
        srv.repository.add_function(
            "plain", lambda x: x,
            [{"shape": [None, 1], "dtype": "float32"}])
        with pytest.raises(MXNetError, match="add_decoder"):
            srv.generate("plain", [1, 2])

    def test_adapter_binds_one_live_engine(self, tiny_lm_server):
        """A second engine on the SAME adapter must be rejected (its
        setup would zero the live engine's KV pool), and a
        stop->start rebind keeps the compiled-program caches."""
        srv, _lm = tiny_lm_server
        srv.generate("lm", [1], max_new_tokens=2, timeout=300)
        eng = list(srv._decoders.values())[0]
        adapter = eng.model
        with pytest.raises(MXNetError, match="one decoder entry serves"):
            serving.DecodeEngine(adapter, srv.config, model_name="dup")
        programs = adapter.programs()
        assert eng.stop(timeout=60)
        assert adapter.pool is None            # pool released
        eng.start()                            # rebind, programs survive
        assert adapter.pool is not None
        out = srv.generate("lm", [1], max_new_tokens=2, timeout=300)
        assert adapter.programs() == programs  # zero recompiles
        assert len(out) == 2

    def test_paged_forward_honors_layer_norm_eps(self):
        """Non-default layer_norm_eps must reach the decode-mode
        forward — prefill logits match the training forward exactly."""
        import jax.numpy as jnp

        from mxnet_tpu.models.transformer_blocks import (
            TransformerDecoderLM, paged_lm_params, paged_prefill)
        mx.random.seed(3)
        lm = TransformerDecoderLM(11, units=8, hidden_size=16,
                                  num_layers=1, num_heads=2,
                                  max_length=8, layer_norm_eps=1e-1)
        lm.initialize(mx.init.Xavier())
        toks = np.array([[1, 2, 3]], np.int32)
        want = lm(nd.NDArray(toks)).asnumpy()[0, -1]
        params = paged_lm_params(lm)
        kp = jnp.zeros((1, 3, 4, 2, 4), jnp.float32)
        bt = np.array([1, 2], np.int32)
        got, _, _ = paged_prefill(
            params, jnp.asarray(toks), jnp.int32(3), jnp.asarray(bt),
            kp, kp, num_heads=2, page_size=4, layer_norm_eps=lm._eps)
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-4)
        # and the default-eps path would NOT match (the eps matters)
        bad, _, _ = paged_prefill(
            params, jnp.asarray(toks), jnp.int32(3), jnp.asarray(bt),
            kp, kp, num_heads=2, page_size=4)
        assert not np.allclose(np.asarray(bad), want, atol=1e-4)

    def test_ttft_histogram_recorded(self, tiny_lm_server):
        srv, _lm = tiny_lm_server
        rm.reset()
        srv.generate("lm", [1, 2], max_new_tokens=2, timeout=300)
        assert rm.SERVING_DECODE_TTFT_SECONDS.count(model="lm") == 1
        p99 = rm.SERVING_DECODE_TTFT_SECONDS.quantile(0.99, model="lm")
        assert np.isfinite(p99) and p99 > 0


# ---------------------------------------------- §9 end to end (real LM)
@pytest.fixture(scope="module")
def spec_lm():
    """One tiny target + one garbage draft (random weights: acceptance
    is incidental, parity is the point)."""
    mx.random.seed(7)
    from mxnet_tpu.models.transformer_blocks import TransformerDecoderLM
    lm = TransformerDecoderLM(13, units=8, hidden_size=16, num_layers=1,
                              num_heads=2, max_length=32)
    lm.initialize(mx.init.Xavier())
    mx.random.seed(29)
    draft = TransformerDecoderLM(13, units=8, hidden_size=16,
                                 num_layers=1, num_heads=2,
                                 max_length=32)
    draft.initialize(mx.init.Xavier())
    return lm, draft


class TestSection9EndToEnd:
    def _ref(self, lm, prompt, n):
        toks = list(prompt)
        for _ in range(n):
            lg = lm(nd.NDArray(np.asarray([toks], np.int32))).asnumpy()
            toks.append(int(np.argmax(lg[0, -1])))
        return toks[len(prompt):]

    def test_prefix_cache_parity_and_program_bound(self, spec_lm):
        lm, _draft = spec_lm
        repo = serving.ModelRepository()
        repo.add_decoder("lm", lm)
        cfg = serving.ServingConfig(
            decode_page_size=4, decode_pool_pages=33,
            decode_max_batch=2, decode_max_new_tokens=4,
            prefix_cache=True)
        with serving.ModelServer(repo, cfg) as srv:
            prompt = [1, 2, 3, 4, 5, 6, 7, 8]
            want = self._ref(lm, prompt, 4)
            a = srv.generate("lm", prompt, max_new_tokens=4,
                             timeout=300).tolist()
            b = srv.generate("lm", prompt, max_new_tokens=4,
                             timeout=300).tolist()
            tail = prompt + [2, 9]
            c = srv.generate("lm", tail, max_new_tokens=4,
                             timeout=300).tolist()
            st = srv.decode_stats("lm")
            adapter = list(srv._decoders.values())[0].model
            # cached results byte-match the uncached reference
            assert a == want and b == want
            assert c == self._ref(lm, tail, 4)
            assert st["prefix_hits"] == 2 and st["prefix_misses"] == 1
            assert st["prefix_tokens_saved"] == 7 + 8
            # the §9 program accounting, via the jit cache-size helper:
            # width-1 (full hit) + width-2 (tail) verify programs and
            # ONE COW copy program beside prefill/decode
            assert st["programs"] <= st["program_bound"], st
            assert adapter._verify_jit._cache_size() == 2
            assert adapter._copy_jit._cache_size() == 1
            assert adapter._decode_jit._cache_size() == 1
            eng = list(srv._decoders.values())[0]
            eng.allocator.check_leaks()

    def test_spec_draft_env_serves_multiple_targets(self, spec_lm):
        """MXNET_SERVING_SPEC_DRAFT names ONE draft entry for every
        decoder — each target engine must get its OWN adapter over the
        draft LM (a shared adapter binds one live engine and would
        reject the second target)."""
        lm, draft = spec_lm
        repo = serving.ModelRepository()
        repo.add_decoder("a", lm)
        repo.add_decoder("b", lm)
        repo.add_decoder("small", draft)
        cfg = serving.ServingConfig(
            decode_page_size=4, decode_pool_pages=33,
            decode_max_batch=2, decode_max_new_tokens=4, spec_k=2,
            spec_draft="small")
        with serving.ModelServer(repo, cfg) as srv:
            out_a = srv.generate("a", [1, 2, 3], max_new_tokens=4,
                                 timeout=300).tolist()
            out_b = srv.generate("b", [1, 2, 3], max_new_tokens=4,
                                 timeout=300).tolist()
            want = self._ref(lm, [1, 2, 3], 4)
            assert out_a == want and out_b == want
            assert srv.decode_stats("a")["spec_k"] == 2
            assert srv.decode_stats("b")["spec_proposed"] > 0

    def test_speculative_byte_identical_and_bound(self, spec_lm):
        """The §9 acceptance criterion: greedy outputs with speculation
        ON equal the plain path byte for byte (garbage draft — worst
        case), programs stay within the spec-aware bound, and the
        acceptance counters move."""
        lm, draft = spec_lm
        repo = serving.ModelRepository()
        repo.add_decoder("lm", lm, draft=draft)
        cfg = serving.ServingConfig(
            decode_page_size=4, decode_pool_pages=33,
            decode_max_batch=2, decode_max_new_tokens=6, spec_k=2)
        with serving.ModelServer(repo, cfg) as srv:
            for prompt in ([1, 2, 3], [5], [2, 4, 6, 8]):
                got = srv.generate("lm", prompt, max_new_tokens=6,
                                   timeout=300).tolist()
                assert got == self._ref(lm, prompt, 6), prompt
            st = srv.decode_stats("lm")
            assert st["spec_proposed"] > 0
            assert 0.0 <= st["spec_acceptance"] <= 1.0
            assert st["programs"] <= st["program_bound"], st
            # batched verification is ONE fixed-shape program (B fixed,
            # width = the k+1 bucket); the per-seq family stays unused
            adapter = list(srv._decoders.values())[0].model
            assert adapter._verify_batch_jit._cache_size() == 1
            assert adapter._verify_jit._cache_size() == 0
            eng = list(srv._decoders.values())[0]
            eng.allocator.check_leaks()
