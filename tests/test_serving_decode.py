"""Decode engine: token-level continuous batching + paged KV cache
(docs/serving.md §6).

Scheduler invariants run on fake numpy models — ZERO XLA compiles — so
admit/evict, page alloc/free, and block-table reuse are tested at step
granularity.  The end-to-end class at the bottom drives a tiny real
``TransformerDecoderLM`` through ``ModelServer.generate()`` (a handful
of tiny compiles) and asserts the program-count bound via the jit
cache-size helper.
"""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, runtime_metrics as rm, serving
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serving.decode import DecodeEngine
from mxnet_tpu.serving.kv_cache import PageAllocator, PageGeometry


@pytest.fixture(autouse=True)
def _metrics_on():
    rm.reset()
    rm.enable()
    yield
    rm.disable()
    rm.reset()


def _cfg(**kw):
    kw.setdefault("decode_page_size", 4)
    kw.setdefault("decode_pool_pages", 9)      # 8 usable
    kw.setdefault("decode_max_batch", 2)
    kw.setdefault("decode_max_new_tokens", 4)
    return serving.ServingConfig(**kw)


class FakeModel:
    """Decode-model protocol in plain numpy: next token = (last + 1)
    mod vocab; prefill proposes the prompt's last token.  Asserts the
    engine's inactive-slot contract on every step."""

    vocab_size = 16
    max_context = 32

    def __init__(self, eos_id=None):
        self.prefills = 0
        self.steps = 0
        self.step_batches = []          # active slot count per step
        if eos_id is not None:
            self.eos_id = eos_id

    def prefill(self, tokens, length, block_table):
        self.prefills += 1
        assert tokens.ndim == 2 and tokens.shape[0] == 1
        assert tokens.shape[1] >= int(length)
        logits = np.zeros((self.vocab_size,), np.float32)
        logits[int(tokens[0, int(length) - 1]) % self.vocab_size] = 1.0
        return logits

    def decode_step(self, tokens, positions, block_tables):
        self.steps += 1
        active = positions > 0
        # inactive slots carry zeros and an all-null block table
        assert np.all(tokens[~active] == 0)
        assert np.all(block_tables[~active] == 0)
        self.step_batches.append(int(active.sum()))
        logits = np.zeros((tokens.shape[0], self.vocab_size), np.float32)
        logits[np.arange(tokens.shape[0]),
               (tokens + 1) % self.vocab_size] = 1.0
        return logits


def _drive(eng, seqs, limit=64):
    """Step until every sequence finished (bounded)."""
    n = 0
    while not all(s.event.is_set() for s in seqs):
        eng.step()
        n += 1
        assert n < limit, "scheduler did not converge"
    return n


def _engine(model=None, **cfg_kw):
    eng = DecodeEngine(model or FakeModel(), _cfg(**cfg_kw),
                       model_name="fake")
    eng._started = True                 # manual stepping, no loop thread
    return eng


# --------------------------------------------------------------- allocator
class TestPageAllocator:
    def _geom(self, **kw):
        kw.setdefault("page_size", 4)
        kw.setdefault("pool_pages", 9)
        kw.setdefault("max_context", 32)
        return PageGeometry(num_layers=1, num_heads=1, head_dim=1, **kw)

    def test_alloc_release_roundtrip(self):
        a = PageAllocator(self._geom())
        assert a.allocate("s1", 3)
        assert a.used_pages == 3 and a.free_pages == 5
        assert 0 not in a.pages_of("s1")            # null page reserved
        assert a.release("s1") == 3
        assert a.used_pages == 0
        a.check_leaks()

    def test_all_or_nothing(self):
        a = PageAllocator(self._geom(max_context=64))   # 16-slot tables
        assert not a.allocate("big", 9)             # > 8 usable
        assert a.used_pages == 0                    # nothing stranded
        assert a.allocate("s1", 8)
        assert not a.allocate("s2", 1)
        a.check_leaks()

    def test_double_release_raises(self):
        a = PageAllocator(self._geom())
        a.allocate("s1", 2)
        a.release("s1")
        with pytest.raises(MXNetError, match="unknown sequence"):
            a.release("s1")

    def test_corrupted_state_detected(self):
        a = PageAllocator(self._geom())
        a.allocate("s1", 2)
        a._free.append(a.pages_of("s1")[0])         # simulate corruption
        with pytest.raises(MXNetError, match="already free"):
            a.release("s1")

    def test_block_table_width_enforced(self):
        g = self._geom(max_context=8)               # 2 slots of 4
        a = PageAllocator(PageGeometry(4, 9, 8, 1, 1, 1))
        assert g.pages_per_seq == 2
        with pytest.raises(MXNetError, match="exceed the block table"):
            a.allocate("s1", 3)

    def test_block_table_null_fill_and_reuse_after_eviction(self):
        a = PageAllocator(self._geom())
        a.allocate("s1", 2)
        first = a.pages_of("s1")
        table = a.block_table("s1")
        assert list(table[:2]) == first and np.all(table[2:] == 0)
        a.release("s1")
        a.allocate("s2", 2)
        # LIFO free list: the evicted pages back the new sequence
        assert set(a.pages_of("s2")) == set(first)
        a.check_leaks()

    def test_random_arrival_finish_orders_never_leak(self):
        rng = np.random.RandomState(0)
        a = PageAllocator(self._geom(pool_pages=17, max_context=64))
        live, next_id = {}, 0
        for _ in range(300):
            if live and rng.rand() < 0.45:
                sid = rng.choice(sorted(live))
                a.release(sid)
                del live[sid]
            else:
                n = int(rng.randint(1, 5))
                sid = next_id = next_id + 1
                if a.allocate(sid, n):
                    live[sid] = n
            a.check_leaks()
            assert a.used_pages == sum(live.values())
        for sid in sorted(live):
            a.release(sid)
        a.check_leaks()
        assert a.free_pages == a.geometry.usable_pages

    def test_geometry_validation(self):
        with pytest.raises(MXNetError, match="null page"):
            PageGeometry(4, 1, 32, 1, 1, 1)
        with pytest.raises(MXNetError, match="page_size"):
            PageGeometry(0, 8, 32, 1, 1, 1)
        g = self._geom()
        assert g.pages_for(0) == 0
        assert g.pages_for(1) == 1
        assert g.pages_for(4) == 1
        assert g.pages_for(5) == 2


# --------------------------------------------------------------- scheduler
class TestSchedulerInvariants:
    def test_greedy_chain_and_prefill_token(self):
        eng = _engine()
        s = eng.submit([1, 2, 3], max_new_tokens=3)
        _drive(eng, [s])
        # prefill proposes last prompt token, then +1 per decode step
        assert s.tokens == [3, 4, 5]
        assert s.finish_reason == "length"
        eng.allocator.check_leaks()

    def test_admit_and_evict_every_step(self):
        """Slot freed by an eviction is refilled on the NEXT step, not
        after the whole batch drains (token-level, not request-level).
        A step is admit -> prefill -> one decode step, so a 2-token
        request finishes WITHIN its admission step."""
        eng = _engine()                 # 2 slots
        long = eng.submit([1], max_new_tokens=6)
        short = eng.submit([2], max_new_tokens=2)
        third = eng.submit([3], max_new_tokens=4)
        eng.step()                      # admits long+short; third waits
        # short got prefill token + one decode token = done this step
        assert short.event.is_set()
        st = eng.stats()
        assert st["running"] == 1 and st["waiting"] == 1
        eng.step()                      # third admitted into freed slot
        st = eng.stats()
        assert st["running"] == 2 and st["waiting"] == 0
        assert not long.event.is_set()
        _drive(eng, [long, short, third])
        eng.allocator.check_leaks()
        assert eng.allocator.used_pages == 0

    def test_short_request_admitted_mid_flight_finishes_first(self):
        """The ISSUE-7 interleave criterion at engine level."""
        eng = _engine()
        long = eng.submit([1], max_new_tokens=8,
                          on_token=lambda t: None)
        eng.step()                      # long is mid-flight
        short = eng.submit([2], max_new_tokens=2)
        _drive(eng, [short])
        assert short.event.is_set() and not long.event.is_set()
        _drive(eng, [long])
        eng.allocator.check_leaks()

    def test_admission_gates_on_page_reservation(self):
        # 8 usable pages; each request needs ceil((1+15)/4) = 4 pages
        model = FakeModel()
        model.max_context = 16
        eng = _engine(model, decode_max_batch=4, decode_pool_pages=9)
        a = eng.submit([1], max_new_tokens=15)
        b = eng.submit([2], max_new_tokens=15)
        c = eng.submit([3], max_new_tokens=15)
        eng.step()
        st = eng.stats()
        # only two reservations fit even though a slot is free
        assert st["running"] == 2 and st["waiting"] == 1
        assert st["free_pages"] == 0
        _drive(eng, [a, b, c], limit=64)
        eng.allocator.check_leaks()
        assert eng.allocator.free_pages == 8

    def test_eos_evicts(self):
        eng = _engine()
        # chain 5 -> 6 -> 7(eos)
        s = eng.submit([5], max_new_tokens=8, eos_id=7)
        _drive(eng, [s])
        assert s.tokens[-1] == 7 and s.finish_reason == "eos"
        assert len(s.tokens) == 3
        eng.allocator.check_leaks()

    def test_streaming_callbacks_in_order(self):
        eng = _engine()
        got = []
        s = eng.submit([1, 2], max_new_tokens=3, on_token=got.append)
        _drive(eng, [s])
        assert got == s.tokens == [2, 3, 4]

    def test_callback_exception_does_not_kill_sequence(self):
        eng = _engine()

        def boom(tok):
            raise RuntimeError("client went away")

        s = eng.submit([1], max_new_tokens=2, on_token=boom)
        _drive(eng, [s])
        assert s.error is None and len(s.tokens) == 2

    def test_submit_validation(self):
        eng = _engine()
        with pytest.raises(MXNetError, match=">= 1 token"):
            eng.submit([], max_new_tokens=2)
        with pytest.raises(MXNetError, match="max_context"):
            eng.submit([1] * 30, max_new_tokens=10)
        with pytest.raises(MXNetError, match="max_new_tokens"):
            eng.submit([1], max_new_tokens=0)

    def test_waiting_queue_sheds_past_queue_depth(self):
        eng = _engine(queue_depth=2, shed_watermark=2)
        eng.submit([1], max_new_tokens=4)
        eng.submit([2], max_new_tokens=4)
        with pytest.raises(serving.ServerOverloadedError,
                           match="queue_depth"):
            eng.submit([3], max_new_tokens=4)
        assert eng.stats()["shed"] == 1

    def test_cancelled_waiting_pruned_even_with_full_batch(self):
        """A timed-out waiting request is dropped on the next step even
        when no slot frees — it must not occupy bounded queue space."""
        eng = _engine(decode_max_batch=1)
        running = eng.submit([1], max_new_tokens=8)
        eng.step()                      # occupies the only slot
        waiting = eng.submit([2], max_new_tokens=8)
        with pytest.raises(MXNetError):
            eng.result(waiting, timeout=0.01)   # cancels it
        before = rm.SERVING_DECODE_EVICTIONS.value(model="fake")
        eng.step()                      # batch still full, yet pruned
        assert waiting.event.is_set()
        assert waiting.finish_reason == "cancelled"
        assert eng.stats()["waiting"] == 0
        # never admitted -> not an eviction (pages were never held)
        assert rm.SERVING_DECODE_EVICTIONS.value(model="fake") == before
        _drive(eng, [running])
        eng.allocator.check_leaks()

    def test_result_timeout_cancels_and_reclaims(self):
        eng = _engine()
        s = eng.submit([1], max_new_tokens=8)
        eng.step()
        assert eng.allocator.used_pages > 0
        with pytest.raises(MXNetError, match="cancelled"):
            eng.result(s, timeout=0.01)
        eng.step()                      # eviction happens on the step
        assert s.finish_reason == "cancelled"
        eng.allocator.check_leaks()
        assert eng.allocator.used_pages == 0

    def test_metrics_published(self):
        eng = _engine()
        s = eng.submit([1, 2], max_new_tokens=3)
        _drive(eng, [s])
        assert rm.SERVING_DECODE_TOKENS.value(model="fake") == 3
        assert rm.SERVING_DECODE_EVICTIONS.value(model="fake") == 1
        assert rm.SERVING_DECODE_TTFT_SECONDS.count(model="fake") == 1
        assert rm.SERVING_DECODE_TOKEN_SECONDS.count(model="fake") == 2
        assert "serving_decode_steps" in rm.dump_prometheus()

    def test_threaded_engine_lifecycle(self):
        """autostart path: background loop, concurrent submitters,
        clean stop failing a straggler."""
        model = FakeModel()
        eng = DecodeEngine(model, _cfg(decode_max_batch=2),
                           model_name="fake", autostart=True)
        try:
            outs = {}

            def gen(i):
                outs[i] = eng.generate([i + 1], max_new_tokens=2,
                                       timeout=60)

            ts = [threading.Thread(target=gen, args=(i,))
                  for i in range(5)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(120)
            assert len(outs) == 5
            for i, toks in outs.items():
                assert toks.tolist() == [(i + 1) % 16, (i + 2) % 16]
            eng.allocator.check_leaks()
            assert eng.allocator.used_pages == 0
        finally:
            assert eng.stop(timeout=30)
        with pytest.raises(MXNetError, match="not accepting"):
            eng.submit([1])

    def test_stop_fails_outstanding(self):
        eng = DecodeEngine(FakeModel(), _cfg(), model_name="fake",
                           autostart=True)
        # saturate so one request stays waiting, then stop immediately
        seqs = [eng.submit([1], max_new_tokens=4) for _ in range(3)]
        assert eng.stop(timeout=30)
        for s in seqs:
            assert s.event.is_set()
            # each either finished legitimately or was failed by stop
            assert s.finish_reason in ("length", "stopped")
        eng.allocator.check_leaks()


# ------------------------------------------------------------- end to end
@pytest.fixture(scope="module")
def tiny_lm_server():
    mx.random.seed(7)
    from mxnet_tpu.models.transformer_blocks import TransformerDecoderLM
    lm = TransformerDecoderLM(13, units=8, hidden_size=16, num_layers=1,
                              num_heads=2, max_length=16)
    lm.initialize(mx.init.Xavier())
    repo = serving.ModelRepository()
    repo.add_decoder("lm", lm)
    cfg = serving.ServingConfig(decode_page_size=4, decode_pool_pages=17,
                                decode_max_batch=2,
                                decode_max_new_tokens=4)
    srv = serving.ModelServer(repo, cfg)
    yield srv, lm
    srv.stop()


class TestGenerateEndToEnd:
    def _ref_generate(self, lm, prompt, n):
        toks = list(prompt)
        for _ in range(n):
            lg = lm(nd.NDArray(np.asarray([toks], np.int32))).asnumpy()
            toks.append(int(np.argmax(lg[0, -1])))
        return toks[len(prompt):]

    def test_generate_matches_full_forward(self, tiny_lm_server):
        srv, lm = tiny_lm_server
        for prompt, n in (([1, 2, 3], 3), ([5], 2), ([2, 4], 3)):
            got = srv.generate("lm", prompt, max_new_tokens=n,
                               timeout=300).tolist()
            assert got == self._ref_generate(lm, prompt, n)

    def test_concurrent_mixed_lengths_bound_programs(self, tiny_lm_server):
        """Program-count bound under a mixed-length run, via the jit
        cache-size helper (delta around the run — the pjit cache is per
        underlying function, and this adapter owns a fresh one)."""
        srv, lm = tiny_lm_server
        outs = {}

        def gen(i):
            prompt = list(range(1, 2 + i % 4))
            outs[i] = (prompt,
                       srv.generate("lm", prompt,
                                    max_new_tokens=2 + i % 3,
                                    timeout=300))

        ts = [threading.Thread(target=gen, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(300)
        assert len(outs) == 8
        for i, (prompt, toks) in outs.items():
            assert toks.tolist() == self._ref_generate(
                lm, prompt, 2 + i % 3)
        st = srv.decode_stats("lm")
        # <= prefill buckets + 1 decode program, from the pjit caches
        assert st["programs"] <= st["program_bound"], st
        from mxnet_tpu.serving.batcher import bucket_set
        adapter = list(srv._decoders.values())[0].model
        assert adapter._decode_jit._cache_size() == 1
        assert adapter._prefill_jit._cache_size() \
            <= len(bucket_set(adapter.max_context))

    def test_predict_and_generate_reject_wrong_kind(self, tiny_lm_server):
        srv, _lm = tiny_lm_server
        with pytest.raises(MXNetError, match="generate"):
            srv.predict("lm", np.zeros((1, 4), np.int32))
        srv.repository.add_function(
            "plain", lambda x: x,
            [{"shape": [None, 1], "dtype": "float32"}])
        with pytest.raises(MXNetError, match="add_decoder"):
            srv.generate("plain", [1, 2])

    def test_adapter_binds_one_live_engine(self, tiny_lm_server):
        """A second engine on the SAME adapter must be rejected (its
        setup would zero the live engine's KV pool), and a
        stop->start rebind keeps the compiled-program caches."""
        srv, _lm = tiny_lm_server
        srv.generate("lm", [1], max_new_tokens=2, timeout=300)
        eng = list(srv._decoders.values())[0]
        adapter = eng.model
        with pytest.raises(MXNetError, match="one decoder entry serves"):
            serving.DecodeEngine(adapter, srv.config, model_name="dup")
        programs = adapter.programs()
        assert eng.stop(timeout=60)
        assert adapter.pool is None            # pool released
        eng.start()                            # rebind, programs survive
        assert adapter.pool is not None
        out = srv.generate("lm", [1], max_new_tokens=2, timeout=300)
        assert adapter.programs() == programs  # zero recompiles
        assert len(out) == 2

    def test_paged_forward_honors_layer_norm_eps(self):
        """Non-default layer_norm_eps must reach the decode-mode
        forward — prefill logits match the training forward exactly."""
        import jax.numpy as jnp

        from mxnet_tpu.models.transformer_blocks import (
            TransformerDecoderLM, paged_lm_params, paged_prefill)
        mx.random.seed(3)
        lm = TransformerDecoderLM(11, units=8, hidden_size=16,
                                  num_layers=1, num_heads=2,
                                  max_length=8, layer_norm_eps=1e-1)
        lm.initialize(mx.init.Xavier())
        toks = np.array([[1, 2, 3]], np.int32)
        want = lm(nd.NDArray(toks)).asnumpy()[0, -1]
        params = paged_lm_params(lm)
        kp = jnp.zeros((1, 3, 4, 2, 4), jnp.float32)
        bt = np.array([1, 2], np.int32)
        got, _, _ = paged_prefill(
            params, jnp.asarray(toks), jnp.int32(3), jnp.asarray(bt),
            kp, kp, num_heads=2, page_size=4, layer_norm_eps=lm._eps)
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-4)
        # and the default-eps path would NOT match (the eps matters)
        bad, _, _ = paged_prefill(
            params, jnp.asarray(toks), jnp.int32(3), jnp.asarray(bt),
            kp, kp, num_heads=2, page_size=4)
        assert not np.allclose(np.asarray(bad), want, atol=1e-4)

    def test_ttft_histogram_recorded(self, tiny_lm_server):
        srv, _lm = tiny_lm_server
        rm.reset()
        srv.generate("lm", [1, 2], max_new_tokens=2, timeout=300)
        assert rm.SERVING_DECODE_TTFT_SECONDS.count(model="lm") == 1
        p99 = rm.SERVING_DECODE_TTFT_SECONDS.quantile(0.99, model="lm")
        assert np.isfinite(p99) and p99 > 0
