"""Test configuration: run the whole suite on a virtual 8-device CPU mesh.

Mirrors the reference's "real runtime, fake scale" test philosophy
(SURVEY.md §4: launcher-local multi-process tests): JAX host-platform
device multiplexing stands in for a TPU pod slice, so sharding/collective
paths execute for real without TPU hardware.
"""
import os

# Force CPU with 8 virtual devices. The interpreter may have already
# imported jax with an accelerator platform selected (sitecustomize), so the
# env var alone is not enough: override via jax.config before any backend
# initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _fixed_seed():
    """Reference pattern: tests/python/unittest/common.py with_seed()."""
    import mxnet_tpu as mx
    seed = int(os.environ.get("MXNET_TEST_SEED", "42"))
    mx.random.seed(seed)
    np.random.seed(seed)
    yield


@pytest.fixture(autouse=True)
def _no_thread_leaks():
    """Under MXNET_ENGINE_SANITIZE=1 every test asserts at teardown
    that no framework thread (engine.make_thread) survived its owner's
    stop — the runtime twin of mxlint's thread-lifecycle pass.  Zero
    cost when the sanitizer is off (the tier-1 default): both calls
    are no-ops behind the module-level _SANITIZE bool."""
    from mxnet_tpu import engine
    yield
    engine.check_thread_leaks()


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 `-m 'not slow'` budget run "
        "(ROADMAP.md); the full suite still runs them")
