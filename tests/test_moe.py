"""MoE ops + gluon.contrib.MoEFFN + expert-parallel sharding."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon, parallel
from mxnet_tpu.gluon.contrib import MoEFFN


def test_top1_dispatch_routing():
    from mxnet_tpu.ops.moe import moe_top1_dispatch
    logits = jnp.asarray([[2.0, 0.0], [0.0, 3.0], [1.5, 0.1],
                          [0.0, 2.5]], jnp.float32)      # S=4, E=2
    combine, dispatch, aux = moe_top1_dispatch(logits, capacity=2)
    d = np.asarray(dispatch)
    # token 0, 2 -> expert 0 at positions 0, 1; token 1, 3 -> expert 1
    assert d[0, 0, 0] == 1 and d[2, 0, 1] == 1
    assert d[1, 1, 0] == 1 and d[3, 1, 1] == 1
    # each token dispatched exactly once
    np.testing.assert_allclose(d.sum(axis=(1, 2)), 1.0)
    # combine carries the softmax gate of the chosen expert
    gates = np.asarray(jax.nn.softmax(np.asarray(logits), axis=-1))
    np.testing.assert_allclose(np.asarray(combine).sum(axis=(1, 2)),
                               gates.max(axis=1), rtol=1e-6)
    assert np.isfinite(float(aux))


def test_top1_capacity_drop():
    from mxnet_tpu.ops.moe import moe_top1_dispatch
    # all four tokens prefer expert 0; capacity 2 drops the last two
    logits = jnp.asarray([[5.0, 0.0]] * 4, jnp.float32)
    combine, dispatch, aux = moe_top1_dispatch(logits, capacity=2)
    d = np.asarray(dispatch)
    np.testing.assert_allclose(d.sum(), 2.0)
    np.testing.assert_allclose(d.sum(axis=(1, 2)), [1, 1, 0, 0])


def test_moe_ffn_single_expert_equals_mlp():
    from mxnet_tpu.ops.moe import moe_ffn
    rng = np.random.RandomState(0)
    S, C, H = 8, 4, 16
    x = jnp.asarray(rng.randn(S, C).astype(np.float32))
    wg = jnp.zeros((C, 1), jnp.float32)
    w1 = jnp.asarray(rng.randn(1, C, H).astype(np.float32))
    b1 = jnp.zeros((1, H), jnp.float32)
    w2 = jnp.asarray(rng.randn(1, H, C).astype(np.float32))
    b2 = jnp.zeros((1, C), jnp.float32)
    out, aux = moe_ffn(x, wg, w1, b1, w2, b2, capacity_factor=2.0,
                       activation="relu")
    # E=1: softmax gate == 1, so this IS the plain MLP
    ref = np.maximum(np.asarray(x) @ np.asarray(w1[0]), 0) @ \
        np.asarray(w2[0])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(float(aux), 1.0, rtol=1e-5)  # E*1*1


def test_moe_ffn_under_jit_and_grad():
    from mxnet_tpu.ops.moe import moe_ffn
    rng = np.random.RandomState(1)
    B, L, C, H, E = 2, 8, 4, 8, 4
    x = jnp.asarray(rng.randn(B, L, C).astype(np.float32))
    wg = jnp.asarray(rng.randn(C, E).astype(np.float32))
    w1 = jnp.asarray(rng.randn(E, C, H).astype(np.float32) * 0.1)
    b1 = jnp.zeros((E, H), jnp.float32)
    w2 = jnp.asarray(rng.randn(E, H, C).astype(np.float32) * 0.1)
    b2 = jnp.zeros((E, C), jnp.float32)

    @jax.jit
    def loss(wg, w1, b1, w2, b2):
        out, aux = moe_ffn(x, wg, w1, b1, w2, b2)
        return (out ** 2).sum() + 0.01 * aux

    grads = jax.grad(loss, argnums=(0, 1, 2, 3, 4))(wg, w1, b1, w2, b2)
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()
    # routing gradient reaches the gate through combine weights
    assert np.abs(np.asarray(grads[0])).max() > 0


def test_gluon_moe_block_eager_hybrid_parity():
    mx.random.seed(0)
    layer = MoEFFN(units=8, hidden_size=16, num_experts=4,
                   capacity_factor=2.0)
    layer.initialize(mx.init.Xavier())
    x = nd.array(np.random.RandomState(2).randn(2, 6, 8)
                 .astype(np.float32))
    out_e, aux_e = layer(x)
    layer.hybridize()
    out_h, aux_h = layer(x)
    out_h2, _ = layer(x)
    np.testing.assert_allclose(out_e.asnumpy(), out_h.asnumpy(),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(aux_e.asscalar()),
                               float(aux_h.asscalar()), rtol=1e-5)


def test_moe_trains_with_gradient():
    # tiny regression: MoE layer + residual learns a mapping; aux loss
    # balances experts
    mx.random.seed(1)
    layer = MoEFFN(units=4, hidden_size=8, num_experts=2,
                   capacity_factor=2.0, activation="relu")
    layer.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(layer.collect_params(), "adam",
                            {"learning_rate": 5e-3})
    rng = np.random.RandomState(3)
    X = rng.randn(64, 4).astype(np.float32)
    Y = np.tanh(X[:, ::-1].copy()).astype(np.float32)
    first = None
    for i in range(120):
        x, y = nd.array(X), nd.array(Y)
        with autograd.record():
            out, aux = layer(x)
            loss = ((out + x - y) ** 2).mean() + 0.01 * aux
        loss.backward()
        trainer.step(64)
        if i == 0:
            first = float(loss.asscalar())
    last = float(loss.asscalar())
    assert last < 0.5 * first, (first, last)


def test_expert_parallel_sharded_step():
    # dp=2 x ep=2 mesh on the virtual 8-device CPU backend: the expert
    # dim must actually shard over ep, and one training step must run
    devices = jax.devices()[:4]
    mesh = parallel.make_mesh(dp=2, tp=1, sp=1, ep=2, devices=devices)
    assert mesh.shape["ep"] == 2

    mx.random.seed(2)

    class Net(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.moe = MoEFFN(units=8, hidden_size=16, num_experts=4,
                                  capacity_factor=2.0)

        def hybrid_forward(self, F, x):
            out, aux = self.moe(x)
            return out + x, aux

    net = Net()
    net.initialize(mx.init.Xavier())

    def loss_fn(outputs, y):
        out, aux = outputs
        return ((out - y) ** 2).mean() + \
            0.01 * aux.astype(jnp.float32)

    x = nd.array(np.random.RandomState(4).randn(8, 6, 8)
                 .astype(np.float32))
    y = nd.array(np.random.RandomState(5).randn(8, 6, 8)
                 .astype(np.float32))
    trainer = parallel.ShardedTrainer(
        net, loss_fn, mesh, optimizer="adamw",
        optimizer_params={"learning_rate": 1e-3},
        example_inputs=(x,), n_labels=1)
    loss = trainer.step(x, y)
    assert np.isfinite(float(jax.device_get(loss)))
    # the expert weights really live sharded over ep
    w1 = [n for n in trainer.params if n.endswith("expert_w1")]
    assert w1, list(trainer.params)[:8]
    spec = trainer.params[w1[0]].sharding.spec
    assert spec[0] == "ep", spec


def test_expert_rules_on_mesh_without_ep_axis():
    # a hand-built 3-axis mesh: 'ep' rules degrade to replication, not
    # KeyError
    from jax.sharding import Mesh
    from mxnet_tpu.parallel.sharding import MEGATRON_RULES
    devs = np.array(jax.devices()[:4]).reshape(2, 2, 1)
    mesh = Mesh(devs, axis_names=("dp", "tp", "sp"))
    shardings = MEGATRON_RULES.shardings(
        mesh, {"net_moe_expert_w1": jnp.zeros((4, 8, 16))})
    spec = shardings["net_moe_expert_w1"].spec
    assert spec[0] is None         # ep dropped


def test_make_mesh_ep_backcompat():
    # existing 3-axis call sites keep working; default ep axis size 1
    mesh = parallel.make_mesh(dp=2, tp=2, sp=2,
                              devices=jax.devices()[:8])
    assert mesh.shape["ep"] == 1
    assert mesh.shape["dp"] == 2
