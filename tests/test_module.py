"""Executor (bind/simple_bind) + legacy Module/BucketingModule tests.

Reference strategy: tests/python/unittest/test_module.py,
test_executor.py (SURVEY.md §4).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.io import NDArrayIter, DataBatch


def _mlp_symbol(num_hidden=16, num_classes=4):
    data = sym.var("data")
    label = sym.var("softmax_label")
    h = sym.FullyConnected(data, sym.var("fc1_weight"), sym.var("fc1_bias"),
                           num_hidden=num_hidden, name="fc1")
    h = sym.Activation(h, act_type="relu")
    out = sym.FullyConnected(h, sym.var("fc2_weight"), sym.var("fc2_bias"),
                             num_hidden=num_classes, name="fc2")
    return sym.SoftmaxOutput(out, label, name="softmax")


class TestExecutor:
    def test_simple_bind_forward(self):
        x = sym.var("x")
        y = sym.var("y")
        z = 2.0 * x + y
        ex = z.simple_bind(mx.cpu(), x=(2, 3), y=(2, 3))
        ex.arg_dict["x"][:] = 1.0
        ex.arg_dict["y"][:] = 3.0
        out = ex.forward()[0]
        np.testing.assert_allclose(out.asnumpy(), np.full((2, 3), 5.0))

    def test_bind_backward_grads(self):
        x = sym.var("x")
        w = sym.var("w")
        z = sym.sum(x * w)
        xv = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
        wv = nd.array(np.full((2, 3), 2.0, dtype=np.float32))
        gx = nd.zeros((2, 3))
        gw = nd.zeros((2, 3))
        ex = z.bind(mx.cpu(), {"x": xv, "w": wv},
                    args_grad={"x": gx, "w": gw})
        ex.forward(is_train=True)
        ex.backward()
        np.testing.assert_allclose(gx.asnumpy(), wv.asnumpy())
        np.testing.assert_allclose(gw.asnumpy(), xv.asnumpy())

    def test_grad_req_add_and_null(self):
        x = sym.var("x")
        z = sym.sum(x * x)
        xv = nd.array(np.ones((3,), dtype=np.float32))
        gx = nd.zeros((3,))
        ex = z.bind(mx.cpu(), {"x": xv}, args_grad={"x": gx},
                    grad_req="add")
        for _ in range(3):
            ex.forward(is_train=True)
            ex.backward()
        np.testing.assert_allclose(gx.asnumpy(), np.full((3,), 6.0))
        ex2 = z.bind(mx.cpu(), {"x": xv}, grad_req="null")
        ex2.forward(is_train=True)
        ex2.backward()   # no-op, no crash
        assert ex2.grad_dict == {}

    def test_compile_cache_reused(self):
        x = sym.var("x")
        z = sym.exp(x) + 1.0
        xv = nd.zeros((4, 4))
        ex = z.bind(mx.cpu(), {"x": xv})
        ex.forward()
        n = ex.num_compiles
        for _ in range(5):
            ex.forward(x=nd.array(np.random.rand(4, 4).astype(np.float32)))
        assert ex.num_compiles == n  # same signature -> no retrace

    def test_copy_params_and_outputs_dict(self):
        x = sym.var("x")
        w = sym.var("w")
        z = x + w
        ex = z.simple_bind(mx.cpu(), x=(2,), w=(2,))
        ex.copy_params_from({"w": nd.array(np.array([5., 7.],
                                                    dtype=np.float32))},
                            allow_extra_params=True)
        ex.forward(x=nd.zeros((2,)))
        assert list(ex.output_dict)  # named outputs exist
        np.testing.assert_allclose(ex.outputs[0].asnumpy(), [5., 7.])


class TestExecutorModes:
    def test_dropout_active_in_train_mode(self):
        x = sym.var("x")
        y = sym.Dropout(x, p=0.5)
        xv = nd.array(np.ones((64, 64), dtype=np.float32))
        ex = y.bind(mx.cpu(), {"x": xv})
        train_out = ex.forward(is_train=True)[0].asnumpy()
        assert (train_out == 0).sum() > 0          # dropout applied
        # and stochastic across calls (traced rng key, not baked constant)
        second = ex.forward(is_train=True)[0].asnumpy()
        assert not np.array_equal(train_out, second)
        eval_out = ex.forward(is_train=False)[0].asnumpy()
        np.testing.assert_array_equal(eval_out, np.ones((64, 64)))

    def test_batchnorm_aux_updated_by_executor(self):
        data = sym.var("data")
        bn = sym.BatchNorm(data, sym.var("gamma"), sym.var("beta"),
                           sym.var("mm", attr=None), sym.var("mv"),
                           momentum=0.5, fix_gamma=False)
        bn._outputs[0][0].inputs[3][0].attrs["__aux__"] = "1"
        bn._outputs[0][0].inputs[4][0].attrs["__aux__"] = "1"
        rng = np.random.RandomState(0)
        x = rng.randn(32, 4).astype(np.float32) * 3 + 7
        args = {"data": nd.array(x), "gamma": nd.ones((4,)),
                "beta": nd.zeros((4,))}
        aux = {"mm": nd.zeros((4,)), "mv": nd.ones((4,))}
        ex = bn.bind(mx.cpu(), args, aux_states=aux, grad_req="null")
        ex.forward(is_train=True)
        # moving stats moved toward batch stats (momentum=0.5)
        expect_mm = 0.5 * 0.0 + 0.5 * x.mean(axis=0)
        np.testing.assert_allclose(ex.aux_dict["mm"].asnumpy(), expect_mm,
                                   rtol=1e-4, atol=1e-4)
        assert np.all(ex.aux_dict["mv"].asnumpy() > 1.5)  # var(x) >> 1
        # eval mode must not touch them
        before = ex.aux_dict["mm"].asnumpy().copy()
        ex.forward(is_train=False)
        np.testing.assert_array_equal(ex.aux_dict["mm"].asnumpy(), before)

    def test_module_load_restores_params(self, tmp_path):
        x = sym.var("data")
        out = sym.FullyConnected(x, sym.var("w"), sym.var("b"),
                                 num_hidden=3)
        mod = mx.module.Module(out, label_names=None, context=mx.cpu())
        mod.bind(data_shapes=[("data", (2, 5))], for_training=False)
        mod.init_params(initializer=mx.init.Xavier())
        prefix = str(tmp_path / "m")
        mod.save_checkpoint(prefix, 3)
        mod2 = mx.module.Module.load(prefix, 3, label_names=None,
                                     context=mx.cpu())
        mod2.bind(data_shapes=[("data", (2, 5))], for_training=False)
        # loaded params must be live without an explicit set_params call
        np.testing.assert_array_equal(
            mod2._exec.arg_dict["w"].asnumpy(),
            mod._exec.arg_dict["w"].asnumpy())

    def test_module_tolerates_missing_label(self):
        x = sym.var("data")
        out = sym.FullyConnected(x, sym.var("w"), sym.var("b"),
                                 num_hidden=3)
        mod = mx.module.Module(out, context=mx.cpu())  # default label names
        assert "w" in mod._param_names


class TestInference:
    def test_partial_shape_inference_mlp(self):
        s = _mlp_symbol(num_hidden=16, num_classes=4)
        arg_shapes, out_shapes, _ = s.infer_shape(
            data=(32, 8), softmax_label=(32,))
        shapes = dict(zip(s.list_arguments(), arg_shapes))
        assert shapes["fc1_weight"] == (16, 8)
        assert shapes["fc1_bias"] == (16,)
        assert shapes["fc2_weight"] == (4, 16)
        assert out_shapes == [(32, 4)]

    def test_partial_shape_inference_conv_bn(self):
        data = sym.var("data")
        h = sym.Convolution(data, sym.var("w"), sym.var("b"),
                            kernel=(3, 3), num_filter=8, pad=(1, 1))
        h = sym.BatchNorm(h, sym.var("gamma"), sym.var("beta"),
                          sym.var("mm"), sym.var("mv"))
        args, outs, _ = h.infer_shape(data=(2, 3, 16, 16))
        shapes = dict(zip(h.list_arguments(), args))
        assert shapes["w"] == (8, 3, 3, 3)
        assert shapes["gamma"] == (8,)
        assert outs == [(2, 8, 16, 16)]

    def test_infer_shape_partial_returns_none_holes(self):
        x = sym.var("x")
        y = sym.var("y")
        z = x + y
        args, outs, _ = z.infer_shape_partial(x=(2, 3))
        assert args[z.list_arguments().index("y")] is None
        assert outs == [None]
        with pytest.raises(mx.MXNetError):
            z.infer_shape(x=(2, 3))

    def test_infer_type_propagates(self):
        x = sym.var("x")
        y = sym.Cast(x, dtype="float16")
        types = y.infer_type(x=np.float32)
        assert types[1][0] == np.dtype("float16")
        i = sym.var("i")
        e = sym.Embedding(i, sym.var("w"), input_dim=10, output_dim=4)
        _, outs, _ = e.infer_type(i=np.int32, w=np.float32)
        assert outs[0] == np.dtype("float32")


class TestModule:
    def _toy_data(self, n=64, num_classes=4, seed=0):
        rng = np.random.RandomState(seed)
        centers = rng.randn(num_classes, 8).astype(np.float32) * 3
        y = rng.randint(0, num_classes, size=n)
        x = centers[y] + rng.randn(n, 8).astype(np.float32) * 0.1
        return x, y.astype(np.float32)

    def test_module_fit_converges(self):
        x, y = self._toy_data()
        it = NDArrayIter(x, y, batch_size=16, shuffle=True,
                         label_name="softmax_label")
        mod = mx.module.Module(_mlp_symbol(), context=mx.cpu())
        mod.fit(it, num_epoch=12, optimizer="sgd",
                optimizer_params={"learning_rate": 0.5},
                eval_metric="acc",
                initializer=mx.init.Xavier())
        score = mod.score(it, "acc")
        assert dict(score)["accuracy"] > 0.9

    def test_module_fit_default_initializer(self):
        """fit() without an explicit initializer must still break symmetry
        (regression: None once meant keep-current-zeros)."""
        x, y = self._toy_data()
        it = NDArrayIter(x, y, batch_size=16, label_name="softmax_label")
        mod = mx.module.Module(_mlp_symbol(), context=mx.cpu())
        mod.fit(it, num_epoch=6, optimizer="sgd",
                optimizer_params={"learning_rate": 0.5})
        assert dict(mod.score(it, "acc"))["accuracy"] > 0.8
        assert np.abs(mod.get_params()[0]["fc1_weight"].asnumpy()).max() > 0

    def test_module_predict_shapes(self):
        x, y = self._toy_data(n=50)
        it = NDArrayIter(x, y, batch_size=16, label_name="softmax_label")
        mod = mx.module.Module(_mlp_symbol(), context=mx.cpu())
        mod.bind(data_shapes=it.provide_data,
                 label_shapes=it.provide_label)
        mod.init_params()
        out = mod.predict(it)
        assert out.shape == (50, 4)  # pad rows stripped

    def test_module_checkpoint_roundtrip(self, tmp_path):
        x, y = self._toy_data()
        it = NDArrayIter(x, y, batch_size=16, label_name="softmax_label")
        mod = mx.module.Module(_mlp_symbol(), context=mx.cpu())
        mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
        mod.init_params(initializer=mx.init.Xavier())
        prefix = str(tmp_path / "toy")
        mod.save_checkpoint(prefix, 0)
        symbol, arg_params, aux_params = mx.module.load_checkpoint(prefix, 0)
        mod2 = mx.module.Module(symbol, context=mx.cpu())
        mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
        mod2.set_params(arg_params, aux_params)
        it.reset()
        batch = next(it)
        mod.forward(batch, is_train=False)
        mod2.forward(batch, is_train=False)
        np.testing.assert_allclose(mod.get_outputs()[0].asnumpy(),
                                   mod2.get_outputs()[0].asnumpy(),
                                   rtol=1e-5, atol=1e-6)

    def test_fixed_params_not_updated(self):
        x, y = self._toy_data()
        it = NDArrayIter(x, y, batch_size=16, label_name="softmax_label")
        mod = mx.module.Module(_mlp_symbol(), context=mx.cpu(),
                               fixed_param_names=["fc1_weight"])
        mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
        mod.init_params(initializer=mx.init.Xavier())
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.5})
        before = mod.get_params()[0]["fc1_weight"].asnumpy().copy()
        batch = next(it)
        mod.forward_backward(batch)
        mod.update()
        after = mod.get_params()[0]["fc1_weight"].asnumpy()
        np.testing.assert_array_equal(before, after)


class TestModuleRebind:
    def _mod(self):
        x = sym.var("data")
        out = sym.FullyConnected(x, sym.var("w"), sym.var("b"), num_hidden=3)
        mod = mx.module.Module(out, label_names=None, context=mx.cpu())
        mod.bind(data_shapes=[("data", (2, 5))], for_training=False)
        mod.init_params(initializer=mx.init.Xavier())
        return mod

    def test_force_rebind_preserves_params(self):
        mod = self._mod()
        w = mod._exec.arg_dict["w"].asnumpy().copy()
        mod.bind(data_shapes=[("data", (4, 5))], for_training=False,
                 force_rebind=True)
        mod.init_params()  # early-returns; must not be needed
        np.testing.assert_array_equal(mod._exec.arg_dict["w"].asnumpy(), w)

    def test_partial_set_params_keeps_others(self):
        mod = self._mod()
        w = mod._exec.arg_dict["w"].asnumpy().copy()
        mod.set_params({"b": nd.ones((3,))}, {}, allow_missing=True)
        np.testing.assert_array_equal(mod._exec.arg_dict["w"].asnumpy(), w)
        np.testing.assert_array_equal(mod._exec.arg_dict["b"].asnumpy(),
                                      np.ones((3,)))

    def test_forward_shape_mismatch_raises(self):
        mod = self._mod()
        ex = mod._exec
        with pytest.raises(mx.MXNetError):
            ex.forward(data=nd.zeros((7, 5)))


class TestBucketingModule:
    """Variable-length 'RNN-ish' training with a bounded compile cache."""

    @staticmethod
    def _sym_gen(seq_len):
        data = sym.var("data")          # (N, seq_len, F)
        label = sym.var("softmax_label")
        w = sym.var("cls_weight")
        b = sym.var("cls_bias")
        # weight shared across buckets: pool over the variable time axis
        h = sym.mean(data, axis=1)
        out = sym.FullyConnected(h, w, b, num_hidden=3, name="cls")
        return sym.SoftmaxOutput(out, label, name="softmax"), \
            ("data",), ("softmax_label",)

    def _batch(self, seq_len, n=8, seed=0):
        rng = np.random.RandomState(seed + seq_len)
        y = rng.randint(0, 3, size=n).astype(np.float32)
        x = rng.randn(n, seq_len, 4).astype(np.float32) + y[:, None, None]
        b = DataBatch(data=[nd.array(x)], label=[nd.array(y)],
                      provide_data=[("data", (n, seq_len, 4))],
                      provide_label=[("softmax_label", (n,))])
        b.bucket_key = seq_len
        return b

    def test_bucketing_bounded_compiles(self):
        keys = [4, 8, 16]
        bm = mx.module.BucketingModule(self._sym_gen, default_bucket_key=16,
                                       context=mx.cpu(), bucket_keys=keys)
        b16 = self._batch(16)
        bm.bind(data_shapes=b16.provide_data,
                label_shapes=b16.provide_label)
        bm.init_params(initializer=mx.init.Xavier())
        bm.init_optimizer(optimizer="sgd",
                          optimizer_params={"learning_rate": 0.1})
        # many steps across shuffled bucket sizes
        for step in range(12):
            b = self._batch(keys[step % 3], seed=step)
            bm.forward(b, is_train=True)
            bm.backward()
            bm.update()
        assert set(bm.active_buckets) == set(keys)
        # compile-count bound: fwd+bwd per bucket = 2 programs
        assert bm.num_compiles <= 2 * len(keys)
        # params are genuinely shared: one weight object across buckets
        w_def = bm._buckets[16]._exec.arg_dict["cls_weight"]
        for k in (4, 8):
            assert bm._buckets[k]._exec.arg_dict["cls_weight"] is w_def

    def test_bucketing_force_rebind_preserves_params(self):
        bm = mx.module.BucketingModule(self._sym_gen, default_bucket_key=8,
                                       context=mx.cpu(), bucket_keys=[4, 8])
        b8 = self._batch(8)
        bm.bind(data_shapes=b8.provide_data, label_shapes=b8.provide_label)
        bm.init_params(initializer=mx.init.Xavier())
        w = bm.get_params()[0]["cls_weight"].asnumpy().copy()
        assert np.abs(w).max() > 0
        bm.bind(data_shapes=b8.provide_data, label_shapes=b8.provide_label,
                force_rebind=True)
        np.testing.assert_array_equal(
            bm.get_params()[0]["cls_weight"].asnumpy(), w)

    def test_bucketing_rejects_unregistered_key(self):
        bm = mx.module.BucketingModule(self._sym_gen, default_bucket_key=8,
                                       context=mx.cpu(), bucket_keys=[8])
        b8 = self._batch(8)
        bm.bind(data_shapes=b8.provide_data, label_shapes=b8.provide_label)
        bm.init_params()
        with pytest.raises(mx.MXNetError):
            bm.switch_bucket(32, self._batch(32).provide_data)

    def test_bucketing_training_converges(self):
        keys = [4, 8]
        bm = mx.module.BucketingModule(self._sym_gen, default_bucket_key=8,
                                       context=mx.cpu(), bucket_keys=keys)
        b8 = self._batch(8)
        bm.bind(data_shapes=b8.provide_data, label_shapes=b8.provide_label)
        bm.init_params(initializer=mx.init.Xavier())
        bm.init_optimizer(optimizer="sgd",
                          optimizer_params={"learning_rate": 0.3})
        metric = mx.metric.create("acc")
        for step in range(60):
            b = self._batch(keys[step % 2], seed=step % 5)
            bm.forward(b, is_train=True)
            bm.backward()
            bm.update()
        metric.reset()
        for s in range(5):
            b = self._batch(keys[s % 2], seed=s)
            bm.forward(b, is_train=False)
            bm.update_metric(metric, b.label)
        assert metric.get()[1] > 0.8
