"""RNN layer/cell tests (reference: tests/python/unittest/test_gluon_rnn.py)."""
import numpy as np
import pytest

from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import rnn


@pytest.mark.parametrize("layer_cls,nstates", [
    (rnn.RNN, 1), (rnn.GRU, 1), (rnn.LSTM, 2)])
def test_rnn_layer_forward_shapes(layer_cls, nstates):
    layer = layer_cls(16, num_layers=2)
    layer.initialize()
    x = nd.random.uniform(shape=(5, 3, 8))  # TNC
    out = layer(x)
    assert out.shape == (5, 3, 16)
    states = layer.begin_state(3)
    out, new_states = layer(x, states)
    assert out.shape == (5, 3, 16)
    assert len(new_states) == nstates
    assert new_states[0].shape == (2, 3, 16)


def test_bidirectional_lstm_shape():
    layer = rnn.LSTM(10, num_layers=1, bidirectional=True)
    layer.initialize()
    x = nd.random.uniform(shape=(4, 2, 6))
    out = layer(x)
    assert out.shape == (4, 2, 20)


def test_rnn_layer_ntc_layout():
    layer = rnn.GRU(12, layout="NTC")
    layer.initialize()
    x = nd.random.uniform(shape=(2, 7, 5))
    assert layer(x).shape == (2, 7, 12)


def test_rnn_grad_flows():
    layer = rnn.LSTM(8)
    layer.initialize()
    x = nd.random.uniform(shape=(3, 2, 4))
    x.attach_grad()
    with autograd.record():
        out = layer(x)
        loss = (out * out).sum()
    loss.backward()
    assert np.abs(x.grad.asnumpy()).sum() > 0
    for _, p in layer.collect_params().items():
        assert np.abs(p.grad().asnumpy()).sum() > 0, p


def test_lstm_cell_unroll_matches_fused():
    """Cell unroll and fused layer compute the same function when weights
    are shared (the reference's core consistency check)."""
    H, I, T, N = 6, 4, 5, 2
    fused = rnn.LSTM(H, input_size=I)
    fused.initialize()
    cell = rnn.LSTMCell(H, input_size=I)
    cell.initialize()
    # copy weights: fused l0_* <-> cell *
    pf = {k.split("lstm")[-1].split("_", 1)[1]: v
          for k, v in fused.collect_params().items()}
    pc = {k.split("lstmcell")[-1].split("_", 1)[1]: v
          for k, v in cell.collect_params().items()}
    for name in ["i2h_weight", "h2h_weight", "i2h_bias", "h2h_bias"]:
        pc[name].set_data(pf["l0_" + name].data())
    x = nd.random.uniform(shape=(T, N, I))
    out_fused = fused(x).asnumpy()
    outs, _ = cell.unroll(T, x, layout="TNC", merge_outputs=True)
    assert np.allclose(out_fused, outs.asnumpy(), atol=1e-5)


def test_sequential_rnn_cell():
    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(8, input_size=4))
    stack.add(rnn.GRUCell(6, input_size=8))
    stack.initialize()
    x = nd.random.uniform(shape=(2, 4))
    states = stack.begin_state(2)
    out, new_states = stack(x, states)
    assert out.shape == (2, 6)
    assert len(new_states) == 3  # lstm h,c + gru h


def test_residual_cell():
    base = rnn.GRUCell(4, input_size=4)
    cell = rnn.ResidualCell(base)
    cell.initialize()
    x = nd.random.uniform(shape=(2, 4))
    states = cell.begin_state(2)
    out, _ = cell(x, states)
    base_out, _ = base(x, states)
    assert np.allclose(out.asnumpy(),
                       base_out.asnumpy() + x.asnumpy(), atol=1e-6)


def test_cell_unroll_valid_length():
    cell = rnn.RNNCell(5, input_size=3)
    cell.initialize()
    x = nd.random.uniform(shape=(2, 6, 3))  # NTC
    valid = nd.array(np.array([3, 5], dtype=np.float32))
    out, _ = cell.unroll(6, x, layout="NTC", merge_outputs=True,
                         valid_length=valid)
    o = out.asnumpy()
    assert np.abs(o[0, 3:]).sum() == 0  # masked past valid_length
    assert np.abs(o[1, :5]).sum() > 0


def test_bidirectional_valid_length_reversal():
    """Backward cell must see each sample reversed within its valid region
    (review finding: naive reversal feeds padding first)."""
    H, I = 4, 3
    l_cell = rnn.GRUCell(H, input_size=I)
    r_cell = rnn.GRUCell(H, input_size=I)
    bi = rnn.BidirectionalCell(l_cell, r_cell)
    bi.initialize()
    T = 6
    np.random.seed(0)
    x_short = np.random.randn(1, 4, I).astype(np.float32)  # 4 valid steps
    x_pad = np.concatenate(
        [x_short, np.zeros((1, 2, I), np.float32)], axis=1)  # pad to 6
    # padded batch with valid_length=4
    out_pad, _ = bi.unroll(T, nd.array(x_pad), layout="NTC",
                           merge_outputs=True,
                           valid_length=nd.array(np.array([4.0])))
    # unpadded reference run
    out_ref, _ = bi.unroll(4, nd.array(x_short), layout="NTC",
                           merge_outputs=True)
    a = out_pad.asnumpy()[0, :4]
    b = out_ref.asnumpy()[0]
    assert np.allclose(a, b, atol=1e-5), np.abs(a - b).max()
