"""KVStore tests (reference patterns: tests/python/unittest/test_kvstore.py,
test_kvstore_custom.py; SURVEY.md §4 dist-test row)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon, autograd, kvstore
from mxnet_tpu.base import MXNetError

CTXS = [mx.cpu(0), mx.cpu(1)]


def _rand(shape, seed=0):
    return np.random.RandomState(seed).uniform(-1, 1, shape).astype("float32")


@pytest.mark.parametrize("kv_type", ["local", "device", "xla"])
def test_push_pull_sum(kv_type):
    kv = kvstore.create(kv_type)
    shape = (4, 5)
    a, b = _rand(shape, 1), _rand(shape, 2)
    kv.init("w", nd.array(np.zeros(shape, "float32")))
    vals = [nd.array(a, ctx=CTXS[0]), nd.array(b, ctx=CTXS[1])]
    outs = [nd.zeros(shape, ctx=c) for c in CTXS]
    kv.pushpull("w", vals, out=outs)
    for o in outs:
        np.testing.assert_allclose(o.asnumpy(), a + b, rtol=1e-6)


@pytest.mark.parametrize("kv_type", ["local", "device", "xla"])
def test_multi_key_list_api(kv_type):
    kv = kvstore.create(kv_type)
    shapes = [(3,), (2, 4), (5, 1)]
    keys = [str(i) for i in range(len(shapes))]
    kv.init(keys, [nd.zeros(s) for s in shapes])
    per_key = []
    for i, s in enumerate(shapes):
        per_key.append([nd.array(_rand(s, 10 + i), ctx=CTXS[0]),
                        nd.array(_rand(s, 20 + i), ctx=CTXS[1])])
    outs = [[nd.zeros(s, ctx=c) for c in CTXS] for s in shapes]
    kv.pushpull(keys, per_key, out=outs)
    for i, s in enumerate(shapes):
        want = _rand(s, 10 + i) + _rand(s, 20 + i)
        for o in outs[i]:
            np.testing.assert_allclose(o.asnumpy(), want, rtol=1e-6)


def test_xla_bucket_fusion_many_small_keys():
    """Dozens of small keys + one large key: results must be exact even
    when fused into shared buckets (NCCL small-grad fusion analogue)."""
    kv = kvstore.create("xla")
    kv.bigarray_bound = 64  # force several buckets
    n_keys = 20
    shapes = [(7,)] * (n_keys - 1) + [(130,)]
    keys = [str(i) for i in range(n_keys)]
    kv.init(keys, [nd.zeros(s) for s in shapes])
    per_key, want = [], []
    for i, s in enumerate(shapes):
        a, b = _rand(s, i), _rand(s, 100 + i)
        per_key.append([nd.array(a, ctx=CTXS[0]), nd.array(b, ctx=CTXS[1])])
        want.append(a + b)
    outs = [[nd.zeros(s, ctx=c) for c in CTXS] for s in shapes]
    kv.pushpull(keys, per_key, out=outs)
    for i in range(n_keys):
        for o in outs[i]:
            np.testing.assert_allclose(o.asnumpy(), want[i], rtol=1e-6)


def test_xla_four_devices():
    ctxs = [mx.cpu(i) for i in range(4)]
    kv = kvstore.create("xla")
    shape = (6, 3)
    kv.init("0", nd.zeros(shape))
    arrs = [_rand(shape, i) for i in range(4)]
    vals = [nd.array(a, ctx=c) for a, c in zip(arrs, ctxs)]
    outs = [nd.zeros(shape, ctx=c) for c in ctxs]
    kv.pushpull("0", vals, out=outs)
    for o in outs:
        np.testing.assert_allclose(o.asnumpy(), sum(arrs), rtol=1e-5)


def test_update_on_kvstore_optimizer():
    """Reference invariant: store runs SGD on the master copy; pulled
    weights reflect the update."""
    kv = kvstore.create("local")
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5, rescale_grad=1.0))
    w0 = _rand((4,), 3)
    kv.init("0", nd.array(w0))
    g = [nd.array(np.ones(4, "float32"), ctx=CTXS[0]),
         nd.array(np.ones(4, "float32"), ctx=CTXS[1])]
    kv.push("0", g)
    out = [nd.zeros((4,), ctx=CTXS[0])]
    kv.pull("0", out=out)
    np.testing.assert_allclose(out[0].asnumpy(), w0 - 0.5 * 2.0, rtol=1e-6)


def test_xla_rejects_optimizer():
    kv = kvstore.create("xla")
    with pytest.raises(MXNetError):
        kv.set_optimizer(mx.optimizer.SGD())


def test_gradient_compression_2bit():
    kv = kvstore.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init("0", nd.zeros((4,)))
    # grads below threshold are quantized to 0, residual carries over
    g = np.array([0.3, -0.3, 0.8, -0.9], "float32")
    vals = [nd.array(g, ctx=CTXS[0]), nd.array(g, ctx=CTXS[1])]
    outs = [nd.zeros((4,), ctx=CTXS[0])]
    kv.pushpull("0", vals, out=outs)
    np.testing.assert_allclose(outs[0].asnumpy(),
                               np.array([0, 0, 1.0, -1.0], "float32"))
    # second push: residual (0.3) + 0.3 crosses the threshold
    kv.pushpull("0", vals, out=outs)
    np.testing.assert_allclose(outs[0].asnumpy(),
                               np.array([1.0, -1.0, 1.0, -1.0], "float32"))


def test_custom_kvstore_registration():
    """Reference: test_kvstore_custom.py — plugin registry without
    network."""
    from mxnet_tpu.kvstore import KVStoreBase

    @KVStoreBase.register
    class Doubling(kvstore.KVStore):
        _TYPE = "doubling"
        CAPABILITIES = ()

        def _reduce(self, k, vals):
            acc = vals[0]
            for v in vals[1:]:
                acc = acc + v.as_in_context(acc.context)
            return acc * 2

    kv = kvstore.create("doubling")
    assert kv.type == "doubling"
    kv.init("0", nd.zeros((2,)))
    vals = [nd.array(np.ones(2, "float32"), ctx=c) for c in CTXS]
    outs = [nd.zeros((2,), ctx=CTXS[0])]
    kv.pushpull("0", vals, out=outs)
    np.testing.assert_allclose(outs[0].asnumpy(), np.full(2, 4.0))


def test_unknown_type_raises():
    with pytest.raises(MXNetError):
        kvstore.create("no_such_store")


# --------------------------------------------------------------------------
# P1 data parallelism through the reference user API:
# split_and_load + per-ctx backward + Trainer.step
# --------------------------------------------------------------------------
def _make_net(ctxs):
    net = gluon.nn.Dense(1, use_bias=True)
    net.initialize(mx.initializer.Xavier(), ctx=ctxs)
    return net


@pytest.mark.parametrize("kv_type", ["device", "xla"])
def test_trainer_multi_device_matches_single(kv_type):
    """2-ctx data-parallel SGD must equal single-device full-batch SGD."""
    X = _rand((8, 3), 7)
    Y = (X @ np.array([[1.0], [-2.0], [0.5]], "float32")
         + 0.1).astype("float32")
    loss_fn = gluon.loss.L2Loss()

    def run(ctxs, kv):
        mx.random.seed(0)
        net = _make_net(ctxs)
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1}, kvstore=kv)
        for _ in range(5):
            xs = gluon.utils.split_and_load(nd.array(X), ctxs)
            ys = gluon.utils.split_and_load(nd.array(Y), ctxs)
            with autograd.record():
                losses = [loss_fn(net(x), y) for x, y in zip(xs, ys)]
            for l in losses:
                l.backward()
            trainer.step(X.shape[0])
        p = net.collect_params()
        # block name counters auto-increment across nets: compare by order
        return [v.data(ctxs[0]).asnumpy() for v in p.values()]

    single = run([mx.cpu(0)], None)
    multi = run(CTXS, kv_type)
    assert len(single) == len(multi)
    for s, m in zip(single, multi):
        np.testing.assert_allclose(m, s, rtol=1e-5, atol=1e-6)


def test_trainer_multi_device_replicas_stay_synced():
    X = _rand((8, 3), 11)
    Y = _rand((8, 1), 12)
    net = _make_net(CTXS)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-2}, kvstore="xla")
    loss_fn = gluon.loss.L2Loss()
    for _ in range(3):
        xs = gluon.utils.split_and_load(nd.array(X), CTXS)
        ys = gluon.utils.split_and_load(nd.array(Y), CTXS)
        with autograd.record():
            losses = [loss_fn(net(x), y) for x, y in zip(xs, ys)]
        for l in losses:
            l.backward()
        trainer.step(X.shape[0])
    for p in net.collect_params().values():
        copies = [d.asnumpy() for d in p.list_data()]
        np.testing.assert_allclose(copies[0], copies[1], rtol=1e-6)


def test_trainer_set_lr_reaches_all_devices():
    """ADVICE round-1 item: hyperparameter changes must affect every
    device's updates, not just device 0."""
    net = _make_net(CTXS)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore="device")
    X, Y = _rand((4, 3), 1), _rand((4, 1), 2)
    loss_fn = gluon.loss.L2Loss()

    def one_step():
        xs = gluon.utils.split_and_load(nd.array(X), CTXS)
        ys = gluon.utils.split_and_load(nd.array(Y), CTXS)
        with autograd.record():
            losses = [loss_fn(net(x), y) for x, y in zip(xs, ys)]
        for l in losses:
            l.backward()
        trainer.step(X.shape[0])

    one_step()
    trainer.set_learning_rate(0.0)  # freezes ALL replicas if shared
    before = [d.asnumpy() for p in net.collect_params().values()
              for d in p.list_data()]
    one_step()
    after = [d.asnumpy() for p in net.collect_params().values()
             for d in p.list_data()]
    for b, a in zip(before, after):
        np.testing.assert_array_equal(b, a)


def test_trainer_save_load_states_multi_device(tmp_path):
    net = _make_net(CTXS)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-2}, kvstore="device")
    X, Y = _rand((4, 3), 1), _rand((4, 1), 2)
    loss_fn = gluon.loss.L2Loss()
    xs = gluon.utils.split_and_load(nd.array(X), CTXS)
    ys = gluon.utils.split_and_load(nd.array(Y), CTXS)
    with autograd.record():
        losses = [loss_fn(net(x), y) for x, y in zip(xs, ys)]
    for l in losses:
        l.backward()
    trainer.step(X.shape[0])
    fname = str(tmp_path / "trainer.states")
    trainer.save_states(fname)
    trainer2 = gluon.Trainer(net.collect_params(), "adam",
                             {"learning_rate": 1e-2}, kvstore="device")
    trainer2.load_states(fname)
    # states restored into every device updater — load_states on a FRESH
    # trainer must pre-create updaters for all ctxs, not just device 0
    assert len(trainer2._dev_updaters) == len(CTXS)
    for updater in trainer2._dev_updaters.values():
        assert updater.states.keys() == trainer._updater.states.keys()
        assert updater.optimizer is trainer2._optimizer


def test_trainer_update_on_kvstore():
    X = _rand((8, 3), 7)
    Y = (X @ np.array([[1.0], [-2.0], [0.5]], "float32")).astype("float32")
    loss_fn = gluon.loss.L2Loss()

    def run(update_on_kv):
        mx.random.seed(0)
        net = _make_net(CTXS)
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1}, kvstore="local",
                                update_on_kvstore=update_on_kv)
        for _ in range(3):
            xs = gluon.utils.split_and_load(nd.array(X), CTXS)
            ys = gluon.utils.split_and_load(nd.array(Y), CTXS)
            with autograd.record():
                losses = [loss_fn(net(x), y) for x, y in zip(xs, ys)]
            for l in losses:
                l.backward()
            trainer.step(X.shape[0])
        return [v.data(CTXS[0]).asnumpy()
                for v in net.collect_params().values()]

    worker_side = run(False)
    server_side = run(True)
    for w, s in zip(worker_side, server_side):
        np.testing.assert_allclose(s, w, rtol=1e-5, atol=1e-6)


def test_dist_async_documented_unsupported():
    """SURVEY P4: dist_async is parity-by-documentation — a specific,
    explanatory error, not the generic unknown-type one."""
    import pytest
    from mxnet_tpu.base import MXNetError
    with pytest.raises(MXNetError, match="intentionally unsupported"):
        kvstore.create("dist_async")
    with pytest.raises(MXNetError, match="dist_sync"):
        kvstore.create("dist_device_async")
